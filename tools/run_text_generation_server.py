#!/usr/bin/env python
"""Load a checkpoint and serve generation over REST
(reference: tools/run_text_generation_server.py)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from megatron_llm_tpu import checkpointing, global_vars
from megatron_llm_tpu.arguments import transformer_config_from_args
from megatron_llm_tpu.initialize import initialize_megatron
from megatron_llm_tpu.models import MODEL_REGISTRY
from megatron_llm_tpu.parallel import sharding as sh
from megatron_llm_tpu.text_generation_server import (
    MegatronServer, build_server_alerts)


def extra_args(parser):
    g = parser.add_argument_group("server")
    g.add_argument("--model_name", required=True)
    g.add_argument("--port", type=int, default=5000)
    g.add_argument("--host", default="0.0.0.0")
    g.add_argument("--int8_weights", action="store_true",
                   help="weight-only int8 quantization of the linear "
                        "kernels at load (halves decode weight traffic; "
                        "docs/guide/inference.md)")
    g.add_argument("--int8_kv_cache", action="store_true",
                   help="store decode K/V as int8 with per-position "
                        "scales (halves KV HBM traffic — the dominant "
                        "bytes at long context)")
    return parser


def main():
    args = initialize_megatron(extra_args_provider=extra_args)
    # serving observability: --structured_log_dir streams request_done
    # JSONL (analyze offline with tools/serve_report.py), --trace_dir
    # records Chrome spans with per-request trace ids (merge with the
    # router's file via tools/trace_report.py --merge)
    from megatron_llm_tpu import telemetry, tracing
    if args.structured_log_dir:
        telemetry.install_stream(
            telemetry.TelemetryStream(args.structured_log_dir))
    trace_bundle = tracing.build_tracing(args)
    if trace_bundle is not None:
        tracing.start_trace_flusher(trace_bundle)
    # same per-model presets and derivations as finetune.py: the CLI is
    # self-sufficient (--model_name=llama2 implies rotary/swiglu/
    # rmsnorm/no-bias; gemma gets its sqrt(hidden) embedding scale)
    from finetune import MODEL_DEFAULTS, _apply_model_defaults, model_provider
    if args.model_name in MODEL_DEFAULTS:
        _apply_model_defaults(args, sys.argv[1:])
        model = model_provider(args)
    else:
        model = MODEL_REGISTRY[args.model_name](
            transformer_config_from_args(args)
        )
    if args.load:
        params, _, _ = checkpointing.load_checkpoint(args.load, finetune=True)
    else:
        print(" no --load given: serving a randomly initialized model")
        params = model.init(jax.random.PRNGKey(args.seed))
    specs = model.param_specs(params)
    if args.int8_weights:
        from megatron_llm_tpu.quantization import (
            quantize_linear_weights_int8, quantize_param_specs,
            quantized_weight_bytes)
        params = quantize_linear_weights_int8(params)
        specs = quantize_param_specs(specs, params)
        qb, fb = quantized_weight_bytes(params)
        print(f" int8 weights: {qb/1e6:.1f} MB int8 + {fb/1e6:.1f} MB float")
    params = sh.shard_params(params, specs)
    tokenizer = global_vars.get_tokenizer()
    engine = None
    if args.serve_engine:
        from megatron_llm_tpu.serving import EngineConfig, InferenceEngine

        engine = InferenceEngine(model, params, EngineConfig(
            num_slots=args.serve_num_slots,
            block_size=args.serve_block_size,
            num_blocks=args.serve_num_blocks,
            max_model_len=args.serve_max_model_len,
            prefill_chunk=args.serve_prefill_chunk,
            max_queue_depth=args.serve_max_queue_depth,
            default_deadline_secs=args.serve_deadline_secs,
            int8_kv_cache=args.int8_kv_cache,
            prefix_cache=bool(args.serve_prefix_cache),
            host_cache_bytes=args.serve_host_cache_bytes,
            paged_kernel=args.serve_paged_kernel,
            prefill_kernel=args.serve_prefill_kernel,
            speculative=bool(args.serve_speculative),
            draft_k=args.serve_draft_k,
            watchdog_secs=args.serve_watchdog_secs,
            preemption=bool(args.serve_preemption),
            fault_spec=args.serve_fault_inject,
            restart_backoff_secs=args.serve_restart_backoff_secs,
        ))
        print(" * warming up serving engine (compiling prefill/decode "
              "programs)...", flush=True)
        print(f" * paged-attention decode path: {engine.paged_kernel}",
              flush=True)
        print(f" * paged-attention prefill path: {engine.prefill_kernel}",
              flush=True)
        spec = (f"on (draft_k={engine.draft_k})"
                if engine.speculative else "off")
        print(f" * speculative decoding: {spec}", flush=True)
        engine.warmup()
        from megatron_llm_tpu import tracing
        tr = tracing.get_tracing()
        if tr is not None and tr.recompile is not None:
            tr.recompile.mark_steady()
        engine.start()
    server = MegatronServer(model, params, tokenizer,
                            int8_kv_cache=args.int8_kv_cache,
                            engine=engine,
                            log_requests=args.log_requests,
                            max_prompts=args.serve_max_prompts,
                            max_tokens=args.serve_max_tokens)
    # SLO sentinel (serving/alerts.py): burn-rate + threshold alerting
    # over this replica's own /metrics, postmortem bundles under
    # <structured_log_dir>/incidents, transitions on the JSONL stream
    if args.serve_alerts:
        build_server_alerts(server, engine=engine,
                            structured_log_dir=args.structured_log_dir,
                            alert_rules=args.alert_rules,
                            alert_webhook=args.alert_webhook)
    server.run(args.host, args.port)


if __name__ == "__main__":
    main()
