#!/usr/bin/env python
"""AOT scale-proof for the BASELINE.md milestone configs (VERDICT r3 #3).

The 16-GB single v5e cannot *run* a 7B+ training step, but JAX + libtpu
can AOT-compile one against a **virtual TPU topology**
(``jax.experimental.topologies``) with no hardware attached, and the
compiled executable reports per-device memory
(``compiled.memory_analysis()``).  This tool compiles the TRUE shapes of
milestone configs 2-5 — Llama-2-7B TP=8, Mistral-7B TP=8 (GQA + sliding
window), Falcon-40B TP8xPP4, Llama-2-70B 3D on a v5p-256 slice — and
asserts the per-device bytes fit HBM (16 GB v5e / 95 GB v5p), recording
compiled collective counts.

Reference scaling recipes being proven: the SC21 suite
(/root/reference/examples/sc21/run_table_1.sh:14-127) and the 7B/70B
training configs in /root/reference/docs/guide/getting_started.md.

Usage:
  python tools/aot_memcheck.py [config ...]     # default: all
  python tools/aot_memcheck.py --list

Each config runs in a sanitized forced-CPU subprocess (the axon tunnel
must stay out of the picture; AOT needs only the local libtpu compiler).
Prints one JSON line per config and a summary table.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

GB = 1 << 30

# name -> spec.  'topology' is the libtpu topology string; devices are
# chips (v5p-256 in pod-slice naming = 256 cores = 128 megacore chips).
CONFIGS = {
    # milestone 2: Llama-2-7B TP=8 on a v5e-8 slice (16 GB HBM/chip)
    "llama2-7b-tp8": dict(
        family="llama2", size="7B", topology="v5e:2x4", accel="v5litepod-8",
        hbm_gb=16, tp=8, pp=1, vpp=None, seq=4096, micro_batch=1,
        num_micro=1, zero1=False, recompute="selective",
    ),
    # milestone 3: Mistral-7B GQA + sliding-window flash, TP=8.  Full
    # recompute: selective leaves 16.61 GB/chip (0.61 over budget); full
    # drops temp 5.16 -> 2.79 GB -> 14.41 GB/chip (measured via this tool)
    "mistral-7b-tp8": dict(
        family="mistral", size="7B", topology="v5e:2x4", accel="v5litepod-8",
        hbm_gb=16, tp=8, pp=1, vpp=None, seq=4096, micro_batch=1,
        num_micro=1, zero1=False, recompute="full",
    ),
    # trainable-batch 7B on v5e (VERDICT r4 #6): the tp8/mb1/M1 row above
    # is an existence proof with 0.17 GB headroom; this one is a config
    # you could actually train — v5e-16, tp=8 x dp=2, ZeRO-1 over dp,
    # M=8 microbatches (16 seqs/step at seq 4096), full recompute
    "llama2-7b-v5e16-m8": dict(
        family="llama2", size="7B", topology="v5e:4x4", accel="v5litepod-16",
        hbm_gb=16, tp=8, pp=1, vpp=None, seq=4096, micro_batch=1,
        num_micro=8, zero1=True, recompute="full",
    ),
    # milestone 4: Falcon-40B TP=8 x PP=4 (32 x v5p, 95 GB HBM/chip)
    "falcon-40b-tp8pp4": dict(
        family="falcon", size="40B", topology="v5p:4x4x2", accel="v5p-64",
        hbm_gb=95, tp=8, pp=4, vpp=None, seq=2048, micro_batch=1,
        num_micro=8, zero1=False,
    ),
    # milestone 5 / north star: Llama-2-70B full 3D on a v5p-256 slice
    # (128 chips): tp=8 x pp=4 x dp=4, ZeRO-1 over dp
    "llama2-70b-3d-v5p256": dict(
        family="llama2", size="70B", topology="v5p:8x4x4", accel="v5p-256",
        hbm_gb=95, tp=8, pp=4, vpp=None, seq=4096, micro_batch=1,
        num_micro=8, zero1=True,
    ),
    # Llama-3-8B (GQA 8kv, 128k vocab, theta 5e5) at seq 8192 on v5e-16:
    # the 128k-vocab head is exactly where fused CE pays (scale_aot
    # notes), so this row compiles with fused_lm_cross_entropy on
    "llama3-8b-v5e16": dict(
        family="llama3", size="llama3-8B", topology="v5e:4x4",
        accel="v5litepod-16", hbm_gb=16, tp=8, pp=1, vpp=None, seq=8192,
        micro_batch=1, num_micro=4, zero1=True, recompute="full",
        fused_ce=True,
    ),
    # beyond-reference families at scale: Qwen2-7B and Gemma-7B
    "qwen2-7b-tp8": dict(
        family="qwen2", size="7B", topology="v5p:2x2x2", accel="v5p-16",
        hbm_gb=95, tp=8, pp=1, vpp=None, seq=4096, micro_batch=1,
        num_micro=1, zero1=False,
    ),
    "gemma-7b-tp8": dict(
        family="gemma", size="7B", topology="v5p:2x2x2", accel="v5p-16",
        hbm_gb=95, tp=8, pp=1, vpp=None, seq=4096, micro_batch=1,
        num_micro=1, zero1=False,
    ),
    # SC21 weak-scaling suite rows (reference examples/sc21/run_table_1.sh
    # + arXiv 2104.04473 Table 1) mapped onto v5p topologies — GPT-2
    # architecture, seq 2048, same tp/pp split, dp fills the slice
    "sc21-1.7b": dict(
        family="gpt", shape=dict(num_layers=24, hidden_size=2304,
                                 num_attention_heads=24),
        topology="v5p:2x2x1", accel="v5p-8", hbm_gb=95, tp=1, pp=1,
        vpp=None, seq=2048, micro_batch=4, num_micro=2, zero1=True,
    ),
    "sc21-18b": dict(
        family="gpt", shape=dict(num_layers=40, hidden_size=6144,
                                 num_attention_heads=48),
        topology="v5p:4x2x2", accel="v5p-32", hbm_gb=95, tp=8, pp=1,
        vpp=None, seq=2048, micro_batch=1, num_micro=4, zero1=True,
    ),
    "sc21-175b": dict(
        family="gpt", shape=dict(num_layers=96, hidden_size=12288,
                                 num_attention_heads=96),
        topology="v5p:8x4x8", accel="v5p-512", hbm_gb=95, tp=8, pp=16,
        vpp=None, seq=2048, micro_batch=1, num_micro=32, zero1=True,
    ),
}


def _model_for(spec):
    import jax.numpy as jnp

    common = dict(
        seq_length=spec["seq"], max_position_embeddings=spec["seq"],
        params_dtype="bf16", compute_dtype="bf16",
        recompute_granularity=spec.get("recompute", "selective"),
        use_flash_attn=True,
        use_fused_rmsnorm=False,
        fused_lm_cross_entropy=spec.get("fused_ce", False),
    )
    if spec["family"] == "gpt":
        from megatron_llm_tpu.models.gpt import GPTModel
        from megatron_llm_tpu.models.gpt2 import gpt2_config

        common.pop("use_fused_rmsnorm", None)
        return GPTModel(gpt2_config(
            "tiny", **spec["shape"], padded_vocab_size=51200,
            hidden_dropout=0.0, attention_dropout=0.0, **common))
    if spec["family"] == "qwen2":
        from megatron_llm_tpu.models.qwen2 import Qwen2Model, qwen2_config

        return Qwen2Model(qwen2_config(spec["size"], **common))
    if spec["family"] == "gemma":
        from megatron_llm_tpu.models.gemma import GemmaModel, gemma_config

        return GemmaModel(gemma_config(spec["size"], **common))
    if spec["family"] in ("llama2", "llama3"):
        from megatron_llm_tpu.models.llama import LlamaModel, llama_config

        return LlamaModel(llama_config(spec["size"], **common))
    if spec["family"] == "mistral":
        from megatron_llm_tpu.models.mistral import (
            MistralModel,
            mistral_config,
        )

        return MistralModel(mistral_config(spec["size"], **common))
    if spec["family"] == "falcon":
        from megatron_llm_tpu.models.falcon import FalconModel, falcon_config

        common.pop("use_fused_rmsnorm", None)
        return FalconModel(falcon_config(spec["size"], **common))
    raise ValueError(spec["family"])


def _abstract_with_shardings(tree, specs, mesh):
    """eval_shape pytree + logical specs -> ShapeDtypeStructs carrying
    NamedShardings (what jit.lower needs for AOT)."""
    import jax
    from jax.sharding import NamedSharding

    from megatron_llm_tpu.parallel.sharding import logical_to_mesh

    def one(x, s):
        return jax.ShapeDtypeStruct(
            x.shape, x.dtype,
            sharding=NamedSharding(mesh, logical_to_mesh(tuple(s))))

    return jax.tree_util.tree_map(
        one, tree, specs, is_leaf=lambda s: isinstance(s, tuple))


def run_config(name: str) -> dict:
    spec = CONFIGS[name]
    # off-GCP the metadata server 403s and libtpu retries each variable
    # 30x with backoff before the topology init can proceed — skip it
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "true")
    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import NamedSharding, PartitionSpec as P

    from megatron_llm_tpu import topology
    from megatron_llm_tpu.config import ParallelConfig, TrainConfig
    from megatron_llm_tpu.optimizer import MegatronOptimizer

    topo = topologies.get_topology_desc(
        platform="tpu", topology_name=spec["topology"])
    devs = topo.devices
    tp, pp = spec["tp"], spec["pp"]
    dp = len(devs) // (tp * pp)
    mesh = topology.initialize_model_parallel(
        tensor_model_parallel_size=tp, pipeline_model_parallel_size=pp,
        virtual_pipeline_model_parallel_size=spec["vpp"], devices=devs)

    model = _model_for(spec)
    cfg = model.cfg
    key = jax.random.PRNGKey(0)
    params_shape = jax.eval_shape(model.init, key)
    n_params = sum(
        int(np_.size) for np_ in jax.tree_util.tree_leaves(params_shape))
    pspecs = model.param_specs(params_shape)
    params_abs = _abstract_with_shardings(params_shape, pspecs, mesh)

    M, mb = spec["num_micro"], spec["micro_batch"]
    tc = TrainConfig(micro_batch_size=mb, global_batch_size=M * mb * dp,
                     train_iters=0, lr=1e-4, optimizer="adam", bf16=True,
                     clip_grad=1.0)
    pc = ParallelConfig(
        tensor_model_parallel_size=tp, pipeline_model_parallel_size=pp,
        data_parallel_size=dp,
        virtual_pipeline_model_parallel_size=spec["vpp"],
        sequence_parallel=tp > 1,
        use_distributed_optimizer=spec["zero1"],
    )
    opt = MegatronOptimizer(tc, params_dtype=jnp.bfloat16)
    opt_shape = jax.eval_shape(opt.init, params_shape)
    ospecs = opt.state_specs(pspecs, params_shape,
                             zero1=spec["zero1"] and dp > 1, dp_size=dp)
    import jax.tree_util as jtu

    def replicated(tree):
        return jtu.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=NamedSharding(mesh, P())),
            tree)

    opt_abs = opt_shape._replace(
        step=replicated(opt_shape.step),
        grad_scaler=replicated(opt_shape.grad_scaler),
        exp_avg=_abstract_with_shardings(
            opt_shape.exp_avg, ospecs.exp_avg, mesh),
        exp_avg_sq=(
            _abstract_with_shardings(
                opt_shape.exp_avg_sq, ospecs.exp_avg_sq, mesh)
            if opt_shape.exp_avg_sq is not None else None),
        master_params=(
            _abstract_with_shardings(
                opt_shape.master_params, ospecs.master_params, mesh)
            if opt_shape.master_params is not None else None),
    )

    seq = spec["seq"]
    dsh = NamedSharding(mesh, P(None, "dp", None))
    batch = {
        "tokens": jax.ShapeDtypeStruct((M, mb * dp, seq), jnp.int32,
                                       sharding=dsh),
        "labels": jax.ShapeDtypeStruct((M, mb * dp, seq), jnp.int32,
                                       sharding=dsh),
        "loss_mask": jax.ShapeDtypeStruct((M, mb * dp, seq), jnp.float32,
                                          sharding=dsh),
    }
    key_abs = jax.ShapeDtypeStruct((2,), jnp.uint32)
    lr_abs = jax.ShapeDtypeStruct((), jnp.float32)
    wd_abs = jax.ShapeDtypeStruct((), jnp.float32)

    if pp > 1:
        from megatron_llm_tpu.parallel.pipeline import (
            build_pipeline_train_step,
        )

        step = build_pipeline_train_step(model, opt, pc, M)
    else:
        from megatron_llm_tpu.training import build_train_step

        step = build_train_step(model, opt, pc, M)

    print(f"[{name}] lowering: {n_params/1e9:.2f}B params, "
          f"{len(devs)} x {devs[0].device_kind}, tp={tp} pp={pp} dp={dp} "
          f"seq={seq} M={M}", file=sys.stderr, flush=True)
    lowered = step.lower(params_abs, opt_abs, batch, key_abs, lr_abs, wd_abs)
    print(f"[{name}] compiling...", file=sys.stderr, flush=True)
    compiled = lowered.compile()
    ma = compiled.memory_analysis()
    arg_b = int(ma.argument_size_in_bytes)
    out_b = int(ma.output_size_in_bytes)
    tmp_b = int(ma.temp_size_in_bytes)
    alias_b = int(ma.alias_size_in_bytes)
    total = arg_b + out_b + tmp_b - alias_b
    hbm = spec["hbm_gb"] * GB

    colls = {}
    try:
        txt = compiled.as_text()
        if txt and len(txt) < 400 << 20:
            for op in ("all-reduce", "all-gather", "reduce-scatter",
                       "collective-permute", "all-to-all"):
                n = txt.count(f" {op}(") + txt.count(f" {op}-start(")
                if n:
                    colls[op] = n
    except Exception as e:
        colls = {"error": str(e)[:100]}

    rec = {
        "config": name, "n_params": n_params, "devices": len(devs),
        "device_kind": devs[0].device_kind, "tp": tp, "pp": pp, "dp": dp,
        "seq": seq, "num_micro": M, "zero1": spec["zero1"],
        "hbm_gb": spec["hbm_gb"],
        "per_device_bytes": {
            "arguments": arg_b, "outputs": out_b, "temp": tmp_b,
            "aliased": alias_b, "total": total,
        },
        "per_device_gb": round(total / GB, 2),
        "fits": total <= hbm,
        "headroom_gb": round((hbm - total) / GB, 2),
        "collectives": colls,
    }
    print(json.dumps(rec), flush=True)
    return rec


def main(argv):
    if "--list" in argv:
        print("\n".join(CONFIGS))
        return 0
    if argv and argv[0] == "--child":
        return 0 if run_config(argv[1]).get("fits") else 1

    names = [a for a in argv if not a.startswith("-")] or list(CONFIGS)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("JAX_PLATFORM_NAME", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("TPU_WORKER_HOSTNAMES", "localhost")
    # AOT children lower for a TPU topology with a CPU default backend;
    # without this the pallas kernels silently compile as XLA fallbacks
    # (discovered round 5 — rows recorded before then were XLA-attention
    # compiles)
    env["MLT_FORCE_PALLAS"] = "1"
    rc = 0
    for name in names:
        e = dict(env)
        e["TPU_ACCELERATOR_TYPE"] = CONFIGS[name]["accel"]
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", name],
            env=e, cwd=REPO)
        rc |= r.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
