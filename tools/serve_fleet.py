#!/usr/bin/env python
"""Run a supervised, autoscaling replica fleet behind the router.

One process: the multi-replica router (``serving/router.py``) plus the
fleet supervisor (``serving/supervisor.py``) driving a local subprocess
backend.  Replicas are spawned from ``--replica_cmd`` — any command that
prints ``PORT <n>`` on stdout once its HTTP server accepts (the same
handshake ``tools/run_text_generation_server.py --port 0`` and
``tests/_serve_replica.py`` speak):

    python tools/serve_fleet.py \\
        --replica_cmd "python tools/run_text_generation_server.py \\
            --load_checkpoint ckpt/ --port 0" \\
        --min_replicas 1 --max_replicas 4 \\
        --ttft_p95_slo_secs 0.8 --port 8000

The supervisor registers each replica with the router when it reports
ready, respawns dead ones with capped exponential backoff, scales up on
a sustained p95-TTFT / queue-depth breach, scales down by draining the
coldest replica when sustained-idle, and sheds load with honest 429s
(brownout) while new capacity boots.  Clients point at the router
exactly as at a single server: PUT /api, PUT /api/stream, GET /health,
GET /metrics (which now includes a ``fleet`` block and per-event JSONL
via --fleet_event_log).  See docs/guide/fault_tolerance.md, "Fleet
supervision & autoscaling".

With ``--routers N`` the front door itself is sharded: instead of one
in-process router, the supervisor spawns N ``tools/serve_router.py
--dynamic`` subprocesses, keeps their peer lists + replica membership
synchronized through ``RouterTierClient``, respawns dead routers with
the same storm-capped backoff replicas get, and scales the tier on
front-door saturation.  Each router prints ``ROUTER <url>`` on our
stdout as it becomes ready; clients hold the whole list and retry a
sibling on transport error (``serve_bench.py --url ... --url ...``).
See docs/guide/serving.md, "Sharded front door".

For real orchestrators (k8s, GCE MIGs), implement
``serving.supervisor.ReplicaBackend`` (spawn/poll/kill) and reuse
``FleetSupervisor`` unchanged — the policy never knows what a process
is.
"""

import argparse
import os
import shlex
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--replica_cmd", required=True,
                   help="command spawning ONE replica that prints "
                        "'PORT <n>' on stdout when ready (use --port 0 "
                        "so replicas pick free ports)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    # fleet size
    p.add_argument("--initial_replicas", type=int, default=0,
                   help="replicas spawned at startup (0 = min_replicas)")
    p.add_argument("--min_replicas", type=int, default=1)
    p.add_argument("--max_replicas", type=int, default=4)
    # SLO-driven scaling policy
    p.add_argument("--ttft_p95_slo_secs", type=float, default=1.0,
                   help="scale up when windowed p95 TTFT sustains above "
                        "this")
    p.add_argument("--queue_depth_high", type=int, default=16,
                   help="scale up when the fleet-summed engine queue "
                        "depth sustains at/above this")
    p.add_argument("--breach_secs", type=float, default=2.0,
                   help="how long a breach must sustain before scale-up")
    p.add_argument("--scale_cooldown_secs", type=float, default=30.0,
                   help="minimum gap between scaling actions")
    p.add_argument("--scale_down_idle_secs", type=float, default=60.0,
                   help="how long the fleet must be idle before the "
                        "coldest replica is drained")
    p.add_argument("--scale_down_ttft_frac", type=float, default=0.5,
                   help="hysteresis: idle means p95 below this fraction "
                        "of the SLO (between frac*SLO and SLO nothing "
                        "moves)")
    # self-healing
    p.add_argument("--respawn_backoff_secs", type=float, default=1.0)
    p.add_argument("--respawn_backoff_max_secs", type=float, default=30.0)
    p.add_argument("--respawn_storm_window_secs", type=float,
                   default=60.0,
                   help="deaths inside this window double the backoff; "
                        "outside it the backoff resets")
    p.add_argument("--dead_confirmation_secs", type=float, default=3.0,
                   help="a breaker-open replica (process still up) must "
                        "stay dead this long before it is respawned")
    p.add_argument("--poll_interval_secs", type=float, default=1.0,
                   help="supervisor control-loop period")
    p.add_argument("--spawn_eta_secs", type=float, default=60.0,
                   help="prior for spawn->ready time (brownout "
                        "retry_after until observed spawns refine it)")
    # router knobs (mirror tools/serve_router.py)
    p.add_argument("--fail_threshold", type=int, default=3)
    p.add_argument("--cooldown_secs", type=float, default=1.0)
    p.add_argument("--max_cooldown_secs", type=float, default=30.0)
    p.add_argument("--probe_interval_secs", type=float, default=2.0,
                   help="background /health probe period")
    p.add_argument("--affinity_chars", type=int, default=256)
    p.add_argument("--affinity_max", type=int, default=4096)
    p.add_argument("--request_timeout_secs", type=float, default=600.0)
    # sharded front door (0 = legacy single in-process router)
    p.add_argument("--routers", type=int, default=0,
                   help="run N stateless router subprocesses instead of "
                        "one in-process router; they agree on affinity "
                        "via rendezvous hashing and any of them answers "
                        "fleet-wide /metrics")
    p.add_argument("--max_routers", type=int, default=0,
                   help="router-tier scale-up ceiling (default: "
                        "--routers, i.e. a fixed-size tier)")
    p.add_argument("--router_dispatch_p95_slo_secs", type=float,
                   default=0.25,
                   help="scale the router tier up when the windowed "
                        "dispatch-loop p95 sustains above this")
    p.add_argument("--router_inflight_high", type=int, default=64,
                   help="...or when the summed router in-flight "
                        "(connection-queue proxy) sustains at/above "
                        "this")
    # observability
    p.add_argument("--fleet_event_log", default=None,
                   help="append fleet events (replica_spawned/died/"
                        "respawned, scale_up/down, brownout) as JSONL "
                        "here; tools/serve_report.py renders a timeline")
    return p.parse_args(argv)


def _router_tier_argv(args):
    """Command for ONE router subprocess (free port, supervisor-managed
    membership), forwarding the shared router knobs."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [
        sys.executable, os.path.join(root, "tools", "serve_router.py"),
        "--dynamic", "--host", "127.0.0.1", "--port", "0",
        "--fail_threshold", str(args.fail_threshold),
        "--breaker_backoff_secs", str(args.cooldown_secs),
        "--max_cooldown_secs", str(args.max_cooldown_secs),
        "--probe_interval_secs", str(args.probe_interval_secs),
        "--affinity_chars", str(args.affinity_chars),
        "--affinity_max", str(args.affinity_max),
        "--request_timeout_secs", str(args.request_timeout_secs),
    ]


def main(argv=None):
    args = parse_args(argv)
    from megatron_llm_tpu.serving.router import ReplicaRouter, RouterServer
    from megatron_llm_tpu.serving.supervisor import (
        FleetSupervisor,
        LocalProcessBackend,
        PolicyConfig,
        RouterTierClient,
    )

    tier = max(args.routers, 0)
    router_backend = None
    if tier > 0:
        router = RouterTierClient()
        router_backend = LocalProcessBackend(
            _router_tier_argv(args),
            spawn_eta_secs=30.0,
            stderr=None,                # routers share our stderr
        )
    else:
        router = ReplicaRouter(
            [],                         # membership is the supervisor's
            fail_threshold=args.fail_threshold,
            cooldown_secs=args.cooldown_secs,
            max_cooldown_secs=args.max_cooldown_secs,
            affinity_chars=args.affinity_chars,
            affinity_max=args.affinity_max,
            health_interval_secs=args.probe_interval_secs,
            request_timeout_secs=args.request_timeout_secs,
        )
    backend = LocalProcessBackend(
        shlex.split(args.replica_cmd),
        spawn_eta_secs=args.spawn_eta_secs,
        stderr=None,                    # replicas share our stderr
    )
    cfg = PolicyConfig(
        ttft_p95_slo_secs=args.ttft_p95_slo_secs,
        queue_depth_high=args.queue_depth_high,
        breach_secs=args.breach_secs,
        scale_cooldown_secs=args.scale_cooldown_secs,
        scale_down_idle_secs=args.scale_down_idle_secs,
        scale_down_ttft_frac=args.scale_down_ttft_frac,
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        respawn_backoff_secs=args.respawn_backoff_secs,
        respawn_backoff_max_secs=args.respawn_backoff_max_secs,
        respawn_storm_window_secs=args.respawn_storm_window_secs,
        dead_confirmation_secs=args.dead_confirmation_secs,
        min_routers=tier,
        max_routers=max(args.max_routers, tier),
        router_dispatch_p95_slo_secs=args.router_dispatch_p95_slo_secs,
        router_inflight_high=args.router_inflight_high,
    )
    supervisor = FleetSupervisor(
        router, backend, config=cfg,
        poll_interval_secs=args.poll_interval_secs,
        event_log_path=args.fleet_event_log,
        router_backend=router_backend,
    )
    supervisor.spawn_initial(args.initial_replicas or args.min_replicas)
    if tier > 0:
        supervisor.spawn_initial_routers(tier)
    supervisor.start()

    if tier > 0:
        # no local HTTP server: the subprocess routers ARE the front
        # door.  Announce each as it becomes ready and block until a
        # signal; clients keep the whole list and retry siblings.
        import threading
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        signal.signal(signal.SIGINT, lambda *_: stop.set())
        announced = set()
        try:
            while not stop.wait(0.5):
                for url in supervisor.router_urls():
                    if url not in announced:
                        announced.add(url)
                        print(f"ROUTER {url}", flush=True)
        finally:
            supervisor.stop(kill_replicas=True)
        return 0

    server = RouterServer(router)

    def _term(signum, frame):
        server.shutdown()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    try:
        server.run(host=args.host, port=args.port)
    finally:
        supervisor.stop(kill_replicas=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
