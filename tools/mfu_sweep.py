"""On-chip MFU sweep harness (the tool behind docs/perf_tpu.md).

Usage: python tools/mfu_sweep.py <group>   (groups defined at the bottom)
Each trial builds a fresh llama-family model + fused-Adam train step,
runs 2 warmup + 5 timed iterations and prints ms/iter, tokens/s and MFU.
Timing syncs use a host-side scalar fetch, NOT block_until_ready — on the
axon remote platform the latter can return before the first enqueued
execution finishes (docs/perf_tpu.md "measurement traps").
"""

import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import jax

from tools.bench_harness import (enable_compile_cache, make_cfg,
                                 build_concrete, make_batch)

enable_compile_cache()

PEAK = 197e12

def bench_cfg(label, mb=8, remat="selective", flash=True, fused_rms=True,
              L=16, h=1280, ffn=3584, heads=16, seq=2048, iters=5, bq=None,
              bk=None, experts=0, top_k=2, fused_bwd=None, vocab=32000,
              fused_ce=False, opt_state_dtype="fp32"):
    import megatron_llm_tpu.ops.pallas.flash_attention as fa
    orig_bq, orig_bk = fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_K
    orig_fused = fa.FUSED_BACKWARD
    if bq: fa.DEFAULT_BLOCK_Q = bq
    if bk: fa.DEFAULT_BLOCK_K = bk
    if fused_bwd is not None: fa.FUSED_BACKWARD = fused_bwd
    try:
        # model/optimizer init INSIDE the trial guard: the memory-edge
        # trials (bigvocab) can OOM at init, which must fail that one
        # trial, not abort the sweep
        cfg = make_cfg(L=L, h=h, heads=heads, ffn=ffn, seq=seq,
                       vocab=vocab, remat=remat, flash=flash,
                       fused_rms=fused_rms, experts=experts, top_k=top_k,
                       fused_ce=fused_ce)
        model, params, opt, opt_state, step = build_concrete(
            cfg, mb, opt_state_dtype=opt_state_dtype)
        n = model.num_params(params)
        batch = make_batch(mb, seq, vocab)
        key = jax.random.PRNGKey(1)
        for _ in range(2):
            params, opt_state, m = step(params, opt_state, batch, key, 1e-4, 0.0)
            float(m["lm loss"])
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, m = step(params, opt_state, batch, key, 1e-4, 0.0)
        float(m["lm loss"])
        dt = (time.perf_counter() - t0) / iters
        tps = mb * seq / dt
        mfu = tps * model.flops_per_token() / PEAK
        print(f"{label:44s} n={n/1e6:6.1f}M dt={dt*1000:8.1f}ms tps={tps:9.1f} mfu={mfu:.3f}", flush=True)
    except Exception as e:
        print(f"{label:44s} FAILED: {type(e).__name__}: {str(e)[:120]}", flush=True)
    fa.DEFAULT_BLOCK_Q = orig_bq
    fa.DEFAULT_BLOCK_K = orig_bk
    fa.FUSED_BACKWARD = orig_fused

GROUPS = {
    "baseline": [
        dict(label="flash defaults mb4", mb=4),
        dict(label="flash defaults mb8", mb=8),
        dict(label="xla attention mb4", mb=4, flash=False),
    ],
    "blocks": [
        dict(label="flash bq128 bk128", bq=128, bk=128),
        dict(label="flash bq256 bk256", bq=256, bk=256),
        dict(label="flash bq512 bk512", bq=512, bk=512),
        dict(label="flash bq1024 bk1024", bq=1024, bk=1024),
    ],
    "mb": [
        dict(label="flash mb2", mb=2),
        dict(label="flash mb4", mb=4),
        dict(label="flash mb8", mb=8),
        dict(label="flash mb16", mb=16),
    ],
    "remat": [
        dict(label="selective", remat="selective", mb=4),
        dict(label="full", remat="full", mb=4),
        dict(label="none", remat="none", mb=4),
    ],
    "long": [
        dict(label="seq4096 mb4 flash", seq=4096, mb=4),
        dict(label="seq8192 mb2 flash", seq=8192, mb=2),
        dict(label="seq4096 mb4 xla", seq=4096, mb=4, flash=False),
    ],
}
GROUPS["shape"] = [
    dict(label="h1280 nh16 d80", mb=4),
    dict(label="h1280 nh10 d128", mb=4, heads=10),
    dict(label="h2048 nh16 d128 L10 (bench)", mb=4, h=2048, heads=16, ffn=5632, L=10),
    dict(label="h2048 nh16 d128 L10 mb2", mb=2, h=2048, heads=16, ffn=5632, L=10),
]
GROUPS["shape2"] = [
    dict(label="h2048 L10 mb8", mb=8, h=2048, heads=16, ffn=5632, L=10),
    dict(label="h2048 L12 mb4", mb=4, h=2048, heads=16, ffn=5632, L=12),
    dict(label="h2560 nh20 L8 mb4", mb=4, h=2560, heads=20, ffn=6912, L=8),
]
GROUPS["tune650"] = [
    dict(label="650M bq1024 bk1024 (bench)", mb=4, h=2048, heads=16, ffn=5632, L=10),
    dict(label="650M bq512 bk1024", mb=4, h=2048, heads=16, ffn=5632, L=10, bq=512, bk=1024),
    dict(label="650M bq1024 bk512", mb=4, h=2048, heads=16, ffn=5632, L=10, bq=1024, bk=512),
    dict(label="650M remat full", mb=4, h=2048, heads=16, ffn=5632, L=10, remat="full"),
    dict(label="650M mb6", mb=6, h=2048, heads=16, ffn=5632, L=10),
]
GROUPS["moe"] = [
    # MoE on one chip: all experts local (ep needs a mesh); measures the
    # dispatch/combine einsum overhead vs the dense MLP at matched
    # active-FLOPs (dense ffn == top_k * moe ffn per token)
    dict(label="dense h2048 L10 ffn5632 (bench)",
         mb=4, h=2048, heads=16, ffn=5632, L=10),
    dict(label="moe E4 top2 ffn2816 (matched active)",
         mb=4, h=2048, heads=16, ffn=2816, L=10, experts=4),
    dict(label="moe E8 top2 ffn2816",
         mb=4, h=2048, heads=16, ffn=2816, L=10, experts=8),
]
# round-4: the fused single-pass flash backward (the round-3 "known
# headroom") A/B'd at the bench shape and at matched-baseline seq 4096 —
# VERDICT r3 #2 wants MFU >= 0.47 at seq 4096
GROUPS["fusedbwd"] = [
    dict(label="650M seq2048 two-kernel bwd", mb=4, h=2048, heads=16,
         ffn=5632, L=10, fused_bwd=False),
    dict(label="650M seq2048 fused bwd", mb=4, h=2048, heads=16,
         ffn=5632, L=10, fused_bwd=True),
    dict(label="650M seq4096 two-kernel bwd", mb=2, h=2048, heads=16,
         ffn=5632, L=10, seq=4096, fused_bwd=False),
    dict(label="650M seq4096 fused bwd", mb=2, h=2048, heads=16,
         ffn=5632, L=10, seq=4096, fused_bwd=True),
    dict(label="650M seq8192 two-kernel bwd", mb=1, h=2048, heads=16,
         ffn=5632, L=10, seq=8192, fused_bwd=False),
    dict(label="650M seq8192 fused bwd", mb=1, h=2048, heads=16,
         ffn=5632, L=10, seq=8192, fused_bwd=True),
]
GROUPS["seq4096"] = [
    dict(label="650M seq4096 mb1", mb=1, h=2048, heads=16, ffn=5632,
         L=10, seq=4096),
    dict(label="650M seq4096 mb2", mb=2, h=2048, heads=16, ffn=5632,
         L=10, seq=4096),
    dict(label="650M seq4096 mb4", mb=4, h=2048, heads=16, ffn=5632,
         L=10, seq=4096),
    dict(label="650M seq4096 mb2 bq2048", mb=2, h=2048, heads=16,
         ffn=5632, L=10, seq=4096, bq=2048, bk=1024),
    dict(label="650M seq4096 mb2 bk2048", mb=2, h=2048, heads=16,
         ffn=5632, L=10, seq=4096, bq=1024, bk=2048),
    dict(label="650M seq4096 mb2 full-remat", mb=2, h=2048, heads=16,
         ffn=5632, L=10, seq=4096, remat="full"),
]
# fused chunked linear+CE flip point (VERDICT r3 #8): at 32k vocab it
# measured a tie (docs/perf_tpu.md "tried and rejected"); the claim is
# the trade flips at 128k vocab where the [tokens, vocab] fp32 logits
# block is 4x bigger.  Smaller L keeps the 128k-vocab embedding+head
# (h2048: 2 x 0.5 GB bf16) inside 16 GB next to the Adam state.
GROUPS["bigvocab"] = [
    dict(label="v32k  unfused (bench cfg)", mb=4, h=2048, heads=16,
         ffn=5632, L=10),
    dict(label="v32k  fused-CE", mb=4, h=2048, heads=16, ffn=5632, L=10,
         fused_ce=True),
    dict(label="v128k unfused", mb=4, h=2048, heads=16, ffn=5632, L=8,
         vocab=131072),
    dict(label="v128k fused-CE", mb=4, h=2048, heads=16, ffn=5632, L=8,
         vocab=131072, fused_ce=True),
    dict(label="v256k unfused", mb=2, h=2048, heads=16, ffn=5632, L=6,
         vocab=262144),
    dict(label="v256k fused-CE", mb=2, h=2048, heads=16, ffn=5632, L=6,
         vocab=262144, fused_ce=True),
]
# bf16 optimizer-state A/B (optimizer_state_dtype): the Adam moments are
# pure HBM traffic in the step — storing them bf16 halves those
# bytes.  Same shape as the bench config.
GROUPS["optstate"] = [
    dict(label="650M fp32 moments (bench)", mb=4, h=2048, heads=16,
         ffn=5632, L=10),
    dict(label="650M bf16 moments", mb=4, h=2048, heads=16, ffn=5632,
         L=10, opt_state_dtype="bf16"),
    dict(label="650M seq4096 bf16 moments", mb=2, h=2048, heads=16,
         ffn=5632, L=10, seq=4096, opt_state_dtype="bf16"),
]
GROUPS["all"] = GROUPS["baseline"] + GROUPS["blocks"]

if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which not in GROUPS:
        print(f"unknown group {which!r}; available: {', '.join(GROUPS)}")
        sys.exit(1)
    for trial in GROUPS[which]:
        bench_cfg(**trial)
