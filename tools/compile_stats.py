#!/usr/bin/env python
"""Compile-time evidence for the fused flash backward (VERDICT r4 #8).

With the axon tunnel down there are no on-chip ms/iter numbers, but the
real libtpu compiler is local: this tool AOT-compiles the bench-config
train step (llama-650M: L10 h2048 d128, the shape `bench.py` measures)
for a single virtual v5e chip at seq 2048/4096/8192, with the fused
single-pass flash backward ON vs OFF, and records what the compiler
itself reports — `cost_analysis()` FLOPs / bytes-accessed,
`memory_analysis()` temp/total HBM, and optimized-HLO op counts
(fusions, custom-calls = pallas kernels, while loops).

These are COMPILE-TIME numbers, not MFU: they show the fused path's
effect on compiled HBM traffic and kernel count.  The on-chip playbook
in docs/perf_tpu.md supersedes this the moment the tunnel answers.

Same one-process-per-compile structure as tools/aot_memcheck.py (the
local libtpu accepts one client at a time — /tmp/libtpu_lockfile).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

GB = 1 << 30

# (label, seq, micro_batch, fused_backward) — shapes mirror the
# tools/mfu_sweep.py `fusedbwd` trial group so on-chip numbers, when
# they land, are directly comparable.
TRIALS = [
    ("seq2048-twokernel", 2048, 4, False),
    ("seq2048-fused", 2048, 4, True),
    ("seq4096-twokernel", 4096, 2, False),
    ("seq4096-fused", 4096, 2, True),
    ("seq8192-twokernel", 8192, 1, False),
    ("seq8192-fused", 8192, 1, True),
    # fused-CE flip-point insurance (VERDICT r4 #7 is chip-gated; these
    # record the compiler-visible memory/traffic effect at 128k vocab).
    # Smaller body (L4 h1024): at the full bench shape the 128k-vocab
    # model's fp32 optimizer state alone nears the 16 GB HBM and both
    # variants OOM at compile, drowning the CE difference.
    # (label, seq, mb, fused_bwd, vocab, fused_ce, shape)
    ("vocab128k-plainCE", 2048, 4, True, 131072, False, "small"),
    ("vocab128k-fusedCE", 2048, 4, True, 131072, True, "small"),
]

SHAPES = {
    # the bench.py llama-650M shape (docs/perf_tpu.md)
    "bench": dict(num_layers=10, hidden_size=2048, num_attention_heads=16,
                  ffn_hidden_size=5632),
    # d=128 kept (MXU alignment), small body for memory-edge trials
    "small": dict(num_layers=4, hidden_size=1024, num_attention_heads=8,
                  ffn_hidden_size=2816),
}


def run_trial(label: str, seq: int, mb: int, fused: bool,
              vocab: int = 32000, fused_ce: bool = False,
              shape: str = "bench") -> dict:
    # off-GCP the metadata server 403s and libtpu retries each variable
    # 30x with backoff before the topology init can proceed — skip it
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "true")
    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies

    import megatron_llm_tpu.ops.pallas.flash_attention as fa
    from megatron_llm_tpu.config import ParallelConfig, TrainConfig
    from megatron_llm_tpu.models.llama import LlamaModel, llama_config
    from megatron_llm_tpu.optimizer import MegatronOptimizer
    from megatron_llm_tpu.training import build_train_step

    fa.FUSED_BACKWARD = fused

    # smallest expressible v5e topology is one 2x2 host; the program is
    # compiled single-device on its first chip (no collectives), so the
    # memory/cost analysis is the 1-chip bench-config story
    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x2")
    dev = topo.devices[0]

    cfg = llama_config(
        "tiny", **SHAPES[shape], padded_vocab_size=vocab, seq_length=seq,
        max_position_embeddings=seq, params_dtype="bf16",
        compute_dtype="bf16", recompute_granularity="selective",
        use_flash_attn=True, use_fused_rmsnorm=True,
        fused_lm_cross_entropy=fused_ce)
    model = LlamaModel(cfg)
    params_shape = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    n_params = sum(int(x.size)
                   for x in jax.tree_util.tree_leaves(params_shape))

    tc = TrainConfig(micro_batch_size=mb, global_batch_size=mb,
                     train_iters=0, lr=1e-4, optimizer="adam", bf16=True,
                     clip_grad=1.0)
    opt = MegatronOptimizer(tc, params_dtype=jnp.bfloat16)
    opt_shape = jax.eval_shape(opt.init, params_shape)
    step = build_train_step(model, opt, ParallelConfig(), 1)

    batch = {
        "tokens": jax.ShapeDtypeStruct((1, mb, seq), jnp.int32),
        "labels": jax.ShapeDtypeStruct((1, mb, seq), jnp.int32),
        "loss_mask": jax.ShapeDtypeStruct((1, mb, seq), jnp.float32),
    }
    print(f"[{label}] lowering ({n_params/1e6:.0f}M params, "
          f"{dev.device_kind})...", file=sys.stderr, flush=True)
    # donate params/opt_state like the real bench jit (build_train_step's
    # inner donation doesn't survive the outer device-pinning jit), so
    # memory_analysis aliases them instead of double-counting
    lowered = jax.jit(step, device=dev, donate_argnums=(0, 1)).lower(
        params_shape, opt_shape, batch,
        jax.ShapeDtypeStruct((2,), jnp.uint32),
        jax.ShapeDtypeStruct((), jnp.float32),
        jax.ShapeDtypeStruct((), jnp.float32))
    print(f"[{label}] compiling...", file=sys.stderr, flush=True)
    compiled = lowered.compile()

    ma = compiled.memory_analysis()
    mem = {
        "temp_gb": round(int(ma.temp_size_in_bytes) / GB, 3),
        "total_gb": round(
            (int(ma.argument_size_in_bytes) + int(ma.output_size_in_bytes)
             + int(ma.temp_size_in_bytes) - int(ma.alias_size_in_bytes))
            / GB, 3),
    }
    cost = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        for k in ("flops", "bytes accessed", "transcendentals"):
            if k in ca:
                cost[k.replace(" ", "_")] = float(ca[k])
    except Exception as e:
        cost = {"error": str(e)[:100]}

    ops = {}
    try:
        txt = compiled.as_text()
        ops = {
            "custom_calls": txt.count(" custom-call("),
            "fusions": txt.count(" fusion("),
            "while_loops": txt.count(" while("),
        }
    except Exception as e:
        ops = {"error": str(e)[:100]}

    rec = {"trial": label, "seq": seq, "micro_batch": mb, "fused": fused,
           "vocab": vocab, "fused_ce": fused_ce,
           "memory": mem, "cost": cost, "hlo_ops": ops}
    print(json.dumps(rec), flush=True)
    return rec


def main(argv):
    if argv and argv[0] == "--child":
        label = argv[1]
        t = next(t for t in TRIALS if t[0] == label)
        run_trial(*t)
        return 0

    wanted = [t for t in TRIALS if not argv or t[0] in argv]
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("JAX_PLATFORM_NAME", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("TPU_WORKER_HOSTNAMES", "localhost")
    env["TPU_ACCELERATOR_TYPE"] = "v5litepod-4"
    # AOT children lower for a TPU topology with a CPU default backend;
    # without this the kernels silently compile as their XLA fallbacks
    env["MLT_FORCE_PALLAS"] = "1"
    rc = 0
    rows = []
    for t in wanted:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", t[0]],
            env=env, cwd=REPO, capture_output=True, text=True)
        sys.stderr.write(r.stderr[-2000:])
        for line in r.stdout.splitlines():
            if line.startswith("{"):
                rows.append(json.loads(line))
                print(line, flush=True)
        rc |= r.returncode
    if rows:
        print(f"\n{'trial':24} {'temp GB':>8} {'total GB':>9} "
              f"{'GFLOP':>10} {'GB accessed':>12} {'kernels':>8}")
        for r in rows:
            c = r["cost"]
            print(f"{r['trial']:24} {r['memory']['temp_gb']:8.3f} "
                  f"{r['memory']['total_gb']:9.3f} "
                  f"{c.get('flops', 0)/1e9:10.1f} "
                  f"{c.get('bytes_accessed', 0)/GB:12.2f} "
                  f"{r['hlo_ops'].get('custom_calls', -1):8d}")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
