"""Capture an XLA profiler trace of the training step.

Beyond-reference tooling (SURVEY.md §5.1 records that the reference has
"no nsys/profiler integration, no chrome traces"): on TPU the natural
equivalent is `jax.profiler.trace`, which records the device timeline
(MXU occupancy, HBM traffic, per-fusion timing) into an xplane protobuf
that TensorBoard's profile plugin / Perfetto render directly.  This tool
wires it around one jitted train step so "profile, iterate" is one
command:

    python tools/profile_step.py --logdir /tmp/trace           # 650M bench shape
    python tools/profile_step.py --preset tiny --logdir /tmp/t # CI / CPU

Prints the trace directory and the per-step wall times; the trace
contains host + device planes (device plane only on real TPU).

To profile a *real* training run (warm caches, real data, the actual
step cadence) instead of this synthetic one-shot, use the in-loop
capture window: ``--profile --profile_step_start N --profile_step_end M
--profile_dir D`` on finetune.py / pretrain_gpt.py
(megatron_llm_tpu/telemetry.py, docs/guide/observability.md).
"""

import argparse
import glob
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from tools.bench_harness import (BENCH_SHAPE, enable_compile_cache,
                                 make_cfg, build_concrete, make_batch)

import jax

PRESETS = {
    # the on-chip bench shape (docs/perf_tpu.md): ~650M llama
    "bench": dict(**BENCH_SHAPE, seq=2048, mb=4),
    # small enough for CPU / CI
    "tiny": dict(L=2, h=128, heads=4, ffn=352, seq=64, mb=2),
}


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--logdir", required=True,
                    help="directory for the xplane trace (created)")
    ap.add_argument("--preset", choices=sorted(PRESETS), default="bench")
    ap.add_argument("--steps", type=int, default=3,
                    help="traced steps (after 2 untraced warmup steps)")
    ap.add_argument("--seq", type=int, help="override preset seq length")
    ap.add_argument("--micro_batch", type=int, help="override preset mb")
    args = ap.parse_args()

    enable_compile_cache()

    p = dict(PRESETS[args.preset])
    mb = args.micro_batch or p.pop("mb")
    p.pop("mb", None)
    if args.seq:
        p["seq"] = args.seq
    seq = p["seq"]
    vocab = 32000 if args.preset == "bench" else 512
    on_tpu = jax.default_backend() == "tpu"

    cfg = make_cfg(vocab=vocab, flash=on_tpu, fused_rms=on_tpu, **p)
    model, params, opt, opt_state, step = build_concrete(cfg, mb)
    batch = make_batch(mb, seq, vocab)
    key = jax.random.PRNGKey(1)

    print(f"profile_step: preset={args.preset} seq={seq} mb={mb} "
          f"backend={jax.default_backend()}", flush=True)
    for i in range(2):  # compile + warmup, untraced
        params, opt_state, m = step(params, opt_state, batch, key, 1e-4, 0.0)
        float(m["lm loss"])
    print("profile_step: warmup done, tracing", flush=True)

    os.makedirs(args.logdir, exist_ok=True)
    preexisting = set(glob.glob(
        os.path.join(args.logdir, "**", "*.xplane.pb"), recursive=True))
    with jax.profiler.trace(args.logdir):
        for i in range(args.steps):
            t0 = time.perf_counter()
            params, opt_state, m = step(params, opt_state, batch, key,
                                        1e-4, 0.0)
            float(m["lm loss"])  # host sync inside the trace window
            print(f"profile_step: step {i}: "
                  f"{(time.perf_counter() - t0) * 1000:.1f} ms", flush=True)

    # only accept a trace written by THIS run — a reused logdir keeps
    # older timestamped session dirs around (set difference, not mtime:
    # coarse mtime granularity could reject a just-written file)
    planes = sorted(set(glob.glob(
        os.path.join(args.logdir, "**", "*.xplane.pb"), recursive=True))
        - preexisting)
    if not planes:
        print("profile_step: ERROR no fresh .xplane.pb written", flush=True)
        sys.exit(1)
    print(f"profile_step: trace written: {planes[0]}", flush=True)
    print("profile_step: view with: tensorboard --logdir "
          f"{args.logdir}  (profile plugin), or convert to perfetto",
          flush=True)


if __name__ == "__main__":
    main()
