#!/usr/bin/env python
"""Run the multi-replica serving router.

Start N engine replicas (one per chip/host) with
``tools/run_text_generation_server.py``, then put this front-end over
them:

    python tools/run_text_generation_server.py ... --port 5000 &
    python tools/run_text_generation_server.py ... --port 5001 &
    python tools/serve_router.py --backends localhost:5000,localhost:5001

Clients (and ``tools/serve_bench.py``) point at the router exactly as
they would a single server: PUT /api, PUT /api/stream, GET /health,
GET /metrics (JSON or Prometheus).  See docs/guide/serving.md,
"Running a replica fleet".

Routers are stateless and shard-nothing: run several of them over the
same replicas (give each the others via ``--peers``, or let
``tools/serve_fleet.py --routers N`` manage the tier) and they agree on
prefix affinity through rendezvous hashing alone.  ``--dynamic`` starts
with zero backends for supervisor-managed membership (POST
/admin/backends).  See docs/guide/serving.md, "Sharded front door".
"""

import argparse
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--backends", default="",
                   help="comma-separated replica addresses "
                        "(host:port[,host:port...])")
    p.add_argument("--peers", default="",
                   help="comma-separated sibling-router addresses; any "
                        "router then answers fleet-wide /metrics by "
                        "merging its peers' histograms")
    p.add_argument("--router_id", default=None,
                   help="stable id stamped into /metrics and fleet "
                        "events (default: random)")
    p.add_argument("--dynamic", action="store_true",
                   help="allow starting with zero backends; membership "
                        "arrives via POST /admin/backends (the "
                        "serve_fleet supervisor does this)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--fail_threshold", type=int, default=3,
                   help="consecutive transport failures before a replica "
                        "is circuit-broken")
    p.add_argument("--breaker_backoff_secs", "--cooldown_secs",
                   dest="breaker_backoff_secs", type=float, default=1.0,
                   help="initial breaker cooldown (doubles per trip)")
    p.add_argument("--max_cooldown_secs", type=float, default=30.0)
    p.add_argument("--probe_interval_secs", "--health_interval_secs",
                   dest="probe_interval_secs", type=float, default=2.0,
                   help="background /health probe period")
    p.add_argument("--affinity_chars", type=int, default=256,
                   help="prompt prefix length keying session affinity")
    p.add_argument("--affinity_max", type=int, default=4096,
                   help="max tracked affinity entries (LRU beyond)")
    p.add_argument("--request_timeout_secs", type=float, default=600.0)
    p.add_argument("--trace_dir", default=None,
                   help="record router-side Chrome spans (route_request, "
                        "route_stream, failover) keyed by X-Request-Trace "
                        "ids; merge with replica traces via "
                        "tools/trace_report.py --merge")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    from megatron_llm_tpu.serving.router import ReplicaRouter, RouterServer

    # the router module itself stays stdlib-pure; span recording is an
    # opt-in that pulls in the tracing machinery only when requested
    tracer = None
    if args.trace_dir:
        from megatron_llm_tpu.tracing import (SpanTracer, Tracing,
                                              start_trace_flusher)
        tracer = SpanTracer()
        start_trace_flusher(Tracing(tracer=tracer,
                                    trace_dir=args.trace_dir))

    # whitespace-only entries ("a:1,, b:2 ,") are stripped, not passed
    # through as malformed URLs
    backends = [u.strip() for u in args.backends.split(",") if u.strip()]
    if not backends and not args.dynamic:
        print("serve_router: --backends needs at least one replica "
              "address; pass --dynamic for supervisor-managed "
              "membership, or use tools/serve_fleet.py",
              file=sys.stderr)
        raise SystemExit(2)
    router = ReplicaRouter(
        backends,
        fail_threshold=args.fail_threshold,
        cooldown_secs=args.breaker_backoff_secs,
        max_cooldown_secs=args.max_cooldown_secs,
        affinity_chars=args.affinity_chars,
        affinity_max=args.affinity_max,
        health_interval_secs=args.probe_interval_secs,
        request_timeout_secs=args.request_timeout_secs,
        tracer=tracer,
        router_id=args.router_id,
    )
    peers = [u.strip() for u in args.peers.split(",") if u.strip()]
    if peers:
        router.set_peers(peers)
    server = RouterServer(router)

    # deterministic teardown: stop the health prober, then break
    # serve_forever (today the probe thread dies whenever the process
    # does — SIGTERM should be a clean exit, not a daemon-thread race)
    signal.signal(signal.SIGTERM, lambda *_: server.shutdown())
    server.run(host=args.host, port=args.port)


if __name__ == "__main__":
    sys.exit(main())
