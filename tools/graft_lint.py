#!/usr/bin/env python
"""graft-lint: repo-native static analysis (stdlib-only).

Runs the AST checkers in ``megatron_llm_tpu/analysis/`` over the repo
and exits non-zero on any violation not suppressed by the checked-in
baseline (``.graftlint.json`` — every suppression must carry a one-line
justification).  Green at HEAD by construction; new violations ratchet.

    python tools/graft_lint.py                     # all checkers
    python tools/graft_lint.py --checkers locks,flags
    python tools/graft_lint.py --list              # checker catalogue
    python tools/graft_lint.py --record-schema     # after a schema bump
    python tools/graft_lint.py --threads           # thread topology table
    python tools/graft_lint.py --suggest-locks     # TH001 -> annotations
    python tools/graft_lint.py --changed-only main # report changed files

Checkers: recompile (host-sync/retrace hazards reachable from
jax.jit/shard_map), flags (arguments.py wiring + dead config fields),
telemetry (request_done/JSON_SCHEMA_KEYS/golden-test agreement +
version-bump ratchet), stdlib (stdlib-only gate for tools/), locks
(serving lock discipline), threads (thread-topology races/deadlocks),
markers (pytest marker registration).
See docs/guide/static_analysis.md.
"""

import argparse
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_llm_tpu.analysis import CHECKERS, run_checkers
from megatron_llm_tpu.analysis.core import (
    BASELINE_FILENAME, Baseline, BaselineError, Repo,
)
from megatron_llm_tpu.analysis import telemetry_schema


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--root", default=None,
                   help="repo root (default: this file's parent repo)")
    p.add_argument("--checkers", default=None,
                   help="comma-separated subset (default: all): "
                        + ",".join(CHECKERS))
    p.add_argument("--baseline", default=None,
                   help=f"suppression file (default: <root>/"
                        f"{BASELINE_FILENAME})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report everything "
                        "(ratchet review mode)")
    p.add_argument("--list", action="store_true",
                   help="list checkers and exit")
    p.add_argument("--record-schema", action="store_true",
                   help="re-record the telemetry (version, keys) "
                        "snapshot into the baseline after a conscious "
                        "TELEMETRY_SCHEMA_VERSION bump, then lint")
    p.add_argument("--threads", action="store_true",
                   help="print the discovered thread topology table "
                        "and exit (docs/guide/serving.md embeds it)")
    p.add_argument("--suggest-locks", action="store_true",
                   help="print ready-to-paste _lock_protected_ "
                        "annotations for every TH001 finding "
                        "(baseline ignored) and exit")
    p.add_argument("--changed-only", metavar="REF", default=None,
                   help="only REPORT violations in files changed vs "
                        "the given git ref (checkers still analyze "
                        "the whole repo — cross-file topology needs "
                        "it); suppressed/stale accounting unchanged")
    p.add_argument("--expect-checkers", type=int, default=None,
                   metavar="N",
                   help="exit 2 unless at least N checkers ran "
                        "(sweep guard against a silently-narrowed "
                        "checker set)")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="violations only, no summary")
    return p.parse_args(argv)


def _changed_files(root: str, ref: str):
    """Repo-relative paths changed vs ``ref`` (committed + worktree).
    Returns None (= report everything) when git is unavailable."""
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", ref, "--"],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    return {ln.strip().replace(os.sep, "/")
            for ln in out.stdout.splitlines() if ln.strip()}


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.list:
        for name, fn in CHECKERS.items():
            doc = (fn.__module__ or "").rsplit(".", 1)[-1]
            head = (sys.modules[fn.__module__].__doc__ or doc)
            print(f"{name:10s} {head.strip().splitlines()[0]}")
        return 0

    root = args.root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    repo = Repo(root)
    baseline_path = args.baseline or os.path.join(root, BASELINE_FILENAME)
    try:
        baseline = Baseline.load(baseline_path)
    except BaselineError as e:
        print(f"graft-lint: baseline error: {e}", file=sys.stderr)
        return 2

    if args.threads:
        from megatron_llm_tpu.analysis import threads as threads_mod
        print(threads_mod.threads_table(repo))
        return 0

    if args.suggest_locks:
        from megatron_llm_tpu.analysis import threads as threads_mod
        print(threads_mod.suggest_locks(repo))
        return 0

    if args.record_schema:
        snap = telemetry_schema.record_snapshot(repo, baseline)
        baseline.save(baseline_path)
        print(f"recorded telemetry schema snapshot: version "
              f"{snap['version']}, {len(snap['request_done_keys'])} "
              f"request_done keys -> {baseline_path}")

    if args.no_baseline:
        baseline = Baseline(telemetry_schema=baseline.telemetry_schema)

    names = args.checkers.split(",") if args.checkers else None
    try:
        unsuppressed, suppressed, stale = run_checkers(
            repo, baseline, names)
    except ValueError as e:
        print(f"graft-lint: {e}", file=sys.stderr)
        return 2

    ran = len(names) if names else len(CHECKERS)
    if args.expect_checkers is not None and ran < args.expect_checkers:
        print(f"graft-lint: only {ran} checker(s) ran, expected "
              f">= {args.expect_checkers}", file=sys.stderr)
        return 2

    if args.changed_only:
        changed = _changed_files(root, args.changed_only)
        if changed is None:
            print(f"graft-lint: cannot diff against "
                  f"{args.changed_only!r}; reporting everything",
                  file=sys.stderr)
        else:
            unsuppressed = [v for v in unsuppressed if v.path in changed]

    for v in repo.parse_errors:
        print(v.render())
    for v in unsuppressed:
        print(v.render())
    if not args.quiet:
        for fp in stale:
            print(f"note: stale suppression (matched nothing): {fp}")
        n = len(unsuppressed) + len(repo.parse_errors)
        scope = ",".join(names) if names else "all checkers"
        print(f"graft-lint: {n} violation(s), {len(suppressed)} "
              f"suppressed, {len(stale)} stale suppression(s) "
              f"[{scope}; {ran} checker(s) ran]")
    return 1 if (unsuppressed or repo.parse_errors) else 0


if __name__ == "__main__":
    sys.exit(main())
