#!/usr/bin/env python3
"""serve_top: live terminal flight deck for a serving fleet.

Polls one endpoint's ``GET /metrics`` — point it at any router of a
sharded front door for the peer-merged fleet view, or directly at a
single replica — and renders a refreshing per-replica table:
occupancy, tokens/sec, TTFT/TPOT p95, prefix-cache hit rate (lifetime
and frame-windowed), the ghost x10 projected hit rate and evictions/sec
from the cache observatory (serving/cache_observatory.py), the
windowed host-tier hit rate and device->host spills/sec from the
hierarchical KV cache (serving/host_cache.py), the engine-loop ``host
bubble %`` (serving/loop_profiler.py), engine restarts, router
brownout state, and ALERT badges from the SLO sentinel
(serving/alerts.py): per-replica firing rules in the table, the
fleet-wide union (replica-merged + supervisor fleet scope) in the
header line.

Stdlib only (no jax, no requests): runs on a laptop against a tunnel,
like serve_bench / serve_report.

    python tools/serve_top.py --url http://localhost:8000
    python tools/serve_top.py --url http://localhost:8000 --once --json

``--once`` prints a single snapshot and exits (with ``--json``, one
machine-readable object — what the tests consume).  Tokens/sec needs
two polls, so it is null on the first frame and in ``--once`` mode.
"""

import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def fetch_metrics(url: str, timeout: float) -> dict:
    req = urllib.request.Request(
        url.rstrip("/") + "/metrics",
        headers={"Accept": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8", "replace"))


def _hist_pct(snap, q):
    """Percentile from a Histogram.snapshot() shape (linear
    interpolation in the winning bucket — the telemetry.py estimator,
    re-implemented here so this tool stays stdlib-only)."""
    if not (isinstance(snap, dict) and isinstance(snap.get("buckets"), dict)):
        return None
    total = snap.get("count") or 0
    if total <= 0:
        return None
    items = []
    for k, v in snap["buckets"].items():
        bound = float("inf") if k in ("+Inf", "inf") else float(k)
        items.append((bound, int(v)))
    items.sort()
    target = max(min(float(q), 1.0), 0.0) * total
    cum, lo = 0, 0.0
    for bound, c in items:
        if c > 0 and cum + c >= target:
            if bound == float("inf"):
                return lo
            frac = (target - cum) / c if c else 1.0
            return lo + (bound - lo) * max(min(frac, 1.0), 0.0)
        cum += c
        if bound != float("inf"):
            lo = bound
    return lo


def _num(d, *path):
    """Nested numeric lookup; None on any missing/non-numeric hop."""
    cur = d
    for p in path:
        if not isinstance(cur, dict):
            return None
        cur = cur.get(p)
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return cur


def _replica_row(name: str, url, snap) -> dict:
    """One table row from a replica's ServerMetrics snapshot (None when
    the router could not reach it this probe)."""
    row = {
        "name": name,
        "url": url,
        "alive": snap is not None,
        "requests": None, "tokens_generated": None,
        "tokens_per_sec": None,
        "occupancy": None, "queue_depth": None,
        "ttft_p95_secs": None, "tpot_p95_secs": None,
        "cache_hit_rate": None,
        "cache_probes": None, "cache_hits": None,
        "cache_hit_rate_window": None,
        "cache_evictions": None, "evictions_per_sec": None,
        "ghost_x10_hit_rate": None,
        "cache_host_hits": None, "host_spills": None,
        "host_hit_rate_window": None, "host_spills_per_sec": None,
        "device_busy_pct": None, "host_bubble_pct": None,
        "loop_stalls": None, "engine_restarts": None,
        "draining": False,
        "alerts_firing": None, "alert_rules": [],
    }
    if snap is None:
        return row
    ab = snap.get("alerts")
    if isinstance(ab, dict) and isinstance(ab.get("firing"), list):
        rules = [f.get("rule") for f in ab["firing"]
                 if isinstance(f, dict) and f.get("rule")]
        row["alerts_firing"] = len(rules)
        row["alert_rules"] = rules
    row["requests"] = _num(snap, "requests")
    row["tokens_generated"] = _num(snap, "tokens_generated")
    row["ttft_p95_secs"] = (
        _num(snap, "slo", "ttft_secs_p95")
        if _num(snap, "slo", "ttft_secs_p95") is not None
        else _hist_pct((snap.get("histograms") or {}).get("ttft_secs"),
                       0.95))
    row["tpot_p95_secs"] = (
        _num(snap, "slo", "tpot_secs_p95")
        if _num(snap, "slo", "tpot_secs_p95") is not None
        else _hist_pct((snap.get("histograms") or {}).get("tpot_secs"),
                       0.95))
    eng = snap.get("engine")
    if isinstance(eng, dict):
        row["occupancy"] = _num(eng, "mean_batch_occupancy")
        row["queue_depth"] = _num(eng, "queue_depth")
        hits = _num(eng, "prefix_cache_hits") or 0
        misses = _num(eng, "prefix_cache_misses") or 0
        if hits + misses > 0:
            row["cache_hit_rate"] = round(hits / (hits + misses), 4)
        row["device_busy_pct"] = _num(eng, "loop", "device_busy_pct")
        row["host_bubble_pct"] = _num(eng, "loop", "host_bubble_pct")
        row["loop_stalls"] = _num(eng, "loop", "stalls")
        row["engine_restarts"] = _num(eng, "engine_restarts")
        # cache observatory block (serving/cache_observatory.py):
        # cumulative counters here; the windowed rates come from frame
        # deltas in add_rates
        row["cache_probes"] = _num(eng, "cache", "probes")
        row["cache_hits"] = _num(eng, "cache", "hits")
        ec = _num(eng, "cache", "evictions_capacity")
        eh = _num(eng, "cache", "evictions_churn")
        if ec is not None or eh is not None:
            row["cache_evictions"] = (ec or 0) + (eh or 0)
        row["ghost_x10_hit_rate"] = _num(eng, "cache", "ghost", "x10",
                                         "hit_rate")
        # hierarchical KV cache: host-tier rescues out of the two-tier
        # hit attribution, device->host spills from the tier itself
        row["cache_host_hits"] = _num(eng, "cache", "host_hits")
        row["host_spills"] = _num(eng, "cache", "host",
                                  "spills_completed")
    return row


def build_snapshot(url: str, metrics: dict) -> dict:
    """Reduce one /metrics document (router fleet view or a bare
    replica snapshot) to the flight-deck schema."""
    out = {
        "time_unix": time.time(),
        "url": url,
        "source": "router" if "router" in metrics else "replica",
        "router": None,
        "router_tier": None,
        "replicas": [],
    }
    if out["source"] == "router":
        rsnap = metrics.get("router") or {}
        out["router"] = {
            "router_id": rsnap.get("router_id"),
            "backends_total": _num(rsnap, "backends_total"),
            "backends_alive": _num(rsnap, "backends_alive"),
            "requests_total": _num(rsnap, "requests_total"),
            "failovers_total": _num(rsnap, "failovers_total"),
            "inflight_requests": _num(rsnap, "inflight_requests"),
            "brownout_active": bool(rsnap.get("brownout_active")),
            "brownout_remaining_secs": _num(
                rsnap, "brownout_remaining_secs"),
        }
        tier = metrics.get("router_tier")
        if isinstance(tier, dict):
            out["router_tier"] = {
                "routers_total": _num(tier, "routers_total"),
                "routers_reporting": _num(tier, "routers_reporting"),
            }
        meta = rsnap.get("backends") or {}
        snaps = metrics.get("backends") or {}
        for name in sorted(set(meta) | set(snaps),
                           key=lambda n: (len(n), n)):
            m = meta.get(name) or {}
            row = _replica_row(name, m.get("url"), snaps.get(name))
            if m.get("draining"):
                row["draining"] = True
            if not m.get("alive", 1):
                row["alive"] = False
            out["replicas"].append(row)
    else:
        out["replicas"].append(_replica_row("replica_0", url, metrics))
    # alert rollup (serving/alerts.py): replica alerts fleet-merged by
    # the router under aggregate.alerts, the supervisor's own fleet-scope
    # engine under router.fleet.alerts; a bare replica carries its block
    # at top level.  The ALERT badge unions all of them.
    firing = []
    blocks = []
    if out["source"] == "router":
        agg = metrics.get("aggregate")
        if isinstance(agg, dict):
            blocks.append(agg.get("alerts"))
        fl = (metrics.get("router") or {}).get("fleet")
        if isinstance(fl, dict):
            blocks.append(fl.get("alerts"))
    else:
        blocks.append(metrics.get("alerts"))
    for ab in blocks:
        if isinstance(ab, dict) and isinstance(ab.get("firing"), list):
            for f in ab["firing"]:
                if isinstance(f, dict) and f.get("rule"):
                    firing.append({"rule": f.get("rule"),
                                   "scope": f.get("scope"),
                                   "severity": f.get("severity")})
    out["alerts"] = {"firing": firing, "firing_count": len(firing)}
    alive = [r for r in out["replicas"] if r["alive"]]
    out["fleet"] = {
        "replicas_total": len(out["replicas"]),
        "replicas_alive": len(alive),
        "requests": sum(r["requests"] or 0 for r in alive),
        "tokens_generated": sum(r["tokens_generated"] or 0 for r in alive),
        "tokens_per_sec": None,
    }
    return out


def add_rates(snapshot: dict, prev: dict) -> None:
    """Fill per-replica and fleet tokens/sec from the previous frame's
    (time, tokens) pairs; mutates ``snapshot`` in place."""
    if not prev:
        return
    dt = snapshot["time_unix"] - prev.get("time_unix", 0)
    if dt <= 0:
        return
    prev_rows = {r["name"]: r for r in prev.get("replicas", [])}
    fleet_rate = 0.0
    any_rate = False
    for row in snapshot["replicas"]:
        p = prev_rows.get(row["name"])
        if (p is None or row["tokens_generated"] is None
                or p.get("tokens_generated") is None):
            continue
        rate = max(row["tokens_generated"] - p["tokens_generated"], 0) / dt
        row["tokens_per_sec"] = round(rate, 2)
        fleet_rate += rate
        any_rate = True
    for row in snapshot["replicas"]:
        p = prev_rows.get(row["name"])
        if p is None:
            continue
        # windowed cache hit rate: hits/probes over this frame only
        if (row["cache_probes"] is not None
                and p.get("cache_probes") is not None):
            dp = row["cache_probes"] - p["cache_probes"]
            dh = (row["cache_hits"] or 0) - (p.get("cache_hits") or 0)
            if dp > 0:
                row["cache_hit_rate_window"] = round(
                    max(min(dh / dp, 1.0), 0.0), 4)
        if (row["cache_evictions"] is not None
                and p.get("cache_evictions") is not None):
            row["evictions_per_sec"] = round(
                max(row["cache_evictions"] - p["cache_evictions"], 0) / dt,
                2)
        # windowed host-tier hit rate: host-rescued blocks / probes
        # over this frame only (lifetime counters mask regressions)
        if (row["cache_probes"] is not None
                and p.get("cache_probes") is not None
                and row["cache_host_hits"] is not None
                and p.get("cache_host_hits") is not None):
            dp = row["cache_probes"] - p["cache_probes"]
            dh = row["cache_host_hits"] - p["cache_host_hits"]
            if dp > 0:
                row["host_hit_rate_window"] = round(
                    max(min(dh / dp, 1.0), 0.0), 4)
        if (row["host_spills"] is not None
                and p.get("host_spills") is not None):
            row["host_spills_per_sec"] = round(
                max(row["host_spills"] - p["host_spills"], 0) / dt, 2)
    if any_rate:
        snapshot["fleet"]["tokens_per_sec"] = round(fleet_rate, 2)


def _fmt(v, spec="", dash="-"):
    if v is None:
        return dash
    try:
        return format(v, spec)
    except (TypeError, ValueError):
        return str(v)


COLUMNS = (
    # header, width, row key, format spec
    ("replica", 12, "name", ""),
    ("up", 4, None, ""),
    ("occ", 6, "occupancy", ".2f"),
    ("queue", 6, "queue_depth", "d"),
    ("tok/s", 9, "tokens_per_sec", ".1f"),
    ("ttft_p95", 9, "ttft_p95_secs", ".3f"),
    ("tpot_p95", 9, "tpot_p95_secs", ".4f"),
    ("hit%", 7, None, ""),
    ("whit%", 7, None, ""),
    ("g10%", 6, None, ""),
    ("hhit%", 7, None, ""),
    ("ev/s", 6, "evictions_per_sec", ".1f"),
    ("sp/s", 6, "host_spills_per_sec", ".1f"),
    ("bubble%", 8, "host_bubble_pct", ".1f"),
    ("stalls", 7, "loop_stalls", "d"),
    ("restarts", 8, "engine_restarts", "d"),
    ("alerts", 16, None, ""),
)


def render(snapshot: dict) -> str:
    lines = []
    r = snapshot.get("router")
    tier = snapshot.get("router_tier")
    fleet = snapshot["fleet"]
    head = (f"serve_top  {snapshot['url']}  "
            f"replicas {fleet['replicas_alive']}/{fleet['replicas_total']}")
    if tier:
        head += (f"  routers {_fmt(tier['routers_reporting'])}"
                 f"/{_fmt(tier['routers_total'])}")
    if r:
        head += f"  inflight {_fmt(r['inflight_requests'])}"
        if r["brownout_active"]:
            head += (f"  BROWNOUT "
                     f"({_fmt(r['brownout_remaining_secs'], '.1f')}s)")
    al = snapshot.get("alerts") or {}
    if al.get("firing_count"):
        rules = sorted({f["rule"] for f in al["firing"]})
        head += (f"  ALERT[{al['firing_count']}] "
                 + ",".join(rules[:4])
                 + ("…" if len(rules) > 4 else ""))
    head += (f"  fleet {_fmt(fleet['tokens_per_sec'], '.1f')} tok/s"
             f"  {time.strftime('%H:%M:%S')}")
    lines.append(head)
    lines.append("")
    lines.append("  ".join(h.ljust(w) for h, w, _, _ in COLUMNS))
    for row in snapshot["replicas"]:
        cells = []
        for h, w, key, spec in COLUMNS:
            if h == "up":
                v = ("DRAIN" if row["draining"]
                     else "up" if row["alive"] else "DOWN")
            elif h == "alerts":
                v = (",".join(row["alert_rules"])[:15]
                     if row["alert_rules"] else "-")
            elif h in ("hit%", "whit%", "g10%", "hhit%"):
                hr = row[{"hit%": "cache_hit_rate",
                          "whit%": "cache_hit_rate_window",
                          "g10%": "ghost_x10_hit_rate",
                          "hhit%": "host_hit_rate_window"}[h]]
                v = _fmt(100.0 * hr, ".1f") if hr is not None else "-"
            else:
                v = _fmt(row.get(key), spec)
            cells.append(str(v).ljust(w))
        lines.append("  ".join(cells))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="live terminal dashboard over a serving fleet's "
                    "/metrics (router or single replica)")
    ap.add_argument("--url", required=True,
                    help="router (fleet view) or replica base URL")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds")
    ap.add_argument("--once", action="store_true",
                    help="one frame, then exit")
    ap.add_argument("--json", action="store_true",
                    help="emit the snapshot as JSON instead of a table")
    ap.add_argument("--timeout", type=float, default=5.0,
                    help="per-poll HTTP timeout")
    args = ap.parse_args(argv)

    prev = {}
    while True:
        try:
            metrics = fetch_metrics(args.url, args.timeout)
        except (OSError, urllib.error.URLError, ValueError) as e:
            print(f"serve_top: cannot fetch {args.url}/metrics: {e}",
                  file=sys.stderr)
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        snap = build_snapshot(args.url, metrics)
        add_rates(snap, prev)
        prev = snap
        if args.json:
            print(json.dumps(snap))
        else:
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")     # clear + home
            print(render(snap))
        sys.stdout.flush()
        if args.once:
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
