#!/bin/bash
# Tunnel watchdog: probe the axon TPU tunnel on a short cycle and run the
# remaining on-chip playbook steps (docs/perf_tpu.md) the moment it answers.
# Each step runs under `timeout` so a mid-run tunnel stall kills the step,
# not the watchdog; partial sweep rows still land in the logs.  A step is
# retried on the next tunnel window until it exits 0 (max 4 attempts, then
# it is marked .gaveup — visibly distinct from .done).
#
# Usage: nohup bash tools/tpu_hunt.sh >/tmp/tpu_hunt.log 2>&1 &
set -u
cd "$(dirname "$0")/.."

# Single instance only: concurrent watchdogs mean concurrent jax clients
# against a tunnel that serializes them (see probe() comment).
exec 9>/tmp/tpu_hunt.lock
flock -n 9 || { echo "[hunt] another instance holds /tmp/tpu_hunt.lock; exiting"; exit 1; }

MARKS=/tmp/tpu_hunt_marks
mkdir -p "$MARKS"
# A fresh launch retries exhausted steps but honors completed ones; say so
# out loud instead of skipping silently.
rm -f "$MARKS"/*.attempts "$MARKS"/*.gaveup
for f in "$MARKS"/*.done; do
  [ -e "$f" ] && echo "[hunt] startup: $(basename "$f" .done) already done (stale marker honored; rm $f to re-run)"
done
DEADLINE=$(( $(date +%s) + 36000 ))   # give up after 10h

# One list of steps, used by the run loop, all_settled, and the final
# status report alike.  Timeouts are generous per-group compile budgets.
# First wave = the VERDICT playbook must-haves; second wave = gravy
# measurements (MoE dispatch overhead, long-seq + xla comparison,
# decode throughput) that
# only run once every first-wave step has settled.
STEPS=(fusedbwd seq4096 bigvocab bench_final moe long decode optstate)
step_cmd() {
  case $1 in
    fusedbwd)    echo "python tools/mfu_sweep.py fusedbwd" ;;
    seq4096)     echo "python tools/mfu_sweep.py seq4096" ;;
    bigvocab)    echo "python tools/mfu_sweep.py bigvocab" ;;
    bench_final) echo "python bench.py" ;;
    moe)         echo "python tools/mfu_sweep.py moe" ;;
    long)        echo "python tools/mfu_sweep.py long" ;;
    decode)      echo "python tools/decode_bench.py" ;;
    optstate)    echo "python tools/mfu_sweep.py optstate" ;;
  esac
}
step_tmo() {
  case $1 in
    fusedbwd) echo 1500 ;; seq4096) echo 1800 ;;
    bigvocab) echo 2100 ;; bench_final) echo 900 ;;
    moe) echo 1200 ;; long) echo 1500 ;; decode) echo 1200 ;;
    optstate) echo 1200 ;;
  esac
}

# 150 s probe: when the tunnel is up, init takes seconds (0.1 s in the
# 03:45 window); when it is down, init hangs forever, so the timeout just
# sets the down-cycle length.  CAUTION (verify skill): the tunnel
# serializes clients and a KILLED client wedges it for several minutes —
# which is exactly what a timed-out probe is.  The 300 s down-sleep keeps
# killed probes ≥7.5 min apart so a wedge can clear between probes; never
# run another jax process concurrently with this watchdog.
# rc 124 (timeout) = tunnel genuinely hung; any other nonzero rc is a fast
# local failure (import error, broken env) that probing harder won't fix —
# surface it and stop instead of reporting "tunnel down" for 10 hours.
probe() {
  timeout 150 python - >/tmp/tpu_probe.log 2>&1 9>&- <<'EOF'
import jax, jax.numpy as jnp
x = jnp.ones((256, 256))
assert jax.devices()[0].platform == "tpu"
float((x @ x).sum())
EOF
  local rc=$?
  if [ "$rc" -ne 0 ] && [ "$rc" -ne 124 ]; then
    echo "[hunt] probe failed fast (rc=$rc) — local error, not a tunnel hang:"
    tail -5 /tmp/tpu_probe.log
    exit 1
  fi
  return "$rc"
}

run_step() {  # name
  local name=$1
  [ -f "$MARKS/$name.done" ] || [ -f "$MARKS/$name.gaveup" ] && return 0
  local att_file="$MARKS/$name.attempts"
  local att=$(( $(cat "$att_file" 2>/dev/null || echo 0) + 1 ))
  echo "$att" > "$att_file"
  if [ "$att" -gt 4 ]; then
    touch "$MARKS/$name.gaveup"
    echo "[hunt $(date +%H:%M:%S)] step $name GAVE UP after 4 attempts"
    return 0
  fi
  echo "[hunt $(date +%H:%M:%S)] step $name attempt $att"
  timeout "$(step_tmo "$name")" bash -c "$(step_cmd "$name")" >> "/tmp/hunt_$name.log" 2>&1 9>&-
  local rc=$?
  if [ "$rc" -eq 0 ]; then
    touch "$MARKS/$name.done"
    echo "[hunt $(date +%H:%M:%S)] step $name DONE"
    return 0
  fi
  echo "[hunt $(date +%H:%M:%S)] step $name failed (rc=$rc$([ "$rc" -eq 124 ] && echo ' = timeout/killed client'))"
  # Backoff before the next probe/attempt: (a) a fast deterministic failure
  # (bad flag, instant OOM) must not burn all 4 attempts inside one window;
  # (b) a timed-out step is a killed client, which wedges the tunnel for
  # several minutes -- give it time to clear before the next probe.
  sleep 180
  return 1
}

all_settled() {
  for s in "${STEPS[@]}"; do
    [ -f "$MARKS/$s.done" ] || [ -f "$MARKS/$s.gaveup" ] || return 1
  done
  return 0
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if all_settled; then break; fi
  if probe; then
    echo "[hunt $(date +%H:%M:%S)] tunnel UP"
    for s in "${STEPS[@]}"; do
      run_step "$s" || continue 2
    done
  else
    echo "[hunt $(date +%H:%M:%S)] tunnel down"
    sleep 300
  fi
done
echo "[hunt] final status:"
for s in "${STEPS[@]}"; do
  if [ -f "$MARKS/$s.done" ]; then st=done
  elif [ -f "$MARKS/$s.gaveup" ]; then st=GAVE-UP
  else st=never-ran; fi
  echo "[hunt]   $s: $st"
done
