#!/bin/bash
# Thin wrapper kept for muscle memory / existing nohup invocations.
# The watchdog logic (tunnel probe, settle marks, retry/backoff policy)
# now lives in the declarative sweep manifest + runner:
#
#     tools/tpu_sweep.py            (see --list / --dry-run)
#
# Usage (unchanged): nohup bash tools/tpu_hunt.sh >/tmp/tpu_hunt.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
exec python tools/tpu_sweep.py run "$@"
