#!/usr/bin/env python
"""Interactive client for the generation server
(reference: tools/text_generation_cli.py)."""

import json
import sys
import urllib.request


def main():
    if len(sys.argv) != 2:
        print("usage: text_generation_cli.py <host:port>")
        sys.exit(1)
    url = f"http://{sys.argv[1]}/api"
    while True:
        try:
            prompt = input("Enter prompt: ")
        except EOFError:
            break
        tokens = input("Enter number of tokens to generate: ")
        req = urllib.request.Request(
            url,
            data=json.dumps({
                "prompts": [prompt],
                "tokens_to_generate": int(tokens),
            }).encode(),
            headers={"Content-Type": "application/json"},
            method="PUT",
        )
        with urllib.request.urlopen(req) as resp:
            out = json.loads(resp.read())
        print("Megatron Response:")
        print(out["text"][0])


if __name__ == "__main__":
    main()
