#!/usr/bin/env python
"""Push an HF-format checkpoint (e.g. produced by
weights_conversion/megatron_to_hf.py) to the Hugging Face Hub.

Reference: ``tools/push_to_hub.py`` — loads the model + tokenizer, applies
optional dtype conversion and RoPE-scaling config overrides, then
``push_to_hub`` (or saves to --output_folder for offline use; this image
has no egress, so the save path is the testable one).
"""

from __future__ import annotations

import argparse


def parse_args():
    p = argparse.ArgumentParser(
        description="Push an HF-format checkpoint to the Hugging Face Hub "
                    "or re-save it with a different dtype / rope scaling.")
    p.add_argument("model_name_or_path")
    p.add_argument("--dtype", default="auto",
                   choices=["auto", "bf16", "fp16", "fp32"])
    p.add_argument("--hf_repo_name", default=None)
    p.add_argument("--auth_token", default=None)
    p.add_argument("--output_folder", default=None)
    p.add_argument("--max_shard_size", default="10GB")
    p.add_argument("--unsafe", action="store_true",
                   help="disable safetensors serialization")
    p.add_argument("--rope_scaling_type", default=None,
                   choices=[None, "linear", "dynamic"])
    p.add_argument("--rope_scaling_factor", type=float, default=None)
    args = p.parse_args()
    # validate before the (potentially multi-hundred-GB) model load
    if args.rope_scaling_type is not None and args.rope_scaling_factor is None:
        p.error("--rope_scaling_type requires --rope_scaling_factor")
    if args.rope_scaling_factor is not None and args.rope_scaling_factor <= 1.0:
        p.error("--rope_scaling_factor must be > 1.0")
    if args.hf_repo_name is None and args.output_folder is None:
        p.error("need --hf_repo_name and/or --output_folder")
    return args


def main():
    args = parse_args()

    import torch
    from transformers import AutoModelForCausalLM, AutoTokenizer

    dtype = {"auto": "auto", "bf16": torch.bfloat16, "fp16": torch.float16,
             "fp32": torch.float32}[args.dtype]
    print(f" > loading {args.model_name_or_path} (dtype={args.dtype})",
          flush=True)
    model = AutoModelForCausalLM.from_pretrained(
        args.model_name_or_path, torch_dtype=dtype)
    tokenizer = AutoTokenizer.from_pretrained(args.model_name_or_path)

    if args.rope_scaling_factor is not None:
        model.config.rope_scaling = {
            "type": args.rope_scaling_type or "linear",
            "factor": args.rope_scaling_factor,
        }
        print(f" > set rope_scaling = {model.config.rope_scaling}",
              flush=True)

    kwargs = dict(max_shard_size=args.max_shard_size,
                  safe_serialization=not args.unsafe)
    if args.output_folder:
        model.save_pretrained(args.output_folder, **kwargs)
        tokenizer.save_pretrained(args.output_folder)
        print(f" > saved to {args.output_folder}", flush=True)
    if args.hf_repo_name:
        model.push_to_hub(args.hf_repo_name, token=args.auth_token, **kwargs)
        tokenizer.push_to_hub(args.hf_repo_name, token=args.auth_token)
        print(f" > pushed to {args.hf_repo_name}", flush=True)


if __name__ == "__main__":
    main()
