"""Shared harness for the perf tools (mfu_sweep, profile_step).

One place for the model/optimizer/train-step/batch construction and the
persistent-compile-cache setup, so the batch contract ([num_micro, mb,
seq] tokens/labels/loss_mask) and TrainConfig defaults cannot drift
between tools.  bench.py deliberately does NOT import this: the driver
artifact must stay self-contained (it is run by an external harness and
has its own deadline/fallback machinery).
"""

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)


def enable_compile_cache():
    """Persistent XLA compile cache under ROOT/.jax_cache (same knobs as
    bench.py), so iterate loops don't pay the full compile each run."""
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(ROOT, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


# the on-chip bench shape (docs/perf_tpu.md): ~650M llama, MXU-aligned
# head_dim 128 — ONE definition shared by bench-shape presets in
# profile_step / decode_bench (mfu_sweep's GROUPS spell shapes out per
# trial because shapes ARE its sweep axes)
BENCH_SHAPE = dict(L=10, h=2048, heads=16, ffn=5632)


def make_cfg(*, L=16, h=1280, heads=16, ffn=3584, seq=2048, vocab=32000,
             remat="selective", flash=True, fused_rms=True, experts=0,
             top_k=2, fused_ce=False):
    """The llama-family config every perf tool measures."""
    from megatron_llm_tpu.models.llama import llama_config
    return llama_config(
        "tiny", num_layers=L, hidden_size=h, num_attention_heads=heads,
        ffn_hidden_size=ffn, padded_vocab_size=vocab, seq_length=seq,
        max_position_embeddings=seq, params_dtype="bf16",
        compute_dtype="bf16", recompute_granularity=remat,
        use_flash_attn=flash, use_fused_rmsnorm=fused_rms,
        num_experts=experts, moe_top_k=top_k,
        fused_lm_cross_entropy=fused_ce)


def build_concrete(cfg, mb, num_micro=1, opt_state_dtype="fp32"):
    """Initialized (model, params, opt, opt_state, step) for one config."""
    import jax
    import jax.numpy as jnp
    from megatron_llm_tpu.config import ParallelConfig, TrainConfig
    from megatron_llm_tpu.models.llama import LlamaModel
    from megatron_llm_tpu.optimizer import MegatronOptimizer
    from megatron_llm_tpu.training import build_train_step
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    tc = TrainConfig(micro_batch_size=mb,
                     global_batch_size=mb * num_micro, train_iters=0,
                     lr=1e-4, optimizer="adam", bf16=True, clip_grad=1.0,
                     optimizer_state_dtype=opt_state_dtype)
    opt = MegatronOptimizer(tc, params_dtype=jnp.bfloat16)
    opt_state = opt.init(params)
    step = build_train_step(model, opt, ParallelConfig(), num_micro)
    return model, params, opt, opt_state, step


def make_batch(mb, seq, vocab, num_micro=1, np_seed=0):
    """Synthetic [num_micro, mb, seq] batch in the train-step layout."""
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.RandomState(np_seed)
    toks = jnp.asarray(rng.randint(0, vocab, (num_micro, mb, seq)))
    return {"tokens": toks, "labels": jnp.roll(toks, -1, -1),
            "loss_mask": jnp.ones_like(toks, jnp.float32)}
