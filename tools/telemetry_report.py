#!/usr/bin/env python
"""Summarize a run's structured telemetry stream (telemetry.jsonl).

Reads the JSONL written by ``--structured_log_dir`` (one record per log
boundary, megatron_llm_tpu/telemetry.py) and prints:

* a per-step table — iteration, loss, grad norm, step time,
  tokens/sec/device, MFU, memory in use
* aggregates — p50/p95 step time, mean/max MFU, mean tokens/sec/device
* a recovery-event timeline — the log boundaries where any recovery
  counter (rewinds, save_retries, watchdog_fires, signal_saves)
  advanced, and by how much
* model-health aggregates when the run carried ``layer_stats`` records
  (schema 3, --log_layer_stats_interval): the worst per-group
  update-to-weight ratio seen and the boundaries where any group had
  non-finite gradients (per-layer breakdown: tools/health_report.py).
  Schema-2 streams simply have no such records; both parse.
* per-slice attribution when the run was multi-slice (schema 4,
  --num_slices > 1): a worst-slice table — per-slice mean/max step
  time, how often each slice was the one the fleet waited on, and the
  cumulative stall seconds it cost (goodput.slice_stall_secs) — plus a
  fleet-event timeline (``elastic_resume`` / ``preempt_rescue`` kinds).
  Single-slice streams simply have no such fields; both parse.

Pure stdlib + JSONL parsing — no jax import, so it runs anywhere the log
file does (laptop, login node) and costs nothing to start.

Usage:
    python tools/telemetry_report.py RUN_DIR_OR_JSONL [--json]

``--json`` emits the aggregates as one machine-readable JSON object
(for CI trend tracking) instead of the human tables.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional


def load_records(path: str) -> List[Dict]:
    """Accept a telemetry.jsonl file or the --structured_log_dir holding
    one.  Unparseable lines are counted and skipped (a crash can truncate
    the final line), never fatal."""
    if os.path.isdir(path):
        path = os.path.join(path, "telemetry.jsonl")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no telemetry stream at {path}")
    records, bad = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if rec.get("kind", "log") == "log":
                records.append(rec)
    if bad:
        print(f"(skipped {bad} unparseable line{'s' if bad > 1 else ''})",
              file=sys.stderr)
    return records


def percentile(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 100]); None on empty input."""
    if not values:
        return None
    s = sorted(values)
    idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[idx]


def _fmt(v, spec: str = ".3g", none: str = "-") -> str:
    return none if v is None else format(v, spec)


def per_step_table(records: List[Dict]) -> str:
    header = (f"{'iter':>8} {'lm loss':>11} {'grad norm':>10} "
              f"{'step ms':>9} {'tok/s/dev':>10} {'MFU':>6} "
              f"{'mem MiB':>8}")
    lines = [header, "-" * len(header)]
    for r in records:
        st = r.get("step_time_secs")
        mem = (r.get("memory") or {}).get("bytes_in_use")
        mfu = r.get("mfu")
        lines.append(
            f"{r.get('iteration', '?'):>8} "
            f"{_fmt(r.get('lm_loss'), '.5e'):>11} "
            f"{_fmt(r.get('grad_norm'), '.3f'):>10} "
            f"{_fmt(st * 1000.0 if st is not None else None, '.1f'):>9} "
            f"{_fmt(r.get('tokens_per_sec_per_device'), '.1f'):>10} "
            f"{_fmt(mfu * 100.0 if mfu is not None else None, '.1f'):>6} "
            f"{_fmt(mem / 2**20 if mem is not None else None, '.1f'):>8}")
    return "\n".join(lines)


def aggregates(records: List[Dict]) -> Dict:
    step_times = [r["step_time_secs"] for r in records
                  if r.get("step_time_secs") is not None]
    mfus = [r["mfu"] for r in records if r.get("mfu") is not None]
    tpsd = [r["tokens_per_sec_per_device"] for r in records
            if r.get("tokens_per_sec_per_device") is not None]
    # tracing-era fields (schema 2, --trace_dir): goodput_pct is
    # cumulative, so the final record carries the run-level number;
    # recompiles/straggler_events are monotone counters
    goodputs = [r["goodput_pct"] for r in records
                if r.get("goodput_pct") is not None]
    # model-health fields (schema 3, --log_layer_stats_interval): absent
    # on schema <=2 records -> None / 0, never a parse error
    worst_ratio = None
    nan_layer_events = 0
    for r in records:
        ls = r.get("layer_stats")
        if not ls:
            continue
        ratios = [v for v in (ls.get("update_ratio") or [])
                  if isinstance(v, (int, float))]
        if ratios and (worst_ratio is None or max(ratios) > worst_ratio):
            worst_ratio = max(ratios)
        if any(n > 0 for n in (ls.get("nonfinite_grads") or [])):
            nan_layer_events += 1
    return {
        "log_boundaries": len(records),
        "p50_step_time_secs": percentile(step_times, 50),
        "p95_step_time_secs": percentile(step_times, 95),
        "mean_mfu": sum(mfus) / len(mfus) if mfus else None,
        "max_mfu": max(mfus) if mfus else None,
        "mean_tokens_per_sec_per_device":
            sum(tpsd) / len(tpsd) if tpsd else None,
        "goodput_pct": goodputs[-1] if goodputs else None,
        "recompiles": next((r["recompiles"] for r in reversed(records)
                            if r.get("recompiles") is not None), None),
        "straggler_events": next(
            (r["straggler_events"] for r in reversed(records)
             if r.get("straggler_events") is not None), None),
        "worst_update_ratio": worst_ratio,
        "nan_layer_events": nan_layer_events,
    }


def slice_aggregates(records: List[Dict]) -> Optional[Dict]:
    """Per-slice attribution rollup (schema 4, multi-slice runs): from
    the per-boundary ``slice_times`` / ``worst_slice`` fields and the
    cumulative ``goodput.slice_stall_secs`` map.  None when the stream
    carries no slice dimension (single-slice runs, older schemas)."""
    per: Dict[str, List[float]] = {}
    worst_count: Dict[str, int] = {}
    lag: Dict[str, float] = {}
    stall: Dict[str, float] = {}
    for r in records:
        for k, v in (r.get("slice_times") or {}).items():
            if isinstance(v, (int, float)):
                per.setdefault(str(k), []).append(float(v))
        ws = r.get("worst_slice")
        if ws and ws.get("slice") is not None:
            key = str(ws["slice"])
            worst_count[key] = worst_count.get(key, 0) + 1
            lag[key] = lag.get(key, 0.0) + float(ws.get("lag_secs") or 0.0)
        # cumulative counter: the latest record wins
        gp = (r.get("goodput") or {}).get("slice_stall_secs")
        if isinstance(gp, dict):
            stall = {str(k): float(v) for k, v in gp.items()}
    if not per and not stall:
        return None
    slices = sorted(set(per) | set(stall), key=lambda s: (len(s), s))
    return {
        s: {
            "mean_step_secs":
                sum(per[s]) / len(per[s]) if per.get(s) else None,
            "max_step_secs": max(per[s]) if per.get(s) else None,
            "times_worst": worst_count.get(s, 0),
            "total_lag_secs": lag.get(s, 0.0),
            "stall_secs": stall.get(s, 0.0),
        }
        for s in slices
    }


def slice_table(slices: Dict) -> str:
    header = (f"{'slice':>6} {'mean step ms':>13} {'max step ms':>12} "
              f"{'times worst':>12} {'lag secs':>9} {'stall secs':>11}")
    lines = [header, "-" * len(header)]
    for s, row in sorted(slices.items(),
                         key=lambda kv: -kv[1]["stall_secs"]):
        mean = row["mean_step_secs"]
        mx = row["max_step_secs"]
        lines.append(
            f"{s:>6} "
            f"{_fmt(mean * 1000.0 if mean is not None else None, '.1f'):>13} "
            f"{_fmt(mx * 1000.0 if mx is not None else None, '.1f'):>12} "
            f"{row['times_worst']:>12} "
            f"{row['total_lag_secs']:>9.2f} "
            f"{row['stall_secs']:>11.2f}")
    return "\n".join(lines)


def fleet_events(path: str) -> List[Dict]:
    """Elastic-resume / preemption-rescue events from the stream (these
    are non-``log`` kinds, so ``load_records`` drops them)."""
    if os.path.isdir(path):
        path = os.path.join(path, "telemetry.jsonl")
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("kind") in ("elastic_resume", "preempt_rescue"):
                out.append(rec)
    return out


def recovery_timeline(records: List[Dict]) -> List[Dict]:
    """Log boundaries where any recovery counter advanced, with deltas."""
    events = []
    prev: Dict[str, int] = {}
    for r in records:
        counters = r.get("recovery") or {}
        deltas = {k: v - prev.get(k, 0)
                  for k, v in counters.items() if v - prev.get(k, 0) > 0}
        if deltas:
            events.append({"iteration": r.get("iteration"), **deltas})
        prev = counters or prev
    return events


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize a telemetry.jsonl stream")
    ap.add_argument("path",
                    help="telemetry.jsonl or the --structured_log_dir")
    ap.add_argument("--json", action="store_true",
                    help="emit aggregates + recovery timeline as JSON")
    args = ap.parse_args(argv)

    try:
        records = load_records(args.path)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    if not records:
        print("no log records in stream", file=sys.stderr)
        return 2

    agg = aggregates(records)
    timeline = recovery_timeline(records)
    slices = slice_aggregates(records)
    fleet = fleet_events(args.path)

    if args.json:
        print(json.dumps({"aggregates": agg,
                          "recovery_timeline": timeline,
                          "slices": slices,
                          "fleet_events": fleet}, indent=1))
        return 0

    print(per_step_table(records))
    print()
    p50, p95 = agg["p50_step_time_secs"], agg["p95_step_time_secs"]
    print(f"log boundaries: {agg['log_boundaries']}")
    print(f"step time p50: {_fmt(p50 * 1000.0 if p50 else None, '.1f')} ms"
          f" | p95: {_fmt(p95 * 1000.0 if p95 else None, '.1f')} ms")
    print(f"mean MFU: {_fmt(agg['mean_mfu'], '.4f')}"
          f" | max MFU: {_fmt(agg['max_mfu'], '.4f')}")
    print(f"mean tokens/sec/device: "
          f"{_fmt(agg['mean_tokens_per_sec_per_device'], '.1f')}")
    if agg["goodput_pct"] is not None:
        print(f"goodput: {agg['goodput_pct']:.1f}%"
              f" | recompiles: {_fmt(agg['recompiles'], 'd')}"
              f" | straggler events: {_fmt(agg['straggler_events'], 'd')}"
              f"  (full breakdown: tools/trace_report.py)")
    if agg["worst_update_ratio"] is not None or agg["nan_layer_events"]:
        print(f"layer stats: worst update ratio "
              f"{_fmt(agg['worst_update_ratio'], '.3g')}"
              f" | NaN-layer events: {agg['nan_layer_events']}"
              f"  (per-layer breakdown: tools/health_report.py)")
    if slices:
        print("\nper-slice attribution (fleet waits on its slowest "
              "slice):")
        print(slice_table(slices))
    if fleet:
        print("\nfleet events:")
        for ev in fleet:
            if ev.get("kind") == "elastic_resume":
                deltas = ", ".join(
                    f"{k} {v.get('from')} -> {v.get('to')}"
                    for k, v in (ev.get("changed") or {}).items())
                print(f"  elastic resume at iteration "
                      f"{ev.get('iteration', '?')}: {deltas} "
                      f"(consumed_samples "
                      f"{ev.get('consumed_samples', '?')})")
            else:
                print(f"  preemption rescue at iteration "
                      f"{ev.get('iteration', '?')}: exit code "
                      f"{ev.get('exit_code', '?')}, "
                      f"saved={ev.get('saved')}")
    if timeline:
        print("\nrecovery events:")
        for ev in timeline:
            deltas = ", ".join(f"{k}+{v}" for k, v in ev.items()
                               if k != "iteration")
            print(f"  iteration {ev['iteration']}: {deltas}")
    else:
        print("\nno recovery events")
    return 0


if __name__ == "__main__":
    sys.exit(main())
