#!/usr/bin/env python
"""Chat jsonl -> paired (text, role) mmap datasets for instruction tuning.

Reference: ``tools/preprocess_instruct_data.py`` — each jsonl line is a
conversation (list of {role, content} turns); tokens are written to a
``-text`` dataset and the per-token role ids to a parallel ``-role``
dataset, consumed by ``InstructionDataset``.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_llm_tpu.data.indexed_dataset import (
    MMapIndexedDatasetBuilder,
    best_fitting_dtype,
    data_file_path,
    index_file_path,
)
from megatron_llm_tpu.data.instruction_dataset import ROLES
from megatron_llm_tpu.tokenizer import build_tokenizer


def get_args():
    p = argparse.ArgumentParser()
    p.add_argument("--input", required=True)
    p.add_argument("--output_prefix", "--output-prefix",
                   dest="output_prefix", required=True)
    p.add_argument("--tokenizer_type", dest="tokenizer_type", required=True)
    p.add_argument("--vocab_file", dest="vocab_file")
    p.add_argument("--merge_file", dest="merge_file")
    p.add_argument("--tokenizer_path", dest="tokenizer_path")
    p.add_argument("--vocab_size", type=int, default=None)
    p.add_argument("--conversation_key", default="conversations")
    p.add_argument("--append_eod", action="store_true")
    args = p.parse_args()
    args.make_vocab_size_divisible_by = 128
    args.tensor_model_parallel_size = 1
    args.rank = 0
    return args


def main():
    args = get_args()
    tok = build_tokenizer(args)
    text_b = MMapIndexedDatasetBuilder(
        data_file_path(args.output_prefix + "-text"),
        dtype=best_fitting_dtype(tok.vocab_size),
    )
    role_b = MMapIndexedDatasetBuilder(
        data_file_path(args.output_prefix + "-role"), dtype="int8"
    )
    n = 0
    with open(args.input, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            conv = json.loads(line)[args.conversation_key]
            ids, roles = [], []
            for turn in conv:
                role_id = ROLES.get(turn["role"])
                if role_id is None:
                    raise ValueError(f"unknown role {turn['role']!r}")
                t = tok.tokenize(turn["content"])
                ids.extend(t)
                roles.extend([role_id] * len(t))
            if args.append_eod:
                ids.append(tok.eod)
                roles.append(ROLES["assistant"])
            text_b.add_item(ids)
            text_b.end_document()
            role_b.add_item(roles)
            role_b.end_document()
            n += 1
    text_b.finalize(index_file_path(args.output_prefix + "-text"))
    role_b.finalize(index_file_path(args.output_prefix + "-role"))
    print(f" done: {n} conversations -> {args.output_prefix}-text/-role")


if __name__ == "__main__":
    main()
