#!/usr/bin/env python
"""Build the REALM/ORQA evidence-block embedding index.

Reference: ``megatron/indexer.py`` IndexBuilder driven with the reference
flag names (``--ict_load``, ``--indexer_batch_size``,
``--indexer_log_interval``, ``--block_data_path`` /
``--embedding_path``, ``--evidence_data_path``): embed every evidence
block with the context tower of a trained BiEncoder and write the
embeddings store consumed by ``tasks/main.py --task=ORQA``.

Usage:
    python tools/create_doc_index.py --model_name=bert \\
        --evidence_data_path=/data/wiki_blocks \\
        --titles_data_path=/data/wiki_titles \\
        --ict_load=/ckpts/ict --embedding_path=/data/block_emb.pkl \\
        --tokenizer_type=BertWordPieceLowerCase --vocab_file=vocab.txt \\
        --num_layers=12 --hidden_size=768 --num_attention_heads=12 \\
        --seq_length=256 --max_position_embeddings=512
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def extra_args(parser):
    g = parser.add_argument_group("indexer")
    g.add_argument("--evidence_data_path", default=None,
                   help="indexed dataset of evidence blocks (falls back "
                        "to --data_path)")
    g.add_argument("--titles_data_path", default=None,
                   help="required for indexed-dataset evidence; unused "
                        "for wiki-TSV evidence")
    g.add_argument("--embedding_path", "--block_data_path",
                   dest="embedding_path", required=True,
                   help="output embeddings store (reference spells this "
                        "--block_data_path)")
    g.add_argument("--ict_load", default=None,
                   help="ICT/biencoder checkpoint (falls back to --load)")
    g.add_argument("--bert_load", default=None,
                   help="pretrained BERT trunk when no biencoder ckpt")
    g.add_argument("--indexer_batch_size", type=int, default=128)
    g.add_argument("--indexer_log_interval", type=int, default=1000)
    g.add_argument("--retriever_seq_length", type=int, default=256)
    g.add_argument("--ict_head_size", "--biencoder_projection_dim",
                   dest="biencoder_projection_dim", type=int, default=0)
    g.add_argument("--biencoder_shared_query_context_model",
                   action="store_true")
    g.add_argument("--use_one_sent_docs", action="store_true")
    g.add_argument("--model_name", default="bert")  # config preset only
    return parser


def main():
    import jax

    from megatron_llm_tpu import checkpointing
    from megatron_llm_tpu.arguments import transformer_config_from_args
    from megatron_llm_tpu.data.dataset_utils import get_indexed_dataset_
    from megatron_llm_tpu.data.ict_dataset import ICTDataset
    from megatron_llm_tpu.global_vars import get_tokenizer
    from megatron_llm_tpu.indexer import IndexBuilder
    from megatron_llm_tpu.initialize import initialize_megatron
    from megatron_llm_tpu.models.biencoder import BiEncoderModel

    args = initialize_megatron(extra_args_provider=extra_args)
    tokenizer = get_tokenizer()

    cfg = transformer_config_from_args(args)
    model = BiEncoderModel(
        cfg,
        projection_dim=args.biencoder_projection_dim,
        shared_query_context=args.biencoder_shared_query_context_model,
    )
    load_dir = args.ict_load or args.load or args.bert_load
    params = None
    if load_dir:
        params, _, _ = checkpointing.load_checkpoint(load_dir, finetune=True)
    if params is None:
        print(" > WARNING: indexing with a randomly initialized biencoder",
              flush=True)
        params = model.init(jax.random.PRNGKey(args.seed))

    evidence = args.evidence_data_path or (
        args.data_path[0] if args.data_path else None)
    if evidence is None:
        raise SystemExit("need --evidence_data_path or --data_path")
    if str(evidence).endswith(".tsv"):
        # DPR wiki-TSV evidence (same corpus format the reference's
        # orqa_wiki_dataset reads); no titles dataset needed
        from megatron_llm_tpu.data.orqa_wiki_dataset import (
            OpenRetrievalEvidenceDataset,
        )
        from megatron_llm_tpu.indexer import EvidenceIndexBuilder

        ds = OpenRetrievalEvidenceDataset(
            evidence, tokenizer, args.retriever_seq_length)
        # EvidenceIndexBuilder handles the multi-host barrier + rank-0
        # merge internally
        EvidenceIndexBuilder(
            model, params, ds, args.embedding_path,
            batch_size=args.indexer_batch_size,
            rank=jax.process_index(), world_size=jax.process_count(),
            log_interval=args.indexer_log_interval,
        ).build_and_save_index()
        print(f" > wrote evidence embeddings to {args.embedding_path}")
        return
    if args.titles_data_path is None:
        raise SystemExit("--titles_data_path is required for "
                         "indexed-dataset evidence")
    blocks = get_indexed_dataset_(evidence)
    titles = get_indexed_dataset_(args.titles_data_path)
    ict = ICTDataset(
        name="index", block_dataset=blocks, title_dataset=titles,
        data_prefix=evidence, num_epochs=1, max_num_samples=None,
        max_seq_length=args.retriever_seq_length, query_in_block_prob=1.0,
        seed=1, tokenizer=tokenizer,
        use_one_sent_docs=args.use_one_sent_docs,
    )
    builder = IndexBuilder(
        model, params, ict, args.embedding_path,
        batch_size=args.indexer_batch_size,
        log_interval=args.indexer_log_interval,
    )
    builder.build_and_save_index()
    print(f" > wrote block embeddings to {args.embedding_path}")


if __name__ == "__main__":
    main()
