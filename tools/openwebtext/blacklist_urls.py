"""Filter a crawl URL list: blacklisted domains/extensions, malformed,
short, and duplicate URLs.

Reference: ``tools/openwebtext/blacklist_urls.py:1-299``.  The domain and
extension blacklists below are the reference pipeline's published filter
data (they define *what* OpenWebText excludes -- media hosts, social
networks, binary file types -- and are kept for workflow parity).  The
code around them is original; in particular ``registered_domain`` replaces
the reference's ``tldextract`` dependency with a small public-suffix
heuristic good enough for blacklist matching (it only needs the
second-level label, e.g. ``youtube`` from ``www.youtube.co.uk``).

Usage::

    python blacklist_urls.py <dir with *.txt url lists | single file> <clean_urls.txt>
"""

from __future__ import annotations

import argparse
import glob
import os
import re
import sys
import time
from urllib.parse import urlsplit


# The reference pipeline's domain blacklist (media/social/binary hosts).
_DOMAIN_BLACKLIST = set("""
500px aapks akamaihd amazon apple artifactfire artstation awwni bandcamp
battleforthenet coinscalendar dailymotion deviantart discord discordapp
dlapkandroid dropbox e621 ebay edealinfo erome eroshare explosm facebook
fbcdn flickr furaffinity futhead gatopardo gfycat gifsound gifsoup giphy
github google gunprime gyazo hotdealstar imagefap imageshack imgflip imgur
instagram karmadecay kryptocal kym-cdn liveleak livememe lmgtfy magaimg
memegenerator minorplanetcenter minus mobafire morejpeg nocookie
pcpartpicker photobucket pinimg pinterest pixiv pornhub prntscr puu qkme
quickmeme radd redd reddit reddit-stream redditlog redditmedia
reddituploads redtube reupp reverb roanoke rollingstone sli soundcloud
soundgasm spankbang spotify strawpoll streamable timeanddate tinypic
touhouradio tumblr twimg twitch twitter vid vimeo vine vkaao vocaroo
voyagefusion walmart wciu wikimedia wikipedia xhamster xkcd xvideos youtu
youtube youtubedoubler ytimg zillexplorer
""".split())

# Non-document file extensions (media, archives, binaries, markup assets).
_EXTENSION_BLACKLIST = tuple("""
.3gp .7z .ai .aif .apk .app .avi .bin .bmp .bz2 .css .csv .dat .deb .dmg
.doc .docx .exe .gif .gifv .gz .iso .jar .jpeg .jpg .js .log .mid .midi
.mkv .mov .mp3 .mp4 .mpeg .mpg .ogg .ogv .otf .pdf .pkg .png .pps .ppt
.pptx .psd .py .qt .ram .rar .sql .svg .swf .tar .tar.gz .tgz .tiff .ttf
.txt .wav .webm .wma .wmv .xls .xlsx .xml .xz .zip
""".split())

# Common multi-label public suffixes; enough to peel ccTLD second levels
# (co.uk, com.au, ...) so the registered label lands on the actual site
# name.  Deliberately small: blacklist matching only needs the label, and
# an unknown exotic suffix just means the label check runs on the suffix's
# left neighbor, which is still the right label for .com/.org/.net etc.
_TWO_LEVEL_SUFFIXES = {
    "co.uk", "ac.uk", "gov.uk", "org.uk", "me.uk", "net.uk",
    "com.au", "net.au", "org.au", "edu.au", "gov.au",
    "co.jp", "ne.jp", "or.jp", "ac.jp", "go.jp",
    "co.kr", "or.kr", "co.in", "net.in", "org.in", "ac.in", "gov.in",
    "com.br", "net.br", "org.br", "com.cn", "net.cn", "org.cn",
    "com.mx", "com.tr", "com.tw", "co.za", "co.nz", "com.sg",
    "com.hk", "co.il", "com.ar", "com.my", "co.th", "com.vn",
}


def registered_domain(url: str) -> str:
    """Second-level label of the URL's host: ``https://www.youtube.co.uk/x``
    -> ``youtube``.  Empty string for hosts/IPs with no such label."""
    try:
        host = urlsplit(url).hostname or ""
    except ValueError:
        return ""
    if not host or re.fullmatch(r"[\d.]+", host):
        return ""  # bare IP: no registered label
    labels = host.lower().split(".")
    if len(labels) < 2:
        return labels[0] if labels else ""
    if len(labels) >= 3 and ".".join(labels[-2:]) in _TWO_LEVEL_SUFFIXES:
        return labels[-3]
    return labels[-2]


def domain_is_blacklisted(url: str) -> bool:
    return registered_domain(url) in _DOMAIN_BLACKLIST


def extension_is_blacklisted(url: str) -> bool:
    path = re.split(r"[?#]", url, 1)[0]  # drop query AND fragment
    return path.lower().endswith(_EXTENSION_BLACKLIST)


# Same acceptance contract as the reference's url_regex
# (``blacklist_urls.py:205-211``): scheme + hostname-or-IP + optional
# port + optional path.
_URL_RE = re.compile(
    r"^https?://"
    r"(?:[a-z0-9](?:[a-z0-9-]{0,61}[a-z0-9])?"
    r"(?:\.[a-z0-9](?:[a-z0-9-]{0,61}[a-z0-9])?)+\.?"
    r"|\d{1,3}(?:\.\d{1,3}){3})"
    r"(?::\d+)?"
    r"(?:/?|[/?]\S+)$",
    re.IGNORECASE)


def url_is_malformed(url: str) -> bool:
    return _URL_RE.match(url) is None


def classify(url: str, seen: set) -> str | None:
    """Rejection reason, or None if the URL should be kept."""
    if domain_is_blacklisted(url):
        return "domain"
    if extension_is_blacklisted(url):
        return "extension"
    if len(url) <= 8:
        return "short"
    if url_is_malformed(url):
        return "malformed"
    if url in seen:
        return "duplicate"
    return None


def main(argv=None):
    p = argparse.ArgumentParser(description="remove blacklisted urls")
    p.add_argument("path", help="directory of *.txt url lists, or one file")
    p.add_argument("output", help="clean url list out")
    p.add_argument("--quiet", action="store_true",
                   help="don't print each rejected url")
    args = p.parse_args(argv)

    files = (sorted(glob.glob(os.path.join(args.path, "*.txt")))
             if os.path.isdir(args.path) else [args.path])
    print(f"> found {len(files)} url file(s)", flush=True)

    seen = set()
    counts = {"domain": 0, "extension": 0, "short": 0,
              "malformed": 0, "duplicate": 0, "total": 0}
    start = time.time()
    for name in files:
        with open(name, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                url = line.strip()
                if not url:
                    continue
                counts["total"] += 1
                why = classify(url, seen)
                if why is None:
                    seen.add(url)
                else:
                    counts[why] += 1
                    if not args.quiet:
                        print(f"[{why.upper()}]: {url}", flush=True)

    print(f"FINAL | {time.time() - start:.2f}s | " +
          " | ".join(f"{k}: {v}" for k, v in counts.items()) +
          f" | kept: {len(seen)}", flush=True)
    with open(args.output, "w", encoding="utf-8") as f:
        for url in sorted(seen):
            f.write(url + "\n")


if __name__ == "__main__":
    sys.exit(main())
