"""Self-contained MinHash + banded-LSH near-duplicate detection.

The reference's corpus-dedup pipeline (``tools/openwebtext/find_duplicates.py:1-292``)
depends on the external ``lsh`` C-extension (github.com/mattilyra/LSH) for
its ``minhash.MinHasher`` / ``cache.Cache``.  This module provides the same
two objects with zero dependencies beyond numpy, vectorized instead of
C-accelerated:

- a document's char-ngram shingles are base-hashed once (blake2b -> uint64),
  then all ``num_seeds`` permutations are applied as one [seeds, shingles]
  universal-hash broadcast and min-reduced -- one numpy expression per doc
  rather than a per-shingle C loop;
- the LSH cache splits each fingerprint into ``num_bands`` bands and buckets
  documents by the hash of each band, so candidate pairs are only drawn from
  shared buckets (standard banded Jaccard LSH).

Determinism: base hashes use blake2b (stable across processes/machines,
unlike Python's salted ``hash``), and the permutation constants derive from
a caller-provided seed array, so fingerprints computed in different runs or
processes can be mixed -- which is what makes ``--save_fingerprints`` /
``--load_fingerprints`` recurrent dedup (reference behavior) work.
"""

from __future__ import annotations

import hashlib

import numpy as np

# Mersenne prime 2^61 - 1: universal-hash modulus, big enough that
# collisions across <= 2^32 shingle hashes are negligible, small enough
# that (a*h + b) stays inside uint128-free numpy by using Python ints via
# object arrays -- instead we keep everything in uint64 and rely on
# wraparound multiply-shift hashing (Dietzfelbinger), which needs no
# modulus at all.
_FP_DTYPE = np.uint64


def shingles(text: str, char_ngram: int = 5) -> set:
    """Set of overlapping character n-grams of ``text``.

    Same contract as the reference's ``shingles``
    (``find_duplicates.py:17-19``) — char 5-grams over the raw string —
    except the final shingle is included (the reference's range drops it).
    """
    return {text[i:i + char_ngram]
            for i in range(0, len(text) - char_ngram + 1)}


def _base_hashes(shingle_set) -> np.ndarray:
    """Stable 64-bit hash per shingle (blake2b digest -> uint64)."""
    if not shingle_set:
        return np.zeros((0,), dtype=_FP_DTYPE)
    out = np.empty((len(shingle_set),), dtype=_FP_DTYPE)
    for i, s in enumerate(shingle_set):
        d = hashlib.blake2b(s.encode("utf-8", "replace"),
                            digest_size=8).digest()
        out[i] = int.from_bytes(d, "little")
    return out


class MinHasher:
    """MinHash fingerprinter (drop-in for ``lsh.minhash.MinHasher``).

    ``seeds`` is an integer array (one per hash function); each seed is
    expanded to an (odd multiplier, addend) pair for multiply-shift
    universal hashing.  ``fingerprint(text)`` returns a uint64 vector of
    length ``len(seeds)``.
    """

    def __init__(self, seeds, char_ngram: int = 5):
        seeds = np.asarray(seeds, dtype=np.uint64)
        rng = np.random.RandomState(
            np.uint32(np.bitwise_xor.reduce(seeds.astype(np.uint32))) & 0x7FFFFFFF)
        n = len(seeds)
        # Odd multipliers + independent addends, one pair per seed.
        self._a = (rng.randint(1, 2 ** 62, size=n).astype(np.uint64) << np.uint64(1)) | np.uint64(1)
        self._b = rng.randint(1, 2 ** 62, size=n).astype(np.uint64)
        self.num_seeds = n
        self.char_ngram = char_ngram

    @classmethod
    def from_params(cls, a_bytes: bytes, b_bytes: bytes,
                    char_ngram: int) -> "MinHasher":
        """Rebuild a hasher from ``params()`` output (for worker processes:
        guarantees byte-identical fingerprints to the parent's hasher)."""
        self = cls.__new__(cls)
        self._a = np.frombuffer(a_bytes, dtype=_FP_DTYPE).copy()
        self._b = np.frombuffer(b_bytes, dtype=_FP_DTYPE).copy()
        self.num_seeds = len(self._a)
        self.char_ngram = char_ngram
        return self

    def params(self):
        return self._a.tobytes(), self._b.tobytes(), self.char_ngram

    def fingerprint(self, text: str) -> np.ndarray:
        base = _base_hashes(shingles(text, self.char_ngram))
        if base.size == 0:
            # Degenerate (too-short) document: constant fingerprint so it
            # buckets with other degenerates instead of crashing.
            return np.zeros((self.num_seeds,), dtype=_FP_DTYPE)
        with np.errstate(over="ignore"):
            # [seeds, 1] * [1, shingles] + [seeds, 1], uint64 wraparound.
            table = self._a[:, None] * base[None, :] + self._b[:, None]
        return table.min(axis=1)


class LSHCache:
    """Banded LSH index (drop-in for ``lsh.cache.Cache``).

    ``bins`` is a list of ``num_bands`` dicts mapping bucket-key -> set of
    doc ids; documents sharing any bucket are near-duplicate candidates.
    Pickles cleanly (pure dict/set state) for fingerprint save/load.
    """

    def __init__(self, num_bands: int, hasher: MinHasher):
        if hasher.num_seeds % num_bands != 0:
            raise ValueError(
                f"num_seeds ({hasher.num_seeds}) must be divisible by "
                f"num_bands ({num_bands})")
        self.num_bands = num_bands
        self.rows_per_band = hasher.num_seeds // num_bands
        self.hasher = hasher
        self.bins = [dict() for _ in range(num_bands)]
        self.fingerprints = {}

    def add_fingerprint(self, fingerprint: np.ndarray, doc_id) -> None:
        self.fingerprints[doc_id] = fingerprint
        r = self.rows_per_band
        for band, bucket in enumerate(self.bins):
            # blake2b, NOT the builtin hash(): bucket keys must be stable
            # across processes (hash() is salted per interpreter), or a
            # pickled index could never match keys computed after load.
            key = hashlib.blake2b(
                fingerprint[band * r:(band + 1) * r].tobytes(),
                digest_size=8).digest()
            bucket.setdefault(key, set()).add(doc_id)

    def add_doc(self, text: str, doc_id) -> None:
        self.add_fingerprint(self.hasher.fingerprint(text), doc_id)

    def candidate_pairs(self):
        """All unordered candidate pairs across every bucket (exact small-
        corpus path; the CLI uses per-bucket heuristics instead)."""
        pairs = set()
        for bucket in self.bins:
            for ids in bucket.values():
                if len(ids) > 1:
                    items = sorted(ids)
                    for i in range(len(items)):
                        for j in range(i + 1, len(items)):
                            pairs.add((items[i], items[j]))
        return pairs


def jaccard(set_a: set, set_b: set, mode: str = "union") -> float:
    """Jaccard similarity with the reference's three normalizations
    (``find_duplicates.py:24-36``): 'union' (true Jaccard), 'min', 'max'."""
    if len(set_a) < 1 or len(set_b) < 1:
        return 0.0
    inter = len(set_a & set_b)
    if mode == "min":
        return inter / min(len(set_a), len(set_b))
    if mode == "max":
        return inter / max(len(set_a), len(set_b))
    return inter / len(set_a | set_b)
