"""Drop all-but-one document of every duplicate group from a jsonl corpus.

Stage 4 of the dedup pipeline (reference:
``tools/openwebtext/remove_group_duplicates.py:1-56``): for each group
line ``{"idx": [id, id, ...]}`` keep the first id and mark the rest for
removal, then stream the corpus and drop marked documents.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def ids_to_remove(group_lines):
    remove = set()
    for line in group_lines:
        rec = json.loads(line)
        for ids in rec.values():
            remove.update(ids[1:])  # keep the first member of each group
    return remove


def main(argv=None):
    p = argparse.ArgumentParser(
        description="remove grouped duplicate docs from a jsonl corpus")
    p.add_argument("groups", help="group jsonl from group_duplicate_urls.py")
    p.add_argument("data", help="input corpus jsonl")
    p.add_argument("output", help="deduplicated corpus jsonl out")
    p.add_argument("--key", default="url",
                   help="json field holding the doc id (default: url)")
    args = p.parse_args(argv)

    with open(args.groups, "r", encoding="utf-8") as f:
        remove = ids_to_remove(f)
    print(f"will be removing {len(remove)} documents", flush=True)

    written = removed = removed_chars = 0
    start = time.time()
    with open(args.output, "w", encoding="utf-8") as fout, \
            open(args.data, "r", encoding="utf-8") as fin:
        for line in fin:
            try:
                rec = json.loads(line)
                if rec[args.key] in remove:
                    removed += 1
                    removed_chars += len(rec.get("text", ""))
                    continue
                fout.write(json.dumps(rec, ensure_ascii=False) + "\n")
                written += 1
            except Exception as exc:
                print(f"[SKIPPING] {exc}", flush=True)

    print(f"written: {written} | removed: {removed} "
          f"({removed_chars} chars) in {time.time() - start:.2f}s",
          flush=True)


if __name__ == "__main__":
    sys.exit(main())
