"""Corpus-curation suite (OpenWebText-style), self-contained.

TPU-framework counterpart of the reference's ``tools/openwebtext/``
pipeline: URL blacklisting, MinHash-LSH near-duplicate detection and
removal, encoding/language/length cleanup, and downstream-task n-gram
decontamination — with zero external dependencies (the reference needs
the ``lsh`` C extension, ``tldextract``, ``ftfy``, ``langdetect``,
``nltk``).  See README.md here for the end-to-end workflow.
"""
