"""Scrub downstream-task n-grams out of a training corpus.

Reference: ``tools/openwebtext/filter_ngrams.py:1-476`` (13-gram task
decontamination, as in the GPT-3 paper): build a dictionary of word
n-grams from evaluation-task texts; wherever a training document contains
one, cut the match plus ``--remove_char_each_side`` characters on both
sides (extending to sentence punctuation), keep the surrounding pieces,
drop pieces shorter than ``--filter_text_char_len``, and drop the whole
document once it has been split more than ``--max_splits`` times.

Task ingestion is generalized instead of hardcoded per task: any mix of
``--task_files path:jsonkey`` (jsonl) or plain ``.txt`` files feeds the
ngram dictionary; task texts shorter than ``--max_ngram_size`` words
contribute their full word sequence.  ``--save_ngrams``/``--load_ngrams``
persist the dictionary for reuse across shards (reference's save/load
dictionary feature).
"""

from __future__ import annotations

import argparse
import json
import pickle
import re
import sys
import time

_PUNCT = ".!?"


def get_words(text: str):
    """Lowercased word tokens + their character offsets."""
    words, positions = [], []
    for m in re.finditer(r"\w+", text.lower()):
        words.append(m.group(0))
        positions.append(m.start())
    return words, positions


def build_ngrams(task_texts, max_ngram_size: int) -> dict:
    """Map ngram-string -> word length, from every task text."""
    ngrams = {}
    for text in task_texts:
        words, _ = get_words(text)
        if not words:
            continue
        if len(words) < max_ngram_size:
            ngrams[" ".join(words)] = len(words)
        else:
            for i in range(len(words) - max_ngram_size + 1):
                ngrams[" ".join(words[i:i + max_ngram_size])] = max_ngram_size
    return ngrams


def _split_around(text: str, match_start: int, match_char_len: int,
                  pad: int):
    """Cut ``pad`` chars each side of the match, extending each cut
    outward to sentence punctuation (reference ``split_text`` semantics:
    ``filter_ngrams.py:28-48``)."""
    pos = match_start - pad
    first = ""
    while pos > 0 and text[pos] not in _PUNCT:
        pos -= 1
    if pos > 0:
        first = text[:pos + 1]
    pos = match_start + match_char_len + pad
    second = ""
    while pos < len(text) and text[pos] not in _PUNCT:
        pos += 1
    if pos + 1 < len(text):
        second = text[pos + 1:]
    return first, second


def scrub_text(text: str, ngrams: dict, max_ngram_size: int,
               remove_char_each_side: int = 200,
               filter_text_char_len: int = 200,
               max_splits: int = 10):
    """Return (clean pieces, n_matches) for one document; pieces == []
    means the document is entirely removed."""
    sizes = sorted({max_ngram_size} | set(ngrams.values()), reverse=True)
    pending = [text]
    clean = []
    matches = 0
    while pending:
        if matches > max_splits:
            return [], matches  # document shredded: drop it wholesale
        piece = pending.pop(0)
        words, positions = get_words(piece)
        hit = None
        for i in range(len(words)):
            for size in sizes:
                if i + size > len(words):
                    continue
                seq = " ".join(words[i:i + size])
                if seq in ngrams:
                    last = i + size - 1
                    char_len = (positions[last] + len(words[last])
                                - positions[i])
                    hit = (positions[i], char_len)
                    break
            if hit:
                break
        if hit is None:
            clean.append(piece)
            continue
        matches += 1
        first, second = _split_around(piece, hit[0], hit[1],
                                      remove_char_each_side)
        if len(first) > filter_text_char_len:
            clean.append(first)
        if len(second) > filter_text_char_len:
            pending.append(second)
    if matches > max_splits:  # final hit can push past the cap after the
        return [], matches    # in-loop check last ran
    return clean, matches


def load_task_texts(task_files):
    texts = []
    for spec in task_files:
        if ":" in spec and not spec.endswith(".txt"):
            path, key = spec.rsplit(":", 1)
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    try:
                        texts.append(json.loads(line)[key])
                    except Exception as exc:
                        print(f"Error reading {path}: {exc}", flush=True)
        else:
            with open(spec, "r", encoding="utf-8") as f:
                texts.append(f.read())
    return texts


def main(argv=None):
    p = argparse.ArgumentParser(
        description="remove downstream-task ngrams from a training corpus")
    p.add_argument("--task_files", nargs="*", default=[],
                   help="task sources: jsonl as path:key, or plain .txt")
    p.add_argument("--dedup_dataset", nargs=2,
                   metavar=("FILE", "KEY"), required=False,
                   help="training jsonl + its text key")
    p.add_argument("--output", type=str, default=None)
    p.add_argument("--max_ngram_size", type=int, default=13)
    p.add_argument("--remove_char_each_side", type=int, default=200)
    p.add_argument("--filter_text_char_len", type=int, default=200)
    p.add_argument("--max_splits", type=int, default=10)
    p.add_argument("--save_ngrams", type=str, default=None)
    p.add_argument("--load_ngrams", nargs="*", default=None)
    args = p.parse_args(argv)

    ngrams = {}
    if args.load_ngrams:
        for name in args.load_ngrams:
            with open(name, "rb") as f:
                ngrams.update(pickle.load(f))
            print(f" > loaded ngrams from {name} (total {len(ngrams)})",
                  flush=True)
    if args.task_files:
        texts = load_task_texts(args.task_files)
        ngrams.update(build_ngrams(texts, args.max_ngram_size))
        print(f" > built {len(ngrams)} task ngrams from "
              f"{len(texts)} task texts", flush=True)
    if args.save_ngrams:
        with open(args.save_ngrams, "wb") as f:
            pickle.dump(ngrams, f)
        print(f" > saved ngrams to {args.save_ngrams}", flush=True)

    if not args.dedup_dataset or not args.output:
        return 0

    data_file, key = args.dedup_dataset
    stats = {"docs": 0, "untouched": 0, "trimmed": 0, "removed": 0,
             "pieces": 0}
    start = time.time()
    with open(args.output, "w", encoding="utf-8") as fout, \
            open(data_file, "r", encoding="utf-8") as fin:
        for line in fin:
            stats["docs"] += 1
            try:
                rec = json.loads(line)
                text = rec[key]
            except Exception as exc:
                print(f"Error: {exc}", flush=True)
                continue
            pieces, matches = scrub_text(
                text, ngrams, args.max_ngram_size,
                args.remove_char_each_side, args.filter_text_char_len,
                args.max_splits)
            if matches == 0:
                stats["untouched"] += 1
                fout.write(json.dumps(rec, ensure_ascii=False) + "\n")
                continue
            if not pieces:
                stats["removed"] += 1
                continue
            stats["trimmed"] += 1
            for piece in pieces:
                stats["pieces"] += 1
                out = dict(rec)
                out[key] = piece
                fout.write(json.dumps(out, ensure_ascii=False) + "\n")
    print(f"[FINAL] {time.time() - start:.1f}s | " +
          " | ".join(f"{k}: {v}" for k, v in stats.items()), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
