"""Group near-duplicate document ids into connected components.

Stage 3 of the dedup pipeline (reference:
``tools/openwebtext/group_duplicate_url.py:1-77``).  Reads the pair file
emitted by ``find_duplicates.py`` -- jsonl lines of
``{main_id: [{other_id: sim}, ...]}`` -- keeps edges whose similarity is
at or above the threshold (default 0.7, same as the reference), and
union-finds the ids into groups.  Output: one jsonl line per multi-member
group, ``{"<group_index>": [id, id, ...]}``; downstream keeps the first
id of each group and drops the rest.

Implementation difference: the reference grows index sets with manual
merge bookkeeping; this uses a path-compressed union-find, which is the
same result with less state to get wrong.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


class UnionFind:
    def __init__(self):
        self.parent = {}

    def find(self, x):
        # Iterative walk + full path compression: duplicate chains from
        # boilerplate/template pages can be thousands of links long, which
        # would overflow a recursive find.
        root = self.parent.setdefault(x, x)
        while self.parent[root] != root:
            root = self.parent[root]
        while self.parent[x] != root:
            self.parent[x], x = root, self.parent[x]
        return root

    def union(self, a, b):
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self.parent[rb] = ra


def group_pairs(pair_lines, threshold: float):
    """Union-find over (main, other) edges with sim >= threshold."""
    uf = UnionFind()
    for line in pair_lines:
        rec = json.loads(line)
        for main_id, dups in rec.items():
            uf.find(main_id)
            for entry in dups:
                for other_id, sim in entry.items():
                    if sim >= threshold:
                        uf.union(main_id, other_id)
    groups = {}
    for x in list(uf.parent):
        groups.setdefault(uf.find(x), []).append(x)
    # Deterministic order inside each group (stable "keep the first" rule).
    return [sorted(v) for v in groups.values() if len(v) > 1]


def main(argv=None):
    p = argparse.ArgumentParser(
        description="group duplicate ids from find_duplicates.py output")
    p.add_argument("input", help="pair jsonl from find_duplicates.py")
    p.add_argument("output", help="group jsonl out")
    p.add_argument("threshold", nargs="?", type=float, default=0.7,
                   help="min jaccard similarity to join a group")
    args = p.parse_args(argv)

    start = time.time()
    with open(args.input, "r", encoding="utf-8") as f:
        groups = group_pairs(f, args.threshold)

    removed = sum(len(g) - 1 for g in groups)
    kept = len(groups)
    print(f"out of {removed + kept} grouped ids, {kept} are unique and "
          f"{removed} should be removed "
          f"({time.time() - start:.2f}s)", flush=True)

    with open(args.output, "w", encoding="utf-8") as f:
        for i, g in enumerate(groups):
            f.write(json.dumps({str(i): g}, ensure_ascii=False) + "\n")


if __name__ == "__main__":
    sys.exit(main())
