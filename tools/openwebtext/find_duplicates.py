"""Find near-duplicate documents in loose-jsonl corpora via MinHash LSH.

Workflow + argument parity with the reference
(``tools/openwebtext/find_duplicates.py:178-292``): fingerprint every
document of every ``--inputs file key`` pair, optionally save/load the
fingerprint index for recurrent dedup, then emit one jsonl line per
retained "main" document listing the bucket-mates whose Jaccard
similarity exceeds 0.5::

    {"<main_id>": [{"<other_id>": 0.83}, ...]}

Differences from the reference, by design:
- the LSH engine is the in-repo numpy one (``minhash_lsh.py``), not the
  external C extension;
- fingerprinting parallelism uses a bounded process pool only when
  ``--num_workers > 1`` (the reference hardcodes 40 workers, which on a
  shared CI box just thrashes);
- bucket scanning is sequential by default; ``--jaccard_parallel`` fans
  buckets out across processes like the reference's bin-parallel mode.
"""

from __future__ import annotations

import argparse
import json
import multiprocessing
import pickle
import random
import sys
import time

import numpy as np

try:
    from .minhash_lsh import LSHCache, MinHasher, jaccard, shingles
except ImportError:  # run as a script: python tools/openwebtext/find_duplicates.py
    from minhash_lsh import LSHCache, MinHasher, jaccard, shingles


def dedup_bucket(bucket_ids, id_text, jaccard_mode, heuristic_iter, rng,
                 shingle_memo):
    """Reference heuristic (``find_duplicates.py:50-84``): repeatedly pick
    a random 'main' doc from the bucket, mark every other member with
    similarity > 0.5 as its duplicate, drop them all from the bucket, and
    repeat up to ``heuristic_iter`` rounds (-1 = until the bucket empties,
    i.e. exact within-bucket)."""
    def sh(doc_id):
        s = shingle_memo.get(doc_id)
        if s is None:
            s = shingle_memo[doc_id] = shingles(id_text[doc_id])
        return s

    out = []
    flagged = set()
    compared = 0
    bucket = list(bucket_ids)
    iteration = 0
    while len(bucket) > 1:
        if heuristic_iter != -1 and iteration == heuristic_iter:
            break
        main = bucket[rng.randrange(len(bucket))]
        main_sh = sh(main)
        dups = []
        keep = []
        for other in bucket:
            if other == main:
                continue
            compared += 1
            sim = jaccard(main_sh, sh(other), jaccard_mode)
            if sim > 0.5:
                dups.append({other: round(sim, 4)})
                flagged.add(other)
            else:
                keep.append(other)
        bucket = keep
        if dups:
            out.append({main: dups})
        iteration += 1
    return out, flagged, compared


# Worker-side corpus state, installed once per worker by the Pool
# initializer (portable across fork/spawn/forkserver start methods; under
# fork the dict pages are also shared copy-on-write) instead of pickling
# the full id_text dict into every per-band payload -- for a large corpus
# that serialization would dwarf the scan.
_SCAN_STATE = {}


def _init_scan_state(state):
    _SCAN_STATE.update(state)


def _scan_one_bin(payload):
    bin_index, seed = payload
    bin_dict = _SCAN_STATE["bins"][bin_index]
    id_text = _SCAN_STATE["id_text"]
    jaccard_mode = _SCAN_STATE["jaccard"]
    heuristic_iter = _SCAN_STATE["heuristic_iter"]
    skip = _SCAN_STATE["skip"]
    rng = random.Random(seed)
    lines = []
    flagged = set()
    compared = 0
    shingle_memo = {}
    for ids in bin_dict.values():
        live = [i for i in ids if i not in skip and i not in flagged]
        if len(live) <= 1:
            continue
        recs, f, c = dedup_bucket(live, id_text, jaccard_mode,
                                  heuristic_iter, rng, shingle_memo)
        flagged |= f
        compared += c
        lines.extend(recs)
    return lines, flagged, compared


def scan_buckets(args, cache, id_text):
    """Walk every LSH bucket and write the duplicate-pair jsonl.

    A near-duplicate pair collides in most bands, so later bins skip doc
    ids already flagged as duplicates (sequential mode threads the
    flagged set through; parallel workers each start from the ids
    flagged before the pool launched, and the parent drops repeated
    (main, dup) edges at write time)."""
    start = time.time()
    _SCAN_STATE.update({
        "bins": cache.bins, "id_text": id_text, "jaccard": args.jaccard,
        "heuristic_iter": args.heuristic_iter, "skip": set(),
    })
    total_flagged = set()
    total_compared = 0
    seen_edges = set()
    with open(args.output, "w", encoding="utf-8") as f_out:
        def emit(lines):
            for rec in lines:
                for main_id, dups in rec.items():
                    fresh = []
                    for e in dups:
                        other = next(iter(e))
                        if (main_id, other) not in seen_edges and \
                                (other, main_id) not in seen_edges:
                            seen_edges.add((main_id, other))
                            fresh.append(e)
                    if fresh:
                        f_out.write(json.dumps({main_id: fresh},
                                               ensure_ascii=False) + "\n")

        if args.jaccard_parallel and len(cache.bins) > 1:
            payloads = [(i, args.seed + i) for i in range(len(cache.bins))]
            with multiprocessing.Pool(min(len(payloads),
                                          multiprocessing.cpu_count()),
                                      initializer=_init_scan_state,
                                      initargs=(_SCAN_STATE,)) as pool:
                for lines, flagged, compared in pool.imap(_scan_one_bin,
                                                          payloads):
                    total_flagged |= flagged
                    total_compared += compared
                    emit(lines)
        else:
            for i in range(len(cache.bins)):
                _SCAN_STATE["skip"] = total_flagged
                lines, flagged, compared = _scan_one_bin((i, args.seed + i))
                total_flagged |= flagged
                total_compared += compared
                emit(lines)
    print(f" > jaccard scan: {total_compared} comparisons, "
          f"{len(total_flagged)} duplicates flagged in "
          f"{time.time() - start:.2f}s", flush=True)


def _parse_line(line, key):
    try:
        rec = json.loads(line)
        return rec[key], rec["text"]
    except Exception as exc:  # malformed line: skip, like the reference
        print(f"Error: {exc}", flush=True)
        return None, None


_WORKER_HASHER = None


def _init_worker_hasher(hasher_params):
    # Rebuild the hasher once per worker from the parent's exact (a, b)
    # constants so worker fingerprints are byte-identical -- via the Pool
    # initializer, not per-line payloads (the params are constant).
    global _WORKER_HASHER
    _WORKER_HASHER = MinHasher.from_params(*hasher_params)


def _fingerprint_line(payload):
    line, key = payload
    doc_id, text = _parse_line(line, key)
    if doc_id is None:
        return None, None, None
    return doc_id, text, _WORKER_HASHER.fingerprint(text)


def ingest_inputs(args, cache, id_text):
    hasher = cache.hasher
    counter = 0
    start = time.time()
    for input_file, key in zip(args.inputs[::2], args.inputs[1::2]):
        print(f" > fingerprinting {input_file} (id key: {key})", flush=True)
        with open(input_file, "r", encoding="utf-8") as fin:
            if args.num_workers > 1:
                with multiprocessing.Pool(
                        args.num_workers,
                        initializer=_init_worker_hasher,
                        initargs=(hasher.params(),)) as pool:
                    it = pool.imap(
                        _fingerprint_line,
                        ((line, key) for line in fin), 256)
                    for doc_id, text, fp in it:
                        counter += 1
                        if doc_id is not None:
                            id_text[doc_id] = text
                            cache.add_fingerprint(fp, doc_id)
            else:
                for line in fin:
                    counter += 1
                    doc_id, text = _parse_line(line, key)
                    if doc_id is not None:
                        id_text[doc_id] = text
                        cache.add_doc(text, doc_id)
    print(f" > fingerprinted {counter} documents in "
          f"{time.time() - start:.2f}s", flush=True)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="MinHash-LSH near-duplicate finder for jsonl corpora")
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument("--inputs", nargs="*", default=None,
                   help="pairwise list: file1 idkey1 file2 idkey2 ...")
    p.add_argument("--load_fingerprints", nargs="*", default=None,
                   help="pickle files from --save_fingerprints to merge in")
    p.add_argument("--save_fingerprints", type=str, default=None,
                   help="pickle the LSH index + texts for recurrent dedup")
    p.add_argument("--output", type=str, default=None,
                   help="jsonl of {main_id: [{dup_id: sim}, ...]} records")
    p.add_argument("--jaccard", type=str, default="union",
                   choices=["union", "min", "max"])
    p.add_argument("--heuristic_iter", type=int, default=1,
                   help="dedup rounds per bucket; -1 = until empty (exact)")
    p.add_argument("--num_bands", type=int, default=10)
    p.add_argument("--num_seeds", type=int, default=100,
                   help="minhash permutations; must divide by num_bands")
    p.add_argument("--num_workers", type=int, default=1,
                   help="fingerprinting processes (>1 enables the pool)")
    p.add_argument("--jaccard_parallel", action="store_true",
                   help="scan LSH bins in parallel processes")
    args = p.parse_args(argv)

    random.seed(args.seed)
    np.random.seed(args.seed)
    seeds = np.random.randint(0, 1_000_000, size=args.num_seeds)

    hasher = MinHasher(seeds=seeds, char_ngram=5)
    cache = LSHCache(num_bands=args.num_bands, hasher=hasher)
    id_text = {}

    if args.load_fingerprints:
        for i, name in enumerate(args.load_fingerprints):
            print(f" > loading fingerprints from {name}", flush=True)
            with open(name, "rb") as f:
                loaded_cache = pickle.load(f)
                loaded_texts = pickle.load(f)
            if i == 0 and not cache.fingerprints:
                cache = loaded_cache
                id_text.update(loaded_texts)
            else:
                for doc_id, fp in loaded_cache.fingerprints.items():
                    id_text[doc_id] = loaded_texts[doc_id]
                    cache.add_fingerprint(fp, doc_id)

    if args.inputs:
        if len(args.inputs) % 2 != 0:
            p.error("--inputs must be file/key pairs")
        ingest_inputs(args, cache, id_text)

    if args.save_fingerprints:
        print(f" > saving fingerprints to {args.save_fingerprints}",
              flush=True)
        with open(args.save_fingerprints, "wb") as f:
            pickle.dump(cache, f)
            pickle.dump(id_text, f)

    if args.output:
        scan_buckets(args, cache, id_text)

    print("done :-)", flush=True)


if __name__ == "__main__":
    sys.exit(main())
