"""Concatenate every ``*.json``/``*.jsonl`` shard in a directory into one
loose-jsonl file (reference: ``tools/openwebtext/merge_jsons.py:1-42``),
validating each line parses before writing."""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def main(argv=None):
    p = argparse.ArgumentParser(description="merge jsonl shards")
    p.add_argument("--json_path", type=str, default=".")
    p.add_argument("--output_file", type=str, default="merged_output.json")
    args = p.parse_args(argv)

    shards = sorted(glob.glob(os.path.join(args.json_path, "*.json"))
                    + glob.glob(os.path.join(args.json_path, "*.jsonl")))
    n = 0
    with open(args.output_file, "w", encoding="utf-8") as out:
        for name in shards:
            if os.path.abspath(name) == os.path.abspath(args.output_file):
                continue
            with open(name, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    json.loads(line)  # validate, fail loud on corrupt shards
                    out.write(line + "\n")
                    n += 1
    print(f"merged {len(shards)} shard(s), {n} records -> "
          f"{args.output_file}", flush=True)


if __name__ == "__main__":
    sys.exit(main())
