"""Stamp a sequential id onto every record of a jsonl file.

Reference: ``tools/openwebtext/add_id.py:1-54`` (adds ``adlr_id`` of the
form ``<prefix>-NNNNNNNNNN``); same field + format here so downstream
tooling that keys on it keeps working.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None):
    p = argparse.ArgumentParser(description="add ids to a jsonl dataset")
    p.add_argument("--input_file", required=True)
    p.add_argument("--output_file", required=True)
    p.add_argument("--id_prefix", required=True)
    p.add_argument("--log_interval", type=int, default=100)
    args = p.parse_args(argv)

    start = time.time()
    n = 0
    with open(args.input_file, "r", encoding="utf-8") as fin, \
            open(args.output_file, "w", encoding="utf-8") as fout:
        for line in fin:
            n += 1
            rec = json.loads(line)
            rec["adlr_id"] = f"{args.id_prefix}-{n:010d}"
            fout.write(json.dumps(rec, ensure_ascii=False) + "\n")
            if n % args.log_interval == 0:
                print(f"    processed {n:9d} documents in "
                      f"{time.time() - start:.2f}s", flush=True)
    print("done :-)", flush=True)


if __name__ == "__main__":
    sys.exit(main())
