"""Task-flag document cleaner (reference:
``tools/openwebtext/cleanup_fix_dataset.py:1-177``): apply a chosen set
of cleanup tasks to a jsonl corpus, writing kept/cleaned docs to one
file and removed docs to another.

Tasks (same names as the reference so recipes port unchanged):

- ``remove_512``              drop docs under 512 characters
- ``remove_256_javascript``   drop docs under 256 chars mentioning
                              'javascript' (boilerplate/script scrapes)
- ``remove_512_non_english``  drop short non-English docs (in-repo
                              stopword heuristic instead of langdetect)
- ``ftfy_fix_text``           mojibake repair (in-repo ``fix_text``
                              instead of ftfy)
- ``general_cleaning``        collapse repeated spaces / stray newlines

Tasks apply in the order given on the command line (reference
semantics): a filtering task that triggers short-circuits the rest; a
fixing task rewrites the text that later tasks then see — so
``--tasks ftfy_fix_text remove_512`` measures length on the FIXED text.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time

try:
    from .cleanup_dataset import fix_text, is_english
except ImportError:  # run as a script
    from cleanup_dataset import fix_text, is_english

TASKS = ("remove_512", "remove_256_javascript", "remove_512_non_english",
         "ftfy_fix_text", "general_cleaning")


def _general_cleaning(text: str) -> str:
    # stray newlines (with any surrounding spaces) -> one space, then
    # collapse space runs — two passes so space runs created by the
    # newline replacement are themselves collapsed
    text = re.sub(r"[ \t]*\n+[ \t]*", " ", text)
    return re.sub(r"  +", " ", text)


def process_doc(text: str, tasks) -> tuple:
    """Returns (new_text, removal_reason or None); ``tasks`` apply in
    the order given (see module docstring)."""
    for task in tasks:
        if task == "remove_512":
            if len(text) < 512:
                return text, task
        elif task == "remove_256_javascript":
            if len(text) < 256 and "javascript" in text.lower():
                return text, task
        elif task == "remove_512_non_english":
            if len(text) < 512 and not is_english(text):
                return text, task
        elif task == "ftfy_fix_text":
            text = fix_text(text)
        elif task == "general_cleaning":
            text = _general_cleaning(text)
    return text, None


def main(argv=None):
    p = argparse.ArgumentParser(
        description="task-flag document cleaner: filter/fix a jsonl "
                    "corpus into kept + removed outputs")
    p.add_argument("input", help="jsonl corpus in")
    p.add_argument("output_cleaned", help="kept/cleaned jsonl out")
    p.add_argument("output_filtered", help="removed docs jsonl out")
    p.add_argument("--tasks", nargs="+", choices=TASKS, required=True)
    p.add_argument("--text_key", default="text")
    args = p.parse_args(argv)

    counts = dict.fromkeys(TASKS, 0)
    counts.update(docs=0, kept=0, errors=0)
    start = time.time()
    with open(args.output_cleaned, "w", encoding="utf-8") as f_clean, \
            open(args.output_filtered, "w", encoding="utf-8") as f_filt, \
            open(args.input, "r", encoding="utf-8",
                 errors="replace") as fin:
        for line in fin:
            counts["docs"] += 1
            try:
                rec = json.loads(line)
                new_text, reason = process_doc(rec[args.text_key],
                                               args.tasks)
                if reason is not None:
                    counts[reason] += 1
                    f_filt.write(json.dumps(rec, ensure_ascii=False)
                                 + "\n")
                    continue
                rec[args.text_key] = new_text
                f_clean.write(json.dumps(rec, ensure_ascii=False) + "\n")
                counts["kept"] += 1
            except Exception as exc:
                counts["errors"] += 1
                print(f"  skipping line: {exc}", flush=True)

    print(f"[FINAL] {time.time() - start:.1f}s | " +
          " | ".join(f"{k}: {v}" for k, v in counts.items() if v),
          flush=True)


if __name__ == "__main__":
    sys.exit(main())
