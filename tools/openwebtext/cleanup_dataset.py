"""Clean a loose-jsonl corpus: fix encoding damage, keep English docs,
drop short docs.

Reference: ``tools/openwebtext/cleanup_dataset.py:1-102``, which leans on
``ftfy.fix_text`` and ``langdetect.detect`` -- neither shippable here, so
both are replaced with self-contained equivalents tuned for the same
filtering decisions:

- ``fix_text``: the high-value ftfy repair is mojibake reversal (UTF-8
  bytes mis-decoded as cp1252, the classic ``â€™`` class).
  We detect the cp1252-mojibake signature and reverse it by re-encoding,
  iterating for doubly-encoded text, then NFC-normalize and strip control
  characters.
- ``is_english``: a stopword-hit-rate + latin-letter-ratio heuristic.
  langdetect builds char-ngram profiles for 55 languages; for a binary
  keep/drop-English gate, function-word density separates English from
  other latin-script languages and the letter ratio rejects non-latin
  scripts.

Doc-length gate: the reference requires >= 128 GPT-2 tokens, short-
circuited by a ``len(text) < 8 * 128`` char pre-check.  Word count is a
closer token proxy (GPT-2 averages ~1.3 tokens/word) and needs no vocab
download; ``--min_words 128`` is the shipped default.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
import unicodedata


# cp1252 renderings of UTF-8 lead bytes C2/C3/C5/E2/F0 -- their presence
# is the mojibake signature that makes a reversal attempt worthwhile.
_MOJIBAKE_CHARS = "ÂÃÅâð"
_CTRL_RE = re.compile(r"[\x00-\x08\x0b\x0c\x0e-\x1f\x7f]")


def _demojibake_once(text: str) -> str:
    """Reverse one layer of UTF-8-read-as-cp1252, if cleanly reversible
    and actually an improvement (fewer signature characters)."""
    try:
        fixed = text.encode("cp1252").decode("utf-8")
    except (UnicodeEncodeError, UnicodeDecodeError):
        return text
    before = sum(text.count(c) for c in _MOJIBAKE_CHARS)
    after = sum(fixed.count(c) for c in _MOJIBAKE_CHARS)
    return fixed if after < before else text


def fix_text(text: str) -> str:
    """Self-contained stand-in for ftfy.fix_text (see module docstring)."""
    for _ in range(3):  # doubly/triply-encoded text unwinds one layer/pass
        if not any(c in text for c in _MOJIBAKE_CHARS):
            break
        fixed = _demojibake_once(text)
        if fixed == text:
            break
        text = fixed
    text = unicodedata.normalize("NFC", text)
    text = text.replace("\r\n", "\n").replace("\r", "\n")
    return _CTRL_RE.sub("", text)


# High-frequency English function words.  Hit-rate on these separates
# English from other latin-script languages (their function words --
# le/la/der/die/el/het -- barely intersect).
_EN_STOPWORDS = frozenset(
    "the of and to a in is that it was for on are with as his they at be "
    "this have from or had by not but what all were when we there can an "
    "your which their said if will each about how up out them she many "
    "some so these would other into has more her two like him see no way "
    "could people my than first been who its now did get made".split())


def english_score(text: str, sample_chars: int = 4000):
    """(stopword hit-rate, latin-letter ratio) over a prefix sample."""
    sample = text[:sample_chars]
    words = re.findall(r"[^\W\d_]+", sample.lower())
    if not words:
        return 0.0, 0.0
    hits = sum(1 for w in words if w in _EN_STOPWORDS)
    letters = [c for c in sample if c.isalpha()]
    latin = sum(1 for c in letters if c.isascii())
    return hits / len(words), (latin / len(letters)) if letters else 0.0


def is_english(text: str) -> bool:
    stop_rate, latin_ratio = english_score(text)
    return stop_rate >= 0.08 and latin_ratio >= 0.90


def word_count(text: str) -> int:
    return len(re.findall(r"\S+", text))


def filter_corpus(in_name: str, out_name: str, min_words: int = 128,
                  print_interval: int = 10000) -> dict:
    counts = {"docs": 0, "written": 0, "fixed": 0,
              "non_english": 0, "small": 0, "errors": 0}
    start = time.time()
    with open(out_name, "w", encoding="utf-8") as fout, \
            open(in_name, "r", encoding="utf-8", errors="replace") as fin:
        for line in fin:
            counts["docs"] += 1
            try:
                rec = json.loads(line)
                text = fix_text(rec["text"])
                if text != rec["text"]:
                    counts["fixed"] += 1
                rec["text"] = text
                if not is_english(text):
                    counts["non_english"] += 1
                    continue
                if word_count(text) < min_words:
                    counts["small"] += 1
                    continue
                fout.write(json.dumps(rec, ensure_ascii=False) + "\n")
                counts["written"] += 1
            except Exception as exc:
                counts["errors"] += 1
                print(f"  skipping line: {exc}", flush=True)
            if counts["docs"] % print_interval == 0:
                print(f"[PROGRESS] {time.time() - start:.1f}s | " +
                      " | ".join(f"{k}: {v}" for k, v in counts.items()),
                      flush=True)
    print(f"[FINAL] {time.time() - start:.1f}s | " +
          " | ".join(f"{k}: {v}" for k, v in counts.items()), flush=True)
    return counts


def main(argv=None):
    p = argparse.ArgumentParser(
        description="fix + language-filter + length-filter a jsonl corpus")
    p.add_argument("input")
    p.add_argument("output")
    p.add_argument("--min_words", type=int, default=128,
                   help="min whitespace-word count (~token proxy)")
    args = p.parse_args(argv)
    filter_corpus(args.input, args.output, args.min_words)


if __name__ == "__main__":
    sys.exit(main())
