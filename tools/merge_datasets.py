#!/usr/bin/env python
"""Merge multiple mmap datasets with the same dtype into one
(reference: tools/merge_datasets.py)."""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_llm_tpu.data.indexed_dataset import (
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
    data_file_path,
    index_file_path,
)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--input", nargs="+", required=True,
                   help="dataset prefixes to merge, in order")
    p.add_argument("--output_prefix", "--output-prefix",
                   dest="output_prefix", required=True)
    args = p.parse_args()

    first = MMapIndexedDataset(args.input[0])
    builder = MMapIndexedDatasetBuilder(
        data_file_path(args.output_prefix), dtype=first.dtype
    )
    for prefix in args.input:
        builder.merge_file_(prefix)
        print(f" merged {prefix}")
    builder.finalize(index_file_path(args.output_prefix))
    print(f" done -> {args.output_prefix}.bin/.idx")


if __name__ == "__main__":
    main()
