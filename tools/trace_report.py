#!/usr/bin/env python
"""Summarize a run's span trace (trace.json) + telemetry stream.

Reads the Chrome ``trace_event`` JSON written by ``--trace_dir``
(megatron_llm_tpu/tracing.py) — the same file Perfetto loads — and
prints:

* a goodput breakdown — wall-clock attributed to productive-step /
  compile / checkpoint / eval / rewind / data-stall / other, with
  ``goodput_pct`` and a bar chart
* span coverage — how much of the traced wall-clock any span accounts
  for (the acceptance bar is >= 95%)
* the top-N slowest spans (the root ``train`` span excluded — it always
  "wins")
* a recompile timeline — every steady-state backend compile, timestamped
* a straggler timeline — per-host straggler events (which host, which
  section, how far past the median)

When the sibling ``telemetry.jsonl`` (``--structured_log_dir``) exists,
the per-boundary ``goodput_pct`` trend is appended.

``--merge`` stitches several processes' traces (e.g. the serving
router's + each replica's) onto ONE Chrome-trace timeline: every
trace carries its wall-clock origin (``otherData.trace_start_unix``),
so events shift onto a shared clock and each input file becomes its own
process row.  A request's ``route_request`` span (router) then lines up
under the same trace id as its ``queue_wait`` / ``prefill_chunk`` /
``decode_step`` spans (replica) — the fleet-wide request lifecycle in
one Perfetto view.

Pure stdlib — no jax import, runs anywhere the files do.

Usage:
    python tools/trace_report.py TRACE_DIR_OR_JSON [--top N] [--json]
    python tools/trace_report.py A/trace.json B/trace.json --merge \
        --out merged.json [--trace TRACE_ID]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

GOODPUT_ORDER = ("step", "compile", "checkpoint", "eval", "rewind", "data",
                 "other")
BAR_WIDTH = 40


def load_trace(path: str) -> Dict:
    """Accept a trace.json file or the --trace_dir holding one."""
    if os.path.isdir(path):
        path = os.path.join(path, "trace.json")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no trace at {path}")
    with open(path) as f:
        return json.load(f)


def spans(trace: Dict) -> List[Dict]:
    """The complete ('X') events, sorted by start time."""
    evs = [e for e in trace.get("traceEvents", []) if e.get("ph") == "X"]
    return sorted(evs, key=lambda e: e.get("ts", 0.0))


def instants(trace: Dict, name: Optional[str] = None) -> List[Dict]:
    return [e for e in trace.get("traceEvents", [])
            if e.get("ph") == "i" and (name is None or e.get("name") == name)]


def coverage(trace: Dict) -> Optional[float]:
    """Fraction of the traced wall-clock covered by at least one span:
    union of [ts, ts+dur) intervals over the trace's own extent.  None
    when the trace holds no spans."""
    xs = spans(trace)
    if not xs:
        return None
    intervals = sorted((e["ts"], e["ts"] + e.get("dur", 0.0)) for e in xs)
    lo = intervals[0][0]
    hi = max(end for _, end in intervals)
    if hi <= lo:
        return None
    covered, cur_start, cur_end = 0.0, intervals[0][0], intervals[0][1]
    for start, end in intervals[1:]:
        if start > cur_end:
            covered += cur_end - cur_start
            cur_start, cur_end = start, end
        else:
            cur_end = max(cur_end, end)
    covered += cur_end - cur_start
    return covered / (hi - lo)


def goodput_breakdown(trace: Dict) -> Optional[Dict]:
    return (trace.get("otherData") or {}).get("goodput")


def top_spans(trace: Dict, n: int = 10) -> List[Dict]:
    """Slowest spans by duration; the root 'train' span excluded."""
    xs = [e for e in spans(trace) if e.get("name") != "train"]
    xs.sort(key=lambda e: e.get("dur", 0.0), reverse=True)
    return [{"name": e["name"], "category": e.get("cat", "?"),
             "start_secs": e["ts"] / 1e6, "dur_secs": e.get("dur", 0.0) / 1e6,
             "args": {k: v for k, v in (e.get("args") or {}).items()
                      if k != "goodput"}}
            for e in xs[:n]]


def recompile_timeline(trace: Dict) -> List[Dict]:
    out = []
    for e in spans(trace):
        if e.get("name") == "recompile":
            out.append({"at_secs": e["ts"] / 1e6,
                        "compile_secs": e.get("dur", 0.0) / 1e6})
    for e in instants(trace, "suspected_recompile"):
        out.append({"at_secs": e["ts"] / 1e6, "suspected": True,
                    "step_secs": (e.get("args") or {}).get("step_secs")})
    return sorted(out, key=lambda r: r["at_secs"])


def straggler_timeline(trace: Dict) -> List[Dict]:
    out = []
    for e in instants(trace, "straggler"):
        a = e.get("args") or {}
        out.append({"at_secs": e["ts"] / 1e6,
                    "iteration": a.get("iteration"),
                    "host": a.get("host"), "section": a.get("section"),
                    # multi-slice runs (telemetry schema 4) name the slice
                    # the straggling host belongs to; absent otherwise
                    "slice": a.get("slice"),
                    "secs": a.get("secs"), "median_secs": a.get("median_secs"),
                    "ratio": a.get("ratio")})
    return sorted(out, key=lambda r: r["at_secs"])


def goodput_trend(log_dir: str) -> List[Dict]:
    """Per-boundary goodput_pct from a sibling telemetry.jsonl (empty
    when the stream is absent or predates tracing)."""
    path = os.path.join(log_dir, "telemetry.jsonl") \
        if os.path.isdir(log_dir) else log_dir
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("kind") == "log" and rec.get("goodput_pct") is not None:
                out.append({"iteration": rec.get("iteration"),
                            "goodput_pct": rec["goodput_pct"]})
    return out


def merge_traces(traces: List[Dict],
                 names: Optional[List[str]] = None) -> Dict:
    """Merge N Chrome traces onto one timeline.

    Each SpanTracer trace's timestamps are relative to its own process
    start; ``otherData.trace_start_unix`` anchors that origin to the
    wall clock.  The earliest origin becomes the merged zero, every
    other file's events shift right by its offset, and each file gets a
    distinct pid (with a ``process_name`` metadata row naming it) so
    Perfetto shows one row per process."""
    if not traces:
        raise ValueError("nothing to merge")
    names = names or [f"trace_{i}" for i in range(len(traces))]
    origins = []
    for t in traces:
        o = (t.get("otherData") or {}).get("trace_start_unix")
        origins.append(float(o) if o is not None else None)
    known = [o for o in origins if o is not None]
    base = min(known) if known else 0.0
    events: List[Dict] = []
    for i, (t, name) in enumerate(zip(traces, names)):
        shift_us = ((origins[i] - base) * 1e6
                    if origins[i] is not None else 0.0)
        label = f"p{i}:{os.path.basename(name) or name}"
        events.append({"ph": "M", "name": "process_name", "pid": i,
                       "tid": 0, "args": {"name": label}})
        for e in t.get("traceEvents", []):
            e = dict(e)
            e["pid"] = i
            if e.get("ph") == "M":
                if e.get("name") == "process_name":
                    continue    # replaced by the per-file label above
            else:
                e["ts"] = e.get("ts", 0.0) + shift_us
            events.append(e)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_from": list(names),
            "trace_start_unix": base,
        },
    }


def request_timeline(merged: Dict, trace_id: str) -> List[Dict]:
    """All events carrying a given request trace id, across every merged
    process, in time order — the 'where did this slow request spend its
    time' answer."""
    out = []
    for e in merged.get("traceEvents", []):
        if e.get("ph") == "M":
            continue
        a = e.get("args") or {}
        ids = a.get("traces") if isinstance(a.get("traces"), list) \
            else [a.get("trace")]
        if trace_id not in ids:
            continue
        out.append({"pid": e.get("pid"), "name": e.get("name"),
                    "ph": e.get("ph"), "at_secs": e.get("ts", 0.0) / 1e6,
                    "dur_secs": e.get("dur", 0.0) / 1e6,
                    "args": {k: v for k, v in a.items()
                             if k not in ("trace", "traces", "goodput")}})
    return sorted(out, key=lambda r: r["at_secs"])


def render_timeline(rows: List[Dict], trace_id: str) -> str:
    lines = [f"request {trace_id}: {len(rows)} events"]
    for r in rows:
        extra = (" " + json.dumps(r["args"], sort_keys=True)
                 if r["args"] else "")
        dur = (f" {r['dur_secs'] * 1000:.1f} ms"
               if r["ph"] == "X" else "")
        lines.append(f"  @ {r['at_secs']:9.4f}s p{r['pid']} "
                     f"{r['name']}{dur}{extra}")
    return "\n".join(lines)


def _bar(frac: float) -> str:
    n = int(round(max(min(frac, 1.0), 0.0) * BAR_WIDTH))
    return "#" * n + "." * (BAR_WIDTH - n)


def render(trace: Dict, top_n: int, trend: List[Dict]) -> str:
    lines = []
    g = goodput_breakdown(trace)
    other = trace.get("otherData") or {}
    if g:
        wall = g.get("wall_secs") or 0.0
        lines.append(f"goodput breakdown (wall {wall:.2f}s, goodput "
                     f"{g.get('goodput_pct', 0.0):.1f}%):")
        for cat in GOODPUT_ORDER:
            secs = g.get(f"{cat}_secs", 0.0)
            frac = secs / wall if wall else 0.0
            lines.append(f"  {cat:>10} {secs:9.2f}s {frac * 100:5.1f}% "
                         f"|{_bar(frac)}|")
    else:
        lines.append("(no goodput breakdown in trace)")
    cov = coverage(trace)
    if cov is not None:
        lines.append(f"\nspan coverage of traced wall-clock: "
                     f"{cov * 100:.1f}%")
    dropped = other.get("dropped_events", 0)
    if dropped:
        lines.append(f"dropped events (ring eviction): {dropped} — oldest "
                     f"history is gone; raise --trace_buffer_size")

    tops = top_spans(trace, top_n)
    if tops:
        lines.append(f"\ntop {len(tops)} slowest spans:")
        for s in tops:
            extra = (" " + json.dumps(s["args"], sort_keys=True)
                     if s["args"] else "")
            lines.append(f"  {s['dur_secs'] * 1000:10.1f} ms  "
                         f"{s['name']} [{s['category']}] "
                         f"@ {s['start_secs']:.2f}s{extra}")

    rec = recompile_timeline(trace)
    lines.append(f"\nrecompiles: {other.get('recompiles', len(rec))}")
    for r in rec:
        if r.get("suspected"):
            lines.append(f"  @ {r['at_secs']:.2f}s suspected (step "
                         f"{(r.get('step_secs') or 0.0):.2f}s, outlier "
                         f"heuristic)")
        else:
            lines.append(f"  @ {r['at_secs']:.2f}s backend compile "
                         f"{r['compile_secs']:.2f}s after steady state")

    st = straggler_timeline(trace)
    lines.append(f"\nstraggler events: {other.get('straggler_events', len(st))}")
    for s in st:
        who = (f"slice {s['slice']} host {s['host']}"
               if s.get("slice") is not None else f"host {s['host']}")
        lines.append(f"  iteration {s['iteration']}: {who} "
                     f"{s['section']} {(s['secs'] or 0.0) * 1000:.1f} ms = "
                     f"{(s['ratio'] or 0.0):.2f}x median "
                     f"({(s['median_secs'] or 0.0) * 1000:.1f} ms)")

    if trend:
        lines.append("\ngoodput_pct per log boundary:")
        for t in trend:
            lines.append(f"  iteration {t['iteration']:>8}: "
                         f"{t['goodput_pct']:5.1f}% "
                         f"|{_bar(t['goodput_pct'] / 100.0)}|")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize a span trace (trace.json), or --merge "
                    "several processes' traces onto one timeline")
    ap.add_argument("paths", nargs="+", metavar="path",
                    help="trace.json or the --trace_dir (several with "
                         "--merge)")
    ap.add_argument("--merge", action="store_true",
                    help="merge the given traces (router + replicas) "
                         "onto one Chrome-trace timeline via their "
                         "trace_start_unix anchors")
    ap.add_argument("--out", default=None,
                    help="with --merge: write the merged Chrome trace "
                         "here (loadable in Perfetto)")
    ap.add_argument("--trace", default=None,
                    help="with --merge: print the cross-process timeline "
                         "of this request trace id")
    ap.add_argument("--log_dir", default=None,
                    help="telemetry.jsonl (or its dir) for the per-boundary "
                         "goodput trend; defaults to the trace's own dir")
    ap.add_argument("--top", type=int, default=10,
                    help="how many slowest spans to list")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as one JSON object")
    args = ap.parse_args(argv)

    if len(args.paths) > 1 and not args.merge:
        print("multiple traces require --merge", file=sys.stderr)
        return 2

    if args.merge:
        try:
            traces = [load_trace(p) for p in args.paths]
        except (FileNotFoundError, json.JSONDecodeError) as e:
            print(str(e), file=sys.stderr)
            return 2
        merged = merge_traces(traces, names=args.paths)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(merged, f)
            print(f"merged {len(args.paths)} traces "
                  f"({len(merged['traceEvents'])} events) -> {args.out}")
        if args.trace:
            rows = request_timeline(merged, args.trace)
            if args.json:
                print(json.dumps(rows, indent=1))
            else:
                print(render_timeline(rows, args.trace))
        elif not args.out:
            if args.json:
                print(json.dumps(merged))
            else:
                print(f"merged {len(args.paths)} traces "
                      f"({len(merged['traceEvents'])} events); use --out "
                      f"to save or --trace ID for a request timeline")
        return 0

    try:
        trace = load_trace(args.paths[0])
    except (FileNotFoundError, json.JSONDecodeError) as e:
        print(str(e), file=sys.stderr)
        return 2

    log_dir = args.log_dir
    if log_dir is None:
        log_dir = args.paths[0] if os.path.isdir(args.paths[0]) \
            else os.path.dirname(os.path.abspath(args.paths[0]))
    trend = goodput_trend(log_dir)

    if args.json:
        print(json.dumps({
            "goodput": goodput_breakdown(trace),
            "coverage": coverage(trace),
            "dropped_events":
                (trace.get("otherData") or {}).get("dropped_events", 0),
            "top_spans": top_spans(trace, args.top),
            "recompile_timeline": recompile_timeline(trace),
            "straggler_timeline": straggler_timeline(trace),
            "goodput_trend": trend,
        }, indent=1))
        return 0

    print(render(trace, args.top, trend))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:         # e.g. piped into head
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
