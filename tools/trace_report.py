#!/usr/bin/env python
"""Summarize a run's span trace (trace.json) + telemetry stream.

Reads the Chrome ``trace_event`` JSON written by ``--trace_dir``
(megatron_llm_tpu/tracing.py) — the same file Perfetto loads — and
prints:

* a goodput breakdown — wall-clock attributed to productive-step /
  compile / checkpoint / eval / rewind / data-stall / other, with
  ``goodput_pct`` and a bar chart
* span coverage — how much of the traced wall-clock any span accounts
  for (the acceptance bar is >= 95%)
* the top-N slowest spans (the root ``train`` span excluded — it always
  "wins")
* a recompile timeline — every steady-state backend compile, timestamped
* a straggler timeline — per-host straggler events (which host, which
  section, how far past the median)

When the sibling ``telemetry.jsonl`` (``--structured_log_dir``) exists,
the per-boundary ``goodput_pct`` trend is appended.

Pure stdlib — no jax import, runs anywhere the files do.

Usage:
    python tools/trace_report.py TRACE_DIR_OR_JSON [--top N] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

GOODPUT_ORDER = ("step", "compile", "checkpoint", "eval", "rewind", "data",
                 "other")
BAR_WIDTH = 40


def load_trace(path: str) -> Dict:
    """Accept a trace.json file or the --trace_dir holding one."""
    if os.path.isdir(path):
        path = os.path.join(path, "trace.json")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no trace at {path}")
    with open(path) as f:
        return json.load(f)


def spans(trace: Dict) -> List[Dict]:
    """The complete ('X') events, sorted by start time."""
    evs = [e for e in trace.get("traceEvents", []) if e.get("ph") == "X"]
    return sorted(evs, key=lambda e: e.get("ts", 0.0))


def instants(trace: Dict, name: Optional[str] = None) -> List[Dict]:
    return [e for e in trace.get("traceEvents", [])
            if e.get("ph") == "i" and (name is None or e.get("name") == name)]


def coverage(trace: Dict) -> Optional[float]:
    """Fraction of the traced wall-clock covered by at least one span:
    union of [ts, ts+dur) intervals over the trace's own extent.  None
    when the trace holds no spans."""
    xs = spans(trace)
    if not xs:
        return None
    intervals = sorted((e["ts"], e["ts"] + e.get("dur", 0.0)) for e in xs)
    lo = intervals[0][0]
    hi = max(end for _, end in intervals)
    if hi <= lo:
        return None
    covered, cur_start, cur_end = 0.0, intervals[0][0], intervals[0][1]
    for start, end in intervals[1:]:
        if start > cur_end:
            covered += cur_end - cur_start
            cur_start, cur_end = start, end
        else:
            cur_end = max(cur_end, end)
    covered += cur_end - cur_start
    return covered / (hi - lo)


def goodput_breakdown(trace: Dict) -> Optional[Dict]:
    return (trace.get("otherData") or {}).get("goodput")


def top_spans(trace: Dict, n: int = 10) -> List[Dict]:
    """Slowest spans by duration; the root 'train' span excluded."""
    xs = [e for e in spans(trace) if e.get("name") != "train"]
    xs.sort(key=lambda e: e.get("dur", 0.0), reverse=True)
    return [{"name": e["name"], "category": e.get("cat", "?"),
             "start_secs": e["ts"] / 1e6, "dur_secs": e.get("dur", 0.0) / 1e6,
             "args": {k: v for k, v in (e.get("args") or {}).items()
                      if k != "goodput"}}
            for e in xs[:n]]


def recompile_timeline(trace: Dict) -> List[Dict]:
    out = []
    for e in spans(trace):
        if e.get("name") == "recompile":
            out.append({"at_secs": e["ts"] / 1e6,
                        "compile_secs": e.get("dur", 0.0) / 1e6})
    for e in instants(trace, "suspected_recompile"):
        out.append({"at_secs": e["ts"] / 1e6, "suspected": True,
                    "step_secs": (e.get("args") or {}).get("step_secs")})
    return sorted(out, key=lambda r: r["at_secs"])


def straggler_timeline(trace: Dict) -> List[Dict]:
    out = []
    for e in instants(trace, "straggler"):
        a = e.get("args") or {}
        out.append({"at_secs": e["ts"] / 1e6,
                    "iteration": a.get("iteration"),
                    "host": a.get("host"), "section": a.get("section"),
                    # multi-slice runs (telemetry schema 4) name the slice
                    # the straggling host belongs to; absent otherwise
                    "slice": a.get("slice"),
                    "secs": a.get("secs"), "median_secs": a.get("median_secs"),
                    "ratio": a.get("ratio")})
    return sorted(out, key=lambda r: r["at_secs"])


def goodput_trend(log_dir: str) -> List[Dict]:
    """Per-boundary goodput_pct from a sibling telemetry.jsonl (empty
    when the stream is absent or predates tracing)."""
    path = os.path.join(log_dir, "telemetry.jsonl") \
        if os.path.isdir(log_dir) else log_dir
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("kind") == "log" and rec.get("goodput_pct") is not None:
                out.append({"iteration": rec.get("iteration"),
                            "goodput_pct": rec["goodput_pct"]})
    return out


def _bar(frac: float) -> str:
    n = int(round(max(min(frac, 1.0), 0.0) * BAR_WIDTH))
    return "#" * n + "." * (BAR_WIDTH - n)


def render(trace: Dict, top_n: int, trend: List[Dict]) -> str:
    lines = []
    g = goodput_breakdown(trace)
    other = trace.get("otherData") or {}
    if g:
        wall = g.get("wall_secs") or 0.0
        lines.append(f"goodput breakdown (wall {wall:.2f}s, goodput "
                     f"{g.get('goodput_pct', 0.0):.1f}%):")
        for cat in GOODPUT_ORDER:
            secs = g.get(f"{cat}_secs", 0.0)
            frac = secs / wall if wall else 0.0
            lines.append(f"  {cat:>10} {secs:9.2f}s {frac * 100:5.1f}% "
                         f"|{_bar(frac)}|")
    else:
        lines.append("(no goodput breakdown in trace)")
    cov = coverage(trace)
    if cov is not None:
        lines.append(f"\nspan coverage of traced wall-clock: "
                     f"{cov * 100:.1f}%")
    dropped = other.get("dropped_events", 0)
    if dropped:
        lines.append(f"dropped events (ring eviction): {dropped} — oldest "
                     f"history is gone; raise --trace_buffer_size")

    tops = top_spans(trace, top_n)
    if tops:
        lines.append(f"\ntop {len(tops)} slowest spans:")
        for s in tops:
            extra = (" " + json.dumps(s["args"], sort_keys=True)
                     if s["args"] else "")
            lines.append(f"  {s['dur_secs'] * 1000:10.1f} ms  "
                         f"{s['name']} [{s['category']}] "
                         f"@ {s['start_secs']:.2f}s{extra}")

    rec = recompile_timeline(trace)
    lines.append(f"\nrecompiles: {other.get('recompiles', len(rec))}")
    for r in rec:
        if r.get("suspected"):
            lines.append(f"  @ {r['at_secs']:.2f}s suspected (step "
                         f"{(r.get('step_secs') or 0.0):.2f}s, outlier "
                         f"heuristic)")
        else:
            lines.append(f"  @ {r['at_secs']:.2f}s backend compile "
                         f"{r['compile_secs']:.2f}s after steady state")

    st = straggler_timeline(trace)
    lines.append(f"\nstraggler events: {other.get('straggler_events', len(st))}")
    for s in st:
        who = (f"slice {s['slice']} host {s['host']}"
               if s.get("slice") is not None else f"host {s['host']}")
        lines.append(f"  iteration {s['iteration']}: {who} "
                     f"{s['section']} {(s['secs'] or 0.0) * 1000:.1f} ms = "
                     f"{(s['ratio'] or 0.0):.2f}x median "
                     f"({(s['median_secs'] or 0.0) * 1000:.1f} ms)")

    if trend:
        lines.append("\ngoodput_pct per log boundary:")
        for t in trend:
            lines.append(f"  iteration {t['iteration']:>8}: "
                         f"{t['goodput_pct']:5.1f}% "
                         f"|{_bar(t['goodput_pct'] / 100.0)}|")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize a span trace (trace.json)")
    ap.add_argument("path", help="trace.json or the --trace_dir")
    ap.add_argument("--log_dir", default=None,
                    help="telemetry.jsonl (or its dir) for the per-boundary "
                         "goodput trend; defaults to the trace's own dir")
    ap.add_argument("--top", type=int, default=10,
                    help="how many slowest spans to list")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as one JSON object")
    args = ap.parse_args(argv)

    try:
        trace = load_trace(args.path)
    except (FileNotFoundError, json.JSONDecodeError) as e:
        print(str(e), file=sys.stderr)
        return 2

    log_dir = args.log_dir
    if log_dir is None:
        log_dir = args.path if os.path.isdir(args.path) \
            else os.path.dirname(os.path.abspath(args.path))
    trend = goodput_trend(log_dir)

    if args.json:
        print(json.dumps({
            "goodput": goodput_breakdown(trace),
            "coverage": coverage(trace),
            "dropped_events":
                (trace.get("otherData") or {}).get("dropped_events", 0),
            "top_spans": top_spans(trace, args.top),
            "recompile_timeline": recompile_timeline(trace),
            "straggler_timeline": straggler_timeline(trace),
            "goodput_trend": trend,
        }, indent=1))
        return 0

    print(render(trace, args.top, trend))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:         # e.g. piped into head
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
