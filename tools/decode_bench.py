"""Decode (serving) throughput benchmark.

Training MFU is covered by bench.py / mfu_sweep.py; this measures the
generation stack (tools/run_text_generation_server.py's engine):
prefill throughput and steady-state decode tokens/s on the same ~650M
bench shape, greedy, jitted while-loop decode with the KV cache.

The decode rate is isolated by differencing two runs (gen N and gen 2N
tokens from the same prompts): decode_tps = b*N / (t_2N - t_N) — the
shared prefill and fixed overheads cancel, so neither needs to be
timed separately.

    python tools/decode_bench.py            # 650M, TPU shape
    python tools/decode_bench.py --preset tiny   # CPU / CI

Usage mirrors mfu_sweep: one line per trial.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from tools.bench_harness import BENCH_SHAPE, enable_compile_cache, make_cfg

import jax
import jax.numpy as jnp
import numpy as np

PRESETS = {
    "bench": dict(**BENCH_SHAPE, vocab=32000,
                  prompt=128, gen=256, batches=(1, 8)),
    "tiny": dict(L=2, h=128, heads=4, ffn=352, vocab=512,
                 prompt=16, gen=8, batches=(2,)),
}


def run_trial(model, params, b, prompt, gen, vocab, kv_int8=False):
    from megatron_llm_tpu.text_generation.generation import generate_tokens
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(1, vocab, (b, prompt)))
    lens = jnp.full((b,), prompt, jnp.int32)
    key = jax.random.PRNGKey(0)

    # both runs use the SAME cache allocation (prompt + 2*gen): decode
    # masks the unused tail, so per-step cost is identical between the
    # gen-N and gen-2N runs and the differencing below is unbiased
    cache = prompt + 2 * gen

    def timed(n_new):
        # compile (first call per n_new) then measure
        out = generate_tokens(model, params, toks, lens, key,
                              max_new_tokens=n_new, min_prompt_len=prompt,
                              greedy=True, cache_len=cache,
                              int8_kv_cache=kv_int8)
        float(out[1].sum())  # host sync (axon: block_until_ready can lie)
        t0 = time.perf_counter()
        out = generate_tokens(model, params, toks, lens, key,
                              max_new_tokens=n_new, min_prompt_len=prompt,
                              greedy=True, cache_len=cache,
                              int8_kv_cache=kv_int8)
        float(out[1].sum())
        return time.perf_counter() - t0

    t1 = timed(gen)
    t2 = timed(2 * gen)
    e2e_tps = b * 2 * gen / t2
    tag = " kv-int8" if kv_int8 else ""
    if t2 - t1 < 0.05 * t2:
        # the N extra decode steps are inside run-to-run jitter: a
        # differenced rate would be noise presented as signal
        print(f"b={b:3d} prompt={prompt} gen={2*gen}{tag}: decode   INVALID "
              f"(t2-t1 jitter) | e2e {e2e_tps:9.1f} tok/s "
              f"(t={t2*1000:.0f} ms)", flush=True)
        return
    decode_tps = b * gen / (t2 - t1)
    print(f"b={b:3d} prompt={prompt} gen={2*gen}{tag}: "
          f"decode {decode_tps:9.1f} tok/s | e2e {e2e_tps:9.1f} tok/s "
          f"(t={t2*1000:.0f} ms)", flush=True)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--preset", choices=sorted(PRESETS), default="bench")
    args = ap.parse_args()
    enable_compile_cache()

    p = PRESETS[args.preset]
    on_tpu = jax.default_backend() == "tpu"
    seq_budget = p["prompt"] + 2 * p["gen"]
    cfg = make_cfg(L=p["L"], h=p["h"], heads=p["heads"], ffn=p["ffn"],
                   vocab=p["vocab"], seq=max(seq_budget, 128),
                   flash=False,  # decode is seq-1 steps: flash is a
                   fused_rms=on_tpu)  # prefill-only win, keep it simple
    from megatron_llm_tpu.models.llama import LlamaModel
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    n = model.num_params(params)
    print(f"decode_bench: {n/1e6:.0f}M params, backend="
          f"{jax.default_backend()}", flush=True)
    for b in p["batches"]:
        run_trial(model, params, b, p["prompt"], p["gen"], p["vocab"])
    # weight-only int8 A/B: decode re-reads every dense weight per
    # token, so halving those bytes targets the decode bandwidth bound
    from megatron_llm_tpu.quantization import quantize_linear_weights_int8
    qparams = quantize_linear_weights_int8(params)
    print("decode_bench: int8 weight-only quantized kernels", flush=True)
    for b in p["batches"]:
        run_trial(model, qparams, b, p["prompt"], p["gen"], p["vocab"])
    # int8 KV cache on top of int8 weights: fully int8 decode bytes
    print("decode_bench: + int8 KV cache", flush=True)
    for b in p["batches"]:
        run_trial(model, qparams, b, p["prompt"], p["gen"], p["vocab"],
                  kv_int8=True)
    # speculative prompt-lookup A/B on a repetitive prompt (the
    # favorable case: summarization/code-edit-like repetition) —
    # exactness is covered by tests/test_serving_engine.py's greedy
    # parity test, this measures the accepted-draft speedup through the
    # engine's fixed-shape K+1 verify step
    run_spec_trial(model, params, p["prompt"], p["gen"], p["vocab"])


def run_spec_trial(model, params, prompt, gen, vocab, draft_k=4):
    from megatron_llm_tpu.serving import EngineConfig, InferenceEngine
    from megatron_llm_tpu.serving.request import SamplingParams
    rng = np.random.RandomState(1)
    pattern = rng.randint(1, vocab, max(prompt // 4, 2))
    toks = [int(t) for t in np.tile(pattern, prompt // len(pattern) + 1)
            [:prompt]]
    sp = SamplingParams(max_new_tokens=2 * gen, temperature=0.0)

    def timed(speculative):
        eng = InferenceEngine(model, params, EngineConfig(
            num_slots=1, block_size=16,
            prefill_chunk=max(prompt, 16),
            max_model_len=prompt + 2 * gen + draft_k,
            default_deadline_secs=0.0,
            speculative=speculative, draft_k=draft_k))
        eng.warmup()
        eng.start()
        try:
            eng.submit(toks, sp).result(timeout=600)  # warm run
            t0 = time.perf_counter()
            r = eng.submit(toks, sp).result(timeout=600)
            dt = time.perf_counter() - t0
            return dt, len(r.out_tokens), eng.stats()
        finally:
            eng.stop()

    t_van, n_van, _ = timed(False)
    t_spec, n_spec, stats = timed(True)
    drafted = stats.get("drafted_tokens") or 0
    accepted = stats.get("accepted_tokens") or 0
    rate = f"{accepted / drafted:.2f}" if drafted else "-"
    print(f"b=  1 prompt={prompt} gen={2*gen} (repetitive): "
          f"greedy {n_van/t_van:9.1f} tok/s | speculative[K+1={draft_k+1}] "
          f"{n_spec/t_spec:9.1f} tok/s ({t_van/t_spec:.2f}x, "
          f"accept {rate})", flush=True)


if __name__ == "__main__":
    main()
