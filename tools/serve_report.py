#!/usr/bin/env python
"""Offline analyzer for serving telemetry JSONL (request_done records).

Reads the ``kind: "serve", event: "request_done"`` records a serving
replica writes into its ``--structured_log_dir`` (telemetry schema >= 5:
trace_id, per-request phase attribution, tpot_secs) and prints:

* latency percentiles — e2e / TTFT / TPOT p50/p95/p99 over every
  finished request (the offline twin of the live ``/metrics``
  histograms, but exact: computed from raw values, not buckets)
* a phase breakdown — where request wall-clock went: queue wait,
  admission, prefill compute, amortized decode, stream write; mean
  seconds per request and share of mean e2e latency
* SLO attainment — the fraction of requests meeting configurable TTFT
  (``--ttft_slo``) and TPOT (``--tpot_slo``) targets, individually and
  jointly (the Gemma-on-TPU serving framing: "X% of requests within
  TTFT <= a and TPOT <= b")
* prefill throughput — computed-prefill tokens per second of prefill
  compute, attributed to the attention path (``prefill_kernel``) that
  served them, next to the TTFT numbers it drives
* speculative-decoding summary — fleet accept rate (accepted vs
  drafted tokens, schema >= 8) and mean TPOT for drafting vs plain
  requests: what the PR 14 prompt-lookup speculation bought end-to-end
* cache-hit stratification — the same latency table split by whether
  the request adopted prefix-cache pages (``cached_prompt_tokens > 0``),
  quantifying what the PR 6 prefix cache is worth end-to-end
* engine-loop goodput — ``engine_loop_stats`` rollups (telemetry
  schema >= 10, serving/loop_profiler.py): per-phase share of dispatch
  wall-clock (schedule / draft / build_inputs / device / emit),
  device-busy vs host-bubble percent, the windowed bubble trend, and
  the dispatch-gap stall count — the offline twin of ``/metrics``'
  ``engine.loop`` block; absent (and the report unchanged) on logs
  written before schema 10
* cache observatory — ``cache_stats`` rollups (telemetry schema >= 11,
  serving/cache_observatory.py): the per-prefix heat top-K (salted
  digests only — never token ids), the miss-cause breakdown (cold vs
  evicted-then-wanted-again regret), eviction forensics (capacity vs
  churn), and the ghost capacity projection — per simulated tier
  (2x/4x/10x the block pool) the exact hit rate a bigger cache would
  have had on this trace plus the projected TTFT savings at the log's
  measured prefill throughput; absent on logs before schema 11
* host spill tier — hierarchical KV cache rollups (telemetry schema
  >= 12, serving/host_cache.py): host-tier hit share of the two-tier
  rate, spill/eviction/swap-in volume, the two-tier hit rate compared
  against the ghost projections it realizes, and the TTFT saved per
  request net of the measured host->device swap-in time
* per-replica comparison — pass several JSONL files/dirs (one per
  replica) and each gets its own column plus the fleet total
* fleet-event timeline — supervisor events (``kind: "fleet"``, schema
  >= 7: replica_spawned/died/respawned, scale_up/down, brownout) from a
  serve log or a ``tools/serve_fleet.py --fleet_event_log`` JSONL,
  rendered as counters plus a chronological timeline
* incident timeline — ``alert_transition`` records (telemetry schema
  >= 13, serving/alerts.py) reconstructed into firing->resolved
  incidents, each correlated with the fleet events and engine restarts
  that happened inside its window (±30s) and pointing at its
  postmortem bundle directory

Pure stdlib — no jax import, runs anywhere the files do.

Usage:
    python tools/serve_report.py LOG_DIR_OR_JSONL [more...] \\
        [--ttft_slo SECS] [--tpot_slo SECS] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

STREAM_FILENAME = "telemetry.jsonl"     # mirrors telemetry.STREAM_FILENAME

PHASE_KEYS = ("queue_secs", "admission_secs", "prefill_secs",
              "decode_secs", "stream_write_secs")

# engine-loop host phases; mirrors loop_profiler.LOOP_PHASES (this tool
# must not import jax-adjacent modules)
LOOP_PHASE_KEYS = ("schedule", "draft", "build_inputs", "device", "emit")


RESILIENCE_EVENTS = ("engine_restart", "preemption", "drain")

# supervisor control-loop events (kind "fleet", schema >= 7); the order
# here is the counter order in the report
FLEET_EVENTS = ("replica_spawned", "replica_died", "replica_respawned",
                "scale_up", "scale_down", "brownout",
                "router_spawned", "router_died", "router_respawned",
                "router_scale_up", "router_scale_down")


def load_records(path: str) -> List[Dict]:
    """request_done records from a telemetry.jsonl (or its dir)."""
    return _load(path)[0]


def load_resilience_events(path: str) -> List[Dict]:
    """engine_restart / preemption / drain events from a serve log."""
    return _load(path)[1]


def load_fleet_events(path: str) -> List[Dict]:
    """Supervisor fleet events (scale_up / replica_died / ...) from a
    serve log or a --fleet_event_log JSONL."""
    return _load(path)[2]


def load_loop_stats(path: str) -> List[Dict]:
    """engine_loop_stats rollups (telemetry schema >= 10) from a serve
    log, in file order (cumulative per engine lifetime)."""
    return _load(path)[3]


def load_cache_stats(path: str) -> List[Dict]:
    """cache_stats rollups (telemetry schema >= 11) from a serve log,
    in file order (cumulative per engine lifetime)."""
    return _load(path)[4]


def load_alert_transitions(path: str) -> List[Dict]:
    """alert_transition records (telemetry schema >= 13) from a serve
    log — replica-scope (kind serve) and fleet-scope (kind fleet)."""
    return _load(path)[5]


def _load(path: str):
    if os.path.isdir(path):
        path = os.path.join(path, STREAM_FILENAME)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no serve log at {path}")
    records, events, fleet, loop, cache, alerts = [], [], [], [], [], []
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            # alert transitions ride both kinds: "serve" from the
            # replica sentinel, "fleet" from the supervisor's
            # merged-histogram engine (serving/alerts.py)
            if rec.get("event") == "alert_transition":
                alerts.append(rec)
                continue
            if rec.get("kind") == "fleet" \
                    and rec.get("event") in FLEET_EVENTS:
                fleet.append(rec)
                continue
            if rec.get("kind") != "serve":
                continue
            if rec.get("event") == "request_done":
                records.append(rec)
            elif rec.get("event") == "engine_loop_stats":
                loop.append(rec)
            elif rec.get("event") == "cache_stats":
                cache.append(rec)
            elif rec.get("event") in RESILIENCE_EVENTS:
                events.append(rec)
    return records, events, fleet, loop, cache, alerts


def _percentile(values: List[float], q: float) -> Optional[float]:
    # nearest-rank with rounding — same estimator as tools/serve_bench.py
    # so the two tools agree on identical samples
    if not values:
        return None
    s = sorted(values)
    return s[min(int(q * (len(s) - 1) + 0.5), len(s) - 1)]


def _vals(records: List[Dict], key: str) -> List[float]:
    return [r[key] for r in records
            if isinstance(r.get(key), (int, float))]


def latency_summary(records: List[Dict]) -> Dict:
    out: Dict[str, object] = {"requests": len(records)}
    for key, name in (("latency_secs", "e2e"), ("ttft_secs", "ttft"),
                      ("tpot_secs", "tpot")):
        vals = _vals(records, key)
        out[f"{name}_mean_secs"] = (sum(vals) / len(vals)
                                    if vals else None)
        for q, tag in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
            out[f"{name}_{tag}_secs"] = _percentile(vals, q)
    return out


def phase_breakdown(records: List[Dict]) -> Dict:
    """Mean seconds per phase and its share of mean e2e latency.  The
    phases need not sum to e2e (decode is amortized; the gap is
    scheduling slack + result pickup), so ``unattributed`` closes the
    account."""
    e2e = _vals(records, "latency_secs")
    mean_e2e = sum(e2e) / len(e2e) if e2e else 0.0
    out: Dict[str, object] = {"mean_e2e_secs": mean_e2e or None}
    attributed = 0.0
    for key in PHASE_KEYS:
        vals = [p[key] for p in (r.get("phases") or {} for r in records)
                if isinstance(p.get(key), (int, float))]
        mean = sum(vals) / len(vals) if vals else 0.0
        attributed += mean
        out[key] = {"mean_secs": mean,
                    "share": (mean / mean_e2e) if mean_e2e else None}
    out["unattributed_secs"] = max(mean_e2e - attributed, 0.0) \
        if mean_e2e else None
    return out


def slo_attainment(records: List[Dict], ttft_slo: float,
                   tpot_slo: float) -> Dict:
    """Fraction of finished requests meeting each target.  A request
    with no measurement for a dimension (e.g. tpot on a 1-token answer)
    counts as meeting it — it cannot have violated it."""
    n = len(records)

    def ok(rec, key, target):
        v = rec.get(key)
        return not isinstance(v, (int, float)) or v <= target

    ttft_ok = sum(ok(r, "ttft_secs", ttft_slo) for r in records)
    tpot_ok = sum(ok(r, "tpot_secs", tpot_slo) for r in records)
    both = sum(ok(r, "ttft_secs", ttft_slo)
               and ok(r, "tpot_secs", tpot_slo) for r in records)
    return {
        "ttft_slo_secs": ttft_slo,
        "tpot_slo_secs": tpot_slo,
        "ttft_attained": (ttft_ok / n) if n else None,
        "tpot_attained": (tpot_ok / n) if n else None,
        "joint_attained": (both / n) if n else None,
    }


def prefill_summary(records: List[Dict]) -> Dict:
    """Computed-prefill throughput: tokens actually pushed through the
    chunked-prefill attention path per second of prefill compute (the
    offline twin of serve_bench's prefill tokens/sec), plus which
    attention path ('pallas'|'xla') served each request so an A/B over
    ``--serve_prefill_kernel`` stays attributable after the fact."""
    toks = sum(r.get("prefill_computed_tokens") or 0 for r in records)
    secs = sum(p["prefill_secs"]
               for p in (r.get("phases") or {} for r in records)
               if isinstance(p.get("prefill_secs"), (int, float)))
    kernels: Dict[str, int] = {}
    for r in records:
        k = r.get("prefill_kernel")
        if k:
            kernels[k] = kernels.get(k, 0) + 1
    # hierarchical KV cache (schema >= 12): blocks served out of host
    # RAM instead of recomputed, and what the swap-in scatters cost
    host_blocks = sum(r.get("host_hit_blocks") or 0 for r in records)
    swap_secs = sum(r.get("swap_in_secs") or 0 for r in records)
    swapping = sum(1 for r in records if (r.get("host_hit_blocks") or 0))
    return {
        "computed_tokens": toks,
        "compute_secs": secs,
        "tokens_per_sec": (toks / secs) if secs > 0 else None,
        "kernel": kernels,
        "host_hit_blocks": host_blocks,
        "swap_in_secs": swap_secs,
        "requests_swapping": swapping,
    }


def speculative_summary(records: List[Dict]) -> Dict:
    """Speculative-decoding effectiveness (telemetry schema >= 8):
    fleet accept rate (total accepted / total drafted), the
    accepted-vs-drafted token totals, how many requests actually
    drafted, and the mean TPOT split by whether the request drafted —
    the offline answer to "what did speculation buy us"."""
    drafted = sum(r.get("drafted_tokens") or 0 for r in records)
    accepted = sum(r.get("accepted_tokens") or 0 for r in records)
    spec = [r for r in records if (r.get("drafted_tokens") or 0) > 0]
    plain = [r for r in records if (r.get("drafted_tokens") or 0) == 0]

    def mean_tpot(rs):
        vals = _vals(rs, "tpot_secs")
        return sum(vals) / len(vals) if vals else None

    return {
        "drafted_tokens": drafted,
        "accepted_tokens": accepted,
        "accept_rate": (accepted / drafted) if drafted > 0 else None,
        "requests_drafting": len(spec),
        "tpot_mean_secs_drafting": mean_tpot(spec),
        "tpot_mean_secs_plain": mean_tpot(plain),
    }


def loop_goodput_summary(per_path: List[List[Dict]]) -> Dict:
    """Engine-loop goodput from ``engine_loop_stats`` rollups: where
    dispatch wall-clock went per host phase, device-busy vs host-bubble
    percent, the windowed bubble trend, and dispatch-gap stall count.

    Rollups are cumulative per engine lifetime, so totals come from
    each log's final record; the trend samples every record's recent
    window (``window.host_bubble_pct``)."""
    totals = {"dispatches": 0, "wall_secs": 0.0, "gap_secs": 0.0,
              "device_secs": 0.0, "stalls": 0}
    phase_secs = {k: 0.0 for k in LOOP_PHASE_KEYS}
    for recs in per_path:
        if not recs:
            continue
        final = recs[-1]
        for key in totals:
            v = final.get(key)
            if isinstance(v, (int, float)):
                totals[key] += v
        ph = final.get("phase_secs") or {}
        for key in LOOP_PHASE_KEYS:
            if isinstance(ph.get(key), (int, float)):
                phase_secs[key] += ph[key]
    busy = totals["wall_secs"] + totals["gap_secs"]
    device_busy = (100.0 * min(totals["device_secs"] / busy, 1.0)
                   if busy > 0 else None)
    out: Dict[str, object] = {
        **totals,
        "phase_secs": phase_secs,
        "phase_share": {
            key: (phase_secs[key] / totals["wall_secs"]
                  if totals["wall_secs"] > 0 else None)
            for key in LOOP_PHASE_KEYS},
        "device_busy_pct": device_busy,
        "host_bubble_pct": (100.0 - device_busy
                            if device_busy is not None else None),
    }
    # windowed host-bubble trend, chronological across all logs
    samples = []
    for recs in per_path:
        for rec in recs:
            b = (rec.get("window") or {}).get("host_bubble_pct")
            if not isinstance(b, (int, float)):
                continue
            t = rec.get("time_unix")
            samples.append((t if isinstance(t, (int, float)) else 0.0, b))
    samples.sort()
    t0 = samples[0][0] if samples else None
    out["bubble_trend"] = [
        {"t_secs": round(t - t0, 3), "host_bubble_pct": round(b, 3)}
        for t, b in samples]
    vals = [b for _, b in samples]
    out["bubble_window_p50_pct"] = _percentile(vals, 0.50)
    out["bubble_window_p95_pct"] = _percentile(vals, 0.95)
    return out


CACHE_COUNTER_KEYS = ("match_calls", "probes", "hits", "misses",
                      "hit_tokens", "miss_cold", "miss_evicted",
                      "evictions_capacity", "evictions_churn",
                      "pool_resets", "inclusion_divergences",
                      "host_hits", "host_hit_tokens", "swap_in_blocks")

# host spill tier counters summed from each log's final cache_stats
# record's ``host`` sub-block (telemetry schema >= 12)
_HOST_TIER_KEYS = ("spills_completed", "spills_dropped", "evictions",
                   "swap_ins", "swap_in_secs")

# heat-table counters summed on fleet merge; mirrors
# serving/cache_observatory.py merge_heat_tops (stdlib re-implementation)
_HEAT_SUM_KEYS = ("hits", "hit_tokens", "residency", "evictions",
                  "regret")


def _merge_heat(tables: List[List[Dict]], k: int = 16) -> List[Dict]:
    merged: Dict[str, Dict] = {}
    for table in tables:
        if not isinstance(table, (list, tuple)):
            continue
        for e in table:
            if not isinstance(e, dict) or "prefix" not in e:
                continue
            cur = merged.get(e["prefix"])
            if cur is None:
                merged[e["prefix"]] = dict(e)
                continue
            for f in _HEAT_SUM_KEYS:
                cur[f] = (cur.get(f) or 0) + (e.get(f) or 0)
            cur["peak_refcount"] = max(cur.get("peak_refcount") or 0,
                                       e.get("peak_refcount") or 0)
    out = sorted(merged.values(),
                 key=lambda e: (-(e.get("hits") or 0)))
    return out[:k]


def cache_observatory_summary(per_path: List[List[Dict]],
                              prefill: Dict,
                              requests: int = 0) -> Dict:
    """Cache observatory rollup from ``cache_stats`` records: counters
    are cumulative per engine lifetime, so totals come from each log's
    final record; heat tables merge by salted prefix (fleet-wide when
    the replicas share MEGATRON_CACHE_SALT).

    The ghost capacity projection prices each simulated tier's extra
    hit tokens at the log's measured prefill throughput: the prefill
    seconds (≈ TTFT) a 2x/4x/10x pool would have saved on this trace."""
    totals = {key: 0 for key in CACHE_COUNTER_KEYS}
    host_totals = {key: 0 for key in _HOST_TIER_KEYS}
    host_enabled = False
    ghost: Dict[str, Dict] = {}
    heat_tables = []
    for recs in per_path:
        if not recs:
            continue
        final = recs[-1]
        for key in CACHE_COUNTER_KEYS:
            v = final.get(key)
            if isinstance(v, (int, float)):
                totals[key] += v
        h = final.get("host")
        if isinstance(h, dict) and h.get("enabled"):
            host_enabled = True
            for key in _HOST_TIER_KEYS:
                v = h.get(key)
                if isinstance(v, (int, float)):
                    host_totals[key] += v
        heat_tables.append(final.get("heat_top") or [])
        for tier, t in (final.get("ghost") or {}).items():
            if not isinstance(t, dict):
                continue
            g = ghost.setdefault(tier, {"hits": 0, "misses": 0,
                                        "hit_tokens": 0, "evictions": 0,
                                        "capacity_blocks": 0})
            for key in g:
                v = t.get(key)
                if isinstance(v, (int, float)):
                    g[key] += v
    probes = totals["probes"]
    out: Dict[str, object] = {
        **totals,
        "hit_rate": (totals["hits"] / probes) if probes else None,
        "heat_top": _merge_heat(heat_tables),
    }
    prefill_tps = (prefill or {}).get("tokens_per_sec")
    tiers = {}
    for tier, g in ghost.items():
        t_probes = g["hits"] + g["misses"]
        extra_tokens = max(g["hit_tokens"] - totals["hit_tokens"], 0)
        saved = (extra_tokens / prefill_tps
                 if prefill_tps else None)
        tiers[tier] = {
            **g,
            "hit_rate": (g["hits"] / t_probes) if t_probes else None,
            "extra_hit_tokens": extra_tokens,
            "prefill_saved_secs_total": saved,
            "ttft_saved_secs_per_request": (
                saved / requests if saved is not None and requests
                else None),
        }
    out["ghost"] = dict(sorted(
        tiers.items(), key=lambda kv: kv[1]["capacity_blocks"]))
    # host spill tier: the realized two-tier rate the ghost tiers only
    # project, with the hit tokens priced at prefill throughput NET of
    # the measured host->device swap-in time (a ghost hit is free; a
    # host hit costs one scatter)
    out["host_tier"] = None
    if host_enabled:
        host_hits = totals["host_hits"]
        saved = (totals["host_hit_tokens"] / prefill_tps
                 if prefill_tps else None)
        net = (saved - host_totals["swap_in_secs"]
               if saved is not None else None)
        out["host_tier"] = {
            **host_totals,
            "hits": host_hits,
            "hit_tokens": totals["host_hit_tokens"],
            "hit_rate": (host_hits / probes) if probes else None,
            "hbm_hit_rate": ((totals["hits"] - host_hits) / probes)
            if probes else None,
            "prefill_saved_secs_total": saved,
            "net_saved_secs_total": net,
            "ttft_saved_secs_per_request": (
                net / requests if net is not None and requests
                else None),
        }
    return out


def cache_stratified(records: List[Dict]) -> Dict:
    hits = [r for r in records
            if (r.get("cached_prompt_tokens") or 0) > 0]
    misses = [r for r in records
              if (r.get("cached_prompt_tokens") or 0) == 0]
    return {"cache_hit": latency_summary(hits),
            "cache_miss": latency_summary(misses)}


def analyze(paths: List[str], ttft_slo: float = 1.0,
            tpot_slo: float = 0.25) -> Dict:
    """Full report over one or more replicas' serve logs."""
    per_replica: Dict[str, Dict] = {}
    all_records: List[Dict] = []
    all_events: List[Dict] = []
    all_fleet: List[Dict] = []
    loop_per_path: List[List[Dict]] = []
    cache_per_path: List[List[Dict]] = []
    all_alerts: List[Dict] = []
    for p in paths:
        records, events, fleet, loop, cache, alerts = _load(p)
        all_records.extend(records)
        all_events.extend(events)
        all_fleet.extend(fleet)
        loop_per_path.append(loop)
        cache_per_path.append(cache)
        all_alerts.extend(alerts)
        if len(paths) > 1:
            per_replica[p] = {
                **latency_summary(records),
                "slo": slo_attainment(records, ttft_slo, tpot_slo),
            }
    out = {
        "paths": list(paths),
        "summary": latency_summary(all_records),
        "phases": phase_breakdown(all_records),
        "slo": slo_attainment(all_records, ttft_slo, tpot_slo),
        "prefill": prefill_summary(all_records),
        "speculative": speculative_summary(all_records),
        "by_cache": cache_stratified(all_records),
        "finish_reasons": {},
        "traced": sum(1 for r in all_records if r.get("trace_id")),
        # resilience activity over the same window (engine restarts with
        # their requeue/fail split, pool-pressure preemptions, drains,
        # and sentinel slot evictions from the finish_reason stream)
        "resilience": {
            "engine_restarts": sum(
                e.get("event") == "engine_restart" for e in all_events),
            "restart_requeued": sum(
                e.get("requeued") or 0 for e in all_events
                if e.get("event") == "engine_restart"),
            "restart_failed": sum(
                e.get("failed") or 0 for e in all_events
                if e.get("event") == "engine_restart"),
            "preemptions": sum(
                e.get("event") == "preemption" for e in all_events),
            "drains": sum(e.get("event") == "drain" for e in all_events),
            "nonfinite_evictions": sum(
                r.get("finish_reason") == "nonfinite"
                for r in all_records),
        },
    }
    for r in all_records:
        fr = r.get("finish_reason") or "?"
        out["finish_reasons"][fr] = out["finish_reasons"].get(fr, 0) + 1
    if any(loop_per_path):
        # only on schema >= 10 logs; older logs keep the old report shape
        out["loop"] = loop_goodput_summary(loop_per_path)
    if any(cache_per_path):
        # only on schema >= 11 logs (cache observatory)
        out["cache"] = cache_observatory_summary(
            cache_per_path, out["prefill"], requests=len(all_records))
    if all_fleet:
        out["fleet"] = fleet_summary(all_fleet)
    if all_alerts:
        # only on schema >= 13 logs (SLO sentinel, serving/alerts.py)
        out["incidents"] = incident_summary(all_alerts, all_fleet,
                                            all_events)
    if per_replica:
        out["replicas"] = per_replica
    return out


def incident_summary(transitions: List[Dict], fleet_events: List[Dict],
                     resilience_events: List[Dict],
                     correlate_secs: float = 30.0) -> Dict:
    """Incident lifecycle reconstructed from ``alert_transition``
    records: each firing opens an incident for its (rule, scope), the
    next resolved closes it.  Every incident carries the fleet events
    and engine restarts that happened within ``correlate_secs`` of its
    window — the "what else was going on" a postmortem starts from."""
    transitions = sorted(transitions,
                         key=lambda t: t.get("time_unix") or 0.0)
    counts = {"firing": 0, "resolved": 0, "pending": 0}
    open_by_key: Dict[tuple, Dict] = {}
    incidents: List[Dict] = []
    for tr in transitions:
        state = tr.get("state")
        if state in counts:
            counts[state] += 1
        key = (tr.get("rule"), tr.get("scope"))
        t = tr.get("time_unix")
        if state == "firing":
            inc = {
                "rule": tr.get("rule"),
                "scope": tr.get("scope"),
                "severity": tr.get("severity"),
                "value": tr.get("value"),
                "threshold": tr.get("threshold"),
                "start_unix": t,
                "end_unix": None,
                "duration_secs": None,
                "bundle": tr.get("bundle"),
                "open": True,
            }
            open_by_key[key] = inc
            incidents.append(inc)
        elif state == "resolved" and key in open_by_key:
            inc = open_by_key.pop(key)
            inc["end_unix"] = t
            inc["open"] = False
            if isinstance(t, (int, float)) \
                    and isinstance(inc["start_unix"], (int, float)):
                inc["duration_secs"] = round(t - inc["start_unix"], 3)
    # correlate each incident with concurrent fleet/resilience activity
    context = sorted(
        (e for e in list(fleet_events) + list(resilience_events)
         if isinstance(e.get("time_unix"), (int, float))),
        key=lambda e: e["time_unix"])
    for inc in incidents:
        start = inc.get("start_unix")
        if not isinstance(start, (int, float)):
            inc["correlated"] = []
            continue
        end = inc["end_unix"] if isinstance(inc.get("end_unix"),
                                            (int, float)) else start
        near = []
        for e in context:
            if start - correlate_secs <= e["time_unix"] \
                    <= end + correlate_secs:
                entry = {"event": e.get("event"),
                         "offset_secs": round(e["time_unix"] - start, 3)}
                for key in ("slot", "url", "reason", "requeued",
                            "failed"):
                    if e.get(key) is not None:
                        entry[key] = e[key]
                near.append(entry)
        inc["correlated"] = near
    return {
        "transitions": counts,
        "incidents": incidents,
        "unresolved": sum(1 for i in incidents if i["open"]),
    }


def fleet_summary(events: List[Dict]) -> Dict:
    """Counters plus a chronological timeline of supervisor activity
    (scale-ups, deaths, respawns, brownouts) with offsets relative to
    the first fleet event — the narrative of a chaos/autoscale run."""
    events = sorted(events, key=lambda e: e.get("time_unix") or 0.0)
    t0 = next((e["time_unix"] for e in events
               if isinstance(e.get("time_unix"), (int, float))), None)
    timeline = []
    for e in events:
        t = e.get("time_unix")
        entry = {
            "t_secs": (round(t - t0, 3)
                       if isinstance(t, (int, float)) and t0 is not None
                       else None),
            "event": e.get("event"),
        }
        for key in ("slot", "url", "reason", "exited_while",
                    "ttft_p95_secs", "queue_depth", "eta_secs",
                    "spawn_secs"):
            if e.get(key) is not None:
                entry[key] = e[key]
        timeline.append(entry)
    return {
        "events": {name: sum(e.get("event") == name for e in events)
                   for name in FLEET_EVENTS},
        "timeline": timeline,
    }


def _fmt(v, unit="s") -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4f}{unit}"
    return f"{v}{unit}"


def _latency_lines(s: Dict, indent: str = "  ") -> List[str]:
    lines = [f"{indent}requests: {s['requests']}"]
    for name in ("e2e", "ttft", "tpot"):
        lines.append(
            f"{indent}{name:>4}  mean {_fmt(s[f'{name}_mean_secs']):>9}"
            f"  p50 {_fmt(s[f'{name}_p50_secs']):>9}"
            f"  p95 {_fmt(s[f'{name}_p95_secs']):>9}"
            f"  p99 {_fmt(s[f'{name}_p99_secs']):>9}")
    return lines


def render(report: Dict) -> str:
    lines = [f"serve_report over {len(report['paths'])} log(s): "
             f"{report['summary']['requests']} requests "
             f"({report['traced']} traced)"]
    lines += _latency_lines(report["summary"])

    ph = report["phases"]
    mean_e2e = ph.get("mean_e2e_secs") or 0.0
    lines.append("\nphase breakdown (mean per request):")
    for key in PHASE_KEYS:
        p = ph[key]
        share = p["share"]
        pct = f"{share * 100:5.1f}%" if share is not None else "    -"
        lines.append(f"  {key:>18} {_fmt(p['mean_secs']):>10} {pct}")
    if ph.get("unattributed_secs") is not None:
        frac = ph["unattributed_secs"] / mean_e2e if mean_e2e else 0.0
        lines.append(f"  {'unattributed':>18} "
                     f"{_fmt(ph['unattributed_secs']):>10} "
                     f"{frac * 100:5.1f}%")

    pf = report.get("prefill") or {}
    if pf.get("computed_tokens"):
        tps = pf.get("tokens_per_sec")
        kern = json.dumps(pf.get("kernel") or {}, sort_keys=True)
        lines.append(f"\nprefill compute: {pf['computed_tokens']} tokens "
                     f"in {_fmt(pf['compute_secs'])} -> "
                     + (f"{tps:.1f} tok/s" if tps else "-")
                     + f" (kernel: {kern})")
        if pf.get("host_hit_blocks"):
            lines.append(
                f"  host swap-ins: {pf['host_hit_blocks']} block(s) "
                f"across {pf['requests_swapping']} request(s) in "
                f"{_fmt(pf['swap_in_secs'])} (prefill skipped, "
                f"scatter paid)")

    sp = report.get("speculative") or {}
    if sp.get("drafted_tokens"):
        rate = sp.get("accept_rate")
        lines.append(
            f"\nspeculative decoding: accepted {sp['accepted_tokens']}/"
            f"{sp['drafted_tokens']} drafted tokens"
            + (f" ({rate * 100:.1f}% accept rate)" if rate is not None
               else "")
            + f" over {sp['requests_drafting']} drafting request(s)")
        lines.append(
            f"  tpot mean  drafting {_fmt(sp['tpot_mean_secs_drafting']):>9}"
            f"  plain {_fmt(sp['tpot_mean_secs_plain']):>9}")

    slo = report["slo"]
    lines.append(f"\nSLO attainment (ttft <= {slo['ttft_slo_secs']}s, "
                 f"tpot <= {slo['tpot_slo_secs']}s):")
    for key in ("ttft_attained", "tpot_attained", "joint_attained"):
        v = slo[key]
        lines.append(f"  {key:>14}: "
                     + (f"{v * 100:.1f}%" if v is not None else "-"))

    lines.append("\nby prefix-cache outcome:")
    for name in ("cache_hit", "cache_miss"):
        s = report["by_cache"][name]
        lines.append(f"  {name} ({s['requests']} requests):")
        if s["requests"]:
            lines += _latency_lines(s, indent="    ")

    if report.get("finish_reasons"):
        lines.append("\nfinish reasons: "
                     + json.dumps(report["finish_reasons"],
                                  sort_keys=True))

    res = report.get("resilience") or {}
    if any(res.values()):
        lines.append("\nresilience activity:")
        for key in ("engine_restarts", "restart_requeued",
                    "restart_failed", "preemptions", "drains",
                    "nonfinite_evictions"):
            lines.append(f"  {key:>20}: {res.get(key, 0)}")

    lp = report.get("loop")
    if lp:
        db, hb = lp.get("device_busy_pct"), lp.get("host_bubble_pct")
        lines.append(f"\nengine loop goodput "
                     f"({lp['dispatches']} dispatches, "
                     f"{lp['stalls']} stall(s)):")
        lines.append("  device busy "
                     + (f"{db:.1f}%" if db is not None else "-")
                     + "  host bubble "
                     + (f"{hb:.1f}%" if hb is not None else "-"))
        for key in LOOP_PHASE_KEYS:
            share = lp["phase_share"].get(key)
            pct = f"{share * 100:5.1f}%" if share is not None else "    -"
            lines.append(f"  {key:>18} "
                         f"{_fmt(lp['phase_secs'].get(key)):>10} {pct}")
        trend = lp.get("bubble_trend") or []
        if trend:
            p95 = lp.get("bubble_window_p95_pct")
            lines.append(
                f"  bubble trend: {trend[0]['host_bubble_pct']:.1f}% -> "
                f"{trend[-1]['host_bubble_pct']:.1f}% over "
                f"{len(trend)} window(s)"
                + (f" (window p95 {p95:.1f}%)" if p95 is not None
                   else ""))

    cache = report.get("cache")
    if cache:
        hr = cache.get("hit_rate")
        lines.append(f"\ncache observatory ({cache['probes']} probes, "
                     + (f"{hr * 100:.1f}% hit rate" if hr is not None
                        else "no hit rate") + "):")
        misses = cache.get("misses") or 0
        mc, me = cache.get("miss_cold") or 0, cache.get("miss_evicted") or 0
        lines.append(
            "  miss causes: "
            + (f"cold {mc} ({mc / misses * 100:.1f}%), evicted-then-"
               f"wanted {me} ({me / misses * 100:.1f}%)" if misses
               else "none"))
        lines.append(f"  evictions: capacity {cache['evictions_capacity']}"
                     f", churn {cache['evictions_churn']}"
                     + (f", pool resets {cache['pool_resets']}"
                        if cache.get("pool_resets") else ""))
        heat = cache.get("heat_top") or []
        if heat:
            lines.append("  hottest prefixes (salted digests):")
            lines.append(f"    {'prefix':<18} {'hits':>7} {'tokens':>8} "
                         f"{'peak_rc':>7} {'evict':>6} {'regret':>6}")
            for e in heat[:10]:
                lines.append(
                    f"    {e.get('prefix', '?'):<18} "
                    f"{e.get('hits', 0):>7} "
                    f"{e.get('hit_tokens', 0):>8} "
                    f"{e.get('peak_refcount', 0):>7} "
                    f"{e.get('evictions', 0):>6} "
                    f"{e.get('regret', 0):>6}")
        ghost = cache.get("ghost") or {}
        if ghost:
            lines.append("  capacity projection (ghost tiers — exact "
                         "replay, not an estimate):")
            lines.append(f"    {'tier':<5} {'blocks':>7} {'hit rate':>9} "
                         f"{'extra tok':>10} {'ttft saved/req':>15}")
            for tier, g in ghost.items():
                ghr = g.get("hit_rate")
                saved = g.get("ttft_saved_secs_per_request")
                lines.append(
                    f"    {tier:<5} {g.get('capacity_blocks', 0):>7} "
                    + (f"{ghr * 100:>8.1f}%" if ghr is not None
                       else f"{'-':>9}")
                    + f" {g.get('extra_hit_tokens', 0):>10} "
                    + (f"{saved:>14.4f}s" if saved is not None
                       else f"{'-':>15}"))
        host = cache.get("host_tier")
        if host:
            lines.append(
                f"  host spill tier: {host['hits']} hit(s) "
                f"({host['hit_rate'] * 100:.1f}% of probes)"
                if host.get("hit_rate") is not None else
                f"  host spill tier: {host['hits']} hit(s)")
            lines.append(
                f"    spills {host['spills_completed']} "
                f"(dropped {host['spills_dropped']}, "
                f"evicted {host['evictions']}), swap-ins "
                f"{host['swap_ins']} in {_fmt(host['swap_in_secs'])}")
            # the realized-vs-projected line: the ghost tiers say what
            # a bigger HBM pool WOULD hit; the host tier is the tier we
            # actually bought — compare the two-tier rate against each
            # projection
            two_tier = cache.get("hit_rate")
            if two_tier is not None and ghost:
                proj = " ".join(
                    f"{t}={g['hit_rate'] * 100:.1f}%"
                    for t, g in ghost.items()
                    if g.get("hit_rate") is not None)
                if proj:
                    lines.append(
                        f"    two-tier hit rate {two_tier * 100:.1f}% "
                        f"vs ghost projection {proj}")
            net = host.get("ttft_saved_secs_per_request")
            if net is not None:
                lines.append(
                    f"    ttft saved/req {net:.4f}s "
                    f"(net of measured swap-in time)")

    fleet = report.get("fleet")
    if fleet:
        counts = " ".join(f"{k}={v}" for k, v in fleet["events"].items()
                          if v)
        lines.append(f"\nfleet events: {counts or '-'}")
        for e in fleet["timeline"]:
            t = e.get("t_secs")
            detail = " ".join(
                f"{k}={e[k]}" for k in ("slot", "url", "reason",
                                        "exited_while", "ttft_p95_secs",
                                        "queue_depth", "eta_secs",
                                        "spawn_secs") if k in e)
            lines.append(f"  +{t if t is not None else '?':>9}s "
                         f"{e['event']:<18} {detail}")

    inc = report.get("incidents")
    if inc:
        tr = inc["transitions"]
        lines.append(f"\nincidents: {len(inc['incidents'])} "
                     f"({inc['unresolved']} unresolved; transitions: "
                     f"pending {tr['pending']}, firing {tr['firing']}, "
                     f"resolved {tr['resolved']})")
        for i in inc["incidents"]:
            dur = (f"{i['duration_secs']:.1f}s"
                   if i.get("duration_secs") is not None
                   else "OPEN")
            lines.append(
                f"  [{i.get('severity', '?'):<4}] {i.get('rule')}"
                f"@{i.get('scope')}  {dur}"
                + (f"  value {i['value']:.4g}"
                   f" (threshold {i['threshold']:.4g})"
                   if isinstance(i.get("value"), (int, float))
                   and isinstance(i.get("threshold"), (int, float))
                   else ""))
            if i.get("bundle"):
                lines.append(f"         bundle: {i['bundle']}")
            for e in i.get("correlated", [])[:8]:
                detail = " ".join(
                    f"{k}={e[k]}" for k in ("slot", "url", "reason",
                                            "requeued", "failed")
                    if k in e)
                lines.append(f"         {e['offset_secs']:+9.1f}s "
                             f"{e['event']:<18} {detail}")

    for path, s in (report.get("replicas") or {}).items():
        lines.append(f"\nreplica {path} "
                     f"(joint SLO "
                     + (f"{s['slo']['joint_attained'] * 100:.1f}%"
                        if s['slo']['joint_attained'] is not None
                        else "-") + "):")
        lines += _latency_lines(s)
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize serving request_done telemetry")
    ap.add_argument("paths", nargs="+", metavar="path",
                    help="telemetry.jsonl file(s) or --structured_log_dir "
                         "dir(s); several -> per-replica comparison")
    ap.add_argument("--ttft_slo", type=float, default=1.0,
                    help="time-to-first-token target in seconds")
    ap.add_argument("--tpot_slo", type=float, default=0.25,
                    help="time-per-output-token target in seconds")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON object")
    args = ap.parse_args(argv)

    try:
        report = analyze(args.paths, ttft_slo=args.ttft_slo,
                         tpot_slo=args.tpot_slo)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    if report["summary"]["requests"] == 0 and not report.get("fleet"):
        print("no request_done records found (serve with "
              "--structured_log_dir and schema >= 5)", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:         # e.g. piped into head
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
