#!/usr/bin/env python
"""Declarative on-chip sweep: one manifest, one runner.

This replaces the logic that used to live inline in ``tools/tpu_hunt.sh``
(that script is now a thin exec wrapper).  The playbook steps are data
(``MANIFEST``), not case tables, so adding a measurement is one entry —
and the planner/status logic is importable and unit-tested
(tests/test_tpu_sweep.py) instead of living in bash.

Runner semantics (unchanged from the shell version):

* single instance via an flock'd lockfile — concurrent jax clients wedge
  the serializing tunnel;
* a 150 s probe decides whether the TPU tunnel is up: rc 124 means the
  tunnel is genuinely hung (down-cycle), any other nonzero rc is a fast
  local failure (import error, broken env) that probing harder won't fix;
* each step runs under its own timeout; rc 0 marks ``<name>.done``, a
  failure backs off 180 s (a timed-out step is a killed client that
  wedges the tunnel for minutes), and after 4 attempts the step is
  marked ``<name>.gaveup`` — visibly distinct from done;
* a fresh launch retries exhausted steps but honors ``.done`` markers.

Beyond the shell version: a step may declare ``needs_tpu=False`` (the
multi-slice smoke runs on the virtual-device CPU mesh), and such steps
run even while the tunnel is down.

stdlib-only; usage:

    python tools/tpu_sweep.py --list
    python tools/tpu_sweep.py --dry-run
    nohup python tools/tpu_sweep.py run >/tmp/tpu_hunt.log 2>&1 &
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The virtual-device CPU environment (tests/conftest.py's contract) for
# steps that do not need the chip.
CPU_MESH_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}

_SMOKE_FLAGS = (
    "--model_name=llama2 --num_layers=2 --hidden_size=64 "
    "--num_attention_heads=4 --seq_length=32 --max_position_embeddings=32 "
    "--micro_batch_size=1 --train_iters=3 --lr=1e-4 "
    "--vocab_size=128 --log_interval=1"
)


@dataclass(frozen=True)
class Step:
    """One sweep entry.  ``wave`` orders the run (0 = static gates,
    CPU-only, no model; 1 = the VERDICT playbook must-haves, 2 = gravy
    measurements); ``env`` is merged over the inherited environment."""

    name: str
    cmd: str
    timeout: int                      # generous per-group compile budget
    wave: int = 1
    needs_tpu: bool = True
    env: Dict[str, str] = field(default_factory=dict)


MANIFEST: List[Step] = [
    # wave 0: static gates — no model, no accelerator, seconds not
    # minutes; a red lint fails the sweep before any compile budget
    # is spent
    Step("graft_lint", "python tools/graft_lint.py --expect-checkers 7",
         120, wave=0, needs_tpu=False),
    Step("fusedbwd", "python tools/mfu_sweep.py fusedbwd", 1500, wave=1),
    Step("seq4096", "python tools/mfu_sweep.py seq4096", 1800, wave=1),
    Step("bigvocab", "python tools/mfu_sweep.py bigvocab", 2100, wave=1),
    Step("bench_final", "python bench.py", 900, wave=1),
    Step("moe", "python tools/mfu_sweep.py moe", 1200, wave=2),
    Step("long", "python tools/mfu_sweep.py long", 1500, wave=2),
    Step("decode", "python tools/decode_bench.py", 1200, wave=2),
    Step("optstate", "python tools/mfu_sweep.py optstate", 1200, wave=2),
    # multi-slice elastic runtime smoke: slice=2 x dp=4 on the virtual
    # CPU mesh (one chip cannot host two slices), hierarchical reduction
    # on — proves the --num_slices surface end to end
    Step("multislice_smoke",
         f"python finetune.py {_SMOKE_FLAGS} "
         "--global_batch_size=8 --num_slices=2",
         600, wave=2, needs_tpu=False, env=dict(CPU_MESH_ENV)),
    # serving chaos harness: the 2-replica fleet e2e (NaN injection +
    # watchdog restart + SIGKILL failover + SIGTERM drain behind the
    # router) — proves every request completes exactly once under faults
    Step("serve_chaos_smoke",
         "python -m pytest tests/test_serving_resilience.py "
         "-m chaos -q -p no:cacheprovider",
         900, wave=2, needs_tpu=False, env=dict(CPU_MESH_ENV)),
    # prefill-kernel A/B smoke: serve_bench --ab serve_prefill_kernel
    # against two real replica processes (Pallas-interpret vs XLA
    # chunked prefill), asserting per-arm prefill tokens/sec + TTFT —
    # proves the whole flag->engine->metrics->bench chain on CPU
    Step("serve_prefill_ab",
         "python -m pytest tests/test_serve_bench_tool.py "
         "-k ab_prefill -q -p no:cacheprovider",
         900, wave=2, needs_tpu=False, env=dict(CPU_MESH_ENV)),
    # speculative-decoding A/B smoke: serve_bench --ab serve_speculative
    # against two real replica processes (prompt-lookup drafting + K+1
    # verify step vs plain decode) on a repeated-suffix workload —
    # asserts a non-zero accept rate and spec-on == spec-off throughput
    # accounting end to end on CPU
    Step("serve_spec_ab",
         "python -m pytest tests/test_serve_bench_tool.py "
         "-k ab_speculative -q -p no:cacheprovider",
         900, wave=2, needs_tpu=False, env=dict(CPU_MESH_ENV)),
    # fleet supervisor chaos: spike schedule breaches the TTFT SLO, the
    # supervisor scales up and p95 recovers; a mid-run SIGKILL is
    # respawned — zero dropped requests, zero engine restarts
    Step("serve_fleet_chaos",
         "python -m pytest tests/test_serve_fleet.py "
         "-m slow -q -p no:cacheprovider",
         1200, wave=2, needs_tpu=False, env=dict(CPU_MESH_ENV)),
    # sharded front door chaos: 2 supervisor-managed router processes
    # over 2 replicas, SIGKILL a router mid-burst — clients retry the
    # sibling from their multi-URL list (exactly-once end to end), the
    # supervisor respawns the router under its slot, and the survivors'
    # engines never restart or recompile
    Step("router_kill_chaos",
         "python -m pytest tests/test_router_tier_chaos.py "
         "-m chaos -q -p no:cacheprovider",
         1200, wave=2, needs_tpu=False, env=dict(CPU_MESH_ENV)),
    # engine-loop profiler overhead gate: per-dispatch goodput
    # bookkeeping (begin + phase marks + finish) must stay under 2% of
    # a measured CPU dispatch A/B'd against the engine running without
    # it — the always-on attribution may not become the bubble it
    # exists to measure
    Step("serve_loop_overhead",
         "python -m pytest tests/test_loop_profiler.py "
         "-m slow -k loop_overhead -q -p no:cacheprovider",
         900, wave=2, needs_tpu=False, env=dict(CPU_MESH_ENV)),
    # cache observatory overhead gate: heat attribution + eviction
    # forensics + three synchronous ghost tiers must stay under 2% of a
    # measured CPU dispatch — the observability tax may not erode the
    # goodput it exists to project
    Step("serve_cache_overhead",
         "python -m pytest tests/test_cache_observatory.py "
         "-m slow -k cache_overhead -q -p no:cacheprovider",
         900, wave=2, needs_tpu=False, env=dict(CPU_MESH_ENV)),
    # hierarchical KV cache A/B smoke: serve_bench
    # --ab serve_host_cache_bytes against two real CPU replicas with an
    # HBM pool half the size of the Zipf prefix working set — the ON
    # arm must rescue evicted prefixes from host RAM (host-tier hits +
    # device->host spills), the OFF arm recomputes them
    Step("serve_host_cache_ab",
         "python -m pytest tests/test_serve_bench_tool.py "
         "-m slow -k ab_host_cache -q -p no:cacheprovider",
         900, wave=2, needs_tpu=False, env=dict(CPU_MESH_ENV)),
    # host spill tier overhead gate: the two-tier bookkeeping
    # (match+pin, swap-in consume, spill enqueue, free-time unpin) must
    # stay under 2% of a measured CPU dispatch — the spill tier's wins
    # come from the copies it avoids, not from taxing the hot path
    Step("serve_host_cache_overhead",
         "python -m pytest tests/test_host_cache.py "
         "-m slow -k host_cache_overhead -q -p no:cacheprovider",
         900, wave=2, needs_tpu=False, env=dict(CPU_MESH_ENV)),
    # SLO sentinel chaos e2e: a 2-replica fleet behind a router, faults
    # injected into one replica until its alert fires — asserts the
    # firing state agrees across /metrics (replica + fleet-merged),
    # the schema-13 alert_transition JSONL, and serve_top; the
    # postmortem bundle is on disk and readable; the incident resolves
    # after the watchdog restart heals the replica
    Step("serve_alert_chaos",
         "python -m pytest tests/test_alerts.py "
         "-m chaos -k alert_chaos -q -p no:cacheprovider",
         1200, wave=2, needs_tpu=False, env=dict(CPU_MESH_ENV)),
    # alert evaluator overhead gate: one full rule-set evaluation over
    # a live metrics snapshot must stay under 2% of a measured CPU
    # dispatch — the sentinel may not become the incident it watches for
    Step("serve_alert_overhead",
         "python -m pytest tests/test_alerts.py "
         "-m slow -k alert_overhead -q -p no:cacheprovider",
         900, wave=2, needs_tpu=False, env=dict(CPU_MESH_ENV)),
]


def validate_manifest(manifest: List[Step] = MANIFEST) -> None:
    seen = set()
    for s in manifest:
        if s.name in seen:
            raise ValueError(f"duplicate step name: {s.name}")
        seen.add(s.name)
        if s.timeout <= 0:
            raise ValueError(f"step {s.name}: timeout must be positive")
        if s.wave not in (0, 1, 2):
            raise ValueError(f"step {s.name}: wave must be 0, 1 or 2")
        if s.wave == 0 and s.needs_tpu:
            raise ValueError(
                f"step {s.name}: wave 0 is the static-gate wave and "
                f"must not need a TPU")
        if not s.cmd.strip():
            raise ValueError(f"step {s.name}: empty command")


def ordered(manifest: List[Step] = MANIFEST) -> List[Step]:
    """Run order: wave 1 first, manifest order within a wave (stable)."""
    return sorted(manifest, key=lambda s: s.wave)


# ---------------------------------------------------------------------------
# Marks: the on-disk settle state (compatible with the old shell layout)
# ---------------------------------------------------------------------------

def step_state(marks_dir: str, name: str) -> str:
    if os.path.exists(os.path.join(marks_dir, name + ".done")):
        return "done"
    if os.path.exists(os.path.join(marks_dir, name + ".gaveup")):
        return "gave-up"
    return "never-ran"


def attempts(marks_dir: str, name: str) -> int:
    try:
        with open(os.path.join(marks_dir, name + ".attempts")) as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def plan(marks_dir: str, manifest: List[Step] = MANIFEST) -> List[Step]:
    """The steps a run would still execute, in run order."""
    return [s for s in ordered(manifest)
            if step_state(marks_dir, s.name) == "never-ran"]


def reset_for_launch(marks_dir: str, manifest: List[Step] = MANIFEST) -> None:
    """Fresh-launch policy: retry exhausted steps, honor completed ones
    (and say so out loud instead of skipping silently)."""
    os.makedirs(marks_dir, exist_ok=True)
    for s in manifest:
        for suffix in (".attempts", ".gaveup"):
            try:
                os.remove(os.path.join(marks_dir, s.name + suffix))
            except OSError:
                pass
        if step_state(marks_dir, s.name) == "done":
            print(f"[hunt] startup: {s.name} already done (stale marker "
                  f"honored; rm {marks_dir}/{s.name}.done to re-run)")


def status_table(marks_dir: str, manifest: List[Step] = MANIFEST) -> str:
    lines = []
    for s in ordered(manifest):
        tpu = "tpu" if s.needs_tpu else "cpu"
        lines.append(f"{s.name:<16} wave{s.wave} {tpu:<4} "
                     f"{s.timeout:>5}s  {step_state(marks_dir, s.name):<9} "
                     f"{s.cmd}")
    return "\n".join(lines)


def all_settled(marks_dir: str, manifest: List[Step] = MANIFEST) -> bool:
    return all(step_state(marks_dir, s.name) != "never-ran"
               for s in manifest)


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------

PROBE_SRC = """\
import jax, jax.numpy as jnp
x = jnp.ones((256, 256))
assert jax.devices()[0].platform == "tpu"
float((x @ x).sum())
"""


def probe(timeout: int = 150, log: str = "/tmp/tpu_probe.log") -> bool:
    """True when the tunnel answers.  A killed (timed-out) client wedges
    the serializing tunnel for minutes, so callers must keep failed
    probes well apart.  Exits the sweep on a fast local failure."""
    with open(log, "w") as f:
        try:
            rc = subprocess.run([sys.executable, "-c", PROBE_SRC],
                                stdout=f, stderr=subprocess.STDOUT,
                                timeout=timeout, cwd=REPO).returncode
        except subprocess.TimeoutExpired:
            return False                    # tunnel genuinely hung
    if rc == 0:
        return True
    print(f"[hunt] probe failed fast (rc={rc}) — local error, not a "
          f"tunnel hang:", flush=True)
    with open(log) as f:
        print("".join(f.readlines()[-5:]), flush=True)
    sys.exit(1)


def _stamp() -> str:
    return time.strftime("%H:%M:%S")


def run_step(step: Step, marks_dir: str, log_dir: str = "/tmp",
             max_attempts: int = 4, backoff_secs: int = 180) -> bool:
    """One attempt at a step; returns True when the step is settled
    (done or gave up), False when the caller should re-probe first."""
    if step_state(marks_dir, step.name) != "never-ran":
        return True
    att = attempts(marks_dir, step.name) + 1
    with open(os.path.join(marks_dir, step.name + ".attempts"), "w") as f:
        f.write(str(att))
    if att > max_attempts:
        open(os.path.join(marks_dir, step.name + ".gaveup"), "w").close()
        print(f"[hunt {_stamp()}] step {step.name} GAVE UP after "
              f"{max_attempts} attempts", flush=True)
        return True
    print(f"[hunt {_stamp()}] step {step.name} attempt {att}", flush=True)
    env = dict(os.environ, **step.env)
    with open(os.path.join(log_dir, f"hunt_{step.name}.log"), "a") as f:
        try:
            rc = subprocess.run(step.cmd, shell=True, stdout=f,
                                stderr=subprocess.STDOUT, env=env,
                                timeout=step.timeout, cwd=REPO).returncode
        except subprocess.TimeoutExpired:
            rc = 124
    if rc == 0:
        open(os.path.join(marks_dir, step.name + ".done"), "w").close()
        print(f"[hunt {_stamp()}] step {step.name} DONE", flush=True)
        return True
    note = " = timeout/killed client" if rc == 124 else ""
    print(f"[hunt {_stamp()}] step {step.name} failed (rc={rc}{note})",
          flush=True)
    # backoff: a fast deterministic failure must not burn every attempt
    # inside one window; a timed-out step needs the tunnel-wedge to clear
    time.sleep(backoff_secs)
    return False


def run(marks_dir: str, hours: float, log_dir: str = "/tmp") -> int:
    import fcntl

    lock = open("/tmp/tpu_hunt.lock", "w")
    try:
        fcntl.flock(lock, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        print("[hunt] another instance holds /tmp/tpu_hunt.lock; exiting")
        return 1

    validate_manifest()
    reset_for_launch(marks_dir)
    deadline = time.time() + hours * 3600

    while time.time() < deadline:
        if all_settled(marks_dir):
            break
        # CPU-capable steps (the multi-slice smoke) never wait on the
        # tunnel — run them regardless of its state
        for s in [s for s in plan(marks_dir) if not s.needs_tpu]:
            run_step(s, marks_dir, log_dir)
        tpu_pending = [s for s in plan(marks_dir) if s.needs_tpu]
        if not tpu_pending:
            continue                        # loop re-checks all_settled
        if not probe():
            print(f"[hunt {_stamp()}] tunnel down", flush=True)
            time.sleep(300)
            continue
        print(f"[hunt {_stamp()}] tunnel UP", flush=True)
        for s in tpu_pending:
            if not run_step(s, marks_dir, log_dir):
                break                       # re-probe before the next try

    print("[hunt] final status:")
    for s in ordered(MANIFEST):
        print(f"[hunt]   {s.name}: {step_state(marks_dir, s.name)}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("action", nargs="?", default="run",
                    choices=["run"], help="run the sweep (default)")
    ap.add_argument("--list", action="store_true",
                    help="print the manifest + settle state and exit")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the steps a run would execute and exit")
    ap.add_argument("--marks", default="/tmp/tpu_hunt_marks",
                    help="settle-state directory")
    ap.add_argument("--log-dir", default="/tmp",
                    help="per-step log directory")
    ap.add_argument("--hours", type=float, default=10.0,
                    help="give up after this many hours")
    args = ap.parse_args(argv)

    validate_manifest()
    os.makedirs(args.marks, exist_ok=True)
    if args.list:
        print(status_table(args.marks))
        return 0
    if args.dry_run:
        for s in plan(args.marks):
            print(f"{s.name}: timeout {s.timeout}s, "
                  f"{'tpu' if s.needs_tpu else 'cpu'}: {s.cmd}")
        return 0
    return run(args.marks, args.hours, args.log_dir)


if __name__ == "__main__":
    sys.exit(main())
