#!/usr/bin/env python
"""Per-layer model-health report from a run's telemetry stream.

Reads the JSONL written by ``--structured_log_dir`` and digests the
``layer_stats`` records that ``--log_layer_stats_interval`` adds to it
(schema 3, megatron_llm_tpu/health.py):

* per-group norm trajectories — grad norm first -> last (with the max),
  final param norm, median and last update-to-weight ratio
* anomaly flags —
    NONFINITE  the group reported non-finite gradients at some boundary
    GRAD>kxMED the group's grad norm exceeded k x the median across
               groups at some boundary (k = --outlier_factor)
    UPD-RATIO  the group's median update ratio sits outside the healthy
               [1e-4, 1e-2] band (too small: effectively frozen; too
               large: the LR is thrashing that tensor)
* a NaN-event timeline — which boundaries had non-finite grads, and in
  which groups (first offender leads)

Pure stdlib — no jax import, runs anywhere the log file does.

Usage:
    python tools/health_report.py RUN_DIR_OR_JSONL [--json]
        [--outlier_factor K] [--last N]

``--json`` emits the per-group table + anomalies as one JSON object.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Any, Dict, List, Optional

# Healthy update-to-weight band; keep in sync with
# megatron_llm_tpu/health.py:UPDATE_RATIO_BAND (duplicated so this tool
# stays importable without jax)
RATIO_LO, RATIO_HI = 1e-4, 1e-2


def load_health_records(path: str) -> List[Dict]:
    """Accept a telemetry.jsonl file or the --structured_log_dir holding
    one; keep only log records that carry layer_stats.  Unparseable
    lines are skipped (a crash can truncate the final line)."""
    if os.path.isdir(path):
        path = os.path.join(path, "telemetry.jsonl")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no telemetry stream at {path}")
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("kind", "log") == "log" and rec.get("layer_stats"):
                out.append(rec)
    return out


def _val(v) -> float:
    """Record values may encode non-finites as strings ("nan"/"inf")."""
    if isinstance(v, str):
        return {"nan": math.nan, "inf": math.inf,
                "-inf": -math.inf}.get(v, math.nan)
    return float(v) if v is not None else math.nan


def _median(values: List[float]) -> Optional[float]:
    vals = sorted(v for v in values if math.isfinite(v))
    if not vals:
        return None
    mid = len(vals) // 2
    return vals[mid] if len(vals) % 2 else 0.5 * (vals[mid - 1] + vals[mid])


def analyze(records: List[Dict],
            outlier_factor: float = 4.0) -> Dict[str, Any]:
    """Fold the stream's layer_stats into per-group trajectories +
    anomaly flags + a NaN-event timeline."""
    groups: List[str] = []
    per: Dict[str, Dict[str, List]] = {}
    nan_events: List[Dict[str, Any]] = []
    for rec in records:
        ls = rec["layer_stats"]
        it = rec.get("iteration")
        names = ls.get("groups") or []
        gn = [_val(v) for v in ls.get("grad_norm") or []]
        med = _median(gn)
        bad_groups = []
        for i, g in enumerate(names):
            if g not in per:
                groups.append(g)
                per[g] = {"iter": [], "grad_norm": [], "param_norm": [],
                          "update_ratio": [], "nonfinite": [],
                          "outlier": []}
            row = per[g]
            row["iter"].append(it)
            row["grad_norm"].append(gn[i] if i < len(gn) else math.nan)
            pn = ls.get("param_norm") or []
            row["param_norm"].append(_val(pn[i]) if i < len(pn)
                                     else math.nan)
            ur = ls.get("update_ratio") or []
            row["update_ratio"].append(
                ur[i] if i < len(ur) and
                isinstance(ur[i], (int, float)) else None)
            nf = ls.get("nonfinite_grads") or []
            n_bad = int(nf[i]) if i < len(nf) else 0
            row["nonfinite"].append(n_bad)
            if n_bad > 0:
                bad_groups.append(g)
            row["outlier"].append(
                bool(med and math.isfinite(gn[i] if i < len(gn)
                                           else math.nan)
                     and gn[i] > outlier_factor * med))
        if bad_groups:
            nan_events.append({"iteration": it, "groups": bad_groups})

    table = []
    anomalies = []
    for g in groups:
        row = per[g]
        ratios = [r for r in row["update_ratio"] if r is not None]
        med_ratio = _median(ratios) if ratios else None
        finite_gn = [v for v in row["grad_norm"] if math.isfinite(v)]
        entry = {
            "group": g,
            "boundaries": len(row["iter"]),
            "grad_norm_first": row["grad_norm"][0] if row["grad_norm"]
            else None,
            "grad_norm_last": row["grad_norm"][-1] if row["grad_norm"]
            else None,
            "grad_norm_max": max(finite_gn) if finite_gn else None,
            "param_norm_last": row["param_norm"][-1] if row["param_norm"]
            else None,
            "update_ratio_median": med_ratio,
            "update_ratio_last": ratios[-1] if ratios else None,
            "flags": [],
        }
        if any(n > 0 for n in row["nonfinite"]):
            entry["flags"].append("NONFINITE")
        if any(row["outlier"]):
            entry["flags"].append(f"GRAD>{outlier_factor:g}xMED")
        if med_ratio is not None and not (RATIO_LO <= med_ratio
                                          <= RATIO_HI):
            entry["flags"].append("UPD-RATIO")
        table.append(entry)
        for fl in entry["flags"]:
            anomalies.append({"group": g, "flag": fl})
    return {"groups": groups, "table": table, "anomalies": anomalies,
            "nan_events": nan_events,
            "boundaries": len(records),
            "outlier_factor": outlier_factor}


def _fmt(v, spec: str = ".3g", none: str = "-") -> str:
    if v is None:
        return none
    if isinstance(v, float) and not math.isfinite(v):
        return "nan" if math.isnan(v) else ("inf" if v > 0 else "-inf")
    return format(v, spec)


def render(analysis: Dict[str, Any]) -> str:
    out = [f"layer-stats boundaries: {analysis['boundaries']}"]
    header = (f"{'group':<14} {'grad first':>11} {'grad last':>11} "
              f"{'grad max':>11} {'param last':>11} {'upd ratio':>10} "
              f"flags")
    out += ["", header, "-" * len(header)]
    for e in analysis["table"]:
        out.append(
            f"{e['group']:<14} "
            f"{_fmt(e['grad_norm_first']):>11} "
            f"{_fmt(e['grad_norm_last']):>11} "
            f"{_fmt(e['grad_norm_max']):>11} "
            f"{_fmt(e['param_norm_last']):>11} "
            f"{_fmt(e['update_ratio_median']):>10} "
            f"{' '.join(e['flags'])}")
    if analysis["nan_events"]:
        out.append("\nnon-finite gradient events:")
        for ev in analysis["nan_events"]:
            out.append(f"  iteration {ev['iteration']}: "
                       f"{', '.join(ev['groups'])} "
                       f"(first: {ev['groups'][0]})")
    else:
        out.append("\nno non-finite gradient events")
    if analysis["anomalies"]:
        out.append("anomalies: "
                   + "; ".join(f"{a['group']} [{a['flag']}]"
                               for a in analysis["anomalies"]))
    else:
        out.append(f"no anomalies (healthy update-ratio band "
                   f"[{RATIO_LO:g}, {RATIO_HI:g}], grad outlier factor "
                   f"{analysis['outlier_factor']:g})")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-layer model-health report from telemetry.jsonl")
    ap.add_argument("path",
                    help="telemetry.jsonl or the --structured_log_dir")
    ap.add_argument("--json", action="store_true",
                    help="emit the analysis as JSON")
    ap.add_argument("--outlier_factor", type=float, default=4.0,
                    help="flag groups whose grad norm exceeds this "
                         "multiple of the cross-group median (default 4)")
    ap.add_argument("--last", type=int, default=0,
                    help="only analyze the last N stats boundaries")
    args = ap.parse_args(argv)

    try:
        records = load_health_records(args.path)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    if not records:
        print("no layer_stats records in stream (run with "
              "--log_layer_stats_interval N)", file=sys.stderr)
        return 2
    if args.last > 0:
        records = records[-args.last:]

    analysis = analyze(records, outlier_factor=args.outlier_factor)
    if args.json:
        print(json.dumps(analysis, indent=1))
    else:
        print(render(analysis))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `| head` closed the pipe — normal CLI usage, not an error
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
