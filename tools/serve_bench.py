#!/usr/bin/env python
"""Load generator for the REST text-generation server (stdlib-only).

Drives N concurrent clients against ``PUT /api`` (or ``/api/stream``
with ``--stream``, which also measures true time-to-first-token), with
either closed-loop arrivals (each client fires its next request as soon
as the previous returns) or open-loop Poisson arrivals (``--rate``
requests/sec across the fleet — the shape real traffic has, and the one
that exposes queueing).  ``--rate_schedule "r1:t1,r2:t2,..."`` drives
piecewise rates instead (a spike→recover workload for the fleet
autoscaler), reporting per-segment throughput and p95 alongside the
run-level tables.

Reports a latency table (mean/p50/p95/p99), TTFT, token throughput, and
the server's own /metrics delta; ``--json`` emits one machine-readable
object instead (every key in ``JSON_SCHEMA_KEYS`` is always present —
asserted by tests/test_serve_bench_tool.py).

Repeat ``--url`` to spread load over a sharded front door (several
``serve_router.py`` processes over one replica fleet): each request
starts at a round-robin-chosen router and fails over to the next URL on
a transport error before the first body byte, so SIGKILLing a router
mid-run costs a retry, not a failed request.  The summary reports
per-router dispatch counts (``per_url_requests``) and how many requests
needed a sibling (``failovers``).

Repeated-prefix workloads (``--prefix_tokens N``) measure the engine's
prefix cache: a fraction of requests (``--shared_prefix_frac``) share an
N-word prompt header and differ only in a short unique tail, so cache
hits show up as ``prefill_tokens_computed`` ≪ ``prefill_tokens_
submitted`` (the ``prefill computed/submitted`` bench column).

Flag A/B (``--ab <server_flag>``, e.g. ``--ab serve_paged_kernel`` or
``--ab serve_prefill_kernel``) runs the identical workload against two
servers — one started with the named boolean flag ``on`` (``--url``)
and one with ``off`` (``--ab_url``) — and emits one result row per arm,
each tagged with ``ab_arm`` and the server's self-reported
``paged_kernel``/``prefill_kernel`` paths, so a Pallas-vs-XLA
throughput delta falls out of a single invocation.  Prefill throughput
(computed-prefill tokens/sec, from the engine's
``prefill_tokens_computed`` counter delta) is reported next to TTFT so
a prefill A/B measures the thing it changes.  ``--ab
serve_speculative`` works the same way: each arm additionally reports
the engine's drafted/accepted token deltas, the accept rate, and
accepted tokens/sec (the decode steps speculation saved).

Examples::

    python tools/serve_bench.py --port 5000 --clients 16 --requests 64
    python tools/serve_bench.py --clients 8 --rate 4 --stream --json
    python tools/serve_bench.py --clients 8 --requests 32 \\
        --prefix_tokens 256 --shared_prefix_frac 0.75 --json
    python tools/serve_bench.py --url http://host:5000 \\
        --ab serve_prefill_kernel --ab_url http://host:5001 --json
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
import urllib.error
import urllib.request


# keys guaranteed in the --json output (value may be None when a
# measurement is unavailable, e.g. no engine /metrics to delta)
JSON_SCHEMA_KEYS = (
    "url", "urls", "per_url_requests", "failovers",
    "clients", "requests", "ok", "errors", "status_counts",
    "wall_secs", "requests_per_sec", "tokens_total", "tokens_per_sec",
    "latency_mean_secs", "latency_p50_secs", "latency_p95_secs",
    "latency_p99_secs", "ttft_mean_secs", "ttft_p50_secs",
    "ttft_p95_secs", "tpot_mean_secs", "tpot_p50_secs",
    "tpot_p95_secs", "stream", "rate", "rate_schedule", "segments",
    "prefix_tokens",
    "shared_prefix_frac", "prefill_tokens_submitted",
    "prefill_tokens_computed", "prefill_tokens_cached",
    "prefill_computed_frac", "prefill_tokens_per_sec",
    "prefix_cache_hits", "prefix_cache_misses",
    "prefix_cache_evictions", "paged_kernel", "prefill_kernel",
    # resilience counters (engine/server /metrics deltas over the run)
    "engine_restarts", "slots_evicted_nonfinite", "preemptions",
    "drained",
    # speculative decoding (engine counter deltas; accept_rate =
    # accepted/drafted, accepted_tokens_per_sec = draft-attributed
    # "free" tokens over the run wall clock)
    "drafted_tokens", "accepted_tokens", "accept_rate",
    "accepted_tokens_per_sec",
    # engine-loop goodput over the run (loop_profiler counter deltas):
    # device-busy vs host-bubble share of the loop's busy time — the
    # before/after line a host/device-overlap A/B reads
    "device_busy_pct", "host_bubble_pct",
    # cache observatory (engine cache block deltas over the run):
    # skewed-popularity workload knobs, the miss-cause split, eviction
    # forensics, and per-ghost-tier projected hit rates ({"x2": ...})
    "prefix_zipf", "prefix_pool",
    "cache_miss_cold", "cache_miss_evicted",
    "cache_evictions_capacity", "cache_evictions_churn",
    "ghost_hit_rates",
    # hierarchical KV cache (host-RAM spill tier deltas over the run):
    # blocks rescued from host RAM, pages spilled device->host, and the
    # swap-in volume/time — the numbers a --serve_host_cache_bytes A/B
    # moves when the prefix pool exceeds the HBM budget
    "cache_host_hits", "cache_host_spills", "cache_swap_in_blocks",
    "cache_swap_in_secs",
    # client-observed SLO attainment (--slo_gate): per-request joint
    # pass/fail against the TTFT/TPOT targets — a failed request counts
    # as NOT attained; requests without a streamed TTFT/TPOT sample
    # gate on success only
    "ttft_slo_secs", "tpot_slo_secs", "slo_joint_attainment",
    "slo_gate",
)

# Exit codes: 0 = all requests succeeded; 1 = at least one request
# failed; 2 = argparse/usage error; 3 = --slo_gate given and the joint
# SLO attainment (min across arms under --ab) fell below the gate.
# tools/tpu_sweep.py and CI read these — renumbering is a breaking
# change.


def parse_rate_schedule(spec: str):
    """``"r1:t1,r2:t2,..."`` -> [(rate_req_per_sec, duration_secs)].
    A ``0`` rate is a silent segment (drain pause in a spike->recover
    workload)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        rate_s, sep, dur_s = part.partition(":")
        if not sep:
            raise ValueError(
                f"rate_schedule segment {part!r} is not 'rate:secs'")
        rate, dur = float(rate_s), float(dur_s)
        if rate < 0 or dur <= 0:
            raise ValueError(
                f"rate_schedule segment {part!r} needs rate >= 0 and "
                f"secs > 0")
        out.append((rate, dur))
    if not out:
        raise ValueError("empty rate_schedule")
    return out


def build_arrivals(schedule, seed: int):
    """Deterministic Poisson arrival times over the piecewise schedule:
    ``[(offset_secs, segment_idx), ...]`` sorted by time.  Pre-generated
    so every client sleeps toward an absolute deadline — the spike stays
    a spike even when slow responses bunch the clients up."""
    rng = random.Random(seed * 1000003 + 17)
    arrivals = []
    t0 = 0.0
    for i, (rate, dur) in enumerate(schedule):
        if rate > 0:
            t = t0 + rng.expovariate(rate)
            while t < t0 + dur:
                arrivals.append((t, i))
                t += rng.expovariate(rate)
        t0 += dur
    return arrivals


def _percentile(values, q: float):
    if not values:
        return None
    s = sorted(values)
    return s[min(int(q * (len(s) - 1) + 0.5), len(s) - 1)]


def _fetch_metrics(base_urls, timeout: float = 10.0):
    """First URL that answers /metrics wins (with a sharded front door
    any router speaks for the fleet)."""
    if isinstance(base_urls, str):
        base_urls = [base_urls]
    for base_url in base_urls:
        try:
            with urllib.request.urlopen(base_url + "/metrics",
                                        timeout=timeout) as resp:
                return json.loads(resp.read())
        except Exception:
            continue
    return None


def _one_request(base_urls, payload: dict, stream: bool,
                 timeout: float, start: int = 0) -> dict:
    """One request with client-side front-door failover: URLs are tried
    round-robin from ``start``, moving to the next ONLY on a transport
    error before the first body byte (status 0, nothing streamed).  An
    HTTP error means the server answered (429 brownout etc.) and a
    mid-stream death means tokens were already consumed — neither is
    retried here, so no request is ever issued twice past first byte.
    The winning URL lands in ``served_by`` and the number of siblings
    tried in ``failovers``."""
    urls = [base_urls] if isinstance(base_urls, str) else list(base_urls)
    r = {}
    for k in range(max(len(urls), 1)):
        url = urls[(start + k) % len(urls)]
        r = _one_request_to(url, payload, stream, timeout)
        r["served_by"] = url
        r["failovers"] = k
        if r["ok"] or r["status"] != 0 or r.get("mid_stream"):
            break
    return r


def _one_request_to(base_url: str, payload: dict, stream: bool,
                    timeout: float) -> dict:
    """Returns {ok, status, secs, ttft_secs, tpot_secs, tokens, error?}.
    TPOT (time per output token) is client-observed inter-token latency
    — (last token - first token) / (tokens - 1) — measurable only on the
    streaming path, where tokens arrive one SSE event at a time."""
    path = "/api/stream" if stream else "/api"
    req = urllib.request.Request(
        base_url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="PUT")
    t0 = time.perf_counter()
    ttft = None
    t_last = None
    tokens = 0
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            if stream:
                for raw in resp:
                    line = raw.strip()
                    if not line.startswith(b"data: "):
                        continue
                    ev = json.loads(line[len(b"data: "):])
                    if "token" in ev:
                        t_last = time.perf_counter()
                        if ttft is None:
                            ttft = t_last - t0
                        tokens += 1
                    if ev.get("done"):
                        break
            else:
                body = json.loads(resp.read())
                ttft = time.perf_counter() - t0
                toks = body.get("tokens")
                if isinstance(toks, list):
                    tokens = sum(len(t) for t in toks
                                 if isinstance(t, list))
            tpot = None
            if stream and tokens > 1 and ttft is not None:
                tpot = (t_last - (t0 + ttft)) / (tokens - 1)
            return {"ok": True, "status": 200,
                    "secs": time.perf_counter() - t0,
                    "ttft_secs": ttft, "tpot_secs": tpot,
                    "tokens": tokens}
    except urllib.error.HTTPError as e:
        e.read()
        return {"ok": False, "status": e.code,
                "secs": time.perf_counter() - t0, "ttft_secs": None,
                "tpot_secs": None, "tokens": 0,
                "retry_after": e.headers.get("Retry-After")}
    except Exception as e:  # noqa: BLE001 - a bench must not die mid-run
        return {"ok": False, "status": 0,
                "secs": time.perf_counter() - t0, "ttft_secs": None,
                "tpot_secs": None, "tokens": 0,
                # tokens already streamed: failover must NOT re-issue
                "mid_stream": ttft is not None,
                "error": f"{type(e).__name__}: {e}"}


def _zipf_rank(rng, pool: int, alpha: float) -> int:
    """Draw a rank in [0, pool) with probability proportional to
    1/(rank+1)**alpha — rank 0 is the hottest prefix."""
    weights = [1.0 / (r + 1) ** alpha for r in range(max(pool, 1))]
    u = rng.random() * sum(weights)
    acc = 0.0
    for r, w in enumerate(weights):
        acc += w
        if u <= acc:
            return r
    return len(weights) - 1


def build_prompt(ticket: int, prompt: str, prefix_tokens: int,
                 shared_prefix_frac: float, seed: int,
                 prefix_zipf: float = 0.0, prefix_pool: int = 16) -> str:
    """Per-ticket prompt for the repeated-prefix workload.  A
    ``shared_prefix_frac`` fraction of tickets open with the same
    ``prefix_tokens``-word header (one small-number word ≈ one token for
    numeric tokenizers) and differ only in a short unique tail; the rest
    get fully unique prompts.  Deterministic in (ticket, seed).

    With ``prefix_zipf`` > 0 the shared header is instead drawn from a
    pool of ``prefix_pool`` distinct prefixes with Zipf(alpha)-skewed
    popularity — the workload the cache observatory's heat table and
    ghost capacity tiers are built to attribute (a few hot prefixes,
    a long cold tail that churns the LRU)."""
    if prefix_tokens <= 0:
        return prompt
    rng = random.Random(seed * 100003 + ticket)
    tail = " ".join(str(rng.randrange(10, 50)) for _ in range(4))
    if rng.random() < shared_prefix_frac:
        if prefix_zipf > 0:
            word = str(100 + _zipf_rank(rng, prefix_pool, prefix_zipf))
        else:
            word = "7"
        header = " ".join([word] * prefix_tokens)
        return f"{header} {tail}"
    # unique header of the same length: submits the same prefill volume
    # but can never hit the shared-prefix cache entries
    header = " ".join(str(rng.randrange(10, 50))
                      for _ in range(prefix_tokens))
    return f"{header} {tail}"


def run_bench(base_url: str, clients: int = 4, requests: int = 16,
              tokens: int = 32, prompt: str = "1 2 3 4",
              rate: float = 0.0, stream: bool = False,
              timeout: float = 300.0, seed: int = 0,
              prefix_tokens: int = 0,
              shared_prefix_frac: float = 1.0,
              prefix_zipf: float = 0.0,
              prefix_pool: int = 16,
              rate_schedule: str = None,
              temperature: float = None,
              ttft_slo: float = 1.0,
              tpot_slo: float = 0.25) -> dict:
    """Drive the load and aggregate results (importable — the tier-1
    smoke test calls this directly against an in-process server).

    With ``rate_schedule`` ("r1:t1,r2:t2,...") the request count and
    arrival times come from the piecewise Poisson schedule —
    ``requests`` and ``rate`` are ignored — and the summary gains a
    per-segment breakdown (``segments``).

    ``base_url`` may be a list of front-door URLs (a sharded router
    tier): requests round-robin across them and fail over to the next
    on a transport error before first byte."""
    urls = [base_url] if isinstance(base_url, str) else list(base_url)
    results = []
    results_lock = threading.Lock()
    schedule = parse_rate_schedule(rate_schedule) if rate_schedule \
        else None
    arrivals = build_arrivals(schedule, seed) if schedule else None
    n_total = len(arrivals) if arrivals is not None \
        else max(int(requests), 1)
    issued = {"n": 0}
    issue_lock = threading.Lock()
    rng = random.Random(seed)
    start_gate = threading.Event()
    t_start = None

    def take_ticket():
        with issue_lock:
            if issued["n"] >= n_total:
                return None
            issued["n"] += 1
            return issued["n"] - 1

    def client_loop():
        start_gate.wait()
        while True:
            ticket = take_ticket()
            if ticket is None:
                return
            segment = None
            if arrivals is not None:
                # absolute deadline, not a relative gap: late clients
                # don't stretch the schedule
                offset, segment = arrivals[ticket]
                delay = (t_start + offset) - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
            elif rate > 0:
                # open-loop Poisson arrivals across the fleet: each
                # client sleeps an exponential gap scaled by fleet size
                time.sleep(rng.expovariate(rate / max(clients, 1)))
            payload = {"prompts": [build_prompt(
                           ticket, prompt, prefix_tokens,
                           shared_prefix_frac, seed,
                           prefix_zipf, prefix_pool)],
                       "tokens_to_generate": int(tokens),
                       "no_log": True}
            if temperature is not None:
                # 0.0 = greedy — the workload speculative decoding
                # drafts on (sampled slots never draft)
                payload["temperature"] = float(temperature)
            r = _one_request(urls, payload, stream, timeout,
                             start=ticket % len(urls))
            if segment is not None:
                r["segment"] = segment
            with results_lock:
                results.append(r)

    m0 = _fetch_metrics(urls)
    threads = [threading.Thread(target=client_loop, daemon=True)
               for _ in range(max(int(clients), 1))]
    for t in threads:
        t.start()
    t_start = time.perf_counter()
    start_gate.set()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    m1 = _fetch_metrics(urls)

    ok = [r for r in results if r["ok"]]
    lat = [r["secs"] for r in ok]
    ttft = [r["ttft_secs"] for r in ok if r["ttft_secs"] is not None]
    tpot = [r["tpot_secs"] for r in ok if r.get("tpot_secs") is not None]
    total_tokens = sum(r["tokens"] for r in ok)

    def _slo_attained(r):
        # joint SLO verdict per request: failures never attain; latency
        # dimensions only gate when the client actually measured them
        # (TTFT/TPOT need --stream)
        if not r["ok"]:
            return False
        t = r.get("ttft_secs")
        if t is not None and t > ttft_slo:
            return False
        tp = r.get("tpot_secs")
        if tp is not None and tp > tpot_slo:
            return False
        return True

    slo_attained = sum(1 for r in results if _slo_attained(r))
    by_status = {}
    for r in results:
        by_status[str(r["status"])] = by_status.get(str(r["status"]), 0) + 1
    per_url = {u: 0 for u in urls}
    for r in results:
        served = r.get("served_by")
        if served in per_url:
            per_url[served] += 1
    out = {
        "url": urls[0],
        # sharded front door: every URL tried, per-router dispatch
        # counts, and how many requests needed a sibling router
        "urls": urls,
        "per_url_requests": per_url,
        "failovers": sum(r.get("failovers", 0) for r in results),
        "clients": clients,
        "requests": len(results),
        "ok": len(ok),
        "errors": len(results) - len(ok),
        "status_counts": by_status,
        "wall_secs": wall,
        "requests_per_sec": len(ok) / wall if wall > 0 else None,
        "tokens_total": total_tokens,
        "tokens_per_sec": total_tokens / wall if wall > 0 else None,
        "latency_mean_secs": sum(lat) / len(lat) if lat else None,
        "latency_p50_secs": _percentile(lat, 0.50),
        "latency_p95_secs": _percentile(lat, 0.95),
        "latency_p99_secs": _percentile(lat, 0.99),
        "ttft_mean_secs": sum(ttft) / len(ttft) if ttft else None,
        "ttft_p50_secs": _percentile(ttft, 0.50),
        "ttft_p95_secs": _percentile(ttft, 0.95),
        # client-observed per-output-token decode latency (--stream only)
        "tpot_mean_secs": sum(tpot) / len(tpot) if tpot else None,
        "tpot_p50_secs": _percentile(tpot, 0.50),
        "tpot_p95_secs": _percentile(tpot, 0.95),
        "stream": stream,
        "rate": rate,
        # piecewise-rate workload (--rate_schedule): the spec string and
        # a per-segment breakdown (filled below), None on constant rate
        "rate_schedule": rate_schedule,
        "segments": None,
        "prefix_tokens": prefix_tokens,
        "shared_prefix_frac": shared_prefix_frac,
        "prefix_zipf": prefix_zipf,
        "prefix_pool": prefix_pool,
        # prefix-cache effectiveness (engine /metrics deltas; None when
        # the server has no engine metrics to delta)
        "prefill_tokens_submitted": None,
        "prefill_tokens_computed": None,
        "prefill_tokens_cached": None,
        "prefill_computed_frac": None,
        # computed-prefill tokens/sec over the run wall clock — the
        # number a prefill-kernel A/B actually changes
        "prefill_tokens_per_sec": None,
        "prefix_cache_hits": None,
        "prefix_cache_misses": None,
        "prefix_cache_evictions": None,
        # which attention paths served the run ('pallas'|'xla', from the
        # engine /metrics block) — makes bench rows attributable
        "paged_kernel": None,
        "prefill_kernel": None,
        # resilience activity during the run (engine restarts, sentinel
        # slot evictions, pool-pressure preemptions, drain initiations)
        "engine_restarts": None,
        "slots_evicted_nonfinite": None,
        "preemptions": None,
        "drained": None,
        # speculative decoding: drafted/accepted engine counter deltas,
        # their ratio, and accepted tokens/sec — the number a
        # --ab serve_speculative run actually changes
        "drafted_tokens": None,
        "accepted_tokens": None,
        "accept_rate": None,
        "accepted_tokens_per_sec": None,
        # engine-loop goodput (loop_profiler deltas over the run)
        "device_busy_pct": None,
        "host_bubble_pct": None,
        # cache observatory (engine cache block deltas over the run):
        # miss-cause split, eviction forensics, and per-ghost-tier
        # projected hit rates computed from hit/probe counter deltas
        "cache_miss_cold": None,
        "cache_miss_evicted": None,
        "cache_evictions_capacity": None,
        "cache_evictions_churn": None,
        "ghost_hit_rates": None,
        # hierarchical KV cache (host-RAM spill tier counter deltas)
        "cache_host_hits": None,
        "cache_host_spills": None,
        "cache_swap_in_blocks": None,
        "cache_swap_in_secs": None,
        # client-observed joint SLO attainment against the targets
        # above; "slo_gate" echoes --slo_gate (None when no gate)
        "ttft_slo_secs": ttft_slo,
        "tpot_slo_secs": tpot_slo,
        "slo_joint_attainment": (round(slo_attained / len(results), 4)
                                 if results else None),
        "slo_gate": None,
    }
    if schedule:
        segs = []
        for i, (seg_rate, seg_dur) in enumerate(schedule):
            rs = [r for r in results if r.get("segment") == i]
            oks = [r for r in rs if r["ok"]]
            seg_lat = [r["secs"] for r in oks]
            seg_ttft = [r["ttft_secs"] for r in oks
                        if r["ttft_secs"] is not None]
            segs.append({
                "segment": i,
                "rate": seg_rate,
                "duration_secs": seg_dur,
                "requests": len(rs),
                "ok": len(oks),
                "errors": len(rs) - len(oks),
                "requests_per_sec": round(len(oks) / seg_dur, 3),
                "latency_p95_secs": _percentile(seg_lat, 0.95),
                "ttft_p95_secs": _percentile(seg_ttft, 0.95),
            })
        out["segments"] = segs
    if m0 is not None and m1 is not None:
        # a router /metrics nests the fleet-summed engine counters (and
        # request counts) under "aggregate" — delta those transparently
        if "aggregate" in m1 and "engine" not in m1:
            m0 = m0.get("aggregate") or {}
            m1 = m1.get("aggregate") or {}
        out["server_metrics_delta"] = {
            "requests": m1.get("requests", 0) - m0.get("requests", 0),
            "errors": m1.get("errors", 0) - m0.get("errors", 0),
            "throttled": m1.get("throttled", 0) - m0.get("throttled", 0),
        }
        if isinstance(m0.get("drained"), (int, float)) \
                and isinstance(m1.get("drained"), (int, float)):
            out["drained"] = m1["drained"] - m0["drained"]
        e0, e1 = m0.get("engine"), m1.get("engine")
        if isinstance(e1, dict):
            out["server_engine"] = e1
            out["paged_kernel"] = e1.get("paged_kernel")
            out["prefill_kernel"] = e1.get("prefill_kernel")
            if isinstance(e0, dict):
                def delta(key):
                    a, b = e0.get(key), e1.get(key)
                    if isinstance(a, (int, float)) \
                            and isinstance(b, (int, float)):
                        return b - a
                    return None
                for key in ("prefill_tokens_submitted",
                            "prefill_tokens_computed",
                            "prefill_tokens_cached",
                            "prefix_cache_hits", "prefix_cache_misses",
                            "prefix_cache_evictions",
                            "engine_restarts",
                            "slots_evicted_nonfinite",
                            "preemptions",
                            "drafted_tokens", "accepted_tokens"):
                    out[key] = delta(key)
                sub, comp = (out["prefill_tokens_submitted"],
                             out["prefill_tokens_computed"])
                if sub and comp is not None:
                    out["prefill_computed_frac"] = round(comp / sub, 4)
                if comp is not None and wall > 0:
                    out["prefill_tokens_per_sec"] = round(comp / wall, 3)
                drafted, accepted = (out["drafted_tokens"],
                                     out["accepted_tokens"])
                if drafted and accepted is not None:
                    out["accept_rate"] = round(accepted / drafted, 4)
                if accepted is not None and wall > 0:
                    out["accepted_tokens_per_sec"] = round(
                        accepted / wall, 3)
                # engine-loop goodput: recompute the busy-time split
                # from cumulative loop counter deltas (the percentages
                # themselves never delta or sum; a router's aggregate
                # sums the per-replica counters, which still deltas
                # correctly)
                # cache observatory: miss-cause / forensics deltas and
                # ghost tier hit rates over this run's probes only
                c0 = e0.get("cache")
                c1 = e1.get("cache")
                if isinstance(c0, dict) and isinstance(c1, dict):
                    def cache_delta(key):
                        a, b = c0.get(key), c1.get(key)
                        if isinstance(a, (int, float)) \
                                and isinstance(b, (int, float)):
                            return b - a
                        return None
                    out["cache_miss_cold"] = cache_delta("miss_cold")
                    out["cache_miss_evicted"] = cache_delta("miss_evicted")
                    out["cache_evictions_capacity"] = cache_delta(
                        "evictions_capacity")
                    out["cache_evictions_churn"] = cache_delta(
                        "evictions_churn")
                    g0 = c0.get("ghost")
                    g1 = c1.get("ghost")
                    if isinstance(g0, dict) and isinstance(g1, dict):
                        rates = {}
                        for tier, t1 in sorted(g1.items()):
                            t0g = g0.get(tier)
                            if not (isinstance(t0g, dict)
                                    and isinstance(t1, dict)):
                                continue
                            dh = (t1.get("hits") or 0) - \
                                (t0g.get("hits") or 0)
                            dp = dh + (t1.get("misses") or 0) - \
                                (t0g.get("misses") or 0)
                            if dp > 0:
                                rates[tier] = round(dh / dp, 4)
                        if rates:
                            out["ghost_hit_rates"] = rates
                    # host-RAM spill tier: two-tier hit attribution
                    # lives on the observatory (host_hits,
                    # swap_in_blocks); spill/swap-in volume on the
                    # tier's own sub-block (cache.host.*)
                    out["cache_host_hits"] = cache_delta("host_hits")
                    out["cache_swap_in_blocks"] = cache_delta(
                        "swap_in_blocks")
                    h0 = c0.get("host")
                    h1 = c1.get("host")
                    if isinstance(h0, dict) and isinstance(h1, dict):
                        def host_delta(key):
                            a, b = h0.get(key), h1.get(key)
                            if isinstance(a, (int, float)) \
                                    and isinstance(b, (int, float)):
                                return b - a
                            return None
                        out["cache_host_spills"] = host_delta(
                            "spills_completed")
                        sw = host_delta("swap_in_secs")
                        if sw is not None:
                            out["cache_swap_in_secs"] = round(sw, 6)
                l0 = e0.get("loop")
                l1 = e1.get("loop")
                if isinstance(l0, dict) and isinstance(l1, dict):
                    def loop_delta(key):
                        a, b = l0.get(key), l1.get(key)
                        if isinstance(a, (int, float)) \
                                and isinstance(b, (int, float)):
                            return b - a
                        return None
                    dev = loop_delta("device_secs")
                    busy = loop_delta("wall_secs")
                    gap = loop_delta("gap_secs")
                    if dev is not None and busy is not None:
                        busy += gap or 0.0
                        if busy > 0:
                            pct = 100.0 * min(dev / busy, 1.0)
                            out["device_busy_pct"] = round(pct, 3)
                            out["host_bubble_pct"] = round(100.0 - pct, 3)
    return out


def run_ab(urls, labels, **kw) -> list:
    """Kernel A/B: run the identical workload once per arm (a server
    started with ``--serve_paged_kernel on`` and one with ``off``) and
    tag each row with its arm label plus the attention path the server
    actually reports — both rows land in the ``--json`` output."""
    rows = []
    for label, url in zip(labels, urls):
        r = run_bench(url, **kw)
        r["ab_arm"] = label
        rows.append(r)
    return rows


def _fmt(v, unit=""):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.3f}{unit}"
    return f"{v}{unit}"


def print_table(r: dict) -> None:
    rows = [
        ("requests (ok/total)", f"{r['ok']}/{r['requests']}"),
        ("status counts", json.dumps(r["status_counts"])),
        ("wall time", _fmt(r["wall_secs"], "s")),
        ("throughput", _fmt(r["requests_per_sec"], " req/s")),
        ("token throughput", _fmt(r["tokens_per_sec"], " tok/s")),
        ("latency mean", _fmt(r["latency_mean_secs"], "s")),
        ("latency p50", _fmt(r["latency_p50_secs"], "s")),
        ("latency p95", _fmt(r["latency_p95_secs"], "s")),
        ("latency p99", _fmt(r["latency_p99_secs"], "s")),
        ("ttft mean", _fmt(r["ttft_mean_secs"], "s")),
        ("ttft p50", _fmt(r["ttft_p50_secs"], "s")),
        ("ttft p95", _fmt(r["ttft_p95_secs"], "s")),
        ("tpot p50", _fmt(r["tpot_p50_secs"], "s")),
        ("tpot p95", _fmt(r["tpot_p95_secs"], "s")),
    ]
    if len(r.get("urls") or ()) > 1:
        rows[1:1] = [
            ("router dispatch", json.dumps(r["per_url_requests"])),
            ("router failovers", _fmt(r["failovers"])),
        ]
    eng = r.get("server_engine")
    if eng:
        rows += [
            ("engine occupancy", _fmt(eng.get("mean_batch_occupancy"))),
            ("engine decode steps", _fmt(eng.get("decode_steps"))),
            ("engine prefill chunks", _fmt(eng.get("prefill_chunks"))),
            ("engine paged kernel", _fmt(r.get("paged_kernel"))),
            ("engine prefill kernel", _fmt(r.get("prefill_kernel"))),
        ]
    if r.get("prefill_tokens_per_sec") is not None:
        rows += [("prefill throughput",
                  _fmt(r["prefill_tokens_per_sec"], " tok/s"))]
    if r.get("device_busy_pct") is not None:
        rows += [("loop device busy / host bubble",
                  f"{_fmt(r['device_busy_pct'], '%')} / "
                  f"{_fmt(r['host_bubble_pct'], '%')}")]
    if r.get("drafted_tokens") is not None:
        rows += [
            ("spec accepted/drafted",
             f"{_fmt(r['accepted_tokens'])}/{_fmt(r['drafted_tokens'])}"
             + (f" ({_fmt(r['accept_rate'])})"
                if r.get("accept_rate") is not None else "")),
        ]
        if r.get("accepted_tokens_per_sec") is not None:
            rows += [("spec accepted throughput",
                      _fmt(r["accepted_tokens_per_sec"], " tok/s"))]
    if r.get("prefill_tokens_submitted") is not None:
        rows += [
            ("prefill computed/submitted",
             f"{_fmt(r['prefill_tokens_computed'])}/"
             f"{_fmt(r['prefill_tokens_submitted'])}"
             + (f" ({_fmt(r['prefill_computed_frac'])})"
                if r.get("prefill_computed_frac") is not None else "")),
            ("prefix cache hit/miss/evict",
             f"{_fmt(r['prefix_cache_hits'])}/"
             f"{_fmt(r['prefix_cache_misses'])}/"
             f"{_fmt(r['prefix_cache_evictions'])}"),
        ]
    if r.get("cache_miss_cold") is not None:
        rows += [
            ("cache miss cold/evicted",
             f"{_fmt(r['cache_miss_cold'])}/"
             f"{_fmt(r['cache_miss_evicted'])}"),
            ("cache evict capacity/churn",
             f"{_fmt(r['cache_evictions_capacity'])}/"
             f"{_fmt(r['cache_evictions_churn'])}"),
        ]
    if r.get("ghost_hit_rates"):
        rows += [("ghost tier hit rates",
                  " ".join(f"{t}={v:.3f}"
                           for t, v in sorted(r["ghost_hit_rates"].items())))]
    if r.get("cache_host_hits") is not None:
        rows += [("host tier hit/spill/swap-in",
                  f"{_fmt(r['cache_host_hits'])}/"
                  f"{_fmt(r['cache_host_spills'])}/"
                  f"{_fmt(r['cache_swap_in_blocks'])}"
                  + (f" ({_fmt(r['cache_swap_in_secs'], 's')} swap)"
                     if r.get("cache_swap_in_secs") is not None else ""))]
    w = max(len(k) for k, _ in rows)
    print(f"serve_bench: {r['clients']} clients -> {r['url']}"
          + (" (stream)" if r["stream"] else ""))
    for k, v in rows:
        print(f"  {k:<{w}}  {v}")
    if r.get("segments"):
        print(f"  rate schedule ({r.get('rate_schedule')}):")
        print(f"    {'seg':>3} {'rate':>8} {'secs':>7} {'ok/total':>9} "
              f"{'req/s':>8} {'lat p95':>9} {'ttft p95':>9}")
        for s in r["segments"]:
            print(f"    {s['segment']:>3} {_fmt(s['rate']):>8} "
                  f"{_fmt(s['duration_secs']):>7} "
                  f"{s['ok']}/{s['requests']:<7} "
                  f"{_fmt(s['requests_per_sec']):>8} "
                  f"{_fmt(s['latency_p95_secs']):>9} "
                  f"{_fmt(s['ttft_p95_secs']):>9}")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=5000)
    p.add_argument("--url", default=None, action="append",
                   help="full base URL (overrides --host/--port); "
                        "repeat for a sharded front door — requests "
                        "round-robin over the URLs and fail over to the "
                        "next on a transport error before first byte")
    p.add_argument("--clients", type=int, default=4)
    p.add_argument("--requests", type=int, default=16,
                   help="total requests across all clients")
    p.add_argument("--tokens", type=int, default=32,
                   help="tokens_to_generate per request")
    p.add_argument("--prompt", default="1 2 3 4")
    p.add_argument("--rate", type=float, default=0.0,
                   help="open-loop Poisson arrival rate in req/s across "
                        "the fleet (0 = closed loop)")
    p.add_argument("--rate_schedule", default=None,
                   metavar="R1:T1,R2:T2,...",
                   help="piecewise open-loop Poisson rates (req/s for "
                        "secs each; 0 rate = silent pause) for "
                        "spike->recover workloads; overrides --rate and "
                        "--requests and adds a per-segment table")
    p.add_argument("--stream", action="store_true",
                   help="use /api/stream (measures true TTFT)")
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--temperature", type=float, default=None,
                   help="per-request sampling temperature (0 = greedy, "
                        "the mode speculative decoding drafts on); "
                        "omitted from the payload by default")
    p.add_argument("--prefix_tokens", type=int, default=0,
                   help="repeated-prefix workload: shared prompt header "
                        "length in words (0 = off, all prompts identical "
                        "to --prompt)")
    p.add_argument("--prefix_zipf", type=float, default=0.0,
                   help="skewed-popularity prefix workload: draw each "
                        "shared header from a pool of --prefix_pool "
                        "distinct prefixes with Zipf(ALPHA) popularity "
                        "(0 = single shared prefix, the default)")
    p.add_argument("--prefix_pool", type=int, default=16,
                   help="distinct shared prefixes for --prefix_zipf")
    p.add_argument("--shared_prefix_frac", type=float, default=1.0,
                   help="fraction of requests sharing the header; the "
                        "rest get unique same-length headers")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit one JSON object instead of the table")
    p.add_argument("--slo_gate", type=float, default=None,
                   metavar="FRAC",
                   help="exit 3 unless the joint SLO attainment "
                        "(fraction of requests succeeding within "
                        "--ttft_slo and --tpot_slo; failures never "
                        "attain) reaches FRAC; under --ab the MIN "
                        "across both arms gates")
    p.add_argument("--ttft_slo", type=float, default=1.0,
                   help="time-to-first-token target in seconds for "
                        "--slo_gate (matches serve_report's default)")
    p.add_argument("--tpot_slo", type=float, default=0.25,
                   help="per-output-token target in seconds for "
                        "--slo_gate (matches serve_report's default)")
    p.add_argument("--ab", default=None, metavar="SERVER_FLAG",
                   help="A/B comparison over any boolean server flag "
                        "(e.g. serve_paged_kernel, serve_prefill_kernel): "
                        "run the workload against --url (the flag-ON "
                        "server) and --ab_url (the flag-OFF server), "
                        "emitting one row per arm")
    p.add_argument("--ab_url", default=None,
                   help="base URL of the second (flag-OFF) server for "
                        "--ab")
    args = p.parse_args(argv)
    base_url = args.url or [f"http://{args.host}:{args.port}"]
    kw = dict(clients=args.clients, requests=args.requests,
              tokens=args.tokens, prompt=args.prompt, rate=args.rate,
              stream=args.stream, timeout=args.timeout, seed=args.seed,
              prefix_tokens=args.prefix_tokens,
              shared_prefix_frac=args.shared_prefix_frac,
              prefix_zipf=args.prefix_zipf,
              prefix_pool=args.prefix_pool,
              rate_schedule=args.rate_schedule,
              temperature=args.temperature,
              ttft_slo=args.ttft_slo, tpot_slo=args.tpot_slo)

    def slo_gate_rc(rows):
        # exit 3 on gate miss — distinct from 1 (request errors) so a
        # sweep can tell "server broke" from "server too slow"
        if args.slo_gate is None:
            return None
        atts = [r.get("slo_joint_attainment") for r in rows]
        worst = min((a for a in atts if a is not None), default=None)
        if worst is None or worst < args.slo_gate:
            print(f"SLO gate FAILED: joint attainment "
                  f"{worst if worst is not None else 'unmeasured'} "
                  f"< {args.slo_gate}", file=sys.stderr)
            return 3
        return None

    if args.ab:
        if not args.ab_url:
            p.error("--ab needs --ab_url (the second arm's server)")
        rows = run_ab([base_url, args.ab_url], ["on", "off"], **kw)
        for row in rows:
            row["slo_gate"] = args.slo_gate
        if args.as_json:
            print(json.dumps({"ab": args.ab, "rows": rows}, indent=2))
        else:
            for r in rows:
                served = (f"decode={r.get('paged_kernel') or 'unknown'} "
                          f"prefill={r.get('prefill_kernel') or 'unknown'}")
                print(f"--- {args.ab}={r['ab_arm']} (served by: {served})")
                print_table(r)
            on, off = rows
            if on["tokens_per_sec"] and off["tokens_per_sec"]:
                print(f"A/B token throughput on/off: "
                      f"{on['tokens_per_sec']:.3f} / "
                      f"{off['tokens_per_sec']:.3f} tok/s "
                      f"({on['tokens_per_sec'] / off['tokens_per_sec']:.2f}x)")
            if on.get("accept_rate") is not None or \
                    off.get("accept_rate") is not None:
                print(f"A/B spec accept rate on/off: "
                      f"{_fmt(on.get('accept_rate'))} / "
                      f"{_fmt(off.get('accept_rate'))} "
                      f"(accepted {_fmt(on.get('accepted_tokens'))} / "
                      f"{_fmt(off.get('accepted_tokens'))} tok)")
            if on.get("prefill_tokens_per_sec") and \
                    off.get("prefill_tokens_per_sec"):
                print(f"A/B prefill throughput on/off: "
                      f"{on['prefill_tokens_per_sec']:.3f} / "
                      f"{off['prefill_tokens_per_sec']:.3f} tok/s "
                      f"({on['prefill_tokens_per_sec'] / off['prefill_tokens_per_sec']:.2f}x)")
            if on.get("device_busy_pct") is not None or \
                    off.get("device_busy_pct") is not None:
                # the loop-overlap A/B readout: did the flag move the
                # host bubble, and did tokens/sec follow?
                print(f"A/B loop device busy on/off: "
                      f"{_fmt(on.get('device_busy_pct'), '%')} / "
                      f"{_fmt(off.get('device_busy_pct'), '%')} "
                      f"(host bubble "
                      f"{_fmt(on.get('host_bubble_pct'), '%')} / "
                      f"{_fmt(off.get('host_bubble_pct'), '%')})")
            if on.get("cache_host_hits") or off.get("cache_host_hits"):
                # the hierarchical-cache A/B readout: blocks rescued
                # from host RAM, and did mean TTFT follow?
                print(f"A/B host-tier hit blocks on/off: "
                      f"{_fmt(on.get('cache_host_hits'))} / "
                      f"{_fmt(off.get('cache_host_hits'))} "
                      f"(ttft mean "
                      f"{_fmt(on.get('ttft_mean_secs'), 's')} / "
                      f"{_fmt(off.get('ttft_mean_secs'), 's')})")
        rc = slo_gate_rc(rows)
        if rc is not None:
            return rc
        return 0 if all(r["errors"] == 0 for r in rows) else 1
    r = run_bench(base_url, **kw)
    r["slo_gate"] = args.slo_gate
    if args.as_json:
        print(json.dumps(r, indent=2))
    else:
        print_table(r)
    rc = slo_gate_rc([r])
    if rc is not None:
        return rc
    return 0 if r["errors"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
