#!/usr/bin/env python
"""AOT serving-scale proof: long-context decode memory, bf16 vs int8.

Training scale is proven by tools/aot_memcheck.py; this is the DECODE
side.  The claim under test: **int8 weights + the int8 KV cache make a
64k-token-context Llama-3-8B serveable on ONE 16-GB v5e chip, where
bf16 cannot fit** (bf16: ~16 GB weights + ~8 GB KV ≈ 24+ GB; int8:
~8 GB + ~4 GB ≈ 13 GB).  The decode-step function (one token through
the full-length cache — the loop body whose residency dominates
serving memory) is AOT-compiled against a virtual v5e through the real
libtpu compiler, and ``memory_analysis()`` reports per-chip bytes.

Usage:
  python tools/aot_decode_memcheck.py            # the 8B/64k headline rows
  python tools/aot_decode_memcheck.py tiny       # CI-sized smoke rows

Each row runs in a sanitized forced-CPU subprocess (AOT needs only the
local libtpu compiler, never the axon tunnel).  One JSON line per row.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

GB = 1 << 30

# llama-3-8b true shape: 32 L, h 4096, 32 q / 8 kv heads, ffn 14336,
# vocab 128256 (the 128k vocab also exercises the fused-CE-free decode
# head).  ctx = prompt + generation budget the cache must hold.
# hbm_gb is libtpu's USABLE v5e budget (its own refusal message says
# "of 15.75G hbm"), not the 16-GB nameplate — a total in (15.75, 16]
# must be a NO
ROWS = {
    "l3-8b-64k-bf16": dict(L=32, h=4096, heads=32, kv=8, ffn=14336,
                           vocab=128256, ctx=65536, wq=False, kvq=False,
                           hbm_gb=15.75),
    "l3-8b-64k-int8": dict(L=32, h=4096, heads=32, kv=8, ffn=14336,
                           vocab=128256, ctx=65536, wq=True, kvq=True,
                           hbm_gb=15.75),
    # speculative decode step: the loop body is the [b, K+1] verify
    # window (serving/engine.py with --serve_speculative), so the
    # residency claim must hold for THAT shape too — K extra query
    # positions and K extra logits rows on top of the int8 row
    "l3-8b-64k-int8-spec4": dict(L=32, h=4096, heads=32, kv=8,
                                 ffn=14336, vocab=128256, ctx=65536,
                                 wq=True, kvq=True, spec_k=4,
                                 hbm_gb=15.75),
    # CI-sized smoke (same code path, minutes -> seconds)
    "tiny-bf16": dict(L=2, h=256, heads=4, kv=2, ffn=704, vocab=512,
                      ctx=512, wq=False, kvq=False, hbm_gb=15.75),
    "tiny-int8": dict(L=2, h=256, heads=4, kv=2, ffn=704, vocab=512,
                      ctx=512, wq=True, kvq=True, hbm_gb=15.75),
    "tiny-int8-spec4": dict(L=2, h=256, heads=4, kv=2, ffn=704,
                            vocab=512, ctx=512, wq=True, kvq=True,
                            spec_k=4, hbm_gb=15.75),
}


def run_row(name: str) -> dict:
    spec = ROWS[name]
    # off-GCP the metadata server 403s and libtpu retries each variable
    # 30x with backoff before the topology init can proceed — skip it
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "true")
    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies

    from megatron_llm_tpu.models.llama import LlamaModel, llama_config
    from megatron_llm_tpu.quantization import quantize_linear_weights_int8
    from megatron_llm_tpu.text_generation.generation import (
        _forward_with_cache,
        init_kv_caches,
    )

    topo = topologies.get_topology_desc(platform="tpu",
                                       topology_name="v5e:2x2")
    dev = topo.devices[0]

    cfg = llama_config(
        "tiny", num_layers=spec["L"], hidden_size=spec["h"],
        num_attention_heads=spec["heads"],
        num_attention_heads_kv=spec["kv"],
        ffn_hidden_size=spec["ffn"], padded_vocab_size=spec["vocab"],
        seq_length=spec["ctx"], max_position_embeddings=spec["ctx"],
        params_dtype="bf16", compute_dtype="bf16",
        # flash never engages in decode (kv_cache forwards use the
        # masked XLA path); keep it off so the row is decode-honest
        use_flash_attn=False, use_fused_rmsnorm=False,
        rope_theta=500000.0,
    )
    model = LlamaModel(cfg)
    params_shape = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    if spec["wq"]:
        params_shape = jax.eval_shape(quantize_linear_weights_int8,
                                      params_shape)
    n_params = sum(int(x.size)
                   for x in jax.tree_util.tree_leaves(params_shape))

    b = 1
    caches_shape = jax.eval_shape(
        lambda: init_kv_caches(cfg, b, spec["ctx"],
                               quantized=spec["kvq"]))

    # spec_k > 0 rows prove the speculative-decoding loop body instead:
    # the engine's fixed-shape [b, K+1] verify window at the last cache
    # positions (K draft tokens + the bonus row)
    k1 = int(spec.get("spec_k", 0)) + 1

    def decode_step(params, caches, tok):
        # one decoded token (or the K+1 verify window) at the LAST
        # cache positions: the steady-state loop body (cache fully
        # resident, weights read once)
        logits, caches = _forward_with_cache(
            model, params, tok, caches, spec["ctx"] - k1)
        return jnp.argmax(logits, axis=-1), caches

    tok = jax.ShapeDtypeStruct((b, k1), jnp.int32)
    print(f"[{name}] lowering ({n_params/1e9:.2f}B params, "
          f"ctx {spec['ctx']})...", file=sys.stderr, flush=True)
    lowered = jax.jit(decode_step, device=dev,
                      donate_argnums=(1,)).lower(
        params_shape, caches_shape, tok)
    print(f"[{name}] compiling...", file=sys.stderr, flush=True)
    compiled = lowered.compile()
    ma = compiled.memory_analysis()
    arg = int(ma.argument_size_in_bytes)
    out = int(ma.output_size_in_bytes)
    tmp = int(ma.temp_size_in_bytes)
    alias = int(ma.alias_size_in_bytes)
    total = (arg + out + tmp - alias) / GB
    rec = {
        "row": name, "n_params": n_params, "ctx": spec["ctx"],
        "int8_weights": spec["wq"], "int8_kv": spec["kvq"],
        "spec_k": spec.get("spec_k", 0),
        "arg_gb": round(arg / GB, 3), "temp_gb": round(tmp / GB, 3),
        "total_gb": round(total, 3), "hbm_gb": spec["hbm_gb"],
        "fits": total <= spec["hbm_gb"],
    }
    print(json.dumps(rec), flush=True)
    return rec


def main(argv):
    if argv and argv[0] == "--list":
        print("\n".join(ROWS))
        return 0
    if argv and argv[0] == "tiny":
        names = [n for n in ROWS if n.startswith("tiny")]
    elif argv:
        names = argv
    else:
        names = [n for n in ROWS if n.startswith("l3-")]
    results = []
    rc = 0
    for name in names:
        # targeted sanitization (same as aot_memcheck.py): drop only the
        # axon tunnel vars, keep/seed the libtpu init vars
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("JAX_PLATFORM_NAME", None)
        env.setdefault("TPU_WORKER_HOSTNAMES", "localhost")
        env.update(JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""))
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--one", name],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=3600)
        sys.stderr.write(r.stderr)
        line = [l for l in r.stdout.splitlines() if l.startswith("{")]
        if r.returncode != 0 or not line:
            # a compiler RESOURCE_EXHAUSTED is a first-class verdict:
            # the config does NOT fit, and libtpu says by how much
            import re as _re
            m = _re.search(r"Used ([0-9.]+)G of ([0-9.]+)G hbm",
                           r.stderr or "")
            if m:
                rec = {"row": name, "ctx": ROWS[name]["ctx"],
                       "int8_weights": ROWS[name]["wq"],
                       "int8_kv": ROWS[name]["kvq"],
                       "spec_k": ROWS[name].get("spec_k", 0),
                       "total_gb": float(m.group(1)),
                       "hbm_gb": ROWS[name]["hbm_gb"], "fits": False,
                       "compiler_verdict": "RESOURCE_EXHAUSTED",
                       "n_params": None}
                results.append(rec)
                print(json.dumps(rec), flush=True)
            else:
                print(json.dumps({"row": name, "error":
                                  (r.stderr or "no output")[-300:]}))
                rc = 1
            continue
        results.append(json.loads(line[-1]))
        print(line[-1], flush=True)
    if results:
        print(f"\n{'row':22s} {'params':>8s} {'ctx':>7s} "
              f"{'total GB':>9s} fits")
        for r in results:
            npb = (f"{r['n_params']/1e9:7.2f}B" if r["n_params"]
                   else "      —")
            verdict = "YES" if r["fits"] else \
                "NO (compiler: RESOURCE_EXHAUSTED)"
            print(f"{r['row']:22s} {npb} "
                  f"{r['ctx']:7d} {r['total_gb']:9.2f} {verdict}")
    return rc


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--one":
        run_row(sys.argv[2])
        sys.exit(0)
    sys.exit(main(sys.argv[1:]))
