#!/usr/bin/env python
"""Checkpoint resharding / dtype conversion.

Reference: ``tools/checkpoint_util.py`` — spawns loader & saver processes
connected by a queue speaking a named-message protocol to re-split
``mp_rank_XX_YYY`` shard files for a new (tp, pp) (:6-88).

TPU: checkpoints are *layout independent* — one logical pytree, written
sharded by orbax/tensorstore.  Re-sharding to a new (tp, pp, dp) happens
implicitly on load (``jax.device_put`` against the new mesh), so this tool
reduces to load -> (optional dtype cast / arg rewrite) -> save.  It exists
for CLI parity and for the cases the reference tool also covers: changing
dtype, re-recording parallel sizes in args, re-writing a release
checkpoint.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--load_dir", required=True)
    p.add_argument("--save_dir", required=True)
    p.add_argument("--target_tensor_parallel_size", type=int, default=None)
    p.add_argument("--target_pipeline_parallel_size", type=int, default=None)
    p.add_argument("--target_data_parallel_size", type=int, default=None)
    p.add_argument("--dtype", choices=["fp32", "bf16", "fp16"], default=None)
    p.add_argument("--release", action="store_true",
                   help="write as a release checkpoint (iteration 0)")
    p.add_argument("--input_format", choices=["orbax", "megatron"],
                   default="orbax",
                   help="megatron = reference-layout torch mp_rank "
                        "checkpoint (weights_conversion/megatron_ckpt.py)")
    p.add_argument("--output_format", choices=["orbax", "megatron"],
                   default="orbax")
    args = p.parse_args()

    import jax.numpy as jnp
    import jax

    from megatron_llm_tpu import checkpointing

    if args.input_format == "megatron":
        from weights_conversion.megatron_ckpt import (
            load_reference_checkpoint,
        )

        params, cfg_over, meta = load_reference_checkpoint(args.load_dir)
        opt_state = None
        meta = dict(meta)
        # megatron checkpoints record args as a namespace; normalize to a
        # plain dict and fold in the recovered config overrides
        rec = meta.get("args") or {}
        if not isinstance(rec, dict):
            rec = dict(vars(rec))
        rec.update(cfg_over)
        meta["args"] = rec
    else:
        params, opt_state, meta = checkpointing.load_checkpoint(
            args.load_dir)
        if params is None:
            params, opt_state, meta = checkpointing.load_checkpoint(
                args.load_dir, release=True
            )
    if params is None:
        raise SystemExit(f"no checkpoint found under {args.load_dir}")

    if args.dtype:
        dt = {"fp32": jnp.float32, "bf16": jnp.bfloat16,
              "fp16": jnp.float16}[args.dtype]
        params = jax.tree_util.tree_map(lambda x: jnp.asarray(x, dt), params)

    ckpt_args = dict(meta.get("args") or {})
    for k, v in (
        ("tensor_model_parallel_size", args.target_tensor_parallel_size),
        ("pipeline_model_parallel_size", args.target_pipeline_parallel_size),
        ("data_parallel_size", args.target_data_parallel_size),
    ):
        if v is not None:
            ckpt_args[k] = v

    iteration = 0 if args.release else int(meta.get("iteration") or 0)
    if args.output_format == "megatron":
        from weights_conversion.megatron_ckpt import (
            save_reference_checkpoint,
        )

        save_reference_checkpoint(
            args.save_dir, iteration, params, ckpt_args,
            tensor_parallel=args.target_tensor_parallel_size or 1)
    else:
        checkpointing.save_checkpoint(
            args.save_dir, iteration, params, opt_state,
            args=ckpt_args,
            consumed_samples=meta.get("consumed_samples", 0),
            release=args.release,
        )
    print(f" resharded {args.load_dir} ({args.input_format}) -> "
          f"{args.save_dir} ({args.output_format}); target sizes recorded "
          f"in args")


if __name__ == "__main__":
    main()
