#!/usr/bin/env python
"""jsonl -> mmap indexed dataset (multi-process).

Reference: ``tools/preprocess_data.py`` — reads a jsonl with one document
per line, tokenizes (optionally splitting sentences / appending EOD), and
writes the (bin, idx) pair with worker parallelism.
"""

import argparse
import json
import multiprocessing
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from megatron_llm_tpu.data.indexed_dataset import (
    MMapIndexedDatasetBuilder,
    best_fitting_dtype,
    data_file_path,
    index_file_path,
)
from megatron_llm_tpu.tokenizer import build_tokenizer

_TOKENIZER = None
_ARGS = None


def _init_worker(args):
    global _TOKENIZER, _ARGS
    _ARGS = args
    _TOKENIZER = build_tokenizer(args)


_SENT_RE = None


def _split_sentences(text):
    """Regex sentence splitter (the reference uses nltk punkt; a
    dependency-free splitter on terminal punctuation keeps the same
    one-sequence-per-sentence dataset shape for BERT/T5/ICT)."""
    global _SENT_RE
    if _SENT_RE is None:
        import re
        _SENT_RE = re.compile(r"(?<=[.!?])\s+(?=[^\s])")
    return [s for s in _SENT_RE.split(text) if s.strip()]


def _encode(line):
    line = line.strip()
    if not line:
        return None, 0
    doc = json.loads(line)
    text = doc[_ARGS.json_key]
    if _ARGS.split_sentences:
        # one sequence per sentence, document boundary preserved — the
        # layout BertDataset/T5Dataset/ICTDataset sample spans from
        ids = [_TOKENIZER.tokenize(s) for s in _split_sentences(text)]
        ids = [s for s in ids if s]
        if ids and _ARGS.append_eod:
            ids[-1] = list(ids[-1]) + [_TOKENIZER.eod]
        return (ids if ids else None), len(line)
    ids = _TOKENIZER.tokenize(text)
    if _ARGS.append_eod:
        ids = list(ids) + [_TOKENIZER.eod]
    return ids, len(line)


def get_args():
    p = argparse.ArgumentParser()
    g = p.add_argument_group("input data")
    g.add_argument("--input", required=True, help="jsonl input path")
    g.add_argument("--json_key", "--json-keys", dest="json_key",
                   default="text")
    g = p.add_argument_group("tokenizer")
    g.add_argument("--tokenizer_type", "--tokenizer-type",
                   dest="tokenizer_type", required=True,
                   choices=["GPT2BPETokenizer", "SentencePieceTokenizer",
                            "FalconTokenizer", "HFAutoTokenizer",
                            "BertWordPieceLowerCase", "BertWordPieceCase",
                            "NullTokenizer"])
    g.add_argument("--vocab_file", "--vocab-file", dest="vocab_file")
    g.add_argument("--merge_file", "--merge-file", dest="merge_file")
    g.add_argument("--tokenizer_path", dest="tokenizer_path")
    g.add_argument("--vocab_size", type=int, default=None)
    g.add_argument("--append_eod", "--append-eod", dest="append_eod",
                   action="store_true")
    g.add_argument("--split_sentences", "--split-sentences",
                   dest="split_sentences", action="store_true",
                   help="one sequence per sentence (BERT/T5/ICT corpora)")
    g = p.add_argument_group("output")
    g.add_argument("--output_prefix", "--output-prefix",
                   dest="output_prefix", required=True)
    g.add_argument("--workers", type=int, default=1)
    g.add_argument("--log_interval", type=int, default=10000)
    args = p.parse_args()
    args.make_vocab_size_divisible_by = 128
    args.tensor_model_parallel_size = 1
    args.rank = 0
    return args


def main():
    args = get_args()
    _init_worker(args)
    vocab_size = _TOKENIZER.vocab_size
    builder = MMapIndexedDatasetBuilder(
        data_file_path(args.output_prefix),
        dtype=best_fitting_dtype(vocab_size),
    )
    t0 = time.time()
    n_docs = n_bytes = 0
    with open(args.input, "r", encoding="utf-8") as f:
        if args.workers > 1:
            pool = multiprocessing.Pool(
                args.workers, initializer=_init_worker, initargs=(args,)
            )
            encoded = pool.imap(_encode, f, chunksize=32)
        else:
            encoded = (_encode(line) for line in f)
        for ids, nb in encoded:
            if ids is None:
                continue
            if args.split_sentences:
                for sent in ids:
                    builder.add_item(sent)
            else:
                builder.add_item(ids)
            builder.end_document()
            n_docs += 1
            n_bytes += nb
            if n_docs % args.log_interval == 0:
                el = time.time() - t0
                print(f" processed {n_docs} documents "
                      f"({n_docs / el:.1f} docs/s, "
                      f"{n_bytes / el / 1024 / 1024:.2f} MB/s)", flush=True)
    builder.finalize(index_file_path(args.output_prefix))
    print(f" done: {n_docs} documents -> {args.output_prefix}.bin/.idx")


if __name__ == "__main__":
    main()
