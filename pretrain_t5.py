#!/usr/bin/env python
"""T5 pretraining entry point (span-corruption denoising).

Reference: ``/root/reference/pretrain_t5.py`` — batches with (text_enc,
text_dec, labels, loss_mask, enc_mask, dec_mask, enc_dec_mask) and a
masked-mean lm loss (:76-135).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu import checkpointing, topology
from megatron_llm_tpu.data.data_samplers import place_host_batch
from megatron_llm_tpu.arguments import (
    parallel_config_from_args,
    train_config_from_args,
    transformer_config_from_args,
)
from megatron_llm_tpu.initialize import initialize_megatron
from megatron_llm_tpu.models.t5 import T5_ARCH_FLAGS, T5Model, t5_config
from megatron_llm_tpu.parallel import sharding as sh
from megatron_llm_tpu.training import pretrain
from jax.sharding import NamedSharding, PartitionSpec as P


def extra_args(parser):
    g = parser.add_argument_group("t5")
    g.add_argument("--masked_lm_prob", "--mask_prob",
                   dest="masked_lm_prob", type=float, default=0.15)
    g.add_argument("--short_seq_prob", type=float, default=0.1)
    return parser


def build_data_iterator(args, mesh, num_micro):
    mb = args.micro_batch_size * args.data_parallel_size
    s_enc = args.seq_length
    s_dec = args.decoder_seq_length or args.seq_length

    if args.data_path is None:
        rng = np.random.RandomState(args.seed)

        def synth():
            ones_ee = np.ones((num_micro, mb, s_enc, s_enc), np.int32)
            ones_dd = np.tril(np.ones((s_dec, s_dec), np.int32))[None, None]
            ones_dd = np.broadcast_to(ones_dd, (num_micro, mb, s_dec, s_dec)).copy()
            ones_de = np.ones((num_micro, mb, s_dec, s_enc), np.int32)
            while True:
                enc = rng.randint(0, args.padded_vocab_size,
                                  (num_micro, mb, s_enc)).astype(np.int32)
                dec = rng.randint(0, args.padded_vocab_size,
                                  (num_micro, mb, s_dec)).astype(np.int32)
                yield {
                    "tokens": enc,
                    "decoder_input_ids": dec,
                    "labels": dec,
                    "loss_mask": np.ones((num_micro, mb, s_dec), np.float32),
                    "encoder_attn_mask": ones_ee,
                    "decoder_attn_mask": ones_dd,
                    "encoder_decoder_attn_mask": ones_de,
                }
        host_iter = synth()
    else:
        from megatron_llm_tpu.data.t5_dataset import (
            build_train_valid_test_datasets,
            t5_collate,
        )
        from megatron_llm_tpu.data.data_samplers import (
            build_pretraining_data_loader,
        )

        n_train = args.train_iters * args.global_batch_size
        train_ds, _, _ = build_train_valid_test_datasets(
            args.data_path, args.split, [n_train, 0, 0],
            max_seq_length=s_enc,
            max_seq_length_dec=s_dec,
            masked_lm_prob=args.masked_lm_prob,
            short_seq_prob=args.short_seq_prob,
            seed=args.seed,
            vocab_extra_ids=args.vocab_extra_ids,
        )
        host_iter = iter(build_pretraining_data_loader(
            train_ds, 0, args.micro_batch_size, args.data_parallel_size,
            num_micro, args.dataloader_type, args.seed,
            collate_fn=t5_collate,
        ))

    def gen():
        for b in host_iter:
            out = {}
            for k, v in b.items():
                arr = np.asarray(v)
                spec = [None, "dp"] + [None] * (arr.ndim - 2)
                out[k] = place_host_batch(arr, NamedSharding(mesh, P(*spec)))
            yield out

    return gen()


def main():
    args = initialize_megatron(extra_args_provider=extra_args)
    if args.padded_vocab_size is None:
        raise SystemExit("need --vocab_size/--padded_vocab_size or a tokenizer")
    if args.pipeline_model_parallel_size > 1:
        # the T5 path runs through the generic (non-pipelined) train step;
        # use finetune.py / pretrain_gpt.py for pp > 1
        raise SystemExit(
            "pretrain_t5.py does not support "
            "--pipeline_model_parallel_size > 1 (tp/dp only)"
        )

    mesh = topology.get_mesh()
    base = transformer_config_from_args(args, "gpt")
    cfg = t5_config(**{
        f.name: getattr(base, f.name)
        for f in base.__dataclass_fields__.values()
        if f.name not in T5_ARCH_FLAGS
    })
    model = T5Model(cfg)
    tc = train_config_from_args(args)
    pc = parallel_config_from_args(args)
    num_micro = args.global_batch_size // (
        args.micro_batch_size * args.data_parallel_size
    )

    params = None
    start_iteration = 0
    opt_state = None
    if args.load:
        params, opt_state, meta = checkpointing.load_checkpoint(
            args.load, finetune=args.finetune,
            iteration=getattr(args, "load_iters", None),
        )
        if params is not None:
            start_iteration = meta["iteration"]
    if params is None:
        params = model.init(jax.random.PRNGKey(args.seed))
    params = sh.shard_params(params, model.param_specs(params))
    if args.fp16 or args.bf16:
        dt = jnp.float16 if args.fp16 else jnp.bfloat16
        params = jax.tree_util.tree_map(lambda p: p.astype(dt), params)

    train_iter = build_data_iterator(args, mesh, num_micro)
    if getattr(args, "eval_only", False):
        # reference --eval_only: forward-only pass over the data, no update
        from megatron_llm_tpu.optimizer import MegatronOptimizer
        from megatron_llm_tpu.training import build_train_step

        opt = MegatronOptimizer(
            tc, params_dtype=jax.tree_util.tree_leaves(params)[0].dtype)
        step = build_train_step(model, opt, pc, num_micro,
                                forward_only=True)
        losses = [float(step(params, next(train_iter), None))
                  for _ in range(args.eval_iters)]
        print(f" eval_only: loss {sum(losses) / len(losses):.6E} over "
              f"{len(losses)} batches")
        return

    params, opt_state, it = pretrain(
        model, params, tc, pc, train_iter,
        log_interval=args.log_interval,
        save_interval=args.save_interval,
        save_dir=args.save,
        start_iteration=start_iteration,
        opt_state=opt_state,
    )
    if args.save:
        checkpointing.save_checkpoint(args.save, it, params, opt_state)


if __name__ == "__main__":
    main()
