#!/usr/bin/env python
"""GPT pretraining entry point — thin alias over finetune.py with
--model_name=gpt defaults (the reference drives GPT pretraining through
the same driver; see examples/pretrain_gpt.sh upstream)."""

import sys

from finetune import main

if __name__ == "__main__":
    if not any(a.startswith("--model_name") for a in sys.argv[1:]):
        sys.argv.append("--model_name=gpt")
    main()
