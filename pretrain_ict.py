#!/usr/bin/env python
"""BiEncoder ICT (inverse cloze task) pretraining entry point.

Reference: ``/root/reference/pretrain_ict.py`` — twin-tower BERT, in-batch
softmax over query x context inner products, top-k retrieval accuracies.
The reference all-gathers tower outputs over the DP group with a custom
autograd function (:47-73); here the batch is dp-sharded under one jit and
XLA inserts the gather for the [B, B] score matmul.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu import checkpointing, topology
from megatron_llm_tpu.data.data_samplers import place_host_batch
from megatron_llm_tpu.arguments import (
    parallel_config_from_args,
    train_config_from_args,
    transformer_config_from_args,
)
from megatron_llm_tpu.initialize import initialize_megatron
from megatron_llm_tpu.models.bert import BERT_ARCH_FLAGS, bert_config
from megatron_llm_tpu.models.biencoder import (
    BiEncoderModel,
    ict_retrieval_loss,
)
from megatron_llm_tpu.parallel import sharding as sh
from megatron_llm_tpu.training import pretrain
from jax.sharding import NamedSharding, PartitionSpec as P


def extra_args(parser):
    g = parser.add_argument_group("ict")
    g.add_argument("--titles_data_path", default=None,
                   help="indexed dataset of one title per document")
    g.add_argument("--query_in_block_prob", type=float, default=0.1)
    g.add_argument("--use_one_sent_docs", action="store_true")
    g.add_argument("--biencoder_projection_dim", type=int, default=0)
    g.add_argument("--biencoder_shared_query_context_model",
                   action="store_true")
    g.add_argument("--retriever_score_scaling", action="store_true")
    g.add_argument("--retriever_report_topk_accuracies", nargs="*",
                   type=int, default=[1, 5])
    return parser


class ICTTrainModel:
    """Adapter matching the generic train-step contract (training.py:48):
    batch key 'tokens' carries the query tokens; the other tower inputs ride
    in the extra batch keys."""

    def __init__(self, bi: BiEncoderModel, score_scaling: bool, topk):
        self.bi = bi
        self.score_scaling = score_scaling
        self.topk = tuple(topk)

    def init(self, key):
        return self.bi.init(key)

    def param_specs(self, params):
        return self.bi.param_specs(params)

    def num_params(self, params):
        return self.bi.num_params(params)

    def flops_per_token(self, seq_len=None):
        from megatron_llm_tpu.models.language_model import flops_per_token
        return 2 * flops_per_token(self.bi.cfg, seq_len)

    def __call__(self, params, tokens, labels=None, *, query_pad_mask,
                 context_tokens, context_pad_mask, rng_key=None,
                 train=False, sequence_parallel=False, **_unused):
        q, c = self.bi(params, tokens, query_pad_mask,
                       context_tokens, context_pad_mask,
                       rng_key=rng_key, train=train)
        return ict_retrieval_loss(
            q, c, score_scaling=self.score_scaling,
            hidden_size=self.bi.cfg.hidden_size, topk=self.topk)


def ict_loss_func(model_out, _loss_mask):
    loss, stats = model_out
    return loss, stats


def ict_collate(micros):
    keys = ("query_tokens", "query_pad_mask", "context_tokens",
            "context_pad_mask")
    out = {}
    for key in keys:
        arr = np.stack([np.stack([s[key] for s in m]) for m in micros])
        name = "tokens" if key == "query_tokens" else key
        out[name] = arr.astype(np.int32)
    b = out["tokens"].shape[:2]
    # dummies for the generic step contract
    out["labels"] = np.zeros(b + (1,), np.int32)
    out["loss_mask"] = np.ones(b + (1,), np.float32)
    return out


def build_data_iterator(args, mesh, num_micro):
    mb = args.micro_batch_size * args.data_parallel_size

    if args.data_path is None:
        rng = np.random.RandomState(args.seed)

        def synth():
            while True:
                yield {
                    "tokens": rng.randint(
                        0, args.padded_vocab_size,
                        (num_micro, mb, args.seq_length)).astype(np.int32),
                    "query_pad_mask": np.ones(
                        (num_micro, mb, args.seq_length), np.int32),
                    "context_tokens": rng.randint(
                        0, args.padded_vocab_size,
                        (num_micro, mb, args.seq_length)).astype(np.int32),
                    "context_pad_mask": np.ones(
                        (num_micro, mb, args.seq_length), np.int32),
                    "labels": np.zeros((num_micro, mb, 1), np.int32),
                    "loss_mask": np.ones((num_micro, mb, 1), np.float32),
                }
        host_iter = synth()
    else:
        if args.titles_data_path is None:
            raise SystemExit("ICT needs --titles_data_path")
        from megatron_llm_tpu.data.data_samplers import (
            build_pretraining_data_loader,
        )
        from megatron_llm_tpu.data.dataset_utils import (
            DSET_TYPE_ICT,
            build_train_valid_test_datasets_core,
            get_indexed_dataset_,
        )
        from megatron_llm_tpu.global_vars import get_tokenizer

        titles = get_indexed_dataset_(args.titles_data_path)
        n_train = args.train_iters * args.global_batch_size
        train_ds, _, _ = build_train_valid_test_datasets_core(
            args.data_path, args.split, [n_train, 0, 0],
            max_seq_length=args.seq_length,
            masked_lm_prob=0.0, short_seq_prob=0.0, seed=args.seed,
            dataset_type=DSET_TYPE_ICT, tokenizer=get_tokenizer(),
            title_dataset=titles,
            query_in_block_prob=args.query_in_block_prob,
            use_one_sent_docs=args.use_one_sent_docs,
        )
        host_iter = iter(build_pretraining_data_loader(
            train_ds, 0, args.micro_batch_size, args.data_parallel_size,
            num_micro, args.dataloader_type, args.seed,
            collate_fn=ict_collate,
        ))

    def gen():
        for b in host_iter:
            out = {}
            for k, v in b.items():
                arr = np.asarray(v)
                spec = [None, "dp"] + [None] * (arr.ndim - 2)
                out[k] = place_host_batch(arr, NamedSharding(mesh, P(*spec)))
            yield out

    return gen()


def main():
    args = initialize_megatron(extra_args_provider=extra_args)
    if args.padded_vocab_size is None:
        raise SystemExit("need --vocab_size/--padded_vocab_size or a tokenizer")
    if (args.tensor_model_parallel_size > 1
            or args.pipeline_model_parallel_size > 1):
        # the reference asserts the same (pretrain_ict.py loss_func)
        raise SystemExit("ICT supports dp only (tp=pp=1)")

    mesh = topology.get_mesh()
    base = transformer_config_from_args(args, "gpt")
    cfg = bert_config(**{
        f.name: getattr(base, f.name)
        for f in base.__dataclass_fields__.values()
        if f.name not in BERT_ARCH_FLAGS
    })
    bi = BiEncoderModel(
        cfg,
        projection_dim=args.biencoder_projection_dim,
        shared_query_context=args.biencoder_shared_query_context_model,
    )
    model = ICTTrainModel(bi, args.retriever_score_scaling,
                          args.retriever_report_topk_accuracies)
    tc = train_config_from_args(args)
    pc = parallel_config_from_args(args)
    num_micro = args.global_batch_size // (
        args.micro_batch_size * args.data_parallel_size
    )

    params = None
    start_iteration = 0
    opt_state = None
    if args.load:
        params, opt_state, meta = checkpointing.load_checkpoint(
            args.load, finetune=args.finetune,
            iteration=getattr(args, "load_iters", None),
        )
        if params is not None:
            start_iteration = meta["iteration"]
    if params is None:
        params = model.init(jax.random.PRNGKey(args.seed))
    params = sh.shard_params(params, model.param_specs(params))
    if args.fp16 or args.bf16:
        dt = jnp.float16 if args.fp16 else jnp.bfloat16
        params = jax.tree_util.tree_map(lambda p: p.astype(dt), params)

    train_iter = build_data_iterator(args, mesh, num_micro)
    if getattr(args, "eval_only", False):
        # reference --eval_only: forward-only pass over the data, no update
        from megatron_llm_tpu.optimizer import MegatronOptimizer
        from megatron_llm_tpu.training import build_train_step

        opt = MegatronOptimizer(
            tc, params_dtype=jax.tree_util.tree_leaves(params)[0].dtype)
        step = build_train_step(model, opt, pc, num_micro, ict_loss_func,
                                forward_only=True)
        losses = [float(step(params, next(train_iter), None))
                  for _ in range(args.eval_iters)]
        print(f" eval_only: loss {sum(losses) / len(losses):.6E} over "
              f"{len(losses)} batches")
        return

    params, opt_state, it = pretrain(
        model, params, tc, pc, train_iter,
        loss_func=ict_loss_func,
        log_interval=args.log_interval,
        save_interval=args.save_interval,
        save_dir=args.save,
        start_iteration=start_iteration,
        opt_state=opt_state,
    )
    if args.save:
        checkpointing.save_checkpoint(args.save, it, params, opt_state)


if __name__ == "__main__":
    main()
