#!/usr/bin/env python
"""Forward-pass correctness harness: this framework vs HuggingFace.

Reference: ``/root/reference/verify_correctness.py`` — runs the Megatron
forward and the HF/Meta forward on the same batches and reports max-abs
logits error + loss delta (:130-189); the golden-model test asserts the
mean max-abs error <= 1e-3 (tests/test_llama_weights.py:117-118).

Usage:
    python verify_correctness.py --model_name=llama2 \
        --load=/ckpts/llama2-7b --huggingface_path=/hf/llama2-7b \
        --iters=10 --batch=2 --seq_length=512
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model_name", default="llama2")
    p.add_argument("--load", required=True,
                   help="framework checkpoint dir (release or iter)")
    p.add_argument("--huggingface_path", required=True)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--seq_length", type=int, default=512)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--atol", type=float, default=1e-3)
    args = p.parse_args()

    import torch
    import jax.numpy as jnp
    from transformers import AutoModelForCausalLM

    from megatron_llm_tpu import checkpointing
    from megatron_llm_tpu.config import TransformerConfig
    from megatron_llm_tpu.models import MODEL_REGISTRY
    from megatron_llm_tpu.ops.cross_entropy import vocab_parallel_cross_entropy

    params, _, meta = checkpointing.load_checkpoint(args.load, finetune=True)
    if params is None:
        params, _, meta = checkpointing.load_checkpoint(
            args.load, release=True, finetune=True
        )
    cfg_args = dict(meta["args"])
    cfg_args.pop("model_name", None)
    cfg = TransformerConfig(**cfg_args, use_flash_attn=False)
    model = MODEL_REGISTRY[args.model_name](cfg)

    hf = AutoModelForCausalLM.from_pretrained(
        args.huggingface_path, torch_dtype=torch.float32
    ).eval()

    rng = np.random.RandomState(args.seed)
    max_errs, loss_deltas = [], []
    for it in range(args.iters):
        toks = rng.randint(0, cfg.padded_vocab_size,
                           (args.batch, args.seq_length))
        labels = np.roll(toks, -1, axis=1)
        with torch.no_grad():
            hf_logits = hf(torch.tensor(toks)).logits.numpy()
        my_logits = np.asarray(model(params, jnp.asarray(toks), train=False))
        err = np.abs(hf_logits - my_logits).max()
        hf_loss = float(np.mean(
            vocab_parallel_cross_entropy(jnp.asarray(hf_logits),
                                         jnp.asarray(labels))))
        my_loss = float(np.mean(
            vocab_parallel_cross_entropy(jnp.asarray(my_logits),
                                         jnp.asarray(labels))))
        max_errs.append(err)
        loss_deltas.append(abs(hf_loss - my_loss))
        print(f" iter {it}: max abs logits err {err:.3e} | "
              f"our loss {my_loss:.6f} | hf loss {hf_loss:.6f}")

    mean_err = float(np.mean(max_errs))
    print(f" mean max-abs logits error over {args.iters} iters: "
          f"{mean_err:.3e} (tolerance {args.atol})")
    if mean_err > args.atol:
        print(" FAIL")
        sys.exit(1)
    print(" OK")


if __name__ == "__main__":
    main()
