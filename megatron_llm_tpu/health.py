"""Model-health observatory: per-layer gradient/update/parameter diagnostics.

The train step can optionally emit a fixed-shape per-group stats pytree —
one slot per top-level param subtree (embedding, each transformer layer,
final norm, lm head): grad L2 norm, param L2 norm, update L2 norm and
non-finite grad count. Everything here reduces on-device inside the
already-jitted step: the stats are `[G]` arrays whose length depends only
on the param tree structure, so enabling them adds exactly one fixed-shape
output and zero steady-state recompiles.

Grouping is by pytree path (the same `jax.tree_util` path keys the
optimizer's weight-decay mask uses), so model refactors that keep the
top-level layout — ``embedding`` / ``transformer.layers`` (stacked, leading
axis = layer) / ``transformer.final_norm`` / ``lm_head`` — keep their
group names, and unknown top-level subtrees degrade to their own group
instead of breaking.

Note on pipeline parallelism with interleaved (vpp) schedules: the stacked
``layers`` leaves are laid out stage-major, so ``layer_003`` names the
fourth stacked row, which is not the fourth layer in execution order. With
``vpp`` unset (or 1) row order equals layer order.

Host-side helpers (`to_record`, `find_offenders`, `describe_offenders`)
turn a fetched stats dict into the JSONL record shape and into a human
diagnosis ("first group with non-finite grads", grad-norm outliers vs. the
median) used by the resilience rewind path and `tools/health_report.py`.
"""

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

# Update-to-weight ratios outside this band usually mean the LR is badly
# tuned for that tensor (too small: frozen; too large: thrashing). Shared
# convention with tools/health_report.py (stdlib-only, so it keeps its own
# copy of the numbers).
UPDATE_RATIO_BAND = (1e-4, 1e-2)

LAYER_GROUP_FMT = "layer_{:03d}"


def _path_names(path) -> List[str]:
    return [getattr(p, "key", getattr(p, "name", str(p))) for p in path]


def _classify(path) -> Tuple[bool, str]:
    """Map a pytree path to (is_stacked_layers, group_name).

    Stacked transformer layers (any path passing through a ``layers`` key)
    report per-leading-axis-row stats; every other leaf folds into a group
    named after its most specific stable ancestor.
    """
    names = _path_names(path)
    if not names:
        return False, "params"
    if "layers" in names:
        return True, "layers"
    if names[0] == "transformer":
        return False, names[1] if len(names) > 1 else "transformer"
    return False, names[0]


def layer_group_names(params) -> List[str]:
    """Deterministic group names for a param tree: ``embedding`` first (when
    present), then one ``layer_NNN`` per stacked transformer-layer row, then
    the remaining top-level groups in flatten order (``final_norm``,
    ``lm_head``, ...)."""
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    num_layers = 0
    others: List[str] = []
    for path, leaf in leaves:
        stacked, g = _classify(path)
        if stacked:
            num_layers = max(num_layers, int(leaf.shape[0]))
        elif g not in others:
            others.append(g)
    names: List[str] = []
    if "embedding" in others:
        names.append("embedding")
        others.remove("embedding")
    names.extend(LAYER_GROUP_FMT.format(i) for i in range(num_layers))
    names.extend(others)
    return names


def _layer_slot(names: Sequence[str]) -> int:
    first = LAYER_GROUP_FMT.format(0)
    return names.index(first) if first in names else len(names)


def _group_sumsq(tree, names: Sequence[str]) -> jnp.ndarray:
    """Per-group sum of squares, [G] fp32."""
    start = _layer_slot(names)
    acc = jnp.zeros((len(names),), dtype=jnp.float32)
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        stacked, g = _classify(path)
        x = jnp.square(leaf.astype(jnp.float32))
        if stacked:
            rows = jnp.sum(x, axis=tuple(range(1, x.ndim)))
            acc = acc.at[start:start + leaf.shape[0]].add(rows)
        else:
            acc = acc.at[names.index(g)].add(jnp.sum(x))
    return acc


def _group_nonfinite(tree, names: Sequence[str]) -> jnp.ndarray:
    """Per-group count of non-finite entries, [G] int32."""
    start = _layer_slot(names)
    acc = jnp.zeros((len(names),), dtype=jnp.int32)
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        stacked, g = _classify(path)
        bad = (~jnp.isfinite(leaf.astype(jnp.float32))).astype(jnp.int32)
        if stacked:
            rows = jnp.sum(bad, axis=tuple(range(1, bad.ndim)))
            acc = acc.at[start:start + leaf.shape[0]].add(rows)
        else:
            acc = acc.at[names.index(g)].add(jnp.sum(bad))
    return acc


def compute_layer_stats(params, grads, updates=None) -> Dict[str, jnp.ndarray]:
    """On-device per-group stats for one optimizer step.

    All inputs share the param tree structure; `grads` should be the
    unscaled, pre-clip gradients (so grad norms partition the global grad
    norm) and `updates` the applied master-weight delta (zero on a skipped
    overflow step). Returns fixed-shape `[G]` arrays:

      grad_norm, param_norm, update_norm (fp32), nonfinite_grads (int32)

    in `layer_group_names(params)` order. Differentiation-free; safe to
    call inside jit (and inside pipeline-sharded steps: the accumulating
    scatter-adds reduce sharded layer rows under GSPMD like any other
    reduction).
    """
    names = layer_group_names(params)
    stats = {
        "grad_norm": jnp.sqrt(_group_sumsq(grads, names)),
        "param_norm": jnp.sqrt(_group_sumsq(params, names)),
        "nonfinite_grads": _group_nonfinite(grads, names),
    }
    if updates is not None:
        stats["update_norm"] = jnp.sqrt(_group_sumsq(updates, names))
    return stats


# ---------------------------------------------------------------------------
# Host-side: JSONL records and offender diagnosis.
# ---------------------------------------------------------------------------

def to_record(names: Sequence[str], stats) -> Dict[str, Any]:
    """Fetched stats dict -> the JSONL / flight-recorder record shape.

    `stats` values are host arrays (post `jax.device_get`). Non-finite
    floats become the strings "nan"/"inf"/"-inf" so the record stays plain
    JSON. Adds the derived per-group update-to-weight ratio.
    """
    def _num(x):
        x = float(x)
        if math.isfinite(x):
            return x
        return "nan" if math.isnan(x) else ("inf" if x > 0 else "-inf")

    rec: Dict[str, Any] = {"groups": list(names)}
    for key in ("grad_norm", "param_norm", "update_norm"):
        if key in stats:
            rec[key] = [_num(v) for v in stats[key]]
    if "nonfinite_grads" in stats:
        rec["nonfinite_grads"] = [int(v) for v in stats["nonfinite_grads"]]
    if "update_norm" in rec and "param_norm" in rec:
        ratios = []
        for u, p in zip(rec["update_norm"], rec["param_norm"]):
            if isinstance(u, str) or isinstance(p, str) or p <= 0.0:
                ratios.append(None)
            else:
                ratios.append(u / p)
        rec["update_ratio"] = ratios
    return rec


def record_value(rec_val) -> float:
    """Inverse of to_record's non-finite string encoding."""
    if isinstance(rec_val, str):
        return {"nan": math.nan, "inf": math.inf, "-inf": -math.inf}.get(
            rec_val, math.nan)
    return float(rec_val)


def derived_params_norm(record: Dict[str, Any]) -> float:
    """Global params norm from the per-group partition of sum-of-squares —
    exact (up to fp rounding), so --log_params_norm needs no second
    whole-tree reduction when layer stats are on."""
    return math.sqrt(sum(record_value(v) ** 2
                         for v in record.get("param_norm", [])))


def find_offenders(record: Dict[str, Any], top_k: int = 3,
                   outlier_factor: float = 4.0) -> Dict[str, Any]:
    """Diagnose a layer-stats record: which groups look responsible?

    Returns {"first_nonfinite", "nonfinite" (all such groups),
    "outliers": [{"group", "grad_norm", "ratio_to_median"}] (top_k, only
    groups whose finite grad norm exceeds outlier_factor x the median)}.
    """
    groups = record.get("groups", [])
    nf = record.get("nonfinite_grads") or [0] * len(groups)
    gn = [record_value(v) for v in record.get("grad_norm", [])]
    nonfinite = [g for g, n in zip(groups, nf) if n > 0]
    finite = sorted(v for v in gn if math.isfinite(v))
    outliers: List[Dict[str, Any]] = []
    if finite:
        mid = len(finite) // 2
        median = (finite[mid] if len(finite) % 2 else
                  0.5 * (finite[mid - 1] + finite[mid]))
        if median > 0.0:
            ranked = sorted(
                ((v / median, g, v) for g, v in zip(groups, gn)
                 if math.isfinite(v) and v > outlier_factor * median),
                reverse=True)
            outliers = [{"group": g, "grad_norm": v, "ratio_to_median": r}
                        for r, g, v in ranked[:top_k]]
    return {
        "first_nonfinite": nonfinite[0] if nonfinite else None,
        "nonfinite": nonfinite,
        "outliers": outliers,
    }


def describe_offenders(offenders: Dict[str, Any]) -> Optional[str]:
    """One-line human summary for rewind logs / flight-recorder dump
    reasons; None when nothing looks wrong."""
    parts = []
    nonfinite = offenders.get("nonfinite") or []
    if nonfinite:
        shown = ", ".join(nonfinite[:4])
        more = f" (+{len(nonfinite) - 4} more)" if len(nonfinite) > 4 else ""
        parts.append(f"non-finite grads in [{shown}{more}], "
                     f"first: {offenders['first_nonfinite']}")
    outliers = offenders.get("outliers") or []
    if outliers:
        shown = ", ".join(f"{o['group']} ({o['ratio_to_median']:.1f}x median)"
                          for o in outliers)
        parts.append(f"grad-norm outliers: {shown}")
    return "; ".join(parts) if parts else None
