"""graft-lint framework: violations, the repo AST cache, the baseline.

Checkers never import the code they analyze — they parse it with
``ast`` through :class:`Repo`, so the linter runs without jax installed
and can't be perturbed by import-time side effects.

Suppression model (ratchet, not allowlist): a :class:`Violation`'s
fingerprint is ``checker:CODE:path:symbol`` — deliberately line-number
free so a suppression survives unrelated edits to the same file but dies
with the symbol it excuses.  ``.graftlint.json`` entries MUST carry a
non-empty ``justification``; :class:`Baseline` refuses to load entries
without one, so "why is this exempt" is answered in the diff that adds
the exemption, not in archaeology.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

BASELINE_FILENAME = ".graftlint.json"

#: directories never scanned (generated/vendored/VCS state)
SKIP_DIRS = frozenset((
    ".git", "__pycache__", ".pytest_cache", "build", "dist",
    ".graft_scratch", "node_modules",
))


@dataclass(frozen=True)
class Violation:
    """One finding.  ``symbol`` is the stable anchor (function name,
    flag dest, record key...) used for the suppression fingerprint, so
    keep it free of line numbers and transient detail."""

    checker: str
    code: str          # e.g. "RC001"
    path: str          # repo-relative, forward slashes
    line: int
    symbol: str
    message: str

    @property
    def fingerprint(self) -> str:
        return f"{self.checker}:{self.code}:{self.path}:{self.symbol}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.code} [{self.checker}] "
                f"{self.message}")


class BaselineError(Exception):
    """Malformed ``.graftlint.json`` (bad JSON, entry without a
    justification, unknown top-level keys)."""


class Baseline:
    """The checked-in suppression + schema-snapshot file.

    Shape::

        {
          "version": 1,
          "telemetry_schema": {"version": 6, "request_done_keys": [...]},
          "suppressions": [
            {"id": "<checker>:<CODE>:<path>:<symbol>",
             "justification": "one line on why this is exempt"}
          ]
        }
    """

    def __init__(self, suppressions: Optional[Dict[str, str]] = None,
                 telemetry_schema: Optional[dict] = None,
                 path: Optional[str] = None):
        self._supp: Dict[str, str] = dict(suppressions or {})
        self.telemetry_schema = telemetry_schema
        self.path = path

    # -- construction ---------------------------------------------------

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(path=path)
        try:
            with open(path) as f:
                raw = json.load(f)
        except (OSError, ValueError) as e:
            raise BaselineError(f"{path}: unreadable baseline: {e}")
        if not isinstance(raw, dict):
            raise BaselineError(f"{path}: baseline must be a JSON object")
        unknown = set(raw) - {"version", "telemetry_schema", "suppressions"}
        if unknown:
            raise BaselineError(f"{path}: unknown keys {sorted(unknown)}")
        supp: Dict[str, str] = {}
        for i, entry in enumerate(raw.get("suppressions", ())):
            if not isinstance(entry, dict) or "id" not in entry:
                raise BaselineError(
                    f"{path}: suppression #{i} must be an object with "
                    f"'id' and 'justification'")
            just = str(entry.get("justification", "")).strip()
            if not just:
                raise BaselineError(
                    f"{path}: suppression {entry['id']!r} has no "
                    f"justification — every exemption must say why")
            supp[str(entry["id"])] = just
        return cls(supp, raw.get("telemetry_schema"), path=path)

    def save(self, path: Optional[str] = None) -> None:
        path = path or self.path
        out = {"version": 1}
        if self.telemetry_schema is not None:
            out["telemetry_schema"] = self.telemetry_schema
        out["suppressions"] = [
            {"id": fp, "justification": just}
            for fp, just in sorted(self._supp.items())
        ]
        with open(path, "w") as f:
            json.dump(out, f, indent=2, sort_keys=False)
            f.write("\n")

    # -- queries --------------------------------------------------------

    def suppresses(self, v: Violation) -> bool:
        return v.fingerprint in self._supp

    def add(self, fingerprint: str, justification: str) -> None:
        if not justification.strip():
            raise BaselineError(
                f"refusing to add {fingerprint!r} without a justification")
        self._supp[fingerprint] = justification

    def fingerprints(self) -> List[str]:
        return sorted(self._supp)

    @staticmethod
    def checker_of(fingerprint: str) -> str:
        return fingerprint.split(":", 1)[0]


class Repo:
    """Filesystem + AST cache over one repo checkout.

    Paths in and out are repo-relative with forward slashes; trees are
    parsed once and shared across checkers.  Files that fail to parse
    are surfaced as a synthetic ``GL000`` violation rather than crashing
    the run (the linter must degrade on a broken worktree)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._trees: Dict[str, Optional[ast.AST]] = {}
        self._sources: Dict[str, str] = {}
        self.parse_errors: List[Violation] = []

    def abspath(self, rel: str) -> str:
        return os.path.join(self.root, *rel.split("/"))

    def exists(self, rel: str) -> bool:
        return os.path.exists(self.abspath(rel))

    def py_files(self, *subdirs: str) -> List[str]:
        """Repo-relative paths of .py files under the given
        subdirectories (the whole repo when none are given), sorted."""
        roots = [self.abspath(s) for s in subdirs] if subdirs else [self.root]
        out: List[str] = []
        for top in roots:
            if os.path.isfile(top) and top.endswith(".py"):
                out.append(os.path.relpath(top, self.root).replace(os.sep, "/"))
                continue
            for dirpath, dirnames, filenames in os.walk(top):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in SKIP_DIRS)
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        rel = os.path.relpath(os.path.join(dirpath, fn),
                                              self.root)
                        out.append(rel.replace(os.sep, "/"))
        return sorted(set(out))

    def source(self, rel: str) -> str:
        if rel not in self._sources:
            with open(self.abspath(rel), encoding="utf-8") as f:
                self._sources[rel] = f.read()
        return self._sources[rel]

    def tree(self, rel: str) -> Optional[ast.AST]:
        """Parsed AST, or None when the file is missing/unparseable (a
        GL000 violation is recorded once for the latter)."""
        if rel not in self._trees:
            if not self.exists(rel):
                self._trees[rel] = None
            else:
                try:
                    self._trees[rel] = ast.parse(self.source(rel),
                                                 filename=rel)
                except SyntaxError as e:
                    self._trees[rel] = None
                    self.parse_errors.append(Violation(
                        "core", "GL000", rel, e.lineno or 0, "syntax",
                        f"file does not parse: {e.msg}"))
        return self._trees[rel]


# -- small AST helpers shared by checkers -------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """Constant-string tuple/list literal -> tuple of strings."""
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            s = const_str(el)
            if s is None:
                return None
            out.append(s)
        return tuple(out)
    return None


def dict_str_keys(node: ast.AST) -> List[Tuple[str, int]]:
    """(key, lineno) for every constant-string key of a dict literal
    (``**spread`` entries are ignored — callers decide if that's ok)."""
    out: List[Tuple[str, int]] = []
    if isinstance(node, ast.Dict):
        for k in node.keys:
            if k is not None:
                s = const_str(k)
                if s is not None:
                    out.append((s, k.lineno))
    return out


def walk_functions(tree: ast.AST):
    """Yield every FunctionDef/AsyncFunctionDef in the tree."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# -- cross-module call-graph machinery ----------------------------------
#
# Shared by the ``recompile`` checker (jit-root reachability) and the
# ``threads`` checker (thread-root reachability).  Kept here so both
# walk the same resolution rules: nested defs, ``self._method``, module
# functions, package imports, lambdas.


class Scope:
    """Lexical scope of a def: enclosing class (if method) and the
    chain of enclosing function nodes (for nested-def resolution)."""

    def __init__(self, cls: Optional[str], chain: Tuple[ast.AST, ...]):
        self.cls = cls
        self.chain = chain


class ModuleIndex:
    """One parsed module: top-level functions, class methods (top-level
    AND nested classes), imports, and a scope map for every def."""

    def __init__(self, path: str, tree: ast.AST):
        self.path = path
        self.tree = tree
        self.functions: Dict[str, ast.AST] = {}           # top-level defs
        self.methods: Dict[str, Dict[str, ast.AST]] = {}  # class -> defs
        self.classes: Dict[str, ast.ClassDef] = {}        # incl. nested
        self.imports: Dict[str, Tuple[str, Optional[str]]] = {}
        self.scopes: Dict[int, Scope] = {}                # id(def) -> scope
        self._index()

    def _index(self) -> None:
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
        # imports anywhere (tools import heavy deps inside main())
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._record_import(node)
        # every class (however nested) and its direct methods
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                meths = {}
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        meths[sub.name] = sub
                self.methods.setdefault(node.name, {}).update(meths)
                self.classes.setdefault(node.name, node)
        # scope map for every def (and lambda), however nested
        def visit(node, cls, chain):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    self.scopes[id(child)] = Scope(cls, chain)
                    visit(child, cls, chain + (child,))
                elif isinstance(child, ast.ClassDef):
                    visit(child, child.name, chain)
                else:
                    visit(child, cls, chain)
        visit(self.tree, None, ())

    def _record_import(self, node) -> None:
        if isinstance(node, ast.Import):
            for a in node.names:
                self.imports[a.asname or a.name.split(".")[0]] = \
                    (a.name, None)
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                self.imports[a.asname or a.name] = (node.module, a.name)


class PackageIndex:
    """All modules of the given subtrees, keyed both by path and dotted
    module name."""

    def __init__(self, repo: "Repo", *subdirs: str):
        self.by_mod: Dict[str, ModuleIndex] = {}
        self.by_path: Dict[str, ModuleIndex] = {}
        for sub in subdirs:
            for rel in repo.py_files(sub):
                tree = repo.tree(rel)
                if tree is None:
                    continue
                mod = ModuleIndex(rel, tree)
                self.by_path[rel] = mod
                dotted = rel[:-3].replace("/", ".")
                if dotted.endswith(".__init__"):
                    dotted = dotted[: -len(".__init__")]
                self.by_mod[dotted] = mod

    def resolve_import(self, mod: ModuleIndex, local: str
                       ) -> Optional[Tuple[ModuleIndex, Optional[str]]]:
        tgt = mod.imports.get(local)
        if tgt is None:
            return None
        modname, attr = tgt
        other = self.by_mod.get(modname)
        if other is None:
            return None
        return other, attr

    def resolve_class(self, mod: ModuleIndex, name: str
                      ) -> Optional[Tuple[ModuleIndex, ast.ClassDef]]:
        """(module, ClassDef) a bare name denotes in ``mod``: defined
        there, or imported — chasing re-export chains (a class imported
        from a package ``__init__`` that itself imports it)."""
        seen = set()
        while (mod.path, name) not in seen:
            seen.add((mod.path, name))
            if name in mod.classes:
                return mod, mod.classes[name]
            hit = self.resolve_import(mod, name)
            if hit is None:
                return None
            mod, attr = hit
            name = attr or name
        return None


def resolve_callable(index: PackageIndex, mod: ModuleIndex, scope: Scope,
                     expr: ast.AST) -> List[Tuple[ModuleIndex, ast.AST]]:
    """Function-def nodes an expression may denote: nested defs in the
    enclosing scope, ``self._method``, module functions, or functions
    imported from package modules.  Lambdas resolve to themselves."""
    if isinstance(expr, ast.Lambda):
        return [(mod, expr)]
    d = dotted_name(expr)
    if d is None:
        return []
    parts = d.split(".")
    if parts[0] == "self" and len(parts) == 2 and scope.cls:
        meth = mod.methods.get(scope.cls, {}).get(parts[1])
        return [(mod, meth)] if meth is not None else []
    if len(parts) == 1:
        name = parts[0]
        for encl in reversed(scope.chain):
            for child in ast.walk(encl):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) \
                        and child.name == name and child is not encl:
                    return [(mod, child)]
        if name in mod.functions:
            return [(mod, mod.functions[name])]
        hit = index.resolve_import(mod, name)
        if hit:
            other, attr = hit
            if attr and attr in other.functions:
                return [(other, other.functions[attr])]
        return []
    if len(parts) == 2:
        hit = index.resolve_import(mod, parts[0])
        if hit:
            other, attr = hit
            if attr is None and parts[1] in other.functions:
                return [(other, other.functions[parts[1]])]
    return []


def enclosing_scope(mod: ModuleIndex, node: ast.AST) -> Scope:
    """Scope for resolving names at an arbitrary node: the innermost
    def containing it (by position), with its class context."""
    best: Optional[ast.AST] = None
    best_scope = Scope(None, ())
    line = getattr(node, "lineno", None)
    if line is None:
        return best_scope
    for n in ast.walk(mod.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(n, "end_lineno", n.lineno)
            if n.lineno <= line <= end:
                if best is None or n.lineno >= best.lineno:
                    best = n
    if best is None:
        return best_scope
    outer = mod.scopes.get(id(best), Scope(None, ()))
    return Scope(outer.cls, outer.chain + (best,))
