"""graft-lint: repo-native, stdlib-only static analysis.

The codebase stakes its serving and training performance on invariants
that runtime tests can only spot-check: zero steady-state recompiles,
flags threaded by hand from ``arguments.py`` to consumers, a versioned
telemetry schema, the stdlib-only contract for the report/bench tools,
and the serving engine's lock discipline.  This package encodes those
invariants as AST checkers so drift becomes a lint error at review time
instead of a production regression (the MegaScale observation: at scale
these classes of drift are caught by tooling, not review).

Everything here is standard library only (``ast`` + ``json`` + ``os``)
so ``tools/graft_lint.py`` runs anywhere — no jax, no repo imports at
analysis time (the *target* files are parsed, never imported).

Checkers (see docs/guide/static_analysis.md for the catalogue):

==========  =====================================================
name        invariant
==========  =====================================================
recompile   no host-sync / retrace hazards reachable from
            ``jax.jit`` / ``shard_map`` / ``pallas_call`` roots
flags       every ``arguments.py`` flag is consumed and every
            ``args.x`` read exists; config dataclass fields are read
telemetry   request_done writer keys == golden test frozenset ==
            recorded schema snapshot; key changes require a
            ``TELEMETRY_SCHEMA_VERSION`` bump
stdlib      tools documented as stdlib-only import only the stdlib
locks       no blocking calls while a serving lock is held; writes
            to ``_lock_protected_`` fields hold the declared lock
threads     thread-topology races & deadlocks: unlocked cross-
            thread writes (TH001), lock-order cycles (TH002),
            blocking under a contested lock (TH003),
            use-after-drain in daemon loops (TH004)
markers     every ``pytest.mark.<m>`` under tests/ is registered
==========  =====================================================

Suppressions live in ``.graftlint.json`` at the repo root; every entry
must carry a one-line justification (enforced at load time).
"""

from __future__ import annotations

from megatron_llm_tpu.analysis.core import (  # noqa: F401
    Baseline,
    BaselineError,
    Repo,
    Violation,
)
from megatron_llm_tpu.analysis import (  # noqa: F401
    flags,
    locks,
    markers,
    recompile,
    stdlib_gate,
    telemetry_schema,
    threads,
)

#: checker name -> callable(Repo, Baseline) -> list[Violation].
#: Ordered: output and --checkers selection follow this order.
CHECKERS = {
    "recompile": recompile.check,
    "flags": flags.check,
    "telemetry": telemetry_schema.check,
    "stdlib": stdlib_gate.check,
    "locks": locks.check,
    "threads": threads.check,
    "markers": markers.check,
}


def run_checkers(repo, baseline, names=None):
    """Run the named checkers (all when ``names`` is None).

    Returns ``(unsuppressed, suppressed, stale_suppressions)`` — the
    violations not covered by the baseline, the ones that were, and the
    baseline fingerprints that matched nothing (ratchet candidates).
    """
    names = list(CHECKERS) if names is None else list(names)
    unknown = [n for n in names if n not in CHECKERS]
    if unknown:
        raise ValueError(
            f"unknown checker(s) {unknown}; available: {list(CHECKERS)}")
    found = []
    for name in names:
        found.extend(CHECKERS[name](repo, baseline))
    found.sort(key=lambda v: (v.path, v.line, v.code))
    unsuppressed = [v for v in found if not baseline.suppresses(v)]
    suppressed = [v for v in found if baseline.suppresses(v)]
    matched = {v.fingerprint for v in suppressed}
    stale = [fp for fp in baseline.fingerprints()
             if fp not in matched and baseline.checker_of(fp) in names]
    return unsuppressed, suppressed, stale
