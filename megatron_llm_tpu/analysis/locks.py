"""Checker ``locks``: serving-engine lock discipline.

The serving stack is three threads (HTTP front-end, engine loop,
watchdog) sharing the block manager, the router's backend table, and
the engine's restart state.  The discipline that keeps p99s flat is
(a) never block while holding a lock — a ``time.sleep`` or HTTP round
trip under ``BlockManager._lock`` stalls every admission on the box —
and (b) every write to shared state holds the owning lock.  Chaos
tests exercise (a)/(b) probabilistically; this checker makes them
structural:

* ``LD001`` — blocking call (``time.sleep``, ``subprocess.*``,
  ``socket.*``/``urllib``/``http.client`` IO, ``open()``,
  ``.result()``, ``.getresponse()``, ``.join()``) lexically inside a
  ``with self.<...lock...>:`` block in ``serving/*.py``.
* ``LD002`` — a class declares its shared fields with a
  ``_lock_protected_`` class attribute (tuple ⇒ guarded by
  ``self._lock``; dict ⇒ field → lock attribute name).  Writing such
  a field — assignment, augmented assignment, ``x[k] = v`` stores, or
  a mutating method call (``append``/``pop``/``update``/...) —
  outside a ``with self.<lock>:`` block is an error.  ``__init__``
  and methods named ``*_locked`` (the "caller holds the lock"
  convention) are exempt.

The annotation is deliberately in the code, next to the fields it
protects, so the contract travels with refactors instead of living in
the linter.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from megatron_llm_tpu.analysis.core import Repo, Violation, dotted_name

CHECKER = "locks"

SERVING_DIR = "megatron_llm_tpu/serving"

#: single files outside SERVING_DIR that are part of the serving stack
#: and carry ``_lock_protected_`` annotations (the HTTP front-end)
EXTRA_FILES = ("megatron_llm_tpu/text_generation_server.py",)

ANNOTATION = "_lock_protected_"
DEFAULT_LOCK = "_lock"

#: dotted-call prefixes that block the calling thread
_BLOCKING_PREFIXES = (
    "time.sleep", "subprocess.", "socket.", "urllib.", "http.client.",
    "os.fsync", "select.", "shutil.",
)
#: bare calls that do file IO
_BLOCKING_NAMES = frozenset(("open",))
#: attribute-call names that mutate a container in place
_MUTATORS = frozenset((
    "append", "extend", "insert", "pop", "popitem", "popleft", "clear",
    "remove", "discard", "add", "update", "setdefault", "appendleft",
    "move_to_end", "sort", "fill",
))


def _is_lock_attr(name: str) -> bool:
    return "lock" in name.lower()


def _with_lock_names(node: ast.With) -> Set[str]:
    """Names of self.<lock> attributes this with-statement acquires."""
    out: Set[str] = set()
    for item in node.items:
        d = dotted_name(item.context_expr)
        if d and d.startswith("self.") and _is_lock_attr(d[5:]):
            out.add(d[5:])
    return out


def _protected_fields(cls: ast.ClassDef) -> Dict[str, str]:
    """field -> required lock name, from the ``_lock_protected_``
    class attribute (tuple of names, or dict name -> lock attr)."""
    for node in cls.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == ANNOTATION:
                    v = node.value
                    if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
                        return {el.value: DEFAULT_LOCK
                                for el in v.elts
                                if isinstance(el, ast.Constant)
                                and isinstance(el.value, str)}
                    if isinstance(v, ast.Dict):
                        out = {}
                        for k, lv in zip(v.keys, v.values):
                            if isinstance(k, ast.Constant) \
                                    and isinstance(lv, ast.Constant):
                                out[k.value] = lv.value
                        return out
    return {}


def _self_field(expr: ast.AST) -> Optional[str]:
    """'x' for an expression rooted at ``self.x`` (through any chain of
    subscripts/attributes), else None."""
    node = expr
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr
        node = node.value
    return None


class _FunctionScanner:
    """One pass over a method body tracking the set of locks held at
    each node (lexically, via enclosing ``with self.<lock>:``)."""

    def __init__(self, rel: str, cls_name: str, fn: ast.AST,
                 protected: Dict[str, str], out: List[Violation]):
        self.rel = rel
        self.cls_name = cls_name
        self.fn = fn
        self.protected = protected
        self.out = out
        self.check_writes = bool(protected) \
            and fn.name != "__init__" \
            and not fn.name.endswith("_locked")

    def scan(self) -> None:
        for stmt in self.fn.body:
            self._visit(stmt, frozenset())

    def _visit(self, node: ast.AST, held: frozenset) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return      # nested defs run later, outside this lock scope
        if isinstance(node, ast.With):
            inner = held | _with_lock_names(node)
            for item in node.items:
                self._visit(item.context_expr, held)
            for sub in node.body:
                self._visit(sub, inner)
            return
        self._check(node, held)
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    def _check(self, node: ast.AST, held: frozenset) -> None:
        label = f"{self.cls_name}.{self.fn.name}"
        if isinstance(node, ast.Call) and held:
            d = dotted_name(node.func)
            blocking = None
            if d is not None:
                if d in _BLOCKING_NAMES:
                    blocking = d
                else:
                    for p in _BLOCKING_PREFIXES:
                        if d == p.rstrip(".") or d.startswith(p):
                            blocking = d
                            break
            if blocking is None and isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                recv = dotted_name(node.func.value)
                if attr in ("result", "getresponse"):
                    blocking = f".{attr}()"
                elif attr in ("join", "wait", "acquire") \
                        and recv is not None \
                        and recv.startswith("self.") \
                        and not _is_lock_attr(recv):
                    # thread/event waits held on self (str.join and
                    # local-variable receivers are out of scope)
                    blocking = f"{recv}.{attr}()"
            if blocking is not None:
                locks = "/".join(sorted(held))
                self.out.append(Violation(
                    CHECKER, "LD001", self.rel, node.lineno,
                    f"{label}/{blocking}",
                    f"blocking call {blocking} while holding "
                    f"self.{locks} in {label} — do the slow work "
                    f"outside the critical section"))
        if self.check_writes:
            fields = []
            if isinstance(node, ast.Assign):
                fields = [(_self_field(t), t) for t in node.targets]
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                fields = [(_self_field(node.target), node.target)]
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                fields = [(_self_field(node.func.value), node.func)]
            elif isinstance(node, ast.Delete):
                fields = [(_self_field(t), t) for t in node.targets]
            for field, tnode in fields:
                # plain `self.x = ...` rebinding is only a protected
                # write when x itself is protected; `self.x[k] = v`
                # and mutator calls count too (same object mutated)
                if field is None or field not in self.protected:
                    continue
                need = self.protected[field]
                if need not in held:
                    self.out.append(Violation(
                        CHECKER, "LD002", self.rel, tnode.lineno,
                        f"{label}/{field}",
                        f"write to lock-protected field self.{field} "
                        f"in {label} without holding self.{need} "
                        f"(declared in {self.cls_name}.{ANNOTATION})"))


def check(repo: Repo, baseline=None) -> List[Violation]:
    out: List[Violation] = []
    targets = list(repo.py_files(SERVING_DIR))
    targets += [rel for rel in EXTRA_FILES
                if repo.tree(rel) is not None
                and rel not in targets]
    for rel in targets:
        tree = repo.tree(rel)
        if tree is None:
            continue
        for cls in [n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef)]:
            protected = _protected_fields(cls)
            for fn in cls.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    _FunctionScanner(rel, cls.name, fn, protected,
                                     out).scan()
    return out
