"""Checker ``telemetry``: the versioned JSONL schema contract.

``TELEMETRY_SCHEMA_VERSION`` has been bumped six times by hand across
PRs 2–11; the invariant that keeps downstream consumers
(``tools/serve_report.py``, ``tools/serve_bench.py``, dashboards) sane
is three-way agreement between writers, the golden test, and the bench
schema — plus "changing the record shape bumps the version".  Each leg
is enforced statically:

* ``TS001`` — the ``request_done`` record literal in
  ``engine._retire`` must carry exactly the keys in the golden
  frozenset of ``test_request_done_schema_golden``.
* ``TS002`` — the ``phases`` sub-record (``Request.phases``) must
  match its golden frozenset.
* ``TS003`` — the summary dict in ``tools/serve_bench.py`` must carry
  exactly ``JSON_SCHEMA_KEYS`` (conditionally-added extras like
  ``server_metrics_delta`` are documented as optional and not part of
  the guaranteed schema).
* ``TS004`` — ratchet: the baseline records a
  ``(version, request_done_keys)`` snapshot.  Changing the writer's
  keys while ``TELEMETRY_SCHEMA_VERSION`` is unchanged is an error —
  bump the version, then re-record with
  ``tools/graft_lint.py --record-schema``.
* ``TS005`` — stale snapshot: the version moved but the snapshot
  wasn't re-recorded (run ``--record-schema``).
* ``TS006`` — the golden test's pinned version literal must equal
  ``telemetry.TELEMETRY_SCHEMA_VERSION`` (the test and the module
  drifting apart means the "conscious act" guard is dead).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from megatron_llm_tpu.analysis.core import (
    Repo, Violation, dict_str_keys, dotted_name, str_tuple,
)

CHECKER = "telemetry"

ENGINE = "megatron_llm_tpu/serving/engine.py"
REQUEST = "megatron_llm_tpu/serving/request.py"
TELEMETRY = "megatron_llm_tpu/telemetry.py"
GOLDEN_TEST = "tests/test_serving_engine.py"
BENCH = "tools/serve_bench.py"


def _function(tree: ast.AST, name: str) -> Optional[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def _record_literal_keys(fn: ast.AST, var: str) -> Tuple[Set[str], int]:
    """Keys of ``var = {...}`` plus later ``var["k"] = ...`` writes."""
    keys: Set[str] = set()
    line = fn.lineno
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == var \
                        and isinstance(node.value, ast.Dict):
                    keys.update(k for k, _ in dict_str_keys(node.value))
                    line = node.lineno
                elif isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == var \
                        and isinstance(t.slice, ast.Constant) \
                        and isinstance(t.slice.value, str):
                    keys.add(t.slice.value)
    return keys, line


def _return_dict_keys(fn: ast.AST) -> Set[str]:
    keys: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Dict):
            keys.update(k for k, _ in dict_str_keys(node.value))
    return keys


def writer_request_done_keys(repo: Repo) -> Tuple[Set[str], int]:
    tree = repo.tree(ENGINE)
    if tree is None:
        return set(), 0
    fn = _function(tree, "_retire")
    if fn is None:
        return set(), 0
    return _record_literal_keys(fn, "record")


def _golden_sets(repo: Repo):
    """(record_golden, phases_golden, pinned_version, line) from the
    golden test, each None when not found."""
    tree = repo.tree(GOLDEN_TEST)
    if tree is None:
        return None, None, None, 0
    fn = _function(tree, "test_request_done_schema_golden")
    if fn is None:
        return None, None, None, 0
    record = phases = version = None
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare) and len(node.comparators) == 1:
            left, right = node.left, node.comparators[0]
            ld = dotted_name(left)
            if ld and ld.endswith("TELEMETRY_SCHEMA_VERSION") \
                    and isinstance(right, ast.Constant) \
                    and isinstance(right.value, int):
                version = right.value
            if isinstance(left, ast.Call) \
                    and dotted_name(left.func) == "frozenset" \
                    and isinstance(right, ast.Call) \
                    and dotted_name(right.func) == "frozenset" \
                    and right.args:
                keys = str_tuple(right.args[0])
                if keys is None:
                    continue
                arg = left.args[0] if left.args else None
                if isinstance(arg, ast.Name):
                    record = set(keys)
                elif isinstance(arg, ast.Subscript):
                    phases = set(keys)
    return record, phases, version, fn.lineno


def _module_version(repo: Repo) -> Tuple[Optional[int], int]:
    tree = repo.tree(TELEMETRY)
    if tree is None:
        return None, 0
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) \
                        and t.id == "TELEMETRY_SCHEMA_VERSION" \
                        and isinstance(node.value, ast.Constant):
                    return node.value.value, node.lineno
    return None, 0


def _bench_schema(repo: Repo):
    """(JSON_SCHEMA_KEYS set, summary-dict-literal key set, line)."""
    tree = repo.tree(BENCH)
    if tree is None:
        return None, None, 0
    schema = None
    line = 0
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "JSON_SCHEMA_KEYS":
                    keys = str_tuple(node.value)
                    if keys is not None:
                        schema, line = set(keys), node.lineno
    # the guaranteed summary record: the largest dict literal bound to
    # a name (the optional extras are subscript-assigned and excluded)
    best: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            keys = {k for k, _ in dict_str_keys(node.value)}
            if len(keys) > len(best):
                best = keys
    return schema, best or None, line


def _fmt(keys) -> str:
    return ", ".join(sorted(keys))


def check(repo: Repo, baseline=None) -> List[Violation]:
    out: List[Violation] = []
    writer, wline = writer_request_done_keys(repo)
    golden, phases_golden, pinned, gline = _golden_sets(repo)
    version, vline = _module_version(repo)

    if writer and golden is not None and writer != golden:
        missing = golden - writer
        extra = writer - golden
        out.append(Violation(
            CHECKER, "TS001", ENGINE, wline, "request_done",
            f"request_done writer keys != golden frozenset in "
            f"{GOLDEN_TEST} (writer-only: [{_fmt(extra)}]; "
            f"golden-only: [{_fmt(missing)}]) — update both together"))

    if phases_golden is not None:
        rtree = repo.tree(REQUEST)
        fn = _function(rtree, "phases") if rtree is not None else None
        if fn is not None:
            pkeys = _return_dict_keys(fn)
            if pkeys and pkeys != phases_golden:
                out.append(Violation(
                    CHECKER, "TS002", REQUEST, fn.lineno, "phases",
                    f"Request.phases() keys != phases golden frozenset "
                    f"(writer: [{_fmt(pkeys)}]; golden: "
                    f"[{_fmt(phases_golden)}])"))

    schema, summary, sline = _bench_schema(repo)
    if schema is not None and summary is not None and schema != summary:
        out.append(Violation(
            CHECKER, "TS003", BENCH, sline, "JSON_SCHEMA_KEYS",
            f"serve_bench summary dict != JSON_SCHEMA_KEYS "
            f"(summary-only: [{_fmt(summary - schema)}]; schema-only: "
            f"[{_fmt(schema - summary)}])"))

    snap = baseline.telemetry_schema if baseline is not None else None
    if writer and isinstance(snap, dict):
        snap_keys = set(snap.get("request_done_keys", ()))
        snap_version = snap.get("version")
        if version is not None and version != snap_version:
            out.append(Violation(
                CHECKER, "TS005", TELEMETRY, vline, "schema_snapshot",
                f"TELEMETRY_SCHEMA_VERSION is {version} but the "
                f"baseline snapshot records {snap_version} — re-record "
                f"with tools/graft_lint.py --record-schema"))
        elif snap_keys and writer != snap_keys:
            out.append(Violation(
                CHECKER, "TS004", ENGINE, wline, "request_done",
                f"request_done keys changed without a "
                f"TELEMETRY_SCHEMA_VERSION bump (still {version}): "
                f"added [{_fmt(writer - snap_keys)}], removed "
                f"[{_fmt(snap_keys - writer)}] — bump the version, "
                f"update the history comment, then --record-schema"))

    if pinned is not None and version is not None and pinned != version:
        out.append(Violation(
            CHECKER, "TS006", GOLDEN_TEST, gline, "pinned_version",
            f"golden test pins schema version {pinned} but "
            f"telemetry.TELEMETRY_SCHEMA_VERSION is {version}"))
    return out


def record_snapshot(repo: Repo, baseline) -> dict:
    """Refresh the baseline's (version, request_done_keys) snapshot —
    the conscious act after a schema bump."""
    writer, _ = writer_request_done_keys(repo)
    version, _ = _module_version(repo)
    baseline.telemetry_schema = {
        "version": version,
        "request_done_keys": sorted(writer),
    }
    return baseline.telemetry_schema
