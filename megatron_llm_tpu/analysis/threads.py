"""Checker ``threads``: thread-topology race & deadlock detection.

The serving stack is a real concurrent system — engine loop, watchdog
daemon, router health prober, fleet supervisor, drain waiters, HTTP
handler threads — and ``locks`` (LD001/LD002) only verifies fields
someone remembered to annotate.  This checker goes the other way: it
*discovers* the thread topology, computes which functions run on which
threads, and infers shared state from actual cross-thread access.

Topology: every ``threading.Thread(target=...)`` / ``threading.Timer``
spawn (including lambdas and bound methods), every ``do_*`` method of a
stdlib HTTP handler class, every ``signal.signal`` callback, and every
``main()`` entry point becomes a *thread root*; a BFS over the shared
call graph (``core.PackageIndex``) — through constructor-typed
receivers and registered callbacks (``engine.request_done_hook = ...``,
ctor kwargs like ``on_fire=lambda: self.restart(...)``) — assigns each
reachable function the set of roots it may run on.

On top of the topology:

* ``TH001`` — attribute written from ≥2 thread roots (or container-
  mutated from one root while another root touches it) with no common
  lock held across all write sites.  The message carries the
  ``_lock_protected_`` declaration to paste, turning LD002 from opt-in
  to enforced.  Reads are advisory; ``__init__`` bodies and ``*_locked``
  methods are exempt; attributes holding ``threading.Event`` / ``queue.
  Queue`` / other sync primitives are thread-safe by contract and
  skipped.  A single-writer scalar rebind with foreign readers (the
  "publish a display counter" idiom) is deliberately NOT flagged.
* ``TH002`` — lock-order inversion: edges of the acquires-while-holding
  graph come from lexically nested ``with <lock>:`` blocks and from
  calls made under a lock into functions whose (transitive) lock set is
  known; any cycle — including a non-reentrant self-cycle — is flagged.
* ``TH003`` — blocking call (``join``, ``Condition.wait`` /
  ``queue.get`` without timeout, subprocess/socket/HTTP I/O) made while
  holding a lock that a *different* thread root also acquires: the
  classic drain/watchdog deadlock shape.
* ``TH004`` — use-after-drain: a daemon-thread loop that tests a
  stop/drain flag, blocks, then mutates shared state without re-reading
  the flag or taking a lock — the shutdown race where a drained object
  is written one more time.

Model limits (documented, on purpose): lock identity is
``<Class>.<attr>`` (instances of one class conflate — per-instance
confinement needs a baseline suppression saying why it is safe), and
locksets are *lexical* per function — a lock held by the caller is
invisible here, so "callers hold the lock" contracts are suppressed
with that rationale rather than silently trusted.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from megatron_llm_tpu.analysis.core import (
    ModuleIndex, PackageIndex, Repo, Scope, Violation,
    dotted_name, enclosing_scope, resolve_callable,
)
from megatron_llm_tpu.analysis.locks import (
    ANNOTATION, _BLOCKING_NAMES, _BLOCKING_PREFIXES, _MUTATORS,
    _is_lock_attr, _protected_fields,
)

CHECKER = "threads"

#: subtrees whose modules participate in the topology
SCAN_DIRS = ("megatron_llm_tpu", "tools")

#: thread-safe-by-contract constructors: attributes holding these are
#: never shared-state findings (the primitive IS the synchronization)
_SYNC_CTORS = frozenset((
    "Event", "Condition", "Semaphore", "BoundedSemaphore", "Barrier",
    "Lock", "RLock", "Queue", "SimpleQueue", "LifoQueue",
    "PriorityQueue", "local",
))
_SYNC_MODULES = frozenset(("threading", "queue"))

#: HTTP handler base classes whose do_* methods are thread entry points
_HTTP_BASES = ("BaseHTTPRequestHandler", "SimpleHTTPRequestHandler",
               "CGIHTTPRequestHandler", "StreamRequestHandler",
               "BaseRequestHandler")

#: container annotation heads whose element type we track
_ELEM_HEADS = frozenset(("List", "list", "Sequence", "Iterable",
                         "Iterator", "Set", "set", "FrozenSet", "Deque",
                         "deque", "Tuple", "tuple"))
_DICT_HEADS = frozenset(("Dict", "dict", "Mapping", "MutableMapping",
                         "DefaultDict", "OrderedDict"))

#: stop/drain flag spellings for TH004
_STOP_FLAG_RE = re.compile(
    r"(stop|running|drain|shutdown|closed|quit|alive|exit)", re.I)

ClsRef = Tuple[str, str]          # (module path, class name)
_TYPE = Tuple[ClsRef, bool]       # (class, is-element-of-container)


class ThreadRoot:
    def __init__(self, name: str, kind: str, path: str, line: int,
                 entry: str, daemon: bool):
        self.name = name
        self.kind = kind          # thread | timer | http | signal | main
        self.path = path
        self.line = line
        self.entry = entry        # label of the entry function
        self.daemon = daemon

    @property
    def concurrent(self) -> bool:
        """Does this root race with the others?  The ``main`` root
        models setup/teardown code, which is ordered against every
        spawned thread by the ``Thread.start()``/``join()``
        happens-before edges — so it never *counts* as a racing writer
        (it still contributes reachability, lock acquisition, and
        TH003 contention).  Signal handlers interrupt the main thread
        asynchronously and DO count (root ``signal``)."""
        return self.kind != "main"


class Access:
    __slots__ = ("owner", "field", "kind", "locks", "path", "line",
                 "label", "exempt", "fn_id")

    def __init__(self, owner: ClsRef, field: str, kind: str,
                 locks: FrozenSet[str], path: str, line: int,
                 label: str, exempt: bool, fn_id: int):
        self.owner = owner
        self.field = field
        self.kind = kind          # write | cmut | read
        self.locks = locks
        self.path = path
        self.line = line
        self.label = label
        self.exempt = exempt
        self.fn_id = fn_id


def _fn_label(fn: ast.AST) -> str:
    return getattr(fn, "name", "<lambda>")


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    return [p.arg for p in
            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]


class Topology:
    """Thread roots, per-function root sets, and the access/lock facts
    the TH checkers evaluate.  Built once per check() run; also the
    engine behind ``--threads`` and ``--suggest-locks``."""

    def __init__(self, repo: Repo):
        self.repo = repo
        self.index = PackageIndex(repo, *[d for d in SCAN_DIRS
                                          if repo.exists(d)])
        # class registry: (path, name) -> (ModuleIndex, ClassDef)
        self.classes: Dict[ClsRef, Tuple[ModuleIndex, ast.ClassDef]] = {}
        for mod in self.index.by_path.values():
            for cname, cnode in mod.classes.items():
                self.classes.setdefault((mod.path, cname), (mod, cnode))
        # inferred types
        self.attr_types: Dict[ClsRef, Dict[str, _TYPE]] = {}
        self.sync_attrs: Dict[ClsRef, Set[str]] = {}
        self.rlock_classes: Set[str] = set()   # classes using RLock
        self.param_types: Dict[Tuple[ClsRef, str, str], _TYPE] = {}
        self.ret_types: Dict[Tuple[ClsRef, str], _TYPE] = {}
        self.fn_ret: Dict[Tuple[str, str], _TYPE] = {}  # module fns
        # callback registry: (owner class, attr/param name) -> fn nodes
        self.callbacks: Dict[Tuple[ClsRef, str],
                             List[Tuple[ModuleIndex, ast.AST]]] = {}
        self.roots: List[ThreadRoot] = []
        self.entries: List[Tuple[str, ModuleIndex, ast.AST]] = []
        self.reach: Dict[int, Set[str]] = {}
        self.fn_site: Dict[int, Tuple[ModuleIndex, ast.AST]] = {}
        self.accesses: List[Access] = []
        self.lock_edges: List[Tuple[str, str, str, int, str]] = []
        self.fn_acquires: Dict[int, Set[str]] = {}
        self.calls_under_lock: List[Tuple[FrozenSet[str], int,
                                          str, int, str]] = []
        self.blocking: List[Tuple[int, FrozenSet[str], str, str, int,
                                  str]] = []
        self._build()

    # -- type inference -------------------------------------------------

    def _resolve_class_name(self, mod: ModuleIndex, name: str
                            ) -> Optional[ClsRef]:
        hit = self.index.resolve_class(mod, name)
        if hit is None:
            return None
        return (hit[0].path, hit[1].name)

    def _ann_type(self, mod: ModuleIndex, ann: Optional[ast.AST],
                  elem: bool = False) -> Optional[_TYPE]:
        """Class a type annotation denotes (unwrapping Optional and
        tracking container element types)."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.Name):
            ref = self._resolve_class_name(mod, ann.id)
            return (ref, elem) if ref else None
        if isinstance(ann, ast.Attribute):
            d = dotted_name(ann)
            if d and d.count(".") == 1:
                head, cls = d.split(".")
                hit = self.index.resolve_import(mod, head)
                if hit and hit[1] is None and cls in hit[0].classes:
                    return ((hit[0].path, cls), elem)
            return None
        if isinstance(ann, ast.Subscript):
            head = ann.value.id if isinstance(ann.value, ast.Name) \
                else (ann.value.attr if isinstance(ann.value,
                                                   ast.Attribute) else "")
            sl = ann.slice
            if head == "Optional":
                return self._ann_type(mod, sl, elem)
            if head in _ELEM_HEADS:
                inner = sl.elts[0] if isinstance(sl, ast.Tuple) \
                    and sl.elts else sl
                return self._ann_type(mod, inner, True)
            if head in _DICT_HEADS:
                if isinstance(sl, ast.Tuple) and len(sl.elts) == 2:
                    return self._ann_type(mod, sl.elts[1], True)
        return None

    def _is_sync_ctor(self, mod: ModuleIndex, call: ast.Call) -> bool:
        d = dotted_name(call.func)
        if d is None:
            return False
        parts = d.split(".")
        if len(parts) == 2 and parts[0] in _SYNC_MODULES \
                and parts[1] in _SYNC_CTORS:
            return True
        if len(parts) == 1 and parts[0] in _SYNC_CTORS:
            imp = mod.imports.get(parts[0])
            return bool(imp and imp[0] in _SYNC_MODULES)
        return False

    def _ctor_class(self, mod: ModuleIndex, call: ast.Call
                    ) -> Optional[ClsRef]:
        """Class a Call constructs, unwrapping builder chains like
        ``EngineWatchdog(...).start()``."""
        func = call.func
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Call):
            inner = self._ctor_class(mod, func.value)
            if inner is not None:
                return inner
        d = dotted_name(func)
        if d is None:
            return None
        parts = d.split(".")
        if len(parts) == 1:
            return self._resolve_class_name(mod, parts[0])
        if len(parts) == 2:
            hit = self.index.resolve_import(mod, parts[0])
            if hit and hit[1] is None and parts[1] in hit[0].classes:
                return (hit[0].path, parts[1])
        return None

    def _self_cls(self, mod: ModuleIndex, scope: Scope
                  ) -> Optional[ClsRef]:
        if scope.cls is None:
            return None
        if (mod.path, scope.cls) in self.classes:
            return (mod.path, scope.cls)
        return None

    def _fn_env(self, mod: ModuleIndex, fn: ast.AST, scope: Scope
                ) -> Dict[str, _TYPE]:
        """Flow-insensitive local type environment: annotated/inferred
        params, ctor assignments, typed for-loop targets.  Closure
        variables inherit from the enclosing defs' environments."""
        env: Dict[str, _TYPE] = {}
        for encl in scope.chain:
            outer_scope = mod.scopes.get(id(encl), Scope(None, ()))
            env.update(self._fn_env_local(mod, encl, outer_scope, {}))
        env.update(self._fn_env_local(mod, fn, scope, env))
        return env

    def _fn_env_local(self, mod, fn, scope, base) -> Dict[str, _TYPE]:
        env: Dict[str, _TYPE] = dict(base)
        selfc = self._self_cls(mod, scope)
        if not isinstance(fn, ast.Lambda):
            for p in (list(fn.args.posonlyargs) + list(fn.args.args) +
                      list(fn.args.kwonlyargs)):
                t = self._ann_type(mod, p.annotation)
                if t is None and selfc is not None:
                    t = self.param_types.get((selfc, fn.name, p.arg))
                if t is not None:
                    env.setdefault(p.arg, t)
        ctx = (mod, selfc, env)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for top in body:
            for node in ast.walk(top):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)) and node is not fn:
                    continue
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    tgts = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    val = node.value
                    t = None
                    if isinstance(node, ast.AnnAssign):
                        t = self._ann_type(mod, node.annotation)
                    if t is None and val is not None:
                        t = self._expr_type(ctx, val)
                    if t is not None:
                        for tg in tgts:
                            if isinstance(tg, ast.Name):
                                env.setdefault(tg.id, t)
                elif isinstance(node, ast.For) \
                        and isinstance(node.target, ast.Name):
                    t = self._expr_type(ctx, node.iter)
                    if t is not None and t[1]:
                        env.setdefault(node.target.id, (t[0], False))
        return env

    def _expr_type(self, ctx, expr) -> Optional[_TYPE]:
        mod, selfc, env = ctx
        if isinstance(expr, ast.Name):
            if expr.id == "self" and selfc is not None:
                return (selfc, False)
            return env.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self._expr_type(ctx, expr.value)
            if base is None or base[1]:
                return None
            return self.attr_types.get(base[0], {}).get(expr.attr)
        if isinstance(expr, ast.Subscript):
            base = self._expr_type(ctx, expr.value)
            if base is not None and base[1]:
                return (base[0], False)
            return None
        if isinstance(expr, ast.Call):
            ref = self._ctor_class(mod, expr)
            if ref is not None:
                return (ref, False)
            f = expr.func
            if isinstance(f, ast.Attribute):
                recv = self._expr_type(ctx, f.value)
                if recv is not None and not recv[1]:
                    return self.ret_types.get((recv[0], f.attr))
            elif isinstance(f, ast.Name):
                return self.fn_ret.get((mod.path, f.id))
        if isinstance(expr, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            t = self._expr_type(ctx, expr.elt) \
                if isinstance(expr.elt, ast.Call) else None
            if t is not None:
                return (t[0], True)
        if isinstance(expr, (ast.List, ast.Tuple, ast.Set)) and expr.elts:
            t = self._expr_type(ctx, expr.elts[0])
            if t is not None and not t[1]:
                return (t[0], True)
        if isinstance(expr, ast.Dict) and expr.values:
            t = self._expr_type(ctx, expr.values[0])
            if t is not None and not t[1]:
                return (t[0], True)
        if isinstance(expr, ast.IfExp):
            return self._expr_type(ctx, expr.body) \
                or self._expr_type(ctx, expr.orelse)
        if isinstance(expr, ast.BoolOp) and expr.values:
            for v in expr.values:
                t = self._expr_type(ctx, v)
                if t is not None:
                    return t
        return None

    # -- build ----------------------------------------------------------

    def _build(self) -> None:
        self._collect_annotations()
        for _ in range(3):
            self._harvest_pass()
        self._find_roots()
        self._bfs()
        self._scan_reachable()

    def _collect_annotations(self) -> None:
        for (path, cname), (mod, cnode) in self.classes.items():
            ref = (path, cname)
            amap = self.attr_types.setdefault(ref, {})
            for node in cnode.body:
                if isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Name):
                    t = self._ann_type(mod, node.annotation)
                    if t is not None:
                        amap[node.target.id] = t
            for mname, meth in mod.methods.get(cname, {}).items():
                rt = self._ann_type(mod, getattr(meth, "returns", None))
                if rt is not None:
                    self.ret_types[(ref, mname)] = rt
        for mod in self.index.by_path.values():
            for fname, fnode in mod.functions.items():
                rt = self._ann_type(mod, getattr(fnode, "returns", None))
                if rt is not None:
                    self.fn_ret[(mod.path, fname)] = rt

    def _callable_targets(self, mod, scope, ctx, expr
                          ) -> List[Tuple[ModuleIndex, ast.AST]]:
        """Function nodes a callback expression may denote, adding
        typed-receiver bound methods to core's resolution."""
        out = list(resolve_callable(self.index, mod, scope, expr))
        if isinstance(expr, ast.Attribute) and not out:
            recv = self._expr_type(ctx, expr.value)
            if recv is not None and not recv[1]:
                cpath, cname = recv[0]
                cmod = self.index.by_path.get(cpath)
                if cmod is not None:
                    meth = cmod.methods.get(cname, {}).get(expr.attr)
                    if meth is not None:
                        out.append((cmod, meth))
        # self._method inside nested classes (core only sees top-level)
        if not out and scope.cls is not None:
            d = dotted_name(expr)
            if d and d.startswith("self.") and d.count(".") == 1:
                meth = mod.methods.get(scope.cls, {}).get(d.split(".")[1])
                if meth is not None:
                    out.append((mod, meth))
        return out

    def _harvest_pass(self) -> None:
        """One round of attribute-type / ctor-param / callback harvest
        over every function body (run to a small fixpoint)."""
        for mod in self.index.by_path.values():
            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                scope = mod.scopes.get(id(node), Scope(None, ()))
                env = self._fn_env(mod, node, scope)
                selfc = self._self_cls(mod, scope)
                ctx = (mod, selfc, env)
                body = node.body
                for top in body:
                    for sub in ast.walk(top):
                        self._harvest_node(mod, node, scope, ctx, sub)

    def _harvest_node(self, mod, fn, scope, ctx, node) -> None:
        _, selfc, env = ctx
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            val = node.value
            for tg in tgts:
                if not isinstance(tg, ast.Attribute):
                    continue
                owner = self._expr_type(ctx, tg.value)
                if owner is None or owner[1]:
                    continue
                oref = owner[0]
                amap = self.attr_types.setdefault(oref, {})
                t = None
                if isinstance(node, ast.AnnAssign):
                    t = self._ann_type(mod, node.annotation)
                if t is None and isinstance(val, ast.Call):
                    if self._is_sync_ctor(mod, val):
                        self.sync_attrs.setdefault(oref, set()).add(
                            tg.attr)
                        d = dotted_name(val.func) or ""
                        if d.rsplit(".", 1)[-1] == "RLock":
                            self.rlock_classes.add(oref[1])
                        continue
                if t is None and val is not None:
                    t = self._expr_type(ctx, val)
                if t is not None:
                    amap.setdefault(tg.attr, t)
                # callback registration: recv.attr = <callable>
                if val is not None:
                    cbs = self._callable_targets(mod, scope, ctx, val)
                    if cbs:
                        key = (oref, tg.attr)
                        cur = self.callbacks.setdefault(key, [])
                        for c in cbs:
                            if all(c[1] is not e[1] for e in cur):
                                cur.append(c)
                # alias: self.X = <param registered as ctor callback>
                if isinstance(val, ast.Name) and selfc is not None \
                        and getattr(fn, "name", "") == "__init__" \
                        and isinstance(tg.value, ast.Name) \
                        and tg.value.id == "self":
                    src = self.callbacks.get((selfc, val.id))
                    if src:
                        cur = self.callbacks.setdefault(
                            (selfc, tg.attr), [])
                        for c in src:
                            if all(c[1] is not e[1] for e in cur):
                                cur.append(c)
        elif isinstance(node, ast.Call):
            ref = self._ctor_class(ctx[0], node)
            if ref is None:
                return
            cmod, cnode = self.classes.get(ref, (None, None))
            if cnode is None:
                return
            init = cmod.methods.get(ref[1], {}).get("__init__")
            if init is None:
                return
            params = [p for p in _param_names(init) if p != "self"]
            bound: List[Tuple[str, ast.AST]] = []
            for i, a in enumerate(node.args):
                if i < len(params):
                    bound.append((params[i], a))
            for kw in node.keywords:
                if kw.arg:
                    bound.append((kw.arg, kw.value))
            for pname, aexpr in bound:
                t = self._expr_type(ctx, aexpr)
                if t is not None:
                    self.param_types.setdefault(
                        (ref, "__init__", pname), t)
                cbs = self._callable_targets(ctx[0], scope, ctx, aexpr)
                if cbs:
                    cur = self.callbacks.setdefault((ref, pname), [])
                    for c in cbs:
                        if all(c[1] is not e[1] for e in cur):
                            cur.append(c)

    # -- roots ----------------------------------------------------------

    def _thread_ctor_kind(self, mod, call) -> Optional[str]:
        d = dotted_name(call.func)
        if d is None:
            return None
        last = d.rsplit(".", 1)[-1]
        if last not in ("Thread", "Timer"):
            return None
        if "." in d:
            return "thread" if last == "Thread" else "timer"
        imp = mod.imports.get(last)
        if imp and imp[0] == "threading":
            return "thread" if last == "Thread" else "timer"
        return None

    def _find_roots(self) -> None:
        seen_names: Dict[str, ThreadRoot] = {}

        def add(name, kind, mod, line, targets, daemon):
            entry = ", ".join(sorted({
                (f"{m.path}:{_fn_label(f)}").rsplit("/", 1)[-1]
                for m, f in targets})) or "?"
            root = seen_names.get(name)
            if root is None:
                root = ThreadRoot(name, kind, mod.path, line, entry,
                                  daemon)
                seen_names[name] = root
                self.roots.append(root)
            else:
                root.daemon = root.daemon or daemon
            for m, f in targets:
                self.entries.append((name, m, f))

        for mod in self.index.by_path.values():
            stem = mod.path.rsplit("/", 1)[-1][:-3]
            # main() entry points collapse into one "main" pseudo-root
            if "main" in mod.functions:
                add("main", "main", mod, mod.functions["main"].lineno,
                    [(mod, mod.functions["main"])], False)
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    base_names = {dotted_name(b) or "" for b in node.bases}
                    if any(b.rsplit(".", 1)[-1] in _HTTP_BASES
                           for b in base_names):
                        handlers = [
                            (mod, m) for m in node.body
                            if isinstance(m, (ast.FunctionDef,
                                              ast.AsyncFunctionDef))
                            and m.name.startswith("do_")]
                        if handlers:
                            add(f"http:{stem}", "http", mod, node.lineno,
                                handlers, False)
                    continue
                if not isinstance(node, ast.Call):
                    continue
                kind = self._thread_ctor_kind(mod, node)
                if kind is not None:
                    scope = enclosing_scope(mod, node)
                    env = {}
                    encl = scope.chain[-1] if scope.chain else None
                    if encl is not None:
                        env = self._fn_env(
                            mod, encl,
                            mod.scopes.get(id(encl), Scope(None, ())))
                    ctx = (mod, self._self_cls(mod, scope), env)
                    tgt_expr = None
                    daemon = kind == "timer"
                    name = None
                    args = list(node.args)
                    for kw in node.keywords:
                        if kw.arg == "target" or \
                                (kind == "timer" and kw.arg == "function"):
                            tgt_expr = kw.value
                        elif kw.arg == "name" and \
                                isinstance(kw.value, ast.Constant):
                            name = str(kw.value.value)
                        elif kw.arg == "daemon" and \
                                isinstance(kw.value, ast.Constant):
                            daemon = bool(kw.value.value)
                    if tgt_expr is None and kind == "timer" \
                            and len(args) >= 2:
                        tgt_expr = args[1]
                    if tgt_expr is None:
                        continue
                    targets = self._callable_targets(mod, scope, ctx,
                                                     tgt_expr)
                    if not targets:
                        continue
                    if name is None:
                        lbl = _fn_label(targets[0][1])
                        name = f"{kind}:{stem}.{lbl}"
                    add(name, kind, mod, node.lineno, targets, daemon)
                else:
                    d = dotted_name(node.func)
                    if d in ("signal.signal",) and len(node.args) == 2:
                        scope = enclosing_scope(mod, node)
                        encl = scope.chain[-1] if scope.chain else None
                        env = self._fn_env(
                            mod, encl,
                            mod.scopes.get(id(encl),
                                           Scope(None, ()))) \
                            if encl is not None else {}
                        ctx = (mod, self._self_cls(mod, scope), env)
                        targets = self._callable_targets(
                            mod, scope, ctx, node.args[1])
                        if targets:
                            # signal handlers run on the main thread
                            # but interrupt it asynchronously
                            add("signal", "signal", mod, node.lineno,
                                targets, False)

    # -- reachability ---------------------------------------------------

    def _edges_from(self, mod, fn) -> List[Tuple[ModuleIndex, ast.AST]]:
        scope_base = mod.scopes.get(id(fn), Scope(None, ()))
        scope = Scope(scope_base.cls, scope_base.chain + (fn,))
        env = self._fn_env(mod, fn, scope_base)
        ctx = (mod, self._self_cls(mod, scope_base), env)
        out: List[Tuple[ModuleIndex, ast.AST]] = []
        # local callable aliases: h = self.hook; ...; h()
        aliases: Dict[str, List[Tuple[ModuleIndex, ast.AST]]] = {}
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        nested = _nested_member_ids(fn)
        for top in body:
            for node in ast.walk(top):
                if id(node) in nested:
                    continue
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and not isinstance(node.value, ast.Call):
                    tg = self._call_targets(mod, scope, ctx, node.value)
                    if tg:
                        aliases[node.targets[0].id] = tg
        for top in body:
            for node in ast.walk(top):
                if id(node) in nested or not isinstance(node, ast.Call):
                    continue
                out.extend(self._call_targets(mod, scope, ctx, node.func))
                if isinstance(node.func, ast.Name) \
                        and node.func.id in aliases:
                    out.extend(aliases[node.func.id])
        return out

    def _call_targets(self, mod, scope, ctx, expr
                      ) -> List[Tuple[ModuleIndex, ast.AST]]:
        out = self._callable_targets(mod, scope, ctx, expr)
        # callback dispatch through a typed receiver attribute
        if isinstance(expr, ast.Attribute):
            recv = self._expr_type(ctx, expr.value)
            if recv is not None and not recv[1]:
                cbs = self.callbacks.get((recv[0], expr.attr))
                if cbs:
                    out = out + [c for c in cbs
                                 if all(c[1] is not e[1] for e in out)]
        return out

    def _bfs(self) -> None:
        queue: List[Tuple[str, ModuleIndex, ast.AST]] = list(self.entries)
        edge_cache: Dict[int, List[Tuple[ModuleIndex, ast.AST]]] = {}
        while queue:
            root, mod, fn = queue.pop()
            if fn is None:
                continue
            fid = id(fn)
            roots = self.reach.setdefault(fid, set())
            if root in roots:
                continue
            roots.add(root)
            self.fn_site[fid] = (mod, fn)
            if fid not in edge_cache:
                edge_cache[fid] = self._edges_from(mod, fn)
            for m2, f2 in edge_cache[fid]:
                queue.append((root, m2, f2))
        self._edge_cache = edge_cache

    # -- access / lock scan ---------------------------------------------

    def _lock_name(self, ctx, expr) -> Optional[str]:
        """'<Class>.<attr>' for a lock-ish attribute expression."""
        if isinstance(expr, ast.Attribute) and _is_lock_attr(expr.attr):
            t = self._expr_type(ctx, expr.value)
            if t is not None and not t[1]:
                return f"{t[0][1]}.{expr.attr}"
        return None

    def _scan_reachable(self) -> None:
        for fid, roots in self.reach.items():
            mod, fn = self.fn_site[fid]
            scope_base = mod.scopes.get(id(fn), Scope(None, ()))
            env = self._fn_env(mod, fn, scope_base)
            selfc = self._self_cls(mod, scope_base)
            ctx = (mod, selfc, env)
            label = _fn_label(fn)
            if scope_base.cls:
                label = f"{scope_base.cls}.{label}"
            exempt_fn = (getattr(fn, "name", "") == "__init__"
                         or str(getattr(fn, "name", "")
                                ).endswith("_locked"))
            acquires = self.fn_acquires.setdefault(fid, set())
            nested = _nested_member_ids(fn)
            body = fn.body if isinstance(fn.body, list) else [fn.body]

            def visit(node, held: FrozenSet[str]):
                if id(node) in nested:
                    return
                if isinstance(node, ast.With):
                    newly = []
                    for item in node.items:
                        ln = self._lock_name(ctx, item.context_expr)
                        if ln is not None:
                            for h in held.union(newly):
                                self.lock_edges.append(
                                    (h, ln, mod.path, node.lineno,
                                     label))
                            newly.append(ln)
                            acquires.add(ln)
                    inner = held.union(newly)
                    for st in node.body:
                        visit(st, inner)
                    return
                if isinstance(node, ast.Call):
                    self._record_call(ctx, fid, label, mod, node, held)
                self._record_access(ctx, fid, label, mod, node, held,
                                    exempt_fn, roots)
                for child in ast.iter_child_nodes(node):
                    visit(child, held)

            for top in body:
                visit(top, frozenset())

    def _record_call(self, ctx, fid, label, mod, node, held) -> None:
        """Blocking-call sites and calls-made-under-a-lock."""
        blk = _blocking_label(ctx, self, mod, node)
        if blk is not None and held:
            self.blocking.append((fid, held, blk, mod.path,
                                  node.lineno, label))
        if held:
            scope_base = mod.scopes.get(id(self.fn_site[fid][1]),
                                        Scope(None, ()))
            scope = Scope(scope_base.cls,
                          scope_base.chain + (self.fn_site[fid][1],))
            for m2, f2 in self._call_targets(mod, scope, ctx, node.func):
                self.calls_under_lock.append(
                    (held, id(f2), mod.path, node.lineno, label))

    def _record_access(self, ctx, fid, label, mod, node, held,
                       exempt_fn, roots) -> None:
        recs: List[Tuple[ClsRef, str, str]] = []
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tg in tgts:
                hit = self._field_of(ctx, tg)
                if hit:
                    owner, fieldname, via_subscript = hit
                    kind = "cmut" if via_subscript else "write"
                    recs.append((owner, fieldname, kind))
        elif isinstance(node, ast.Delete):
            for tg in node.targets:
                hit = self._field_of(ctx, tg)
                if hit:
                    recs.append((hit[0], hit[1], "cmut"))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            hit = self._field_of(ctx, node.func.value)
            if hit:
                # if the receiver field holds a *package* class with a
                # real method of that name (e.g. RequestQueue.remove,
                # internally locked), the call-edge into the method
                # body records any actual mutation — don't double-count
                # it as a raw container mutation here
                ft = self.attr_types.get(hit[0], {}).get(hit[1])
                is_method = False
                if ft is not None and ft[0] in self.classes:
                    fmod = self.classes[ft[0]][0]
                    is_method = node.func.attr in \
                        fmod.methods.get(ft[0][1], {})
                if not is_method:
                    recs.append((hit[0], hit[1], "cmut"))
        elif isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load):
            t = self._expr_type(ctx, node.value)
            if t is not None and not t[1] and t[0] in self.classes:
                recs.append((t[0], node.attr, "read"))
        for owner, fieldname, kind in recs:
            if fieldname.startswith("__"):
                continue
            if fieldname in self.sync_attrs.get(owner, set()):
                continue
            if owner not in self.classes:
                continue
            # methods are code, not state
            cmod = self.classes[owner][0]
            if fieldname in cmod.methods.get(owner[1], {}):
                continue
            if roots:
                self.accesses.append(Access(
                    owner, fieldname, kind, held, mod.path,
                    getattr(node, "lineno", 0), label,
                    exempt_fn and kind != "read", fid))

    def _field_of(self, ctx, expr
                  ) -> Optional[Tuple[ClsRef, str, bool]]:
        """(owner class, field, via-container) for an attribute-rooted
        lvalue, peeling subscripts: ``self.finished[k]`` -> finished."""
        via = False
        while isinstance(expr, ast.Subscript):
            expr = expr.value
            via = True
        if not isinstance(expr, ast.Attribute):
            return None
        t = self._expr_type(ctx, expr.value)
        if t is None or t[1]:
            return None
        if t[0] not in self.classes:
            return None
        return (t[0], expr.attr, via)

    # -- per-fn lock closure (for TH002/TH003) --------------------------

    def transitive_acquires(self) -> Dict[int, Set[str]]:
        """fn id -> locks acquired by it or anything it calls."""
        acq = {fid: set(locks)
               for fid, locks in self.fn_acquires.items()}
        for fid in self.reach:
            acq.setdefault(fid, set())
        changed = True
        while changed:
            changed = False
            for fid in self.reach:
                for m2, f2 in self._edge_cache.get(fid, ()):
                    sub = acq.get(id(f2))
                    if sub and not sub <= acq[fid]:
                        acq[fid] |= sub
                        changed = True
        return acq


def _nested_member_ids(fn: ast.AST) -> Set[int]:
    """ids of every node inside a nested def/lambda/class of fn."""
    out: Set[int] = set()
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for top in body:
        for n in ast.walk(top):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                for sub in ast.walk(n):
                    if sub is not n:
                        out.add(id(sub))
                out.add(id(n))
    return out


_QUEUEISH_RE = re.compile(r"(queue|events|inbox|mailbox|channel)", re.I)


def _blocking_label(ctx, topo, mod, node: ast.Call) -> Optional[str]:
    """Label when a call can block: LD001's list plus join /
    wait-without-timeout / queue.get-without-timeout / .result() /
    .getresponse()."""
    d = dotted_name(node.func)
    if d is not None:
        if any(d.startswith(p) for p in _BLOCKING_PREFIXES):
            return d
        if d in _BLOCKING_NAMES:
            return d
    if not isinstance(node.func, ast.Attribute):
        return None
    attr = node.func.attr
    has_timeout = bool(node.args) or any(
        kw.arg in ("timeout", "block") for kw in node.keywords)
    if attr == "join":
        # joining a thread blocks (bounded or not)
        recv = dotted_name(node.func.value) or ""
        if not isinstance(node.func.value, ast.Constant) and \
                not (recv and recv.split(".")[-1] in ("sep",)):
            # exclude str.join: a constant/str receiver or args that are
            # genexprs over strings — heuristic: thread-ish receivers
            # are attributes/locals named *thread*/*worker* or typed
            if re.search(r"(thread|worker|proc|timer)",
                         (recv or ""), re.I):
                return f"{recv}.join"
        return None
    if attr == "wait" and not has_timeout:
        return f"{dotted_name(node.func.value) or '?'}.wait"
    if attr == "get" and not has_timeout:
        recv = dotted_name(node.func.value) or ""
        if _QUEUEISH_RE.search(recv):
            return f"{recv}.get"
        return None
    if attr in ("result", "getresponse") and not node.args:
        return f"{dotted_name(node.func.value) or '?'}.{attr}"
    return None


# -- checkers ------------------------------------------------------------


def _roots_of(topo: Topology, acc: Access) -> Set[str]:
    return topo.reach.get(acc.fn_id, set())


def _counting_roots(topo: Topology) -> Set[str]:
    """Roots that count as racing writers (see ThreadRoot.concurrent)."""
    return {r.name for r in topo.roots if r.concurrent}


def _th001(topo: Topology, out: List[Violation]) -> None:
    by_field: Dict[Tuple[ClsRef, str], List[Access]] = {}
    for acc in topo.accesses:
        by_field.setdefault((acc.owner, acc.field), []).append(acc)
    for (owner, fieldname), accs in sorted(
            by_field.items(), key=lambda kv: (kv[0][0][0], kv[0][0][1],
                                              kv[0][1])):
        live = [a for a in accs if not a.exempt]
        writes = [a for a in live if a.kind in ("write", "cmut")]
        if not writes:
            continue
        counting = _counting_roots(topo)
        writer_roots: Set[str] = set()
        for a in writes:
            writer_roots |= _roots_of(topo, a) & counting
        access_roots: Set[str] = set()
        for a in live:
            access_roots |= _roots_of(topo, a) & counting
        common = None
        for a in writes:
            common = a.locks if common is None else common & a.locks
        if common:
            continue
        cmut_roots: Set[str] = set()
        for a in writes:
            if a.kind == "cmut":
                cmut_roots |= _roots_of(topo, a) & counting
        multi_writer = len(writer_roots) >= 2
        foreign_touch = bool(cmut_roots) and \
            bool(access_roots - cmut_roots)
        if not (multi_writer or foreign_touch):
            # single-writer scalar publish (display counters): fine
            continue
        cpath = owner[0]
        cmod, cnode = topo.classes[owner]
        declared = _protected_fields(cnode)
        lock_hint = declared.get(fieldname)
        if lock_hint is None:
            # most common lock this class already uses, else _lock
            counts: Dict[str, int] = {}
            for a in accs:
                for ln in a.locks:
                    if ln.startswith(owner[1] + "."):
                        counts[ln] = counts.get(ln, 0) + 1
            lock_hint = max(counts, key=counts.get).split(".", 1)[1] \
                if counts else "_lock"
        first = min((a for a in writes if a.path == cpath),
                    key=lambda a: a.line, default=writes[0])
        wr = ",".join(sorted(writer_roots))
        out.append(Violation(
            CHECKER, "TH001", cpath, cnode.lineno,
            f"{owner[1]}.{fieldname}",
            f"'{owner[1]}.{fieldname}' is written from thread roots "
            f"[{wr}] (e.g. {first.label} at {first.path}:{first.line}) "
            f"with no common lock on all write paths; guard every "
            f"access with 'with self.{lock_hint}:' and declare "
            f"{ANNOTATION} = {{\"{fieldname}\": \"{lock_hint}\"}} so "
            f"LD002 enforces it"))


def _th002(topo: Topology, out: List[Violation]) -> None:
    acq = topo.transitive_acquires()
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for held, ln, path, line, label in topo.lock_edges:
        edges.setdefault((held, ln), (path, line, label))
    for held, callee, path, line, label in topo.calls_under_lock:
        for h in held:
            for ln in acq.get(callee, ()):
                edges.setdefault((h, ln), (path, line, label))
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    # self-cycles: re-acquiring a non-reentrant lock deadlocks alone
    for (a, b), (path, line, label) in sorted(edges.items()):
        if a == b and a.split(".")[0] not in topo.rlock_classes:
            out.append(Violation(
                CHECKER, "TH002", path, line, f"{a}->{b}",
                f"'{label}' acquires {b} while already holding it "
                f"(non-reentrant threading.Lock self-deadlock)"))
    # longer cycles: DFS with a path stack
    def find_cycle() -> Optional[List[str]]:
        color: Dict[str, int] = {}
        stack: List[str] = []

        def dfs(n) -> Optional[List[str]]:
            color[n] = 1
            stack.append(n)
            for m in sorted(graph.get(n, ())):
                if m == n:
                    continue
                if color.get(m) == 1:
                    return stack[stack.index(m):] + [m]
                if color.get(m, 0) == 0:
                    cyc = dfs(m)
                    if cyc:
                        return cyc
            color[n] = 2
            stack.pop()
            return None

        for n in sorted(graph):
            if color.get(n, 0) == 0:
                cyc = dfs(n)
                if cyc:
                    return cyc
        return None

    cyc = find_cycle()
    if cyc:
        # canonical rotation so the fingerprint is stable
        ring = cyc[:-1]
        k = ring.index(min(ring))
        ring = ring[k:] + ring[:k]
        a, b = ring[0], ring[1 % len(ring)]
        path, line, label = edges.get(
            (a, b), next(iter(edges.values())))
        sym = "->".join(ring + [ring[0]])
        out.append(Violation(
            CHECKER, "TH002", path, line, sym,
            f"lock-order inversion: cycle {sym} in the acquires-while-"
            f"holding graph (e.g. '{label}' at {path}:{line}); impose "
            f"a single acquisition order or narrow the outer critical "
            f"section"))


def _th003(topo: Topology, out: List[Violation]) -> None:
    acq = topo.transitive_acquires()
    # which roots acquire each lock (directly or transitively)
    lock_roots: Dict[str, Set[str]] = {}
    for fid, locks in acq.items():
        roots = topo.reach.get(fid, set())
        for ln in locks:
            lock_roots.setdefault(ln, set()).update(roots)
    for fid, held, blk, path, line, label in topo.blocking:
        my_roots = topo.reach.get(fid, set())
        for ln in sorted(held):
            others = lock_roots.get(ln, set()) - my_roots
            if others:
                out.append(Violation(
                    CHECKER, "TH003", path, line, f"{label}/{blk}",
                    f"'{label}' blocks on {blk} while holding {ln}, "
                    f"which thread root(s) [{','.join(sorted(others))}] "
                    f"also need — move the blocking call outside the "
                    f"critical section or bound it with a timeout"))
                break


def _th004(topo: Topology, out: List[Violation]) -> None:
    daemon_roots = {r.name for r in topo.roots
                    if r.daemon or r.kind == "timer"}
    if not daemon_roots:
        return
    # shared fields: TH001-eligible or declared in _lock_protected_
    shared: Set[Tuple[ClsRef, str]] = set()
    for ref, (mod, cnode) in topo.classes.items():
        for f in _protected_fields(cnode):
            shared.add((ref, f))
    counting = _counting_roots(topo)
    seen_fields: Dict[Tuple[ClsRef, str], Set[str]] = {}
    for acc in topo.accesses:
        if acc.kind in ("write", "cmut") and not acc.exempt:
            seen_fields.setdefault((acc.owner, acc.field), set()) \
                .update(_roots_of(topo, acc) & counting)
    for key, roots in seen_fields.items():
        if len(roots) >= 2:
            shared.add(key)
    for fid, roots in topo.reach.items():
        if not (roots & daemon_roots):
            continue
        mod, fn = topo.fn_site[fid]
        scope_base = mod.scopes.get(id(fn), Scope(None, ()))
        env = topo._fn_env(mod, fn, scope_base)
        ctx = (mod, topo._self_cls(mod, scope_base), env)
        label = _fn_label(fn)
        if scope_base.cls:
            label = f"{scope_base.cls}.{label}"
        nested = _nested_member_ids(fn)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for top in body:
            for node in ast.walk(top):
                if id(node) in nested or not isinstance(node, ast.While):
                    continue
                flags = _flag_attrs(ctx, topo, node.test)
                if not flags:
                    continue
                _scan_drain_loop(ctx, topo, mod, node, flags, shared,
                                 label, nested, out)


def _flag_attrs(ctx, topo, test: ast.AST) -> Set[Tuple[ClsRef, str]]:
    """Stop/drain flag attributes read in a while-test."""
    flags: Set[Tuple[ClsRef, str]] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Attribute) \
                and _STOP_FLAG_RE.search(node.attr):
            t = topo._expr_type(ctx, node.value)
            if t is not None and not t[1] and t[0] in topo.classes:
                flags.add((t[0], node.attr))
    return flags


def _scan_drain_loop(ctx, topo, mod, loop, flags, shared, label,
                     nested, out) -> None:
    stmts = sorted((n for n in ast.walk(loop) if n is not loop
                    and id(n) not in nested
                    and hasattr(n, "lineno")),
                   key=lambda n: (n.lineno, getattr(n, "col_offset", 0)))
    blocked_since: Optional[str] = None
    reported: Set[str] = set()
    held_lines = _with_lock_lines(ctx, topo, loop, nested)
    for node in stmts:
        if isinstance(node, ast.Call):
            blk = _blocking_label(ctx, topo, mod, node)
            if blk is not None:
                blocked_since = blk
                continue
        if isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load):
            t = topo._expr_type(ctx, node.value)
            if t is not None and not t[1] and (t[0], node.attr) in flags:
                blocked_since = None   # flag re-checked
                continue
        if blocked_since is None:
            continue
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tg in tgts:
                hit = topo._field_of(ctx, tg)
                if hit is None:
                    continue
                owner, fieldname, _via = hit
                if (owner, fieldname) not in shared:
                    continue
                if node.lineno in held_lines:
                    continue
                if fieldname in reported:
                    continue
                reported.add(fieldname)
                out.append(Violation(
                    CHECKER, "TH004", mod.path, node.lineno,
                    f"{label}/{fieldname}",
                    f"daemon loop '{label}' writes shared "
                    f"'{owner[1]}.{fieldname}' after blocking on "
                    f"{blocked_since} without re-checking its stop/"
                    f"drain flag under a lock — a drained object can "
                    f"be mutated one more time; re-test the flag (or "
                    f"take the lock) after the blocking call"))


def _with_lock_lines(ctx, topo, loop, nested) -> Set[int]:
    """Line numbers inside ``with <lock>:`` bodies within the loop."""
    lines: Set[int] = set()
    for node in ast.walk(loop):
        if id(node) in nested or not isinstance(node, ast.With):
            continue
        if any(topo._lock_name(ctx, it.context_expr)
               for it in node.items):
            end = getattr(node, "end_lineno", node.lineno)
            lines.update(range(node.lineno, end + 1))
    return lines


# -- public API -----------------------------------------------------------


def build_topology(repo: Repo) -> Topology:
    return Topology(repo)


def check(repo: Repo, baseline=None) -> List[Violation]:
    topo = build_topology(repo)
    out: List[Violation] = []
    _th001(topo, out)
    _th002(topo, out)
    _th003(topo, out)
    _th004(topo, out)
    return out


def threads_table(repo: Repo) -> str:
    """Markdown table of discovered thread roots (``--threads``)."""
    topo = build_topology(repo)
    lines = ["| root | kind | daemon | entry | spawned at |",
             "|---|---|---|---|---|"]
    for r in sorted(topo.roots, key=lambda r: (r.kind, r.name)):
        daemon = "yes" if r.daemon else "no"
        lines.append(f"| {r.name} | {r.kind} | {daemon} | {r.entry} "
                     f"| {r.path} |")
    return "\n".join(lines)


def suggest_locks(repo: Repo) -> str:
    """Ready-to-paste ``_lock_protected_`` declarations inferred from
    the TH001 topology (``--suggest-locks``).  Ignores the baseline on
    purpose: suggestions should show suppressed fields too."""
    findings: List[Violation] = []
    topo = build_topology(repo)
    _th001(topo, findings)
    by_cls: Dict[str, List[Tuple[str, str, str]]] = {}
    cls_path: Dict[str, str] = {}
    for v in findings:
        cls, fieldname = v.symbol.split(".", 1)
        m = re.search(r'\{"[^"]+": "([^"]+)"\}', v.message)
        lock = m.group(1) if m else "_lock"
        roots = ""
        mroots = re.search(r"\[([^\]]*)\]", v.message)
        if mroots:
            roots = mroots.group(1)
        by_cls.setdefault(cls, []).append((fieldname, lock, roots))
        cls_path[cls] = v.path
    if not by_cls:
        return "no unprotected shared fields inferred — nothing to do\n"
    chunks: List[str] = []
    for cls in sorted(by_cls):
        chunks.append(f"# {cls_path[cls]}: class {cls}")
        chunks.append(f"{ANNOTATION} = {{")
        for fieldname, lock, roots in sorted(by_cls[cls]):
            chunks.append(f'    "{fieldname}": "{lock}",'
                          f'  # written from: {roots}')
        chunks.append("}")
        chunks.append("")
    return "\n".join(chunks)
