"""Checker ``markers``: every pytest marker used is registered.

There is no ``pytest.ini``/``pyproject.toml`` in this repo — marker
registration lives solely in ``tests/conftest.py``'s
``pytest_configure`` (``config.addinivalue_line("markers", ...)``),
and pytest treats unknown markers as a *warning*, so a typo'd
``@pytest.mark.solw`` silently stops deselecting under
``-m 'not slow'`` and a slow test sneaks into tier-1.  ``PM001`` makes
that a lint error: every ``pytest.mark.<m>`` under ``tests/`` must be
registered or a pytest builtin.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from megatron_llm_tpu.analysis.core import (
    Repo, Violation, const_str, dotted_name,
)

CHECKER = "markers"

CONFTEST = "tests/conftest.py"

#: markers pytest itself defines — always allowed
BUILTIN_MARKERS = frozenset((
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings", "tryfirst", "trylast", "timeout",
))


def registered_markers(repo: Repo) -> Set[str]:
    tree = repo.tree(CONFTEST)
    out: Set[str] = set()
    if tree is None:
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "addinivalue_line" \
                and len(node.args) >= 2 \
                and const_str(node.args[0]) == "markers":
            line = const_str(node.args[1])
            if line:
                out.add(line.split(":", 1)[0].split("(", 1)[0].strip())
    return out


def used_markers(repo: Repo) -> Dict[str, List[Tuple[str, int]]]:
    out: Dict[str, List[Tuple[str, int]]] = {}
    for rel in repo.py_files("tests"):
        tree = repo.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            d = dotted_name(node) if isinstance(node, ast.Attribute) \
                else None
            if d and d.startswith("pytest.mark."):
                m = d.split(".")[2]
                out.setdefault(m, []).append((rel, node.lineno))
    return out


def check(repo: Repo, baseline=None) -> List[Violation]:
    registered = registered_markers(repo)
    out: List[Violation] = []
    for marker, sites in sorted(used_markers(repo).items()):
        if marker in BUILTIN_MARKERS or marker in registered:
            continue
        rel, line = sites[0]
        out.append(Violation(
            CHECKER, "PM001", rel, line, marker,
            f"pytest.mark.{marker} is not registered in {CONFTEST} "
            f"(unknown markers never deselect — a typo here silently "
            f"changes which tests tier-1 runs; {len(sites)} use "
            f"site(s))"))
    return out
