"""Checker ``recompile``: host-sync / retrace hazards in jitted code.

The serving engine's zero-steady-state-recompile guarantee (and the
training step's compile-once discipline) dies by a thousand cuts:
one ``.item()`` in a helper three calls below ``_decode_impl``, one
``if`` on a traced value, one ``self.config.x`` read resolved at trace
time instead of once at ``__init__``.  Runtime guard tests catch the
recompile *after* it happens on a hot path; this checker catches the
hazard in review.

Mechanics: find every ``jax.jit`` / ``shard_map`` / ``pallas_call``
root (call sites, decorators, ``partial(jax.jit, ...)``), resolve the
traced callables (module functions, ``self._method``, nested defs,
lambdas, plus callables handed to ``lax.scan``-family combinators),
walk the intra-package call graph from those roots, and flag inside
every reachable function:

* ``RC001`` — ``.item()`` (device sync, blocks the dispatch pipeline)
* ``RC002`` — ``float()``/``int()``/``bool()`` on a traced parameter
* ``RC003`` — ``np.asarray``/``np.array`` on a traced parameter
  (silent device→host transfer + constant-folding retrace hazard)
* ``RC004`` — ``if``/``while`` branching on a traced parameter
  (``is None``, ``.shape``/``.ndim``/``.dtype``, ``len()`` and
  ``isinstance()`` tests are static and exempt)
* ``RC005`` — reading ``self.config.*`` / ``self.cfg.*`` /
  ``self.args.*`` inside a jit-reachable method: mutable config must
  be resolved ONCE at ``__init__`` into frozen attributes (the
  ``_decode_cfg``/``_prefill_cfg`` pattern), or every config change —
  and every dict-ordering accident — is a retrace.

Parameters are treated as *static* (not traced) when they are ``self``/
``cls``, a known config/mode name, annotated with a python scalar type
or a ``*Config`` dataclass, or defaulted to a bool/str constant —
that is how this codebase spells "static argument" by convention.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from megatron_llm_tpu.analysis.core import (
    Repo, Violation, dotted_name,
    Scope as _Scope, ModuleIndex as _Module, PackageIndex,
    resolve_callable, enclosing_scope as _enclosing_scope,
)

CHECKER = "recompile"

#: parameter names that are static-by-convention in this codebase
STATIC_PARAM_NAMES = frozenset((
    "self", "cls", "cfg", "config", "mcfg", "tcfg", "pcfg", "train_cfg",
    "parallel_cfg", "args", "mesh", "topology", "axis", "axis_name",
    "name", "mode", "dtype", "train", "deterministic", "interpret",
    "block_q", "block_k", "num_stages", "schedule",
))

#: static annotation spellings (python scalars + config dataclasses)
_STATIC_ANNOTATIONS = frozenset(("bool", "str", "int", "float"))

#: call suffixes that trace their callable arguments
_TRACING_COMBINATORS = frozenset((
    "scan", "while_loop", "cond", "fori_loop", "switch", "map",
    "vmap", "grad", "value_and_grad", "checkpoint", "remat",
    "custom_vjp", "custom_jvp", "named_call",
))

_NP_ROOTS = frozenset(("np", "numpy", "onp"))
_NP_HOST_CALLS = frozenset(("asarray", "array", "copy", "frombuffer"))
_SHAPE_ATTRS = frozenset(("shape", "ndim", "dtype", "size"))
#: attribute probes that are static inside a branch test: metadata
#: (shape/dtype) and pytree-structure lookups (`params.get("bias")`)
_STATIC_TEST_ATTRS = _SHAPE_ATTRS | frozenset(
    ("get", "keys", "values", "items"))


def _is_jit_expr(node: ast.AST) -> bool:
    """Does this expression denote jax.jit/pjit itself?"""
    d = dotted_name(node)
    if d is None:
        return False
    return d in ("jax.jit", "jit", "pjit", "jax.pjit") or d.endswith(".pjit")


def _root_kind(func_expr: ast.AST) -> Optional[str]:
    """'jit' | 'shard_map' | 'pallas' for a Call's func expr, else None."""
    d = dotted_name(func_expr)
    if d is None:
        return None
    if _is_jit_expr(func_expr):
        return "jit"
    last = d.rsplit(".", 1)[-1]
    if last == "shard_map":
        return "shard_map"
    if last == "pallas_call":
        return "pallas"
    return None


#: call-graph machinery lives in core.py (shared with the ``threads``
#: checker); kept under the old local names for this module's walkers.
_Index = PackageIndex
_resolve_callable = resolve_callable


def _find_roots(index: _Index) -> List[Tuple[_Module, ast.AST]]:
    """Every function def traced by jit/shard_map/pallas_call."""
    roots: List[Tuple[_Module, ast.AST]] = []
    for mod in index.by_path.values():
        # decorators: @jax.jit, @partial(jax.jit, ...)
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_jit_expr(dec):
                        roots.append((mod, node))
                    elif isinstance(dec, ast.Call):
                        dd = dotted_name(dec.func)
                        if dd and dd.rsplit(".", 1)[-1] == "partial" \
                                and dec.args and _is_jit_expr(dec.args[0]):
                            roots.append((mod, node))
                        elif _root_kind(dec.func):
                            roots.append((mod, node))
            elif isinstance(node, ast.Call):
                kind = _root_kind(node.func)
                if kind is None or not node.args:
                    continue
                scope = _enclosing_scope(mod, node)
                roots.extend(_resolve_callable(index, mod, scope,
                                               node.args[0]))
                # partial(jax.jit, f) spelled as jax.jit(partial(f, ...))
                first = node.args[0]
                if isinstance(first, ast.Call):
                    fd = dotted_name(first.func)
                    if fd and fd.rsplit(".", 1)[-1] == "partial" \
                            and first.args:
                        roots.extend(_resolve_callable(
                            index, mod, scope, first.args[0]))
    return roots


def _static_params(fn: ast.AST) -> Set[str]:
    """Parameter names considered static (non-traced)."""
    static: Set[str] = set()
    a = fn.args
    params = list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
    defaults = list(a.defaults)
    # align defaults with the tail of positional params
    pos = list(a.posonlyargs) + list(a.args)
    pos_defaults = {p.arg: d for p, d in
                    zip(pos[len(pos) - len(defaults):], defaults)}
    kw_defaults = {p.arg: d for p, d in zip(a.kwonlyargs, a.kw_defaults)
                   if d is not None}
    for p in params:
        if p.arg in STATIC_PARAM_NAMES:
            static.add(p.arg)
            continue
        ann = p.annotation
        if ann is not None:
            try:
                s = ast.unparse(ann)
            except Exception:
                s = ""
            base = s.strip("'\"")
            if base in _STATIC_ANNOTATIONS or "Config" in base:
                static.add(p.arg)
                continue
        d = pos_defaults.get(p.arg, kw_defaults.get(p.arg))
        if isinstance(d, ast.Constant) and isinstance(d.value, (bool, str)):
            static.add(p.arg)
    return static


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = {p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    return names


def _exempt_names_in_test(test: ast.AST) -> Set[str]:
    """Names whose appearance in a branch test is static: `x is None`,
    `"key" in x` (pytree structure), `x.shape/...`, `len(x)`,
    `isinstance(x, T)`."""
    exempt: Set[str] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            for operand in [node.left] + list(node.comparators):
                if isinstance(operand, ast.Name):
                    exempt.add(operand.id)
        elif isinstance(node, ast.Compare) and any(
                isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
            # `"k_pages_q" in pages`: dict membership on a pytree is a
            # structure check, resolved at trace time
            for operand in node.comparators:
                if isinstance(operand, ast.Name):
                    exempt.add(operand.id)
        elif isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d in ("len", "isinstance", "getattr", "hasattr",
                     "callable"):
                for arg in ast.walk(node):
                    if isinstance(arg, ast.Name):
                        exempt.add(arg.id)
        elif isinstance(node, ast.Attribute) and \
                node.attr in _STATIC_TEST_ATTRS:
            if isinstance(node.value, ast.Name):
                exempt.add(node.value.id)
    return exempt


def _fn_label(fn: ast.AST) -> str:
    return getattr(fn, "name", "<lambda>")


_ARRAY_CALL_ROOTS = frozenset(("jnp", "jax", "lax"))


def _array_evidence(fn: ast.AST) -> Set[str]:
    """Names used as arrays somewhere in the function body: subscripted
    (``x[i]``), or passed bare to a jnp/jax/lax call.  Static python
    scalars and config flags never show this usage, so RC004 only fires
    on names that demonstrably hold traced data — the alternative (flag
    every branch on a parameter) drowns real hazards in static-config
    branches, which are the dominant idiom in this codebase."""
    names: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Subscript) \
                and isinstance(node.value, ast.Name):
            names.add(node.value.id)
        elif isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d and d.split(".")[0] in _ARRAY_CALL_ROOTS:
                for a in list(node.args) + [k.value for k in
                                            node.keywords]:
                    if isinstance(a, ast.Name):
                        names.add(a.id)
    return names


def _check_function(mod: _Module, fn: ast.AST,
                    out: List[Violation]) -> None:
    traced = _param_names(fn) - _static_params(fn)
    arrayish = traced & _array_evidence(fn)
    label = _fn_label(fn)
    body = fn.body if isinstance(fn.body, list) else [fn.body]

    # skip nested defs: they are visited when (and only when) reachable
    nested = {id(n) for top in body for n in ast.walk(top)
              if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)) and n is not fn}

    def in_nested(node) -> bool:
        return id(node) in nested_members

    nested_members: Set[int] = set()
    for top in body:
        for n in ast.walk(top):
            if id(n) in nested:
                for sub in ast.walk(n):
                    if sub is not n:
                        nested_members.add(id(sub))

    for top in body:
        for node in ast.walk(top):
            if in_nested(node):
                continue
            if isinstance(node, ast.Call):
                d = dotted_name(node.func)
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item" and not node.args:
                    out.append(Violation(
                        CHECKER, "RC001", mod.path, node.lineno,
                        f"{label}/.item",
                        f".item() in jit-reachable '{label}': device "
                        f"sync stalls the dispatch pipeline and breaks "
                        f"async execution"))
                elif d in ("float", "int", "bool") and len(node.args) == 1 \
                        and isinstance(node.args[0], ast.Name) \
                        and node.args[0].id in traced:
                    out.append(Violation(
                        CHECKER, "RC002", mod.path, node.lineno,
                        f"{label}/{d}({node.args[0].id})",
                        f"{d}() on traced '{node.args[0].id}' in "
                        f"jit-reachable '{label}': host sync / "
                        f"ConcretizationTypeError"))
                elif d and "." in d and d.split(".")[0] in _NP_ROOTS \
                        and d.rsplit(".", 1)[-1] in _NP_HOST_CALLS:
                    names = {n.id for a in node.args
                             for n in ast.walk(a)
                             if isinstance(n, ast.Name)}
                    hit = sorted(names & traced)
                    if hit:
                        out.append(Violation(
                            CHECKER, "RC003", mod.path, node.lineno,
                            f"{label}/{d}({hit[0]})",
                            f"{d}() on traced '{hit[0]}' in "
                            f"jit-reachable '{label}': device→host "
                            f"transfer at trace time"))
            elif isinstance(node, (ast.If, ast.While)):
                exempt = _exempt_names_in_test(node.test)
                hits = sorted({n.id for n in ast.walk(node.test)
                               if isinstance(n, ast.Name)
                               and isinstance(n.ctx, ast.Load)
                               and n.id in arrayish} - exempt)
                if hits:
                    kw = "while" if isinstance(node, ast.While) else "if"
                    out.append(Violation(
                        CHECKER, "RC004", mod.path, node.lineno,
                        f"{label}/{kw}({hits[0]})",
                        f"python {kw} on traced '{hits[0]}' in "
                        f"jit-reachable '{label}': retrace per value "
                        f"(use lax.cond/jnp.where)"))
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                inner = node.value
                if isinstance(inner, ast.Attribute) \
                        and isinstance(inner.value, ast.Name) \
                        and inner.value.id == "self" \
                        and inner.attr in ("config", "cfg", "args"):
                    out.append(Violation(
                        CHECKER, "RC005", mod.path, node.lineno,
                        f"{label}/self.{inner.attr}.{node.attr}",
                        f"'self.{inner.attr}.{node.attr}' read inside "
                        f"jit-reachable '{label}': mutable config must "
                        f"be resolved once at __init__ (the _decode_cfg "
                        f"pattern), not at trace time"))


def check(repo: Repo, baseline=None) -> List[Violation]:
    index = _Index(repo, "megatron_llm_tpu")
    roots = _find_roots(index)
    out: List[Violation] = []
    seen: Set[int] = set()
    queue: List[Tuple[_Module, ast.AST]] = list(roots)
    while queue:
        mod, fn = queue.pop()
        if fn is None or id(fn) in seen:
            continue
        seen.add(id(fn))
        _check_function(mod, fn, out)
        # follow calls (incl. callables handed to lax combinators)
        scope_base = mod.scopes.get(id(fn), _Scope(None, ()))
        scope = _Scope(scope_base.cls, scope_base.chain + (fn,))
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for top in body:
            for node in ast.walk(top):
                if not isinstance(node, ast.Call):
                    continue
                queue.extend(_resolve_callable(index, mod, scope,
                                               node.func))
                d = dotted_name(node.func)
                if d and d.rsplit(".", 1)[-1] in _TRACING_COMBINATORS:
                    for arg in list(node.args) + [
                            kw.value for kw in node.keywords]:
                        if isinstance(arg, (ast.Name, ast.Attribute,
                                            ast.Lambda)):
                            queue.extend(_resolve_callable(
                                index, mod, scope, arg))
    return out
