"""Checker ``flags``: CLI flag wiring and config-field liveness.

~40 flags are threaded by hand from ``arguments.py`` through
``validate_args`` into the config dataclasses and the serving engine.
Two drift modes, both silent today:

* a flag is added (or its consumer deleted) and nothing reads
  ``args.x`` any more — dead configuration that users still set and
  reasonably expect to work;
* code reads ``args.y`` for a ``y`` no parser defines — a typo that
  only explodes as ``AttributeError`` on the one code path that
  reaches it.

Codes:

* ``FW001`` — flag defined in ``arguments.py`` with no ``args.<dest>``
  read anywhere in non-test code.  Flags in the documented noop groups
  (``_add_compat_noop_args`` — accepted-and-ignored CUDA-compat
  surface; ``_add_unimplemented_compat_args`` — unimplemented
  reference features that warn when set) are exempt by design.
* ``FW002`` — ``args.<x>`` read (or 2-arg ``getattr(args, "x")``) for
  an ``x`` no parser defines and no code derives (``args.x = ...``).
  3-arg ``getattr`` carries its own default and is never an error.
* ``FW003`` — ``EngineConfig`` / ``TransformerConfig`` field never
  read anywhere in the repo (dead knob).

Namespace attribution: any file may build its own local
``ArgumentParser`` (tools, entry scripts, extra-args providers), so
the "known attrs" universe is the union of every ``add_argument``
dest, every ``set_defaults`` key, and every ``args.x = ...`` /
``setattr(args, 'x', ...)`` derivation in non-test code.  FW001 only
fires for ``arguments.py`` dests (the shared surface); FW002 fires
when a read matches *no* definition anywhere — true typo detection
with no cross-file namespace guessing.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from megatron_llm_tpu.analysis.core import (
    Repo, Violation, const_str, dotted_name,
)

CHECKER = "flags"

ARGUMENTS = "megatron_llm_tpu/arguments.py"
ENGINE = "megatron_llm_tpu/serving/engine.py"
CONFIG = "megatron_llm_tpu/config.py"

#: flag-group functions whose dests are accepted-and-ignored by
#: documented contract: CUDA-reference compatibility details, and
#: unimplemented reference features that warn loudly in validate_args
NOOP_GROUPS = frozenset(("_add_compat_noop_args",
                         "_add_unimplemented_compat_args"))

#: names treated as argparse-namespace variables when attributing reads
_ARGS_NAMES = frozenset(("args", "margs", "ns", "cli_args"))

#: argparse.Namespace own attributes — never flag reads of these
_NAMESPACE_BUILTINS = frozenset(("__dict__",))


def _dest_of(call: ast.Call) -> Optional[Tuple[str, int]]:
    """(dest, lineno) for an ``add_argument`` call, None for
    positionals/non-flag calls."""
    for kw in call.keywords:
        if kw.arg == "dest":
            s = const_str(kw.value)
            if s:
                return s, call.lineno
    first_long = None
    for a in call.args:
        s = const_str(a)
        if s is None:
            return None
        if not s.startswith("-"):
            # positional: the name itself is the dest
            return s.replace("-", "_"), call.lineno
        if s.startswith("--") and first_long is None:
            first_long = s
    if first_long is None:
        return None
    return first_long.lstrip("-").replace("-", "_"), call.lineno


def _enclosing_function_name(tree: ast.AST, call: ast.Call) -> Optional[str]:
    line = call.lineno
    best = None
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(n, "end_lineno", n.lineno)
            if n.lineno <= line <= end and (
                    best is None or n.lineno >= best.lineno):
                best = n
    return best.name if best else None


def _collect_defined(repo: Repo, rel: str, tree: ast.AST,
                     global_dests: Dict[str, Tuple[int, str]],
                     any_defined: Set[str]) -> None:
    """Harvest add_argument dests, set_defaults keys, and derived
    ``args.x = ...`` / ``setattr(args, ...)`` assignments."""
    is_arguments = rel == ARGUMENTS
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "add_argument":
                hit = _dest_of(node)
                if hit:
                    dest, line = hit
                    any_defined.add(dest)
                    if is_arguments:
                        group = _enclosing_function_name(tree, node) or ""
                        global_dests.setdefault(dest, (line, group))
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "set_defaults":
                for kw in node.keywords:
                    if kw.arg:
                        any_defined.add(kw.arg)
            elif d == "setattr" and len(node.args) >= 2 \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in _ARGS_NAMES:
                s = const_str(node.args[1])
                if s:
                    any_defined.add(s)
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in _ARGS_NAMES:
                    any_defined.add(t.attr)


def _collect_reads(rel: str, tree: ast.AST,
                   reads: Dict[str, List[Tuple[str, int]]],
                   guarded: Set[str]) -> None:
    """``args.x`` loads and ``getattr(args, 'x'[, default])`` calls.
    3-arg getattr / hasattr are recorded as guarded (consume the flag
    but can never be a typo error)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in _ARGS_NAMES \
                and node.attr not in _NAMESPACE_BUILTINS:
            reads.setdefault(node.attr, []).append((rel, node.lineno))
        elif isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d in ("getattr", "hasattr") and len(node.args) >= 2 \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in _ARGS_NAMES:
                s = const_str(node.args[1])
                if s:
                    if d == "hasattr" or len(node.args) >= 3:
                        guarded.add(s)
                    else:
                        reads.setdefault(s, []).append((rel, node.lineno))


def _dataclass_fields(tree: ast.AST, cls_name: str) -> Dict[str, int]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            out = {}
            for sub in node.body:
                if isinstance(sub, ast.AnnAssign) \
                        and isinstance(sub.target, ast.Name):
                    out[sub.target.id] = sub.lineno
            return out
    return {}


def _non_test_files(repo: Repo) -> List[str]:
    return [p for p in repo.py_files()
            if not p.startswith("tests/") and "/tests/" not in p]


def check(repo: Repo, baseline=None) -> List[Violation]:
    out: List[Violation] = []
    files = _non_test_files(repo)
    trees = [(rel, repo.tree(rel)) for rel in files]
    trees = [(rel, t) for rel, t in trees if t is not None]

    global_dests: Dict[str, Tuple[int, str]] = {}
    any_defined: Set[str] = set()
    reads: Dict[str, List[Tuple[str, int]]] = {}
    guarded: Set[str] = set()
    for rel, tree in trees:
        _collect_defined(repo, rel, tree, global_dests, any_defined)
        _collect_reads(rel, tree, reads, guarded)

    # FW001: dead global flags (no read anywhere in non-test code)
    consumed = set(reads) | guarded
    for dest, (line, group) in sorted(global_dests.items()):
        if group in NOOP_GROUPS:
            continue
        if dest not in consumed:
            out.append(Violation(
                CHECKER, "FW001", ARGUMENTS, line, dest,
                f"flag dest '{dest}' (group {group or '<module>'}) has "
                f"no args.{dest} consumer in non-test code — dead flag; "
                f"wire it or delete it"))

    # FW002: reads of attrs nothing defines (typo'd args.y)
    for attr, sites in sorted(reads.items()):
        if attr in any_defined:
            continue
        rel, line = sites[0]
        # reads inside arguments.py of a dest being built in the same
        # pass are already covered by any_defined; anything left is a
        # genuine phantom
        out.append(Violation(
            CHECKER, "FW002", rel, line, attr,
            f"args.{attr} read but no parser defines dest '{attr}' and "
            f"no code derives it — runtime AttributeError waiting "
            f"({len(sites)} read site(s))"))

    # FW003: dead config-dataclass fields
    attr_reads: Set[str] = set()
    kw_uses: Set[str] = set()
    for rel, tree in trees + [(p, repo.tree(p)) for p in repo.py_files()
                              if p.startswith("tests/")
                              and repo.tree(p) is not None]:
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                attr_reads.add(node.attr)
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg:
                        kw_uses.add(kw.arg)
    for rel, cls in ((ENGINE, "EngineConfig"), (CONFIG, "TransformerConfig")):
        tree = repo.tree(rel)
        if tree is None:
            continue
        for name, line in sorted(_dataclass_fields(tree, cls).items()):
            if name not in attr_reads:
                out.append(Violation(
                    CHECKER, "FW003", rel, line, f"{cls}.{name}",
                    f"{cls}.{name} is never read anywhere in the repo "
                    f"(constructed-but-dead knob)"))
    return out
