"""Checker ``stdlib``: the stdlib-only contract for report/bench tools.

``serve_bench.py``, ``serve_report.py``, ``trace_report.py``,
``telemetry_report.py``, ``health_report.py``, ``tpu_sweep.py`` and
``serve_router.py`` are documented to run anywhere — a laptop reading
a JSONL dump, a CI box without jax — so a ``jax`` (or ``numpy``, or
``requests``) import sneaking into one of them breaks the contract
silently for everyone who relied on it.  Gate:

* a ``tools/*.py`` file is gated when its module docstring claims
  ``stdlib-only`` or it is in :data:`GATED_TOOLS`;
* every module-scope import in a gated file must be stdlib
  (``sys.stdlib_module_names``), or an explicitly allowed first-party
  module (:data:`ALLOWED_FIRST_PARTY`), or inside a
  ``try/except ImportError`` guard (documented graceful degradation);
* ``SG002``: each allowed first-party module is itself re-checked one
  level deep — its own unguarded module-scope imports must be stdlib,
  so the allowance can't smuggle jax in transitively (the
  "keep this module jax-free" contract in ``serving/router.py``).
"""

from __future__ import annotations

import ast
import sys
from typing import Dict, List, Set

from megatron_llm_tpu.analysis.core import Repo, Violation

CHECKER = "stdlib"

#: gated regardless of docstring (the documented stdlib-only surface)
GATED_TOOLS = frozenset((
    "tools/serve_bench.py",
    "tools/serve_report.py",
    "tools/serve_router.py",
    "tools/telemetry_report.py",
    "tools/trace_report.py",
    "tools/health_report.py",
    "tools/tpu_sweep.py",
    "tools/graft_lint.py",
))

#: gated file -> first-party modules it may import.  Each allowance is
#: itself checked one level deep (SG002): the named module's unguarded
#: module-scope imports must be stdlib or first-party.
ALLOWED_FIRST_PARTY: Dict[str, Set[str]] = {
    "tools/graft_lint.py": {"megatron_llm_tpu.analysis",
                            "megatron_llm_tpu"},
}

_FIRST_PARTY_ROOTS = frozenset(("megatron_llm_tpu", "tools"))

# sys.stdlib_module_names is 3.10+; this linter targets the repo's
# pinned runtime so no fallback table is maintained
_STDLIB = frozenset(getattr(sys, "stdlib_module_names", ()))


def _is_gated(repo: Repo, rel: str) -> bool:
    if rel in GATED_TOOLS:
        return True
    tree = repo.tree(rel)
    if tree is None:
        return False
    doc = ast.get_docstring(tree) or ""
    return "stdlib-only" in doc or "stdlib only" in doc


def _guarded_import_lines(tree: ast.AST) -> Set[int]:
    """Lines of imports inside try/except ImportError (or TYPE_CHECKING
    blocks) — allowed as documented graceful degradation."""
    guarded: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Try):
            catches_import = any(
                h.type is None or any(
                    n in ast.dump(h.type)
                    for n in ("ImportError", "ModuleNotFoundError",
                              "Exception"))
                for h in node.handlers)
            if catches_import:
                for sub in node.body:
                    for n in ast.walk(sub):
                        if isinstance(n, (ast.Import, ast.ImportFrom)):
                            guarded.add(n.lineno)
        elif isinstance(node, ast.If):
            t = ast.dump(node.test)
            if "TYPE_CHECKING" in t:
                for sub in node.body:
                    for n in ast.walk(sub):
                        if isinstance(n, (ast.Import, ast.ImportFrom)):
                            guarded.add(n.lineno)
    return guarded


def _module_scope_imports(tree: ast.AST):
    """(modname, lineno) for every import statement NOT inside a
    function/class body (module scope, including inside module-level
    try/if — those are filtered separately by _guarded_import_lines)."""
    out = []

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(child, ast.Import):
                for a in child.names:
                    out.append((a.name, child.lineno))
            elif isinstance(child, ast.ImportFrom):
                if child.level == 0 and child.module:
                    out.append((child.module, child.lineno))
            else:
                visit(child)

    visit(tree)
    return out


def _violations_for(repo: Repo, rel: str, code: str,
                    allowed_first_party: Set[str]) -> List[Violation]:
    tree = repo.tree(rel)
    if tree is None:
        return []
    guarded = _guarded_import_lines(tree)
    out: List[Violation] = []
    for modname, line in _module_scope_imports(tree):
        if line in guarded:
            continue
        root = modname.split(".")[0]
        if root in _STDLIB or root == "__future__":
            continue
        if any(modname == a or modname.startswith(a + ".")
               for a in allowed_first_party):
            continue
        if root in _FIRST_PARTY_ROOTS:
            out.append(Violation(
                CHECKER, code, rel, line, modname,
                f"unguarded first-party import '{modname}' in "
                f"stdlib-only file — add to ALLOWED_FIRST_PARTY (with "
                f"its own SG002 transitive check) or guard with "
                f"try/ImportError"))
        else:
            out.append(Violation(
                CHECKER, code, rel, line, modname,
                f"non-stdlib import '{modname}' in stdlib-only tool — "
                f"this file is documented to run without {root} "
                f"installed"))
    return out


def check(repo: Repo, baseline=None) -> List[Violation]:
    out: List[Violation] = []
    checked_first_party: Set[str] = set()
    for rel in repo.py_files("tools"):
        if not _is_gated(repo, rel):
            continue
        allowed = ALLOWED_FIRST_PARTY.get(rel, set())
        out.extend(_violations_for(repo, rel, "SG001", allowed))
        checked_first_party |= allowed
    # SG002: one-level transitive check of every allowance — an allowed
    # first-party module may import package siblings (SG002 cares about
    # third-party leaks, not package structure), but not e.g. jax
    for modname in sorted(checked_first_party):
        rel = modname.replace(".", "/") + ".py"
        if not repo.exists(rel):
            rel = modname.replace(".", "/") + "/__init__.py"
        if repo.exists(rel):
            siblings = {m for m, _l in _module_scope_imports(
                repo.tree(rel) or ast.parse(""))
                if m.split(".")[0] in _FIRST_PARTY_ROOTS}
            out.extend(_violations_for(repo, rel, "SG002",
                                       checked_first_party | siblings))
    return out
