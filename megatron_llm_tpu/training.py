"""Training runtime: train_step + pretrain driver.

Reference: ``megatron/training.py`` — ``pretrain`` (:55-169), ``train_step``
(:393-459), ``_train`` loop (:654-770), ``training_log`` (:462-641).

TPU re-design: the reference's train_step is imperative — a Python
microbatch loop (schedules.py) each issuing fwd/bwd, then three grad-sync
phases, then the optimizer.  Here the *entire* step — microbatch
accumulation loop, loss scaling, grad clip, inf check, Adam, master->param
cast — is one jitted function: ``lax.scan`` over the microbatch axis, then
the functional optimizer.  GSPMD turns the dp-sharded batch into data
parallelism (grad psum over dp is inserted where the loss mean crosses the
batch axis), so ``reduce_model_grads``/``allreduce_gradients``
(optimizer.py:280-302, distributed.py:202) have no hand-written analogue.
"""

from __future__ import annotations

import logging
import sys
import time
from functools import partial
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from megatron_llm_tpu.config import TrainConfig, TransformerConfig, ParallelConfig
from megatron_llm_tpu.optimizer import MegatronOptimizer, OptimizerParamScheduler
from megatron_llm_tpu.optimizer.optimizer import global_grad_norm
from megatron_llm_tpu import health
from megatron_llm_tpu import random as mrandom
from megatron_llm_tpu import tracing
from megatron_llm_tpu.global_vars import get_counters

logger = logging.getLogger("megatron_llm_tpu")

# --log_params_norm without layer stats re-reduces the whole param tree at
# every log boundary; jit once so it compiles a single cached program
# instead of retracing op-by-op eagerly each time
_params_norm_jit = jax.jit(global_grad_norm)


def average_losses_across_data_parallel_group(losses):
    """Reference: megatron/utils.py:100-107 — with a single-controller mesh
    the loss pytree is already global; the mean is the DP-averaged value."""
    return jax.tree_util.tree_map(jnp.mean, losses)


def default_loss_func(loss_tok: jax.Array, loss_mask: jax.Array):
    """Masked token-mean loss (reference: finetune.py:201-218)."""
    loss_mask = loss_mask.astype(jnp.float32)
    return jnp.sum(loss_tok * loss_mask) / jnp.maximum(jnp.sum(loss_mask), 1.0)


def build_train_step(
    model,
    optimizer: MegatronOptimizer,
    parallel_cfg: ParallelConfig,
    num_microbatches: int,
    loss_func: Callable = default_loss_func,
    forward_only: bool = False,
    log_num_zeros_in_grad: bool = False,
    log_layer_stats: bool = False,
):
    """Compile one global training step.

    Batch layout: dict of arrays with leading axes [num_micro, batch, seq]
    where ``batch`` is the *global* batch per microbatch (dp-sharded).
    Expected keys: tokens, labels, loss_mask; optional position_ids,
    attention_mask.
    """
    sp = parallel_cfg.sequence_parallel
    # MoE models return (per-token loss, [lb, z] routing aux) — static on
    # the model config, so BERT/T5's own tuple returns are unaffected
    moe_on = getattr(getattr(model, "cfg", None), "num_experts", 0) > 1
    # multi-slice hierarchical (ICI-then-DCN) gradient staging: run the
    # forward under multislice.sliced_forward's explicit slice-vmap so the
    # dp gradient all-reduce stays in-slice and the cross-slice sum is a
    # separate DCN collective.  Per-slice math is unchanged — loss_func
    # still sees the merged global-microbatch per-token losses.
    num_slices = getattr(parallel_cfg, "num_slices", 1) or 1
    hierarchical = (num_slices > 1
                    and getattr(parallel_cfg, "multislice_hierarchical",
                                False))

    def microbatch_loss(params, micro, rng_key, scale):
        # every batch key beyond the canonical trio is forwarded as a model
        # kwarg (tokentype_ids / sentence_order for BERT, encoder inputs for
        # T5 — mirroring the per-arch get_batch of the reference entry points)
        extra = {
            k: v for k, v in micro.items()
            if k not in ("tokens", "labels", "loss_mask")
        }
        if hierarchical:
            from megatron_llm_tpu import multislice
            loss_tok = multislice.sliced_forward(
                model, params, micro, rng_key, num_slices,
                train=not forward_only, sequence_parallel=sp, extra=extra,
            )
        else:
            loss_tok = model(
                params,
                micro["tokens"],
                labels=micro["labels"],
                rng_key=rng_key,
                train=not forward_only,
                sequence_parallel=sp,
                **extra,
            )
        moe_aux = None
        if moe_on:
            loss_tok, moe_aux = loss_tok
        out = loss_func(loss_tok, micro["loss_mask"])
        # loss_func may return (total, {metric: scalar}) to log components
        # separately (reference logs a loss dict per arch, e.g. BERT's
        # {'lm loss', 'sop loss'} — pretrain_bert.py loss_func)
        loss, aux = out if isinstance(out, tuple) else (out, {})
        total = loss
        if moe_aux is not None:
            # the routing losses enter the optimized objective; the logged
            # 'lm loss' stays the pure LM component, with the balance loss
            # (and the z-loss, when enabled) reported under their own names
            # (reference's per-key loss dict)
            cfg = model.cfg
            aux = {**aux, "moe aux loss": moe_aux[0]}
            if cfg.moe_z_loss_coeff > 0.0:
                aux["moe z loss"] = moe_aux[1]
            total = (loss + cfg.moe_aux_loss_coeff * moe_aux[0]
                     + cfg.moe_z_loss_coeff * moe_aux[1])
        # scaled loss for fp16 (reference: optimizer.scale_loss,
        # schedules.py:142-202); scale==1 for bf16/fp32
        return total * scale / num_microbatches, (loss, aux)

    if forward_only:

        def eval_step(params, batch, rng_key):
            def body(carry, micro):
                _, (loss, _aux) = microbatch_loss(params, micro, None, 1.0)
                return carry, loss

            _, losses = jax.lax.scan(body, 0, batch)
            return jnp.mean(losses)

        return jax.jit(eval_step)

    def train_step(params, opt_state, batch, rng_key, lr, wd):
        scale = opt_state.grad_scaler.scale
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )

        def body(carry, scanned):
            grads_acc = carry
            micro, idx = scanned
            mkey = jax.random.fold_in(rng_key, idx)
            grad_fn = jax.value_and_grad(microbatch_loss, has_aux=True)
            (_, (loss, aux)), g = grad_fn(params, micro, mkey, scale)
            grads_acc = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(jnp.float32), grads_acc, g
            )
            return grads_acc, (loss, aux)

        grads, (losses, auxes) = jax.lax.scan(
            body, zeros, (batch, jnp.arange(num_microbatches))
        )
        new_params, new_opt_state, stats = optimizer.step(
            params, grads, opt_state, lr, wd, layer_stats=log_layer_stats
        )
        metrics = {
            "lm loss": jnp.mean(losses),
            "grad_norm": stats["grad_norm"],
            "loss_scale": stats["loss_scale"],
            "skipped_iter": stats["found_inf"].astype(jnp.int32),
        }
        if log_layer_stats:
            # fixed-shape [G] arrays — one extra fused output, no shape
            # dependence on anything but the param tree, so steady state
            # stays zero-recompile
            metrics["layer_stats"] = stats["layer_stats"]
        if log_num_zeros_in_grad:   # reference --log_num_zeros_in_grad
            metrics["num zeros"] = sum(
                jnp.sum(g == 0.0)
                for g in jax.tree_util.tree_leaves(grads)
            ).astype(jnp.int32)
        # component losses reported by the loss_func override the total
        # under their own names ("lm loss" stays the true MLM loss for BERT)
        metrics.update({k: jnp.mean(v) for k, v in auxes.items()})
        return new_params, new_opt_state, metrics

    return jax.jit(train_step, donate_argnums=(0, 1))


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

def training_log(
    iteration: int,
    train_iters: int,
    metrics: Dict[str, float],
    elapsed_per_iter: float,
    tokens_per_iter: float,
    lr: float,
    writer=None,
    printer=print,
    throughput: Optional[Dict] = None,
    interval_time: Optional[float] = None,
):
    """One console/TB log line (reference: training.py:462-641,
    tokens/sec at :591-609).

    ``throughput`` is a ``telemetry.ThroughputCalculator.compute()``
    record; when present the line carries tokens/sec/device, achieved
    TFLOPs/device and MFU (null MFU fields — unknown peak, or the
    fabrication guard — are simply omitted, never printed as numbers).

    ``elapsed_per_iter`` is *train-only* step time (eval and
    checkpoint-save wall-clock excluded, so throughput/MFU reflect the
    step the hardware actually ran); ``interval_time`` is the raw
    log-interval wall per iteration including those sections — both are
    reported so a gap between them is visible instead of silently
    deflating MFU."""
    tps = tokens_per_iter / max(elapsed_per_iter, 1e-9)
    line = (
        f" iteration {iteration:8d}/{train_iters:8d} |"
        f" elapsed time per iteration (ms): {elapsed_per_iter * 1000.0:.1f} |"
    )
    if interval_time is not None:
        line += (f" interval time per iteration (ms):"
                 f" {interval_time * 1000.0:.1f} |")
    line += f" tokens per second: {tps:.1f} |"
    if throughput is not None:
        line += (f" tokens per second per device:"
                 f" {throughput['tokens_per_sec_per_device']:.1f} |")
        if throughput.get("tflops_per_device") is not None:
            line += (f" TFLOPs per device:"
                     f" {throughput['tflops_per_device']:.1f} |")
        if throughput.get("mfu") is not None:
            line += f" MFU: {throughput['mfu'] * 100.0:.1f}% |"
    line += (
        f" learning rate: {lr:.3E} |"
        f" lm loss: {float(metrics.get('lm loss', 0.0)):.6E} |"
        f" loss scale: {float(metrics.get('loss_scale', 1.0)):.1f} |"
        f" grad norm: {float(metrics.get('grad_norm', 0.0)):.3f} |"
        f" skipped iterations: {int(metrics.get('skipped_iter', 0))} |"
    )
    # extra loss components (e.g. BERT's 'sop loss') appear after the
    # standard fields, like the reference's per-key loss dict logging
    known = {"lm loss", "loss_scale", "grad_norm", "skipped_iter"}
    for k in sorted(set(metrics) - known):
        v = metrics[k]
        # recovery counters and other integral extras read better as ints
        line += (f" {k}: {v} |" if isinstance(v, int)
                 else f" {k}: {float(v):.6E} |")
    printer(line)
    if writer is not None:
        for k, v in metrics.items():
            writer.add_scalar(k, float(v), iteration)
        writer.add_scalar("tokens_per_sec", tps, iteration)
        writer.add_scalar("learning_rate", lr, iteration)
        if interval_time is not None:
            writer.add_scalar("interval-time-per-iteration", interval_time,
                              iteration)
        if throughput is not None:
            writer.add_scalar("tokens_per_sec_per_device",
                              throughput["tokens_per_sec_per_device"],
                              iteration)
            if throughput.get("tflops_per_device") is not None:
                writer.add_scalar("tflops_per_device",
                                  throughput["tflops_per_device"], iteration)
            if throughput.get("mfu") is not None:
                writer.add_scalar("mfu", throughput["mfu"], iteration)
    return tps


def pretrain(
    model,
    params,
    train_cfg: TrainConfig,
    parallel_cfg: ParallelConfig,
    batch_iterator,
    *,
    scheduler: Optional[OptimizerParamScheduler] = None,
    optimizer: Optional[MegatronOptimizer] = None,
    loss_func: Callable = default_loss_func,
    log_interval: int = 10,
    save_interval: Optional[int] = None,
    save_dir: Optional[str] = None,
    eval_iterator=None,
    eval_interval: Optional[int] = None,
    eval_iters: int = 10,
    exit_signal_handler=None,
    start_iteration: int = 0,
    opt_state=None,
    on_metrics=None,
    timers=None,
    skip_iters=(),
    exit_interval: Optional[int] = None,
    exit_duration_in_mins: Optional[float] = None,
    train_step=None,
    save_fn=None,
    log_params_norm: bool = False,
    log_num_zeros_in_grad: bool = False,
    log_layer_stats_interval: int = 0,
    writer=None,
    tensorboard_log_interval: int = 1,
    log_timers: bool = True,
    async_save: bool = False,
    log_memory: bool = False,
    log_batch_size: bool = False,
    log_world_size: bool = False,
    log_validation_ppl: bool = False,
    resilience=None,
    telemetry=None,
    preempt_exit_code: int = 0,
):
    """Minimal-dependency pretrain loop (the full CLI driver lives in
    ``finetune.py`` / ``pretrain_gpt.py`` at the repo root).

    ``batch_iterator`` yields batch dicts shaped
    [num_micro, global_batch, seq] (see build_train_step).

    Behavioral flags (reference ``training.py:397-399,731-767``):
      * ``skip_iters`` — iteration numbers that run forward-only (loss is
        still computed/logged, no parameter update).
      * ``exit_interval`` — save + exit when iteration %% interval == 0.
      * ``exit_duration_in_mins`` — save + exit once the loop has run
        this long.

    Timers (reference ``training.py:500-525``): phases that exist under
    the fused-jit TPU design are timed — ``batch-generator``,
    ``train-step`` (async dispatch), ``train-step-sync`` (device wait at
    the log boundary; dispatch+sync ~ the reference's forward-backward +
    optimizer total), ``save-checkpoint``, ``eval-time``.  Finer split
    timers (forward vs backward vs optimizer) do not exist because one
    XLA program runs all three fused — that is the point of the design.

    ``train_step`` overrides the compiled step (same signature as
    ``build_train_step``'s result) — how ``finetune.py`` drives the
    pipelined engine through this one loop.  With a custom step, skipped
    iterations have no forward-only program, so their loss logs as NaN,
    and ``eval_iterator`` is rejected.  ``save_fn(save_dir, it, params,
    opt_state, scheduler)`` overrides checkpoint writing (e.g. to convert
    a VPP stage-major layout back to natural order first).

    ``resilience`` (a ``resilience.ResilienceManager``) arms the
    fault-tolerance runtime: fault injection before/into each batch,
    rolling host snapshots, NaN/spike detection at check boundaries with
    rewind, and the hang watchdog around dispatch/sync.  All of it is
    host-side — the jitted step is untouched.

    ``log_layer_stats_interval`` (reference-free; see ``health.py``) arms
    the model-health observatory: the train step emits per-group
    grad/param/update norms + non-finite grad counts on-device, the host
    fetches them at log boundaries (feeding --log_params_norm and the
    resilience NaN localizer) and emits the full record into JSONL /
    TensorBoard every ``interval`` iterations.

    ``telemetry`` (a ``telemetry.Telemetry``) carries the observability
    runtime: throughput/MFU accounting at log boundaries, the structured
    JSONL stream + flight recorder, and in-loop profiler capture.  When
    None, a default throughput-only bundle is built from the model so
    tokens/sec/device + MFU appear in every run's log lines for free.
    Like resilience, everything is host-side and (for the stream/flight
    recorder) off the device-sync path except at log boundaries.
    """
    from megatron_llm_tpu import checkpointing
    from megatron_llm_tpu.telemetry import Telemetry
    from megatron_llm_tpu.timers import Timers

    if timers is None:
        timers = Timers(log_level=2)
    if telemetry is None:
        telemetry = Telemetry.default(model)
    stream = telemetry.stream
    profiler = telemetry.profiler
    trace = getattr(telemetry, "tracing", None)
    if trace is not None:
        tracing.install_tracing(trace)
    recompile = trace.recompile if trace is not None else None
    straggler = trace.straggler if trace is not None else None
    skip_iters = frozenset(skip_iters or ())

    num_slices = getattr(parallel_cfg, "num_slices", 1) or 1
    num_micro = max(
        train_cfg.global_batch_size
        // (train_cfg.micro_batch_size * parallel_cfg.data_parallel_size
            * num_slices),
        1,
    )
    # per-slice attribution: map the gathered per-host timer snapshots
    # onto slices so the JSONL stream and straggler events name the slice
    # the fleet is waiting on (multi-slice runs only)
    slice_map = None
    if num_slices > 1:
        from megatron_llm_tpu import multislice
        slice_map = multislice.host_slice_map(num_slices=num_slices)
        if straggler is not None:
            straggler.host_slice_map = slice_map
    if optimizer is None:
        optimizer = MegatronOptimizer(
            train_cfg, params_dtype=jax.tree_util.tree_leaves(params)[0].dtype
        )
    if opt_state is None:
        opt_state = optimizer.init(params)
    if scheduler is None:
        # NB: `x if x is not None else y`, not `or` — an explicit 0.0
        # start/end weight decay is a legitimate ramp-from-zero config
        swd = train_cfg.start_weight_decay
        ewd = train_cfg.end_weight_decay
        scheduler = OptimizerParamScheduler(
            max_lr=train_cfg.lr,
            min_lr=train_cfg.min_lr,
            lr_warmup_steps=train_cfg.lr_warmup_iters,
            lr_decay_steps=train_cfg.lr_decay_iters or max(train_cfg.train_iters, 1),
            lr_decay_style=train_cfg.lr_decay_style,
            start_wd=swd if swd is not None else train_cfg.weight_decay,
            end_wd=ewd if ewd is not None else train_cfg.weight_decay,
            wd_incr_steps=max(train_cfg.train_iters, 1),
            wd_incr_style=train_cfg.weight_decay_incr_style,
        )
        scheduler.num_steps = start_iteration

    custom_step = train_step is not None
    if custom_step and eval_iterator is not None:
        raise ValueError(
            "eval_iterator is not supported with a custom train_step "
            "(no forward-only program exists for it)")
    if not custom_step:
        train_step = build_train_step(
            model, optimizer, parallel_cfg, num_micro, loss_func,
            log_num_zeros_in_grad=log_num_zeros_in_grad,
            log_layer_stats=log_layer_stats_interval > 0,
        )
    eval_step = (
        build_train_step(model, optimizer, parallel_cfg, num_micro, loss_func,
                         forward_only=True)
        if eval_iterator is not None
        else None
    )

    base_key = mrandom.base_key(train_cfg.seed)
    counters = get_counters()
    iteration = start_iteration
    last_time = time.perf_counter()
    train_start = time.perf_counter()
    # eval + checkpoint-save wall-clock inside the current log interval:
    # subtracted from the interval so elapsed-per-iteration (and thus
    # tokens/sec + MFU) measures the training step, not the pauses
    # (mutable cell because _save below also accumulates into it)
    non_train = [0.0]
    skip_step = None  # forward-only step, compiled lazily on first skip
    ls_names = None   # health group names, resolved on first stats fetch

    def _layer_stats_record(ls_dev):
        """device stats dict -> host JSONL record ({groups, grad_norm,
        param_norm, update_norm, update_ratio, nonfinite_grads})."""
        nonlocal ls_names
        if ls_names is None:
            ls_names = health.layer_group_names(params)
        return health.to_record(ls_names, jax.device_get(ls_dev))

    injector = resilience.injector if resilience is not None else None
    watchdog = resilience.watchdog if resilience is not None else None
    if resilience is not None:
        resilience.bind_rescue(
            save_dir,
            checkpointing.config_to_args(getattr(model, "cfg", None)))
    if watchdog is not None:
        # armed only after the first step completes: iteration 1 includes
        # XLA compilation, which can dwarf any sane hang timeout
        watchdog.start()
        watchdog.pause()

    def _signals(consensus: bool) -> bool:
        # older handlers (tests, user code) may lack the consensus kwarg
        try:
            return exit_signal_handler.signals_received(consensus=consensus)
        except TypeError:
            return exit_signal_handler.signals_received()

    def _save(it):
        if watchdog is not None:
            watchdog.pause()        # storage latency is not a hang
        t0 = time.perf_counter()
        with tracing.span("checkpoint_save", "checkpoint", iteration=it):
            timers("save-checkpoint", log_level=0).start()
            if save_fn is not None:
                save_fn(save_dir, it, params, opt_state, scheduler)
            else:
                checkpointing.save_checkpoint(
                    save_dir, it, params, opt_state, scheduler,
                    consumed_samples=counters.get("samples", 0),
                    args=checkpointing.config_to_args(
                        getattr(model, "cfg", None)),
                    async_save=async_save,
                )
            timers("save-checkpoint").stop()
        non_train[0] += time.perf_counter() - t0
        if watchdog is not None:
            watchdog.resume()

    # one root span spans the whole loop (category "run" is trace-only,
    # so goodput never counts it) — every second of the run nests under
    # it, which is what makes the exported trace's coverage ~100%.
    # Entered by hand so the loop body keeps its indentation; the
    # finally below closes it on every exit path (SystemExit included).
    root_span = tracing.span("train", "run", start_iteration=start_iteration)
    root_span.__enter__()
    try:
        while iteration < train_cfg.train_iters:
            if resilience is not None and resilience.snapshot_due(iteration):
                # host-copy the last known-good state BEFORE this step runs
                # (donation invalidates the old buffers once dispatched)
                resilience.take_snapshot(iteration, params, opt_state,
                                         scheduler)
            if injector is not None:
                injector.before_iteration(iteration + 1)
            if profiler is not None:
                profiler.maybe_start(iteration + 1)
            timers("batch-generator", log_level=1).start()
            with tracing.span("data_next", "data"):
                batch = next(batch_iterator)
            timers("batch-generator").stop()
            if injector is not None:
                batch = injector.poison_batch(iteration + 1, batch)
            lr, wd = scheduler.step(1)
            if resilience is not None:
                lr = lr * resilience.lr_scale
            step_key = jax.random.fold_in(base_key, iteration)
            if (iteration + 1) in skip_iters:
                # reference training.py:397-399: forward-only, no update
                print(" IMPORTANT! skipping backprop for this iteration!",
                      flush=True)
                if custom_step:
                    # a custom (e.g. pipelined) step has no forward-only
                    # program; skip means "consume data, update nothing"
                    metrics = {"lm loss": jnp.float32(float("nan")),
                               "skipped_iter": 1}
                else:
                    if recompile is not None:
                        # the forward-only program's first compile is
                        # expected — it must not count as a recompile
                        recompile.pause()
                    if skip_step is None:
                        # eval_step is the same forward-only program; reuse
                        # its compilation when available
                        skip_step = eval_step or build_train_step(
                            model, optimizer, parallel_cfg, num_micro,
                            loss_func, forward_only=True)
                    # fresh metrics: grad_norm/loss_scale/aux losses from the
                    # previous step must not masquerade as this iteration's
                    metrics = {"lm loss": skip_step(params, batch, step_key),
                               "skipped_iter": 1}
                    if recompile is not None:
                        recompile.resume()
            else:
                timers("train-step", log_level=1).start()
                t_step0 = time.perf_counter()
                with tracing.span("step", "step", iteration=iteration + 1):
                    params, opt_state, metrics = train_step(
                        params, opt_state, batch, step_key, lr, wd
                    )
                step_secs = time.perf_counter() - t_step0
                timers("train-step").stop()
                if recompile is not None:
                    # a compile that ran inside the dispatch span is not
                    # productive step time — reattribute it to 'compile'
                    _, csecs = recompile.drain()
                    if csecs > 0.0 and trace is not None:
                        trace.tracer.goodput.move("step", "compile", csecs)
                    recompile.observe_step_time(step_secs)
            if watchdog is not None:
                watchdog.resume()   # (re)arms; first arm is post-compile
            iteration += 1
            if recompile is not None and iteration == start_iteration + 1:
                # the train-step program exists now; any later backend
                # compile is a recompile (shape/layout leak in the loop)
                recompile.mark_steady()
            if profiler is not None:
                # sync so the traced window contains the device work of
                # its last step, not just that step's dispatch
                profiler.maybe_stop(
                    iteration,
                    sync=lambda: jax.block_until_ready(metrics["lm loss"]))
            tokens = batch["tokens"].size
            counters["tokens"] += tokens
            # one sample == one sequence: every leading axis but seq
            # (reference tracks consumed_train_samples, training.py:700;
            # this feeds the checkpoint's consumed_samples field)
            counters["samples"] += tokens // batch["tokens"].shape[-1]
            if stream is not None:
                # host-side fields only — the per-iteration flight-recorder
                # entry must never force a device sync
                stream.record_dispatch({
                    "iteration": iteration,
                    "lr": float(lr),
                    "tokens": int(tokens),
                })

            at_log_boundary = bool(log_interval
                                   and iteration % log_interval == 0)
            if (resilience is not None
                    and resilience.check_due(iteration, at_log_boundary)):
                loss_val = float(metrics["lm loss"])    # device sync
                if watchdog is not None:
                    watchdog.progress()
                gn = metrics.get("grad_norm")
                bad = resilience.record_metrics(
                    iteration, loss_val,
                    None if gn is None else float(gn))
                if bad and "layer_stats" in metrics:
                    # NaN localization: hand the sentinel this step's
                    # per-group stats so the rewind names the offenders
                    resilience.observe_layer_stats(
                        iteration,
                        _layer_stats_record(metrics["layer_stats"]),
                        announce=True)
                if bad and resilience.should_rewind():
                    if watchdog is not None:
                        watchdog.pause()
                    params, opt_state, iteration = resilience.rewind(
                        params, opt_state, scheduler, batch_iterator)
                    if watchdog is not None:
                        watchdog.resume()
                    last_time = time.perf_counter()
                    non_train[0] = 0.0
                    continue

            if at_log_boundary:
                ls_host = None
                if "layer_stats" in metrics:
                    # pop before the float() conversion below — the [G]
                    # arrays are fetched once here (a few KB, no extra
                    # device work) and fan out to params norm, resilience,
                    # TensorBoard and the JSONL record
                    metrics = dict(metrics)
                    ls_host = _layer_stats_record(metrics.pop("layer_stats"))
                    if resilience is not None:
                        resilience.observe_layer_stats(iteration, ls_host)
                at_stats_boundary = bool(
                    ls_host is not None and log_layer_stats_interval
                    and iteration % log_layer_stats_interval == 0)
                if log_params_norm:     # reference --log_params_norm
                    metrics = dict(metrics)
                    if ls_host is not None:
                        # the per-group norms partition the sum of squares
                        # — derive the global norm on host instead of
                        # re-reducing the whole tree on device
                        metrics["params norm"] = health.derived_params_norm(
                            ls_host)
                    else:
                        if recompile is not None:
                            # first use compiles the cached standalone
                            # reduction — expected, not a recompile
                            recompile.pause()
                        metrics["params norm"] = _params_norm_jit(params)
                        if recompile is not None:
                            recompile.resume()
                timers("train-step-sync", log_level=1).start()
                with tracing.span("step_sync", "step", iteration=iteration):
                    jax.block_until_ready(metrics["lm loss"])
                timers("train-step-sync").stop()
                now = time.perf_counter()
                # elapsed (-> tokens/sec, MFU) is train-only: eval and
                # checkpoint-save wall inside the interval is subtracted,
                # so a save-heavy interval no longer deflates MFU;
                # interval_time keeps the raw wall for goodput honesty
                interval_time = (now - last_time) / log_interval
                elapsed = max(now - last_time - non_train[0], 1e-9) \
                    / log_interval
                non_train[0] = 0.0
                last_time = now
                # --tensorboard_log_interval is an absolute iteration
                # interval (reference semantics); metrics only exist at log
                # boundaries, so the effective cadence is their intersection
                use_writer = (writer if writer is not None
                              and iteration % max(tensorboard_log_interval, 1)
                              == 0 else None)
                if use_writer is not None:
                    # reference --log_*_to_tensorboard extras
                    # (training.py:509-589)
                    if log_batch_size:
                        use_writer.add_scalar("batch-size",
                                              train_cfg.global_batch_size,
                                              iteration)
                    if log_world_size:
                        use_writer.add_scalar("world-size",
                                              jax.device_count(), iteration)
                    if log_memory:
                        stats = jax.local_devices()[0].memory_stats() or {}
                        use_writer.add_scalar(
                            "mem-bytes-in-use",
                            stats.get("bytes_in_use", 0), iteration)
                        # reference training.py:580-589 also reports the
                        # high-water mark and allocation count (backends
                        # that don't track them just omit the scalars)
                        if "peak_bytes_in_use" in stats:
                            use_writer.add_scalar(
                                "mem-peak-bytes-in-use",
                                stats["peak_bytes_in_use"], iteration)
                        if "num_allocs" in stats:
                            use_writer.add_scalar(
                                "mem-num-allocs",
                                stats["num_allocs"], iteration)
                    if at_stats_boundary:
                        # grouped scalars: layer_stats/<stat>/<group>
                        ur = ls_host.get("update_ratio")
                        for i, g in enumerate(ls_host["groups"]):
                            for key in ("grad_norm", "param_norm",
                                        "update_norm"):
                                if key in ls_host:
                                    use_writer.add_scalar(
                                        f"layer_stats/{key}/{g}",
                                        health.record_value(ls_host[key][i]),
                                        iteration)
                            if ur is not None and ur[i] is not None:
                                use_writer.add_scalar(
                                    f"layer_stats/update_ratio/{g}",
                                    ur[i], iteration)
                log_metrics = {k: float(v) for k, v in metrics.items()}
                if resilience is not None:
                    from megatron_llm_tpu.resilience import recovery_counters
                    log_metrics.update(recovery_counters())
                throughput = (telemetry.throughput.compute(tokens, elapsed)
                              if telemetry.throughput is not None else None)
                training_log(
                    iteration, train_cfg.train_iters,
                    log_metrics,
                    elapsed, tokens, lr,
                    writer=use_writer,
                    throughput=throughput,
                    interval_time=interval_time,
                )
                # one snapshot feeds writer + console; the old
                # write()-then-log() pair double-read (and could
                # double-reset) every timer.  The gathered per-host
                # snapshot doubles as the straggler detector's input and
                # the per-slice attribution source — the allgather
                # already happened at this boundary.
                # --log_timers_to_tensorboard gates the writer sink only;
                # the console line and the straggler-detector snapshot
                # are always produced
                gathered = timers.report(
                    use_writer if log_timers else None, iteration,
                    normalizer=log_interval)
                if straggler is not None and gathered:
                    straggler.check(gathered, iteration)
                if stream is not None:
                    from megatron_llm_tpu.resilience import recovery_counters
                    from megatron_llm_tpu.telemetry import device_memory_stats
                    rec = {
                        "iteration": iteration,
                        "train_iters": train_cfg.train_iters,
                        "lm_loss": log_metrics.get("lm loss"),
                        "grad_norm": log_metrics.get("grad_norm"),
                        "loss_scale": log_metrics.get("loss_scale"),
                        "skipped_iter": int(log_metrics.get("skipped_iter",
                                                            0)),
                        "learning_rate": float(lr),
                        "step_time_secs": elapsed,
                        "interval_time_secs": interval_time,
                        "tokens_per_iter": int(tokens),
                        **(throughput or {}),
                        "memory": device_memory_stats(),
                        "recovery": recovery_counters(),
                    }
                    if trace is not None:
                        g = trace.goodput_summary()
                        rec["goodput_pct"] = g["goodput_pct"]
                        rec["goodput"] = {
                            k: round(v, 4) if isinstance(v, (int, float))
                            else v
                            for k, v in g.items()}
                        rec["recompiles"] = int(
                            counters.get("recompiles", 0))
                        rec["straggler_events"] = int(
                            counters.get("straggler_events", 0))
                    if slice_map is not None and gathered:
                        from megatron_llm_tpu import multislice
                        per_host = gathered.get("train-step")
                        if per_host is None:
                            # elementwise max over whatever sections exist
                            per_host = [max(col) for col
                                        in zip(*gathered.values())]
                        st = multislice.slice_times(per_host, slice_map)
                        rec["slice_times"] = {str(k): round(v, 6)
                                              for k, v in sorted(st.items())}
                        ws = multislice.worst_slice(st)
                        if ws is not None:
                            rec["worst_slice"] = ws
                            if trace is not None:
                                # slice dimension of goodput: the fleet
                                # waited lag_secs/iter on this slice over
                                # the whole interval
                                trace.tracer.goodput.add_slice_stall(
                                    ws["slice"],
                                    ws["lag_secs"] * log_interval)
                    if at_stats_boundary:
                        rec["layer_stats"] = ls_host
                    stream.emit(rec)
                if use_writer is not None and hasattr(use_writer, "flush"):
                    use_writer.flush()
                if on_metrics is not None:
                    on_metrics(iteration, metrics)

            if eval_step is not None and eval_interval and iteration % eval_interval == 0:
                if watchdog is not None:
                    watchdog.pause()    # eval has its own duration budget
                if recompile is not None:
                    # eval's forward-only program compiles on first use —
                    # an expected compile, not a recompile
                    recompile.pause()
                t_eval0 = time.perf_counter()
                with tracing.span("eval", "eval", iteration=iteration):
                    timers("eval-time", log_level=0).start()
                    losses = []
                    for _ in range(eval_iters):
                        eval_batch = next(eval_iterator)
                        losses.append(
                            float(eval_step(params, eval_batch, None)))
                    timers("eval-time").stop()
                non_train[0] += time.perf_counter() - t_eval0
                if recompile is not None:
                    recompile.resume()
                if watchdog is not None:
                    watchdog.resume()
                val = sum(losses) / len(losses)
                print(f" validation loss at iteration {iteration}: {val:.6E}")
                if writer is not None:
                    writer.add_scalar("validation loss", val, iteration)
                    if log_validation_ppl:   # reference --log_validation_ppl...
                        import math
                        writer.add_scalar("validation ppl", math.exp(min(val, 20.0)),
                                          iteration)
                    if hasattr(writer, "flush"):
                        writer.flush()

            saved = False
            if save_interval and save_dir and iteration % save_interval == 0:
                _save(iteration)
                saved = True

            # deterministic consensus boundaries only: every host reaches
            # the same (log / save / final) iterations, so the multi-host
            # allgather inside signals_received always pairs up.  Off these
            # boundaries the poll is local-only and free (the reference
            # all-gathers every iteration, dist_signal_handler.py:73-81).
            at_boundary = (saved or at_log_boundary
                           or iteration >= train_cfg.train_iters)
            if exit_signal_handler is not None and _signals(at_boundary):
                print("exiting on termination signal: saving checkpoint")
                if save_dir:
                    if not saved:
                        _save(iteration)
                    counters["signal_saves"] += 1
                # preemption-aware rescue: the consensus above means every
                # host (every slice) saw the SIGTERM and reaches this save
                # + exit together; a non-zero code (17, shared with the
                # hang watchdog) tells the fleet supervisor to restart —
                # possibly at a different dp x slice shape (elastic resume)
                code = int(preempt_exit_code or 0)
                if code and stream is not None:
                    stream.emit({"kind": "preempt_rescue",
                                 "iteration": iteration,
                                 "exit_code": code,
                                 "saved": bool(save_dir)})
                sys.exit(code)

            # exit based on duration (reference training.py:746-758)
            if exit_duration_in_mins:
                train_mins = (time.perf_counter() - train_start) / 60.0
                if train_mins > exit_duration_in_mins:
                    if save_dir and not saved:
                        _save(iteration)
                    print(f" exiting program after {train_mins:.1f} minutes",
                          flush=True)
                    sys.exit(0)

            # exit based on iterations (reference training.py:761-767)
            if exit_interval and iteration % exit_interval == 0:
                if save_dir and not saved:
                    _save(iteration)
                print(f" exiting program at iteration {iteration}", flush=True)
                sys.exit(0)

    finally:
        # every exit path — normal completion, sys.exit (raises
        # SystemExit), or an exception — flushes in-flight async
        # saves so a durable checkpoint always gets its tracker
        root_span.__exit__(None, None, None)
        if watchdog is not None:
            watchdog.stop()
        if profiler is not None:
            # a window truncated by exit/exception still yields a usable
            # xplane (close() is a no-op when no trace is active)
            profiler.close()
        checkpointing.finalize_async_saves()
    return params, opt_state, iteration
