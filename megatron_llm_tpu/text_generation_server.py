"""REST text-generation server.

Reference: ``megatron/text_generation_server.py`` — a Flask app where
``MegatronGenerate.put`` validates the JSON request (prompts <= 128,
tokens_to_generate, top-k/p, beams, logprobs; :31-233) and rank 0 serves
while other ranks spin in a broadcast loop.

TPU: a stdlib ``http.server`` implementation (Flask is not in the image)
with the same ``PUT /api`` contract and validation rules; there is no
broadcast loop — one controller drives all chips.

Two dispatch paths behind the same contract:

* **legacy** (no engine): one ``generate_and_post_process`` call per
  request under a lock — one generation in flight, others queue on the
  lock.  Always used for beam search, logprobs, and
  ``tokens_to_generate == 0``.
* **engine** (``serving.InferenceEngine`` passed in, e.g. via
  ``tools/run_text_generation_server.py --serve_engine``): requests are
  token-level co-batched by the continuous-batching engine, so N
  concurrent clients share decode steps instead of serializing.
  Admission control maps a full engine queue to HTTP 429 with a
  ``Retry-After`` header, and ``PUT /api/stream`` serves tokens
  incrementally as Server-Sent Events.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from megatron_llm_tpu.text_generation.api import (
    beam_search_and_post_process,
    generate_and_post_process,
    resolve_stop_rules,
)
# canonical home is telemetry.py (the trainer's --status_port and the
# router reuse them); re-exported here for existing importers
from megatron_llm_tpu.telemetry import (   # noqa: F401
    Histogram,
    histogram_percentile,
    prometheus_exposition,
    _wants_prometheus,
)
from megatron_llm_tpu.tracing import new_trace_id

MAX_PROMPTS = 128       # defaults; override with --serve_max_prompts /
MAX_TOKENS = 1024       # --serve_max_tokens (arguments.py)

TRACE_HEADER = "X-Request-Trace"


class ServerMetrics:
    """Serving-path observability (stdlib-only): request/error counts,
    p50/p95 request latency over a bounded window, total tokens
    generated.  Served by ``GET /metrics``; ``GET /health`` is the
    liveness probe.  Thread-safe — the handler runs per-connection
    threads under ``ThreadingHTTPServer``.

    When the continuous-batching engine is active, ``snapshot()`` also
    carries its counters (queue depth, batch occupancy, prefill vs
    decode time, per-reason completions) under ``"engine"``."""

    # lint-enforced (graft-lint threads/TH001): the SLO histograms are
    # fed from the engine loop (request_done hook) and read by HTTP
    # handler threads; drained is bumped from signal context and HTTP
    # threads and read by /metrics; recent_records is appended by the
    # engine loop and read by the alert engine's bundle capture
    _lock_protected_ = {"histograms": "_lock", "drained": "_lock",
                        "recent_records": "_lock"}

    def __init__(self, window: int = 512, recent_records_size: int = 64):
        self._lock = threading.Lock()
        self._window = max(int(window), 1)
        self._latencies = []        # bounded: last `window` request secs
        # last-N finished-request records, verbatim — the alert engine's
        # postmortem bundles embed them so "what were the last requests
        # before the alert" is answerable offline
        self.recent_records = deque(maxlen=max(int(recent_records_size), 1))
        # the SLO sentinel (serving/alerts.py), attached by the host
        # (run_text_generation_server) when alerting is enabled; its
        # snapshot rides in /metrics under "alerts"
        self.alert_engine = None
        self.started_unix = time.time()
        self.requests = 0
        self.errors = 0
        self.throttled = 0          # 429s (admission control)
        self.streamed = 0           # SSE requests served
        self.drained = 0            # graceful-drain initiations
        self.tokens_generated = 0
        self.engine_stats_fn = None  # set when an engine is attached
        # SLO histograms over the full serving lifetime (the bounded
        # latency window above keeps its p50/p95 for cheap liveness
        # checks; these are the mergeable fleet-wide truth).  Fed from
        # the engine's request_done hook.
        self.histograms = {
            "ttft_secs": Histogram(),
            "tpot_secs": Histogram(),
            "e2e_secs": Histogram(),
            "queue_wait_secs": Histogram(),
        }

    def observe_request_done(self, record: dict) -> None:
        """Engine ``request_done_hook``: fold one finished request's
        latency phases into the SLO histograms.  Never raises (the
        engine guards it too, but belt and braces)."""
        try:
            with self._lock:
                self.recent_records.append(dict(record))
                self.histograms["ttft_secs"].observe(
                    record.get("ttft_secs"))
                self.histograms["tpot_secs"].observe(
                    record.get("tpot_secs"))
                self.histograms["e2e_secs"].observe(
                    record.get("latency_secs"))
                phases = record.get("phases") or {}
                self.histograms["queue_wait_secs"].observe(
                    phases.get("queue_secs"))
        except Exception:
            pass

    def note_drained(self) -> None:
        """Count one graceful-drain initiation (called from HTTP
        handler threads and the SIGTERM handler)."""
        with self._lock:
            self.drained += 1

    def observe(self, secs: float, status: int, tokens: int = 0,
                streamed: bool = False) -> None:
        with self._lock:
            self.requests += 1
            if status >= 400:
                self.errors += 1
            if status == 429:
                self.throttled += 1
            if streamed:
                self.streamed += 1
            self.tokens_generated += max(int(tokens), 0)
            self._latencies.append(float(secs))
            if len(self._latencies) > self._window:
                del self._latencies[:len(self._latencies) - self._window]

    @staticmethod
    def _percentile(values, q: float) -> float:
        s = sorted(values)
        return s[min(int(q * (len(s) - 1) + 0.5), len(s) - 1)]

    def snapshot(self) -> dict:
        with self._lock:
            lat = list(self._latencies)
            out = {
                "uptime_secs": time.time() - self.started_unix,
                "requests": self.requests,
                "errors": self.errors,
                "throttled": self.throttled,
                "streamed": self.streamed,
                "drained": self.drained,
                "tokens_generated": self.tokens_generated,
            }
            # histogram snapshots under the same lock that orders the
            # request_done writes (engine loop) — a snapshot taken
            # mid-observe would tear count vs. bucket sums
            hist_snaps = {name: h.snapshot()
                          for name, h in self.histograms.items()}
        out["latency_p50_secs"] = self._percentile(lat, 0.50) if lat else None
        out["latency_p95_secs"] = self._percentile(lat, 0.95) if lat else None
        # histogram snapshots are additive across replicas (the router
        # bucket-sums them); the derived slo percentiles ride alongside
        # as plain (non-summable) gauges and are recomputed fleet-wide
        # from the merged buckets by the router
        out["histograms"] = hist_snaps
        out["slo"] = {}
        for name, snap in hist_snaps.items():
            for q, tag in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
                out["slo"][f"{name}_{tag}"] = histogram_percentile(snap, q)
        fn = self.engine_stats_fn
        if fn is not None:
            try:
                out["engine"] = fn()
            except Exception:
                pass
        alerts = self.alert_engine
        if alerts is not None:
            try:
                out["alerts"] = alerts.snapshot()
            except Exception:
                pass
        return out

    def recent_request_done(self) -> list:
        """The last-N finished-request records (bundle source)."""
        with self._lock:
            return list(self.recent_records)


def _count_tokens(body: dict) -> int:
    """Generated-token count from a successful /api response body (the
    token lists include the prompt; this is a serving throughput gauge,
    not an exact decode count)."""
    toks = body.get("tokens")
    if isinstance(toks, list):
        return sum(len(t) for t in toks if isinstance(t, list))
    return 0


class MegatronGenerate:
    """Request validation + dispatch (reference: text_generation_server.py:31)."""

    def __init__(self, model, params, tokenizer, int8_kv_cache=False,
                 engine=None, log_requests=False,
                 max_prompts=None, max_tokens=None):
        self.model = model
        self.params = params
        self.tokenizer = tokenizer
        self.int8_kv_cache = int8_kv_cache
        self.engine = engine
        self.log_requests = bool(log_requests)
        self.max_prompts = int(max_prompts or MAX_PROMPTS)
        self.max_tokens = int(max_tokens or MAX_TOKENS)
        self.lock = threading.Lock()

    # -- validation -----------------------------------------------------

    def _parse(self, payload: dict):
        """Full request validation.  Returns ``(None, knobs)`` on
        success or ``((code, body), None)`` — every malformed input is a
        JSON 400, never a dead socket."""
        if "prompts" not in payload:
            return (400, {"message": "prompts argument required"}), None
        if "max_len" in payload:
            return (400, {"message": "max_len is no longer used.  Replace "
                                     "with tokens_to_generate"}), None
        if "sentences" in payload:
            return (400, {"message": "sentences is no longer used.  "
                                     "Replace with prompts"}), None
        prompts = payload["prompts"]
        if not isinstance(prompts, list) or not prompts:
            return (400, {"message": "prompts must be a non-empty list"}), \
                None
        if len(prompts) > self.max_prompts:
            return (400, {"message": f"maximum number of prompts is "
                                     f"{self.max_prompts}"}), None
        add_BOS = bool(payload.get("add_BOS", False))
        if not add_BOS and any(len(p) == 0 for p in prompts
                               if isinstance(p, str)):
            return (400, {"message": "Empty prompts require add_BOS=true"}), \
                None
        tokens_to_generate = payload.get("tokens_to_generate", 64)
        if not isinstance(tokens_to_generate, int) or tokens_to_generate < 0:
            return (400, {"message": "tokens_to_generate must be an "
                                     "integer >= 0"}), None
        if tokens_to_generate > self.max_tokens:
            return (400, {"message": f"maximum tokens_to_generate is "
                                     f"{self.max_tokens}"}), None
        top_k = int(payload.get("top_k", 0))
        if top_k < 0 or top_k > 1000:
            return (400, {"message": "top_k must be in [0, 1000]"}), None
        top_p = float(payload.get("top_p", 0.0))
        if top_p < 0.0 or top_p > 1.0:
            return (400, {"message": "top_p must be in [0, 1]"}), None
        temperature = float(payload.get("temperature", 1.0))
        # 0.0 is an explicit, supported value: greedy decoding (matches
        # sampling.sample, which argmaxes at temperature 0)
        if temperature < 0.0 or temperature > 100.0:
            return (400, {"message": "temperature must be in [0, 100] "
                                     "(0 = greedy)"}), None
        top_p_decay = float(payload.get("top_p_decay", 0.0))
        if top_p_decay < 0.0 or top_p_decay > 1.0:
            return (400, {"message": "top_p_decay must be in [0, 1]"}), None
        if top_p_decay > 0.0 and top_p == 0.0:
            return (400, {"message": "top_p_decay requires top_p"}), None
        top_p_bound = float(payload.get("top_p_bound", 0.0))
        if "top_p_bound" in payload and (top_p_bound <= 0.0
                                         or top_p_bound > top_p):
            return (400, {"message": "top_p_bound must be in (0, top_p]"}), \
                None
        knobs = {
            "prompts": prompts,
            "add_BOS": add_BOS,
            "tokens_to_generate": tokens_to_generate,
            "top_k": top_k,
            "top_p": top_p,
            "temperature": temperature,
            "top_p_decay": top_p_decay,
            "top_p_bound": top_p_bound,
            "logprobs": bool(payload.get("logprobs", False)),
            "stop_on_eol": bool(payload.get("stop_on_eol", False)),
            "stop_on_double_eol": bool(payload.get("stop_on_double_eol",
                                                   False)),
            "prevent_newline_after_colon": bool(
                payload.get("prevent_newline_after_colon", False)),
            "beam_width": payload.get("beam_width", None),
            "stop_token": payload.get("stop_token", None),
            "length_penalty": float(payload.get("length_penalty", 1.0)),
            "random_seed": int(payload.get("random_seed", 0)),
            "no_log": bool(payload.get("no_log", False)),
        }
        return None, knobs

    def _log(self, payload: dict, knobs: dict) -> None:
        # request logging is opt-in (--log_requests): prompts are user
        # data and do not belong in server logs by default
        if self.log_requests and not knobs["no_log"]:
            print(json.dumps(payload), flush=True)

    # -- dispatch -------------------------------------------------------

    def handle(self, payload: dict, trace_id=None):
        try:
            err, knobs = self._parse(payload)
        except (TypeError, ValueError) as exc:
            # e.g. a null/None knob from a UI with a cleared field:
            # int(None)/float(None) must be a 400, not a dead socket
            return 400, {"message": f"malformed parameter: {exc}"}
        if err is not None:
            return err
        self._log(payload, knobs)
        use_engine = (self.engine is not None
                      and knobs["beam_width"] is None
                      and not knobs["logprobs"]
                      and knobs["tokens_to_generate"] > 0)
        if use_engine:
            return self._handle_engine(knobs, trace_id=trace_id)
        return self._handle_legacy(knobs)

    def _handle_legacy(self, knobs: dict):
        with self.lock:  # single in-flight generation (reference uses a lock)
            if knobs["beam_width"] is not None:
                if len(knobs["prompts"]) > 1:
                    return 400, {"message": "beam search requires one prompt"}
                texts, scores = beam_search_and_post_process(
                    self.model, self.params, self.tokenizer,
                    knobs["prompts"],
                    tokens_to_generate=knobs["tokens_to_generate"],
                    beam_size=int(knobs["beam_width"]),
                    length_penalty=knobs["length_penalty"],
                    stop_token=(int(knobs["stop_token"])
                                if knobs["stop_token"] is not None else None),
                )
                return 200, {"text": texts, "scores": scores.tolist()}
            texts, segments, log_probs, tokens = generate_and_post_process(
                self.model, self.params, self.tokenizer, knobs["prompts"],
                tokens_to_generate=knobs["tokens_to_generate"],
                return_output_log_probs=knobs["logprobs"],
                top_k_sampling=knobs["top_k"],
                top_p_sampling=knobs["top_p"],
                temperature=knobs["temperature"],
                random_seed=knobs["random_seed"],
                add_BOS=knobs["add_BOS"],
                top_p_decay=knobs["top_p_decay"],
                top_p_bound=knobs["top_p_bound"],
                stop_on_eol=knobs["stop_on_eol"],
                stop_on_double_eol=knobs["stop_on_double_eol"],
                prevent_newline_after_colon=knobs[
                    "prevent_newline_after_colon"],
                int8_kv_cache=self.int8_kv_cache,
            )
            out = {"text": texts, "segments": segments, "tokens": tokens}
            if knobs["logprobs"]:
                out["logprobs"] = log_probs.tolist()
            return 200, out

    # -- engine path ----------------------------------------------------

    def _tokenize(self, prompt: str, add_BOS: bool):
        toks = self.tokenizer.tokenize(prompt)
        if add_BOS:
            bos = getattr(self.tokenizer, "bos_token_id", None)
            if bos is None:
                bos = self.tokenizer.eod
            toks = [bos] + list(toks)
        return list(toks)

    def _sampling_params(self, knobs: dict, index: int):
        from megatron_llm_tpu.serving.request import SamplingParams

        extra_stop, stop_pairs, ban_pairs = resolve_stop_rules(
            self.tokenizer,
            stop_on_eol=knobs["stop_on_eol"],
            stop_on_double_eol=knobs["stop_on_double_eol"],
            prevent_newline_after_colon=knobs[
                "prevent_newline_after_colon"])
        return SamplingParams(
            max_new_tokens=knobs["tokens_to_generate"],
            temperature=knobs["temperature"],
            top_k=knobs["top_k"],
            top_p=knobs["top_p"],
            top_p_decay=knobs["top_p_decay"],
            top_p_bound=knobs["top_p_bound"],
            # distinct streams for identical prompts in one batch, while
            # a single-prompt request reproduces random_seed exactly
            seed=knobs["random_seed"] + index,
            eod_id=getattr(self.tokenizer, "eod", None),
            stop_token_ids=extra_stop,
            stop_pairs=stop_pairs,
            ban_pair=(ban_pairs[0] if ban_pairs else None),
        )

    def _submit_engine(self, knobs: dict, stream: bool = False,
                       trace_id=None):
        """Returns (None, requests) or ((code, body), None)."""
        from megatron_llm_tpu.serving.request import QueueFull

        try:
            token_lists = [self._tokenize(p, knobs["add_BOS"])
                           for p in knobs["prompts"]]
            samplings = [self._sampling_params(knobs, i)
                         for i in range(len(token_lists))]
            reqs = self.engine.submit_many(token_lists, samplings,
                                           stream=stream,
                                           trace_id=trace_id)
            return None, reqs
        except QueueFull as exc:
            # tell clients how backed up we are, not just "go away":
            # depth + estimated wait let a router/load-balancer pick the
            # least-bad replica and clients back off proportionally
            body = {"message": str(exc),
                    "retry_after_secs": exc.retry_after_secs,
                    "queue_depth": self.engine.queue.depth(),
                    "estimated_wait_secs": self.engine.estimate_wait_secs()}
            return (429, body), None
        except ValueError as exc:
            return (400, {"message": str(exc)}), None

    def _result_timeout(self) -> float:
        dl = getattr(self.engine.config, "default_deadline_secs", 0) or 0
        return dl + 60.0 if dl else 600.0

    def _handle_engine(self, knobs: dict, trace_id=None):
        from megatron_llm_tpu.serving.request import EngineError

        err, reqs = self._submit_engine(knobs, trace_id=trace_id)
        if err is not None:
            return err
        texts, segments, tokens = [], [], []
        timeout = self._result_timeout()
        for r in reqs:
            try:
                r.result(timeout=timeout)
            except EngineError as exc:
                return 500, {"message": f"engine error: {exc}"}
            except TimeoutError:
                return 500, {"message": "generation timed out"}
            if r.finish_reason == "deadline":
                return 503, {"message": "request deadline exceeded "
                                        "before completion"}
            if r.finish_reason == "nonfinite":
                # slot-level fault isolation (engine non-finite
                # sentinel): this request's slot produced NaN/inf logits
                # and was evicted; its batch-mates were untouched
                return 500, {"message": r.error or "non-finite logits "
                                                   "detected; slot evicted",
                             "finish_reason": "nonfinite"}
            row = r.tokens
            tokens.append(row)
            texts.append(self.tokenizer.detokenize(row))
            segments.append([self.tokenizer.detokenize([t]) for t in row])
        return 200, {"text": texts, "segments": segments, "tokens": tokens}

    def handle_stream(self, payload: dict, trace_id=None):
        """SSE path (``PUT /api/stream``): returns ``(code, body, None)``
        on rejection or ``(200, {}, events)`` where ``events`` yields one
        JSON-able dict per token and a final ``{"done": ...}`` record."""
        try:
            err, knobs = self._parse(payload)
        except (TypeError, ValueError) as exc:
            return 400, {"message": f"malformed parameter: {exc}"}, None
        if err is not None:
            return err[0], err[1], None
        if self.engine is None:
            return 400, {"message": "streaming requires the continuous-"
                                    "batching engine (start the server "
                                    "with --serve_engine)"}, None
        if len(knobs["prompts"]) != 1:
            return 400, {"message": "streaming supports a single prompt"}, \
                None
        if knobs["beam_width"] is not None or knobs["logprobs"]:
            return 400, {"message": "streaming does not support beam "
                                    "search or logprobs"}, None
        if knobs["tokens_to_generate"] == 0:
            return 400, {"message": "streaming requires "
                                    "tokens_to_generate > 0"}, None
        self._log(payload, knobs)
        err, reqs = self._submit_engine(knobs, stream=True,
                                        trace_id=trace_id)
        if err is not None:
            return err[0], err[1], None
        req = reqs[0]
        tokenizer = self.tokenizer
        timeout = self._result_timeout()

        def events():
            for kind, val in req.events(timeout=timeout):
                if kind == "token":
                    yield {"token": val,
                           "segment": tokenizer.detokenize([val])}
                elif kind == "done":
                    yield {"done": True, "finish_reason": val,
                           "text": tokenizer.detokenize(req.tokens),
                           "tokens": req.tokens}
                else:   # "error"
                    yield {"done": True, "finish_reason": "error",
                           "message": str(val)}

        return 200, {}, events()


class MegatronServer:
    """reference: text_generation_server.py:234-241."""

    def __init__(self, model, params, tokenizer, int8_kv_cache=False,
                 engine=None, log_requests=False,
                 max_prompts=None, max_tokens=None,
                 drain_timeout_secs: float = 600.0):
        self.generator = MegatronGenerate(
            model, params, tokenizer, int8_kv_cache=int8_kv_cache,
            engine=engine, log_requests=log_requests,
            max_prompts=max_prompts, max_tokens=max_tokens)
        self.metrics = ServerMetrics()
        if engine is not None:
            self.metrics.engine_stats_fn = engine.stats
            # every retired request feeds the SLO histograms, whether it
            # arrived over HTTP or was submitted in-process
            engine.request_done_hook = self.metrics.observe_request_done
        # graceful drain (SIGTERM / POST /drain): admission answers 503,
        # /health reports "draining" (the router stops dispatching
        # WITHOUT tripping its breaker), in-flight work finishes, then
        # the process exits cleanly
        self.draining = False
        self.drain_timeout_secs = float(drain_timeout_secs)
        self._drain_lock = threading.Lock()
        self._in_flight = 0
        self._in_flight_lock = threading.Lock()
        self.httpd = None

    def _track(self, delta: int) -> None:
        with self._in_flight_lock:
            self._in_flight += delta

    def begin_drain(self, reason: str = "signal") -> bool:
        """Flip into draining mode and hand off to the waiter thread.
        Idempotent: the first call wins, later ones return False.  Safe
        to call from a signal handler (nothing here blocks)."""
        with self._drain_lock:
            if self.draining:
                return False
            self.draining = True
        self.metrics.note_drained()
        try:
            from megatron_llm_tpu.telemetry import get_stream
            stream = get_stream()
            if stream is not None:
                stream.emit({"kind": "serve", "event": "drain",
                             "reason": reason})
        except Exception:
            pass
        print(f" * draining ({reason}): admission closed, finishing "
              f"in-flight work", flush=True)
        threading.Thread(target=self._drain_and_exit, name="drain-waiter",
                         daemon=True).start()
        return True

    def _drain_and_exit(self) -> None:
        engine = self.generator.engine
        deadline = time.monotonic() + self.drain_timeout_secs
        while time.monotonic() < deadline:
            with self._in_flight_lock:
                busy = self._in_flight > 0
            if engine is not None and not busy:
                busy = engine.scheduler.has_work()
            if not busy:
                break
            time.sleep(0.05)
        if engine is not None:
            try:
                engine.stop()
            except Exception:
                pass
        if self.httpd is not None:
            self.httpd.shutdown()   # run() returns; process exits cleanly

    def run(self, host: str = "0.0.0.0", port: int = 5000):
        generator = self.generator
        metrics = self.metrics
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _send_json(self, code: int, body: dict,
                           trace_id: str = None):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                if trace_id:
                    self.send_header(TRACE_HEADER, trace_id)
                if code == 429 or (code == 503
                                   and "retry_after_secs" in body):
                    self.send_header("Retry-After", str(max(int(
                        body.get("retry_after_secs", 1)), 1)))
                self.end_headers()
                self.wfile.write(data)

            def _reject_draining(self, trace_id=None) -> bool:
                if not outer.draining:
                    return False
                self._send_json(503, {
                    "message": "server draining; retry another replica",
                    "draining": True,
                    "retry_after_secs": 1}, trace_id=trace_id)
                return True

            def _read_payload(self):
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def _trace_id(self):
                # the router minted one upstream; mint locally only for
                # direct (router-less) traffic so every request is
                # traceable either way
                return self.headers.get(TRACE_HEADER) or new_trace_id()

            def do_PUT(self):
                if self.path == "/drain":
                    # operator-initiated graceful drain (the runbook
                    # alternative to SIGTERM, works through port-forwards)
                    started = outer.begin_drain("http")
                    self._send_json(200, {"status": "draining",
                                          "started": bool(started)})
                    return
                if self.path in ("/api/stream", "/generate/stream"):
                    self._do_stream()
                    return
                if self.path not in ("/api", "/generate"):
                    self.send_error(404)
                    return
                t0 = time.perf_counter()
                trace_id = self._trace_id()
                if self._reject_draining(trace_id=trace_id):
                    metrics.observe(time.perf_counter() - t0, 503)
                    return
                try:
                    payload = self._read_payload()
                except (ValueError, json.JSONDecodeError):
                    metrics.observe(time.perf_counter() - t0, 400)
                    self.send_error(400, "invalid JSON")
                    return
                outer._track(+1)
                try:
                    code, body = generator.handle(payload,
                                                  trace_id=trace_id)
                finally:
                    outer._track(-1)
                metrics.observe(time.perf_counter() - t0, code,
                                tokens=(_count_tokens(body)
                                        if code == 200 else 0))
                self._send_json(code, body, trace_id=trace_id)

            def _do_stream(self):
                t0 = time.perf_counter()
                trace_id = self._trace_id()
                if self._reject_draining(trace_id=trace_id):
                    metrics.observe(time.perf_counter() - t0, 503)
                    return
                try:
                    payload = self._read_payload()
                except (ValueError, json.JSONDecodeError):
                    metrics.observe(time.perf_counter() - t0, 400)
                    self.send_error(400, "invalid JSON")
                    return
                code, body, events = generator.handle_stream(
                    payload, trace_id=trace_id)
                if events is None:
                    metrics.observe(time.perf_counter() - t0, code)
                    self._send_json(code, body, trace_id=trace_id)
                    return
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.send_header(TRACE_HEADER, trace_id)
                self.end_headers()
                n_tokens = 0
                outer._track(+1)
                try:
                    for ev in events:
                        if "token" in ev:
                            n_tokens += 1
                        self.wfile.write(b"data: "
                                         + json.dumps(ev).encode()
                                         + b"\n\n")
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass        # client went away mid-stream
                finally:
                    outer._track(-1)
                metrics.observe(time.perf_counter() - t0, 200,
                                tokens=n_tokens, streamed=True)

            do_POST = do_PUT

            def do_GET(self):
                # Demo page (reference serves megatron/static/index.html
                # through Flask; here it rides the same stdlib server).
                if self.path in ("/", "/index.html"):
                    page = os.path.join(os.path.dirname(
                        os.path.abspath(__file__)), "static", "index.html")
                    try:
                        with open(page, "rb") as f:
                            data = f.read()
                    except OSError:
                        self.send_error(404)
                        return
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                elif self.path == "/health":
                    # liveness: the server thread answers => alive (a
                    # generation may still hold the model lock).  While
                    # draining the answer stays 200 — the replica is
                    # healthy, just finishing up — and the router reads
                    # the status body to stop dispatching here without
                    # tripping its circuit breaker.
                    self._send_json(200, {
                        "status": ("draining" if outer.draining
                                   else "ok"),
                        "uptime_secs": time.time()
                        - metrics.started_unix})
                elif self.path == "/metrics" \
                        or self.path.startswith("/metrics?"):
                    snap = metrics.snapshot()
                    if _wants_prometheus(self.path,
                                         self.headers.get("Accept", "")):
                        data = prometheus_exposition(snap).encode()
                        self.send_response(200)
                        self.send_header(
                            "Content-Type",
                            "text/plain; version=0.0.4; charset=utf-8")
                        self.send_header("Content-Length", str(len(data)))
                        self.end_headers()
                        self.wfile.write(data)
                    else:
                        self._send_json(200, snap)
                else:
                    self.send_error(404)

            def log_message(self, fmt, *args):
                pass

        server = ThreadingHTTPServer((host, port), Handler)
        # exposed for tests / embedding (port may be ephemeral: port=0)
        self.httpd = server
        # SIGTERM -> graceful drain (orchestrators send SIGTERM before
        # SIGKILL; signal handlers only install from the main thread —
        # embedded/test servers run() from a worker and rely on /drain)
        if threading.current_thread() is threading.main_thread():
            try:
                signal.signal(signal.SIGTERM,
                              lambda *_: self.begin_drain("SIGTERM"))
            except (ValueError, OSError):
                pass
        print(f" * serving on http://{host}:{server.server_address[1]}/"
              f" (demo page) and /api", flush=True)
        server.serve_forever()


def build_server_alerts(server, engine=None, structured_log_dir=None,
                        alert_rules=None, alert_webhook=None,
                        clock=None, start=True):
    """Wire the SLO sentinel (serving/alerts.py) to a replica server.

    Shared by tools/run_text_generation_server.py and the test replica
    harness so both get identical behaviour: rules from ``--alert_rules``
    (built-in defaults otherwise), metrics from the server's own
    ``/metrics`` snapshot, ``alert_transition`` records on the schema-13
    JSONL stream, and postmortem bundles frozen under
    ``<structured_log_dir>/incidents/<rule>-<seq>`` the moment a rule
    fires.  Returns the started :class:`AlertEngine` (or ``None`` when
    the rules argument fails to parse — the server must keep serving
    even with a bad ``--alert_rules``).
    """
    from megatron_llm_tpu.serving.alerts import AlertEngine, parse_rules_arg
    from megatron_llm_tpu import telemetry as _telemetry
    from megatron_llm_tpu import tracing as _tracing

    rules, opts = None, {}
    if alert_rules:
        try:
            rules, opts = parse_rules_arg(alert_rules)
        except (ValueError, OSError) as exc:
            print(f" * --alert_rules rejected ({exc}); alerting disabled",
                  flush=True)
            return None

    metrics = server.metrics

    def sink(payload: dict) -> None:
        stream = _telemetry.get_stream()
        if stream is not None:
            # schema-13 contract: replica transitions are kind="serve"
            # (the supervisor's fleet-scope engine stamps kind="fleet")
            stream.emit({"kind": "serve", **payload})

    bundle_fn = None
    if structured_log_dir:
        incidents_dir = os.path.join(structured_log_dir, "incidents")
        max_bundles = int(opts.get("max_bundles", 8))
        seq = [0]

        def bundle_fn(transition: dict):
            # Freeze everything a responder needs, bounded per part so a
            # pathological ring can't fill the disk.  Each capture is
            # independently best-effort: a dead trace exporter must not
            # lose the thread stacks.
            parts: dict = {"transition": dict(transition)}
            try:
                parts["metrics"] = metrics.snapshot()
            except Exception as exc:
                parts["metrics"] = {"error": str(exc)}
            try:
                parts["recent_requests"] = metrics.recent_request_done()
            except Exception as exc:
                parts["recent_requests"] = {"error": str(exc)}
            try:
                parts["thread_stacks"] = _telemetry.capture_thread_stacks()
            except Exception as exc:
                parts["thread_stacks"] = f"capture failed: {exc}"
            if engine is not None:
                try:
                    parts["loop_ring"] = engine.loop_profiler.ring_records()
                except Exception as exc:
                    parts["loop_ring"] = {"error": str(exc)}
                try:
                    parts["cache"] = engine.cache_observatory.stats()
                except Exception as exc:
                    parts["cache"] = {"error": str(exc)}
            try:
                rec = _telemetry.get_flight_recorder()
                if rec is not None:
                    parts["flight_recorder"] = rec.records()
            except Exception as exc:
                parts["flight_recorder"] = {"error": str(exc)}
            try:
                trace_path = _tracing.dump_trace(
                    reason=f"alert:{transition.get('rule')}")
                if trace_path:
                    parts["trace"] = {"chrome_trace_path": trace_path}
            except Exception as exc:
                parts["trace"] = {"error": str(exc)}
            seq[0] += 1
            dest = os.path.join(
                incidents_dir, f"{transition.get('rule')}-{seq[0]:04d}")
            path = _telemetry.write_snapshot_bundle(
                dest, parts,
                manifest_extra={"rule": transition.get("rule"),
                                "scope": transition.get("scope"),
                                "severity": transition.get("severity")})
            _prune_incident_bundles(incidents_dir, max_bundles)
            return path

    eng = AlertEngine(
        rules=rules,
        metrics_fn=metrics.snapshot,
        scope="replica",
        interval_secs=float(opts.get("interval_secs", 2.0)),
        transition_sink=sink,
        bundle_fn=bundle_fn,
        webhook_url=alert_webhook,
        max_firing=int(opts.get("max_firing", 10)),
        **({"clock": clock} if clock is not None else {}),
    )
    metrics.alert_engine = eng
    if start:
        eng.start()
    return eng


def _prune_incident_bundles(incidents_dir: str, keep: int) -> None:
    """Cap the incidents directory at ``keep`` bundles, oldest out
    first — incident capture must never become its own disk incident."""
    import shutil
    try:
        names = [n for n in os.listdir(incidents_dir)
                 if os.path.isdir(os.path.join(incidents_dir, n))]
    except OSError:
        return
    if len(names) <= keep:
        return
    names.sort(key=lambda n: os.path.getmtime(
        os.path.join(incidents_dir, n)))
    for n in names[:len(names) - keep]:
        shutil.rmtree(os.path.join(incidents_dir, n), ignore_errors=True)
