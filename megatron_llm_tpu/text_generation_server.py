"""REST text-generation server.

Reference: ``megatron/text_generation_server.py`` — a Flask app where
``MegatronGenerate.put`` validates the JSON request (prompts <= 128,
tokens_to_generate, top-k/p, beams, logprobs; :31-233) and rank 0 serves
while other ranks spin in a broadcast loop.

TPU: a stdlib ``http.server`` implementation (Flask is not in the image)
with the same ``PUT /api`` contract and validation rules; there is no
broadcast loop — one controller drives all chips.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from megatron_llm_tpu.text_generation.api import (
    beam_search_and_post_process,
    generate_and_post_process,
)

MAX_PROMPTS = 128
MAX_TOKENS = 1024


class ServerMetrics:
    """Serving-path observability (stdlib-only): request/error counts,
    p50/p95 request latency over a bounded window, total tokens
    generated.  Served by ``GET /metrics``; ``GET /health`` is the
    liveness probe.  Thread-safe — the handler runs per-connection
    threads under ``ThreadingHTTPServer``."""

    def __init__(self, window: int = 512):
        self._lock = threading.Lock()
        self._window = max(int(window), 1)
        self._latencies = []        # bounded: last `window` request secs
        self.started_unix = time.time()
        self.requests = 0
        self.errors = 0
        self.tokens_generated = 0

    def observe(self, secs: float, status: int, tokens: int = 0) -> None:
        with self._lock:
            self.requests += 1
            if status >= 400:
                self.errors += 1
            self.tokens_generated += max(int(tokens), 0)
            self._latencies.append(float(secs))
            if len(self._latencies) > self._window:
                del self._latencies[:len(self._latencies) - self._window]

    @staticmethod
    def _percentile(values, q: float) -> float:
        s = sorted(values)
        return s[min(int(q * (len(s) - 1) + 0.5), len(s) - 1)]

    def snapshot(self) -> dict:
        with self._lock:
            lat = list(self._latencies)
            out = {
                "uptime_secs": time.time() - self.started_unix,
                "requests": self.requests,
                "errors": self.errors,
                "tokens_generated": self.tokens_generated,
            }
        out["latency_p50_secs"] = self._percentile(lat, 0.50) if lat else None
        out["latency_p95_secs"] = self._percentile(lat, 0.95) if lat else None
        return out


def _count_tokens(body: dict) -> int:
    """Generated-token count from a successful /api response body (the
    token lists include the prompt; this is a serving throughput gauge,
    not an exact decode count)."""
    toks = body.get("tokens")
    if isinstance(toks, list):
        return sum(len(t) for t in toks if isinstance(t, list))
    return 0


class MegatronGenerate:
    """Request validation + dispatch (reference: text_generation_server.py:31)."""

    def __init__(self, model, params, tokenizer, int8_kv_cache=False):
        self.model = model
        self.params = params
        self.tokenizer = tokenizer
        self.int8_kv_cache = int8_kv_cache
        self.lock = threading.Lock()

    def handle(self, payload: dict):
        if "prompts" not in payload:
            return 400, {"message": "prompts argument required"}
        if "max_len" in payload:
            return 400, {"message": "max_len is no longer used.  Replace "
                                    "with tokens_to_generate"}
        if "sentences" in payload:
            return 400, {"message": "sentences is no longer used.  Replace "
                                    "with prompts"}
        prompts = payload["prompts"]
        if not isinstance(prompts, list) or not prompts:
            return 400, {"message": "prompts must be a non-empty list"}
        if len(prompts) > MAX_PROMPTS:
            return 400, {"message": f"maximum number of prompts is {MAX_PROMPTS}"}
        add_BOS = bool(payload.get("add_BOS", False))
        if not add_BOS and any(len(p) == 0 for p in prompts
                               if isinstance(p, str)):
            return 400, {"message": "Empty prompts require add_BOS=true"}
        tokens_to_generate = payload.get("tokens_to_generate", 64)
        if not isinstance(tokens_to_generate, int) or tokens_to_generate < 0:
            return 400, {"message": "tokens_to_generate must be an integer >= 0"}
        if tokens_to_generate > MAX_TOKENS:
            return 400, {"message": f"maximum tokens_to_generate is {MAX_TOKENS}"}
        logprobs = bool(payload.get("logprobs", False))
        try:
            return self._handle_sampling(payload, prompts,
                                         tokens_to_generate, logprobs,
                                         add_BOS)
        except (TypeError, ValueError) as exc:
            # e.g. a null/None knob from a UI with a cleared field:
            # int(None)/float(None) must be a 400, not a dead socket
            return 400, {"message": f"malformed parameter: {exc}"}

    def _handle_sampling(self, payload, prompts, tokens_to_generate,
                         logprobs, add_BOS):
        top_k = int(payload.get("top_k", 0))
        if top_k < 0 or top_k > 1000:
            return 400, {"message": "top_k must be in [0, 1000]"}
        top_p = float(payload.get("top_p", 0.0))
        if top_p < 0.0 or top_p > 1.0:
            return 400, {"message": "top_p must be in [0, 1]"}
        temperature = float(payload.get("temperature", 1.0))
        if temperature < 0.0 or temperature > 100.0:
            return 400, {"message": "temperature must be in (0, 100]"}
        top_p_decay = float(payload.get("top_p_decay", 0.0))
        if top_p_decay < 0.0 or top_p_decay > 1.0:
            return 400, {"message": "top_p_decay must be in [0, 1]"}
        if top_p_decay > 0.0 and top_p == 0.0:
            return 400, {"message": "top_p_decay requires top_p"}
        top_p_bound = float(payload.get("top_p_bound", 0.0))
        if "top_p_bound" in payload and (top_p_bound <= 0.0
                                         or top_p_bound > top_p):
            return 400, {"message": "top_p_bound must be in (0, top_p]"}
        stop_on_double_eol = bool(payload.get("stop_on_double_eol", False))
        stop_on_eol = bool(payload.get("stop_on_eol", False))
        prevent_newline_after_colon = bool(
            payload.get("prevent_newline_after_colon", False))
        no_log = bool(payload.get("no_log", False))
        beam_width = payload.get("beam_width", None)
        stop_token = payload.get("stop_token", None)
        length_penalty = float(payload.get("length_penalty", 1.0))
        random_seed = int(payload.get("random_seed", 0))
        if not no_log:
            print(json.dumps(payload), flush=True)

        with self.lock:  # single in-flight generation (reference uses a lock)
            if beam_width is not None:
                if len(prompts) > 1:
                    return 400, {"message": "beam search requires one prompt"}
                texts, scores = beam_search_and_post_process(
                    self.model, self.params, self.tokenizer, prompts,
                    tokens_to_generate=tokens_to_generate,
                    beam_size=int(beam_width),
                    length_penalty=length_penalty,
                    stop_token=(int(stop_token) if stop_token is not None
                                else None),
                )
                return 200, {"text": texts, "scores": scores.tolist()}
            texts, segments, log_probs, tokens = generate_and_post_process(
                self.model, self.params, self.tokenizer, prompts,
                tokens_to_generate=tokens_to_generate,
                return_output_log_probs=logprobs,
                top_k_sampling=top_k,
                top_p_sampling=top_p,
                temperature=temperature,
                random_seed=random_seed,
                add_BOS=add_BOS,
                top_p_decay=top_p_decay,
                top_p_bound=top_p_bound,
                stop_on_eol=stop_on_eol,
                stop_on_double_eol=stop_on_double_eol,
                prevent_newline_after_colon=prevent_newline_after_colon,
                int8_kv_cache=self.int8_kv_cache,
            )
            out = {"text": texts, "segments": segments, "tokens": tokens}
            if logprobs:
                out["logprobs"] = log_probs.tolist()
            return 200, out


class MegatronServer:
    """reference: text_generation_server.py:234-241."""

    def __init__(self, model, params, tokenizer, int8_kv_cache=False):
        self.generator = MegatronGenerate(model, params, tokenizer,
                                          int8_kv_cache=int8_kv_cache)
        self.metrics = ServerMetrics()

    def run(self, host: str = "0.0.0.0", port: int = 5000):
        generator = self.generator
        metrics = self.metrics

        class Handler(BaseHTTPRequestHandler):
            def _send_json(self, code: int, body: dict):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_PUT(self):
                if self.path not in ("/api", "/generate"):
                    self.send_error(404)
                    return
                t0 = time.perf_counter()
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    metrics.observe(time.perf_counter() - t0, 400)
                    self.send_error(400, "invalid JSON")
                    return
                code, body = generator.handle(payload)
                metrics.observe(time.perf_counter() - t0, code,
                                tokens=(_count_tokens(body)
                                        if code == 200 else 0))
                self._send_json(code, body)

            do_POST = do_PUT

            def do_GET(self):
                # Demo page (reference serves megatron/static/index.html
                # through Flask; here it rides the same stdlib server).
                if self.path in ("/", "/index.html"):
                    page = os.path.join(os.path.dirname(
                        os.path.abspath(__file__)), "static", "index.html")
                    try:
                        with open(page, "rb") as f:
                            data = f.read()
                    except OSError:
                        self.send_error(404)
                        return
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                elif self.path == "/health":
                    # liveness: the server thread answers => alive (a
                    # generation may still hold the model lock)
                    self._send_json(200, {"status": "ok",
                                          "uptime_secs": time.time()
                                          - metrics.started_unix})
                elif self.path == "/metrics":
                    self._send_json(200, metrics.snapshot())
                else:
                    self.send_error(404)

            def log_message(self, fmt, *args):
                pass

        server = ThreadingHTTPServer((host, port), Handler)
        # exposed for tests / embedding (port may be ephemeral: port=0)
        self.httpd = server
        print(f" * serving on http://{host}:{server.server_address[1]}/"
              f" (demo page) and /api", flush=True)
        server.serve_forever()
