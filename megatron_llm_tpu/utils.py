"""Misc host/device utilities.

Reference: ``megatron/utils.py`` — notably
``get_ltor_masks_and_position_ids`` (:137-194) and memory reporting
(:82-96).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def get_ltor_masks_and_position_ids(
    tokens,
    eod_token: Optional[int] = None,
    reset_position_ids: bool = False,
    reset_attention_mask: bool = False,
    eod_mask_loss: bool = False,
):
    """Left-to-right masks + position ids (reference: utils.py:137-194).

    Returns (attention_mask [b,1,s,s] bool True=masked, loss_mask [b,s],
    position_ids [b,s]).  ``reset_*`` restart positions / block attention
    at EOD boundaries for packed multi-doc samples.
    """
    tokens = jnp.asarray(tokens)
    b, s = tokens.shape
    causal = jnp.triu(jnp.ones((s, s), bool), k=1)  # True above diag = masked

    loss_mask = jnp.ones((b, s), jnp.float32)
    if eod_mask_loss and eod_token is not None:
        loss_mask = jnp.where(tokens == eod_token, 0.0, loss_mask)

    if not (reset_position_ids or reset_attention_mask) or eod_token is None:
        position_ids = jnp.broadcast_to(jnp.arange(s), (b, s))
        attention_mask = jnp.broadcast_to(causal[None, None], (b, 1, s, s))
        return attention_mask, loss_mask, position_ids

    # document ids: cumulative count of EODs *before* each position
    is_eod = (tokens == eod_token).astype(jnp.int32)
    doc_ids = jnp.cumsum(is_eod, axis=1) - is_eod  # eod belongs to its doc

    if reset_position_ids:
        # position within document: global pos - pos of doc start
        pos = jnp.broadcast_to(jnp.arange(s), (b, s))
        doc_start = jax.vmap(
            lambda d: jnp.maximum.accumulate(
                jnp.where(jnp.concatenate([jnp.zeros(1, bool),
                                           d[1:] != d[:-1]]),
                          jnp.arange(s), 0)
            )
        )(doc_ids)
        position_ids = pos - doc_start
    else:
        position_ids = jnp.broadcast_to(jnp.arange(s), (b, s))

    if reset_attention_mask:
        same_doc = doc_ids[:, :, None] == doc_ids[:, None, :]
        attention_mask = (~same_doc) | causal[None]
        attention_mask = attention_mask[:, None]
    else:
        attention_mask = jnp.broadcast_to(causal[None, None], (b, 1, s, s))
    return attention_mask, loss_mask, position_ids


def report_memory(name: str = "") -> str:
    """Device memory report (reference: utils.py:82-96 uses
    torch.cuda.memory_allocated; here per-device live-buffer stats)."""
    lines = []
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
            if stats:
                used = stats.get("bytes_in_use", 0) / 2**30
                peak = stats.get("peak_bytes_in_use", 0) / 2**30
                lim = stats.get("bytes_limit", 0) / 2**30
                lines.append(
                    f"{name} | {d}: in_use {used:.2f} GiB | "
                    f"peak {peak:.2f} GiB | limit {lim:.2f} GiB"
                )
        except Exception:
            pass
    report = "\n".join(lines) or f"{name} | memory stats unavailable"
    print(report, flush=True)
    return report


def param_count(tree) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree))
