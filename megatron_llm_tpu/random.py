"""RNG seed-domain design.

Reference: ``megatron/core/tensor_parallel/random.py`` — a stateful
``CudaRNGStatesTracker`` with two seed domains (``model_parallel_cuda_manual_seed``
:144-172): a *default* stream equal across TP ranks (DP-uniform) and a
*tensor-model-parallel* stream distinct per TP rank (seed + 2718 + tp_rank),
plus ``initialize.py:179``'s base-seed offset ``seed + 100 * pp_rank
[+ 10 * dp_rank]``; dropout inside TP regions forks to the TP-distinct
stream so each rank drops a different slice.

TPU design: there are no per-rank RNG states to keep consistent.
``jax.random`` is counter-based and *shape-global*: under GSPMD a dropout
mask drawn for a logical [b, s, h] activation is one global stream whose
shards each rank materialises locally — the exact property the reference's
two-domain machinery exists to emulate (TP ranks see different bits for
different activation slices, the same bits for replicated tensors).  So the
whole tracker collapses to key-folding discipline:

* one base key per run from ``--seed``;
* ``fold_in`` by purpose (init / dropout / data) and by (layer, step) so
  streams never collide;
* per-microbatch keys derived by folding the microbatch index.

The ``CheckpointFunction`` RNG save/restore (:175-252) is likewise
subsumed: ``jax.checkpoint`` replays the same functional keys on recompute
by construction.
"""

from __future__ import annotations

from enum import IntEnum

import jax


class RngDomain(IntEnum):
    INIT = 0
    DROPOUT = 1
    DATA = 2
    SAMPLING = 3


def base_key(seed: int) -> jax.Array:
    return jax.random.PRNGKey(seed)


def domain_key(key: jax.Array, domain: RngDomain) -> jax.Array:
    return jax.random.fold_in(key, int(domain))


def dropout_key(key: jax.Array, layer: int, step: int = 0, micro: int = 0) -> jax.Array:
    k = domain_key(key, RngDomain.DROPOUT)
    k = jax.random.fold_in(k, layer)
    k = jax.random.fold_in(k, step)
    return jax.random.fold_in(k, micro)


class KeySeq:
    """Host-side convenience: hands out fresh fold_in'd subkeys for init."""

    def __init__(self, seed_or_key):
        if isinstance(seed_or_key, int):
            self._key = base_key(seed_or_key)
        else:
            self._key = seed_or_key
        self._n = 0

    def next(self) -> jax.Array:
        self._n += 1
        return jax.random.fold_in(self._key, self._n)
