"""Parallelism core: sharding specs, collective mappings, TP layers, pipeline.

The TPU-native replacement for ``megatron/core/tensor_parallel`` +
``megatron/schedules.py``/``p2p_communication.py``.
"""

from megatron_llm_tpu.parallel.sharding import (
    constrain,
    logical_to_mesh,
    shard_params,
    with_logical_constraint,
)
from megatron_llm_tpu.parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
