"""Zigzag ring attention — load-balanced causal context parallelism
(cp algorithm #3, alongside ``ring_attention`` and ``ulysses``).

The plain ring (``parallel/ring_attention.py``) computes masked scores
for every (q-chunk, kv-chunk) pair: under causal masking, the kv chunks
a rank receives in most ring steps are entirely in its future, so
~half the computed score blocks are fully masked — and the USEFUL work
is imbalanced (rank r's q attends r+1 of the P kv chunks).  Since the
ring is lockstep (a ``ppermute`` barrier every step), wall-clock follows
the heaviest rank.

The zigzag layout fixes both (the scheme used for Llama-3 long-context
training; public zigzag/striped ring-attention implementations use the
same assignment): split the global sequence into 2P half-chunks and give
rank r the PAIR (r, 2P-1-r) — one early chunk, one late chunk.  Every
rank then owns the same amount of "causal past", so per ring step each
rank has the same number of live (q-half, kv-half) sub-blocks, and the
fully-masked sub-blocks are skipped with ``lax.cond`` — compute per step
is balanced AND roughly halved instead of masked-then-discarded.

Data stays contiguously sharded outside this module (same shard_map
specs as ring); the zigzag redistribution is two ``ppermute`` bijections
on entry and their inverses on exit (~2 extra ICI hops, amortized over
the P-step ring).

Exactness: the accumulator is the standard streaming-softmax (m, l, acc)
triple per q half; results equal plain ring / full attention to fp32
associativity (tests/test_zigzag.py).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from megatron_llm_tpu import topology
from megatron_llm_tpu.parallel.ring_attention import (
    DEFAULT_Q_CHUNK,
    NEG_INF,
    _chunk_scores,
)


def _zig_owner(c, P_sz):
    """Zigzag owner rank of global half-chunk c (0 <= c < 2P)."""
    return c if c < P_sz else 2 * P_sz - 1 - c


def _to_zigzag(x, axis_name, cp):
    """Contiguous rank r holds half-chunks (2r, 2r+1) of its seq axis
    (axis 1); redistribute so rank g holds (g, 2P-1-g), returned as
    (low, high) arrays of half length."""
    s = x.shape[1]
    h0, h1 = x[:, : s // 2], x[:, s // 2:]
    perm_a = [(i, _zig_owner(2 * i, cp)) for i in range(cp)]
    perm_b = [(i, _zig_owner(2 * i + 1, cp)) for i in range(cp)]
    got_a = lax.ppermute(h0, axis_name, perm_a)   # carries chunk 2i
    got_b = lax.ppermute(h1, axis_name, perm_b)   # carries chunk 2i+1
    g = lax.axis_index(axis_name)
    # permA delivers chunk g when g is even (2i = g), else chunk 2P-1-g;
    # permB is complementary — order into (low=chunk g, high=chunk 2P-1-g)
    even = (g % 2) == 0
    low = jnp.where(even, got_a, got_b)
    high = jnp.where(even, got_b, got_a)
    return low, high


def _from_zigzag(low, high, axis_name, cp):
    """Inverse of :func:`_to_zigzag`: rank g holds chunks (g, 2P-1-g);
    return the contiguous local [s] = chunks (2r, 2r+1)."""
    g = lax.axis_index(axis_name)
    even = (g % 2) == 0
    # invert the forward bijections: Ainv returns the permA-delivered
    # chunk (the low one on even ranks) to its contiguous owner as h0
    via_a = jnp.where(even, low, high)
    via_b = jnp.where(even, high, low)
    perm_a_inv = [(_zig_owner(2 * i, cp), i) for i in range(cp)]
    perm_b_inv = [(_zig_owner(2 * i + 1, cp), i) for i in range(cp)]
    h0 = lax.ppermute(via_a, axis_name, perm_a_inv)
    h1 = lax.ppermute(via_b, axis_name, perm_b_inv)
    return jnp.concatenate([h0, h1], axis=1)


def zigzag_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    softmax_scale: Optional[float] = None,
    q_chunk_size: int = DEFAULT_Q_CHUNK,
) -> jax.Array:
    """Inside shard_map: q/k/v [b, s_local, heads, d], sequence
    contiguously sharded over ``axis_name``; returns the same layout.
    See module docstring for the algorithm."""
    if softmax_scale is None:
        softmax_scale = 1.0 / math.sqrt(q.shape[-1])
    cp = lax.psum(1, axis_name)
    g = lax.axis_index(axis_name)
    b, s, nh, d = q.shape
    ng = k.shape[2]
    qpg = nh // ng
    cs = s // 2                       # half-chunk length
    assert s % 2 == 0, "zigzag needs an even local sequence length"

    q_low, q_high = _to_zigzag(q, axis_name, cp)
    k_low, k_high = _to_zigzag(k, axis_name, cp)
    v_low, v_high = _to_zigzag(v, axis_name, cp)

    # this rank's q half-chunk ids (traced scalars)
    q_ids = (g, 2 * cp - 1 - g)
    q_parts = (q_low, q_high)

    # q rows are processed qc at a time inside each sub-block (same
    # bound as ring_self_attention: peak score memory [b, heads, qc, cs]
    # instead of [b, heads, cs, cs], which at long local sequences is
    # the [s, s]-scale tensor this stack cannot compile)
    qc = min(q_chunk_size, cs)
    while cs % qc != 0:
        qc -= 1
    n_qc = cs // qc

    def sub_block(q_i, q_id, k_c, v_c, k_id, m_a, l_a, a_a):
        """Streaming-softmax update of one (q-half, kv-half) pair,
        skipped entirely (lax.cond) when causally fully masked."""
        k_pos = k_id * cs + jnp.arange(cs)

        def live(args):
            def q_block(ci, carry_q):
                m_x, l_x, a_x = carry_q
                q_c = lax.dynamic_slice_in_dim(q_i, ci * qc, qc, axis=1)
                q_pos = q_id * cs + ci * qc + jnp.arange(qc)
                scores = _chunk_scores(q_c, k_c, softmax_scale)
                mask = jnp.ones((qc, cs), bool)
                if causal:
                    mask &= k_pos[None, :] <= q_pos[:, None]
                if sliding_window is not None:
                    mask &= k_pos[None, :] > q_pos[:, None] - sliding_window
                scores = jnp.where(mask[None, None, None], scores, NEG_INF)
                m_prev = lax.dynamic_slice_in_dim(m_x, ci * qc, qc, axis=3)
                l_prev = lax.dynamic_slice_in_dim(l_x, ci * qc, qc, axis=3)
                a_prev = lax.dynamic_slice_in_dim(a_x, ci * qc, qc, axis=3)
                m_c = jnp.max(scores, axis=-1)
                m_new = jnp.maximum(m_prev, m_c)
                p = jnp.exp(scores - m_new[..., None])
                p = jnp.where(mask[None, None, None], p, 0.0)
                alpha = jnp.exp(m_prev - m_new)
                l_new = l_prev * alpha + jnp.sum(p, axis=-1)
                o_c = jnp.einsum("bgpst,btgd->bgpsd", p,
                                 v_c.astype(jnp.float32))
                a_new = a_prev * alpha[..., None] + o_c
                return (
                    lax.dynamic_update_slice_in_dim(m_x, m_new, ci * qc, 3),
                    lax.dynamic_update_slice_in_dim(l_x, l_new, ci * qc, 3),
                    lax.dynamic_update_slice_in_dim(a_x, a_new, ci * qc, 3),
                )

            return lax.fori_loop(0, n_qc, q_block, args)

        skip = jnp.bool_(False)
        if causal:
            # kv half entirely in this q half's future
            skip = skip | (k_id > q_id)
        if sliding_window is not None:
            # kv half entirely before the window of every q row
            skip = skip | ((k_id + 1) * cs - 1 <= q_id * cs - sliding_window)
        return lax.cond(skip, lambda args: args, live, (m_a, l_a, a_a))

    def step(carry, _):
        k_l, k_h, v_l, v_h, src, accs = carry
        accs_new = []
        for qi in range(2):
            m_a, l_a, a_a = accs[qi]
            # incoming kv pair holds half-chunks (src, 2P-1-src)
            m_a, l_a, a_a = sub_block(q_parts[qi], q_ids[qi],
                                      k_l, v_l, src, m_a, l_a, a_a)
            m_a, l_a, a_a = sub_block(q_parts[qi], q_ids[qi],
                                      k_h, v_h, 2 * cp - 1 - src,
                                      m_a, l_a, a_a)
            accs_new.append((m_a, l_a, a_a))

        perm = [(i, (i + 1) % cp) for i in range(cp)]
        k_l2 = lax.ppermute(k_l, axis_name, perm)
        k_h2 = lax.ppermute(k_h, axis_name, perm)
        v_l2 = lax.ppermute(v_l, axis_name, perm)
        v_h2 = lax.ppermute(v_h, axis_name, perm)
        return (k_l2, k_h2, v_l2, v_h2, (src - 1) % cp,
                tuple(accs_new)), None

    def init_acc():
        return (jnp.full((b, ng, qpg, cs), NEG_INF, jnp.float32),
                jnp.zeros((b, ng, qpg, cs), jnp.float32),
                jnp.zeros((b, ng, qpg, cs, d), jnp.float32))

    carry0 = (k_low, k_high, v_low, v_high, g, (init_acc(), init_acc()))
    (_, _, _, _, _, accs), _ = lax.scan(
        jax.checkpoint(step), carry0, None, length=cp)

    outs = []
    for m, l, acc in accs:
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o = (acc / l_safe[..., None]).astype(q.dtype)  # [b,g,p,cs,d]
        outs.append(jnp.moveaxis(o, 3, 1).reshape(b, cs, nh, d))
    return _from_zigzag(outs[0], outs[1], axis_name, cp)


def zigzag_context_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    softmax_scale: Optional[float] = None,
    q_chunk_size: int = DEFAULT_Q_CHUNK,
):
    """shard_map wrapper mirroring ``context_parallel_attention``:
    global arrays with the sequence axis contiguously sharded over cp;
    nests under the pipeline engines' manual regions via
    ``topology.nesting_mesh``."""
    mesh, manual = topology.nesting_mesh(topology.CP_AXIS)
    if mesh is None:
        raise RuntimeError(
            "zigzag_context_attention called with no usable 'cp' axis in "
            "scope (callers gate on get_context_parallel_world_size() > 1)")
    if topology.CP_AXIS in manual:
        # cp already manual in the enclosing region (pre-0.6 jax full-
        # manual fallback): inputs are replicated over cp, plain local
        # attention is exact (see ring_attention.context_parallel_attention)
        from megatron_llm_tpu.ops.pallas.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal,
                               sliding_window=sliding_window,
                               softmax_scale=softmax_scale)
    fn = partial(
        zigzag_self_attention,
        axis_name=topology.CP_AXIS,
        causal=causal,
        sliding_window=sliding_window,
        softmax_scale=softmax_scale,
        q_chunk_size=q_chunk_size,
    )
    spec = P(None, topology.CP_AXIS, None, None)
    return topology.shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names=manual | {topology.CP_AXIS},
        check_vma=False,
    )(q, k, v)
