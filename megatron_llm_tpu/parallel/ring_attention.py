"""Ring attention: context-parallel exact attention for long sequences.

The reference has NO long-context attention parallelism (SURVEY §5.7 —
no ring attention, no Ulysses; its sequence parallelism is activation
*memory* sharding only).  This module is the TPU-native long-context
design: the sequence axis of activations is sharded over the ``cp`` mesh
axis, every device holds a contiguous Q chunk, and K/V chunks rotate
around the cp ring with ``lax.ppermute`` (one ICI hop per step) while each
device accumulates its Q-chunk's attention with the online-softmax
combine.  cp_size - 1 hops overlap with the chunk attention compute —
the classic Ring Attention schedule (Liu et al.) on XLA collectives.

Causality needs no per-step case analysis: the mask is derived from
*global* positions (rank * chunk + local index), so chunks from earlier in
the ring contribute fully, the diagonal chunk causally, later ones not at
all.  Autodiff through the scan + ppermute derives the reverse ring for
the backward pass.

Used inside ``shard_map`` manual over {'cp'} (dp/tp stay GSPMD-auto);
attention dispatch in ``models/transformer.py`` routes here when the mesh
has cp > 1.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from megatron_llm_tpu import topology

NEG_INF = -1e30


def _chunk_scores(q, k, scale):
    # q [b, sq, nh, d]; k [b, sk, ng, d] -> scores [b, ng, qpg, sq, sk] f32
    b, sq, nh, d = q.shape
    ng = k.shape[2]
    qpg = nh // ng
    qg = q.reshape(b, sq, ng, qpg, d)
    return jnp.einsum("bsgpd,btgd->bgpst", qg, k).astype(jnp.float32) * scale


DEFAULT_Q_CHUNK = 1024


def ring_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    softmax_scale: Optional[float] = None,
    q_chunk_size: int = DEFAULT_Q_CHUNK,
) -> jax.Array:
    """Exact attention over a cp-sharded sequence, inside shard_map.

    q/k/v: local chunks [b, s_local, heads, d]; sequence is contiguously
    sharded over ``axis_name`` (chunk r holds global positions
    [r*s_local, (r+1)*s_local)).

    Each ring step processes Q in ``q_chunk_size`` rows at a time (an
    inner scan), so peak score memory is [b, heads, qc, s_local] instead
    of [b, heads, s_local, s_local] — at 8k-per-device sequences that is
    the difference between ~0.5 GB and ~4 GB of fp32 scores per step.
    Q-rows are independent in attention, so the chunking is exact.
    """
    if softmax_scale is None:
        softmax_scale = 1.0 / math.sqrt(q.shape[-1])
    cp = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, s, nh, d = q.shape
    ng = k.shape[2]
    qpg = nh // ng
    # largest chunk <= q_chunk_size that divides s (a non-divisor would
    # let dynamic_slice clamp the final block and double-count tail rows)
    qc = min(q_chunk_size, s)
    while s % qc != 0:
        qc -= 1
    n_qc = s // qc

    def step(carry, _):
        kv, src, m_acc, l_acc, acc = carry
        k_c, v_c = kv
        k_pos = src * s + jnp.arange(s)

        def q_block(ci, carry_q):
            m_a, l_a, a_a = carry_q
            q_i = lax.dynamic_slice_in_dim(q, ci * qc, qc, axis=1)
            q_pos = my * s + ci * qc + jnp.arange(qc)
            scores = _chunk_scores(q_i, k_c, softmax_scale)  # [b,g,p,qc,s]
            mask = jnp.ones((qc, s), bool)
            if causal:
                mask &= k_pos[None, :] <= q_pos[:, None]
            if sliding_window is not None:
                mask &= k_pos[None, :] > q_pos[:, None] - sliding_window
            scores = jnp.where(mask[None, None, None], scores, NEG_INF)

            m_prev = lax.dynamic_slice_in_dim(m_a, ci * qc, qc, axis=3)
            l_prev = lax.dynamic_slice_in_dim(l_a, ci * qc, qc, axis=3)
            a_prev = lax.dynamic_slice_in_dim(a_a, ci * qc, qc, axis=3)
            m_c = jnp.max(scores, axis=-1)               # [b, g, p, qc]
            m_new = jnp.maximum(m_prev, m_c)
            p = jnp.exp(scores - m_new[..., None])
            p = jnp.where(mask[None, None, None], p, 0.0)
            alpha = jnp.exp(m_prev - m_new)
            l_new = l_prev * alpha + jnp.sum(p, axis=-1)
            o_c = jnp.einsum("bgpst,btgd->bgpsd", p,
                             v_c.astype(jnp.float32))
            a_new = a_prev * alpha[..., None] + o_c
            return (
                lax.dynamic_update_slice_in_dim(m_a, m_new, ci * qc, 3),
                lax.dynamic_update_slice_in_dim(l_a, l_new, ci * qc, 3),
                lax.dynamic_update_slice_in_dim(a_a, a_new, ci * qc, 3),
            )

        m_acc, l_acc, acc = lax.fori_loop(
            0, n_qc, q_block, (m_acc, l_acc, acc))

        # rotate K/V to the next ring position.  The final rotation's
        # result is discarded (the carry ends the scan) — one redundant
        # ICI hop per call, accepted to keep the scan body uniform; a
        # cond-guarded collective would cost more in program complexity
        # than the 1/cp bandwidth it saves.
        perm = [(i, (i + 1) % cp) for i in range(cp)]
        kv_next = (lax.ppermute(k_c, axis_name, perm),
                   lax.ppermute(v_c, axis_name, perm))
        src_next = (src - 1) % cp
        return (kv_next, src_next, m_acc, l_acc, acc), None

    m0 = jnp.full((b, ng, qpg, s), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, ng, qpg, s), jnp.float32)
    acc0 = jnp.zeros((b, ng, qpg, s, d), jnp.float32)
    (_, _, m, l, acc), _ = lax.scan(
        jax.checkpoint(step), ((k, v), my, m0, l0, acc0), None, length=cp
    )
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l_safe[..., None]).astype(q.dtype)    # [b, ng, qpg, s, d]
    return jnp.moveaxis(out, 3, 1).reshape(b, s, nh, d)


def context_parallel_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    softmax_scale: Optional[float] = None,
    q_chunk_size: int = DEFAULT_Q_CHUNK,
):
    """shard_map wrapper: q/k/v are global arrays with the sequence axis
    sharded over cp ('batch','seq_cp',heads,d); returns same layout.

    Nests under the pipeline engine's pp-manual shard_map: inside a manual
    region jax requires the *abstract* context mesh (whose pp axis is
    already Manual) and the re-declaration of its manual axes
    (``topology.nesting_mesh``)."""
    mesh, manual = topology.nesting_mesh(topology.CP_AXIS)
    if mesh is None:
        raise RuntimeError(
            "context_parallel_attention called with no usable 'cp' axis in "
            "scope (callers gate on get_context_parallel_world_size() > 1; "
            "an enclosing custom mesh without a cp axis cannot host ring "
            "attention)")
    if topology.CP_AXIS in manual:
        # cp is ALREADY manual in the enclosing region (pre-0.6 jax,
        # where topology.shard_map full-manualizes): q/k/v arrive
        # replicated over cp, so plain local attention is exact and a
        # nested cp-manual region is neither legal nor needed
        from megatron_llm_tpu.ops.pallas.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal,
                               sliding_window=sliding_window,
                               softmax_scale=softmax_scale)
    fn = partial(
        ring_self_attention,
        axis_name=topology.CP_AXIS,
        causal=causal,
        sliding_window=sliding_window,
        softmax_scale=softmax_scale,
        q_chunk_size=q_chunk_size,
    )
    spec = P(None, topology.CP_AXIS, None, None)
    return topology.shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names=manual | {topology.CP_AXIS},
        check_vma=False,
    )(q, k, v)
