"""Ulysses-style all-to-all sequence parallelism (context-parallel
algorithm #2, alongside ``ring_attention``).

The reference has no sequence/context parallelism at all (SURVEY §5.7);
this module implements the DeepSpeed-Ulysses formulation on the TPU
``cp`` mesh axis: activations arrive sequence-sharded
``[b, s/P, heads, d]``; one ``lax.all_to_all`` re-shards **heads** and
gathers the **full sequence** per device, attention runs locally over
the whole sequence with ``heads/P`` heads (so the tuned Pallas flash
kernel applies unchanged — no online-softmax carry across devices), and
a second all-to-all restores the sequence sharding.

Trade-off vs ring attention (``parallel/ring_attention.py``): Ulysses
moves 2x the activation bytes per layer through ICI but keeps the
attention arithmetic completely local and dense (no per-hop masking
waste for causal chunks and no cp-1 ppermute latency chain); ring
shards heads nowhere, so it supports head counts < cp.  Requirements
here: ``num_heads % cp == 0`` and ``kv_heads % cp == 0`` — callers
(``models/transformer.attention``) route to ring when the head counts
don't divide.

Reference for the algorithm: DeepSpeed-Ulysses (arXiv 2309.14509);
public TPU precedent for all-to-all head/sequence re-sharding is the
GSPMD all-to-all pattern used by the t5x/MaxText MoE stacks.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from megatron_llm_tpu import topology


def ulysses_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    softmax_scale: Optional[float] = None,
):
    """Inside shard_map: q [b, s/P, nh, d]; k, v [b, s/P, ng, d] with the
    sequence contiguously sharded over ``axis_name`` (chunk r = global
    positions [r*s_local, (r+1)*s_local)).  Returns [b, s/P, nh, d]."""
    if softmax_scale is None:
        softmax_scale = 1.0 / math.sqrt(q.shape[-1])
    P_sz = lax.psum(1, axis_name)
    nh, ng = q.shape[2], k.shape[2]
    assert nh % P_sz == 0 and ng % P_sz == 0, (
        f"ulysses needs heads divisible by cp: nh={nh} ng={ng} cp={P_sz}")

    # a2a #1: scatter heads, gather sequence -> [b, s, nh/P, d].  Parts
    # from rank r' are its contiguous seq chunk, concatenated in rank
    # order, so the gathered axis is the global sequence in order.
    def scatter_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)

    # local attention over the FULL sequence with nh/P heads: the exact
    # same kernel path as single-device attention (pallas flash on TPU,
    # reference math elsewhere), so all flash tuning carries over.
    # sharded_ variant: tp/dp are still GSPMD-auto inside this cp-manual
    # region, and a Mosaic call can't be auto-partitioned over them
    from megatron_llm_tpu.ops.pallas.flash_attention import (
        sharded_flash_attention,
    )

    ctx = sharded_flash_attention(
        qg, kg, vg, causal=causal, sliding_window=sliding_window,
        softmax_scale=softmax_scale)

    # a2a #2: scatter sequence, gather heads -> [b, s/P, nh, d]
    return lax.all_to_all(ctx, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_context_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    softmax_scale: Optional[float] = None,
):
    """shard_map wrapper mirroring ``ring_attention.context_parallel_attention``:
    global arrays with the sequence axis sharded over cp; nests under the
    pipeline engines' manual regions via ``topology.nesting_mesh``."""
    mesh, manual = topology.nesting_mesh(topology.CP_AXIS)
    if mesh is None:
        raise RuntimeError(
            "ulysses_context_attention called with no usable 'cp' axis in "
            "scope (callers gate on get_context_parallel_world_size() > 1)")
    if topology.CP_AXIS in manual:
        # cp already manual in the enclosing region (pre-0.6 jax full-
        # manual fallback): inputs are replicated over cp, plain local
        # attention is exact (see ring_attention.context_parallel_attention)
        from megatron_llm_tpu.ops.pallas.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal,
                               sliding_window=sliding_window,
                               softmax_scale=softmax_scale)
    fn = partial(
        ulysses_self_attention,
        axis_name=topology.CP_AXIS,
        causal=causal,
        sliding_window=sliding_window,
        softmax_scale=softmax_scale,
    )
    spec = P(None, topology.CP_AXIS, None, None)
    return topology.shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names=manual | {topology.CP_AXIS},
        check_vma=False,
    )(q, k, v)


def ulysses_supported(num_heads: int, num_kv_heads: int, cp: int) -> bool:
    return num_heads % cp == 0 and num_kv_heads % cp == 0
