"""Logical-axis sharding rules and constraint helpers (GSPMD path).

The reference encodes parallel placement *imperatively*: each TP layer calls
the right collective by hand (``megatron/core/tensor_parallel/layers.py``).
The TPU-native equivalent is *declarative*: params and activations carry
logical axis names, a rules table maps logical axes to mesh axes, and
``with_sharding_constraint`` pins the placement; XLA/GSPMD inserts the
collectives (the same allreduce/allgather/reduce-scatter pattern — see the
module docstring of ``parallel/mappings.py`` for the explicit versions).

Logical axes used across the framework:

| logical    | meaning                           | mesh axis |
|------------|-----------------------------------|-----------|
| 'batch'    | microbatch dim of activations     | dp        |
| 'seq'      | sequence dim (activations)        | None (tp when sequence-parallel region) |
| 'hidden'   | model hidden dim                  | None      |
| 'vocab'    | vocabulary dim (embedding, head)  | tp        |
| 'ffn'      | MLP intermediate dim              | tp        |
| 'heads'    | attention-head dim (q/k/v/o)      | tp        |
| 'kv_heads' | KV-head dim under GQA             | tp        |
| 'stage'    | stacked pipeline-stage dim        | pp        |
| 'expert'   | MoE expert dim                    | dp (EP folded into dp) |
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from megatron_llm_tpu import topology

DEFAULT_RULES = {
    # 'batch' under the default rules is resolved dynamically by
    # _batch_axes() — ('slice', 'dp') in a multi-slice mesh, 'dp'
    # otherwise; this entry is the custom-rules fallback.
    "batch": topology.DP_AXIS,
    # 'seq' rides the cp axis: a no-op at cp=1, contiguous context-parallel
    # sequence sharding when cp>1 (ring attention handles the cross-chunk
    # attention; everything else is position-wise)
    "seq": topology.CP_AXIS,
    # sequence-parallel (Megatron SP) regions; composes with cp
    "seq_tp": (topology.CP_AXIS, topology.TP_AXIS),
    "seq_cp": topology.CP_AXIS,
    "hidden": None,
    "vocab": topology.TP_AXIS,
    "ffn": topology.TP_AXIS,
    "heads": topology.TP_AXIS,
    "kv_heads": topology.TP_AXIS,
    "stage": topology.PP_AXIS,
    "expert": topology.DP_AXIS,
    "dp_shard": topology.DP_AXIS,  # ZeRO-1 optimizer-state sharding
    None: None,
}


def _batch_axes():
    """Mesh axes for the logical 'batch' dim, resolved at trace time.

    Multi-slice runs span the batch over ('slice', 'dp') — except inside
    the hierarchical slice-vmap forward (multislice.sliced_forward),
    where the vmap's spmd_axis_name supplies the 'slice' entry and the
    model-internal constraint must stay plain 'dp'."""
    from megatron_llm_tpu import multislice

    if multislice.hierarchical_forward_active():
        return topology.DP_AXIS
    axes = topology.data_axes()
    return axes if len(axes) > 1 else axes[0]


def logical_to_mesh(
    logical_spec: Sequence[Optional[str]], rules=None
) -> P:
    rules = rules or DEFAULT_RULES
    def resolve(a):
        if a == "batch" and rules is DEFAULT_RULES:
            return _batch_axes()
        return rules.get(a)
    return P(*(resolve(a) for a in logical_spec))


def _mesh() -> Optional[Mesh]:
    return topology._MESH


def _strip_manual_axes(spec: P) -> P:
    """Drop mesh axes an enclosing manual region already bound (pre-0.6
    jax, where ``topology.shard_map`` full-manualizes): constraining a
    manual axis is a ValueError, and the array is device-local along it
    anyway, so the constraint is meaningless there."""
    bound = topology._bound_manual_axis_sizes()
    if not bound:
        return spec

    def keep(a):
        if isinstance(a, (tuple, list)):
            kept = tuple(x for x in a if x not in bound)
            return kept if kept else None
        return None if a in bound else a

    return P(*(keep(a) for a in spec))


def constrain(x: jax.Array, *logical_axes: Optional[str], rules=None) -> jax.Array:
    """``with_sharding_constraint`` by logical axis names; no-op when no mesh
    is initialized (pure single-device runs and numpy-golden tests)."""
    mesh = _mesh()
    if mesh is None or all(a is None for a in logical_axes):
        return x
    spec = _strip_manual_axes(logical_to_mesh(logical_axes, rules))
    if all(a is None for a in spec):
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def with_logical_constraint(tree, specs, rules=None):
    """Tree-map constrain: ``specs`` is a pytree of logical-axis tuples
    matching ``tree``."""
    mesh = _mesh()
    if mesh is None:
        return tree
    def one(x, s):
        spec = _strip_manual_axes(logical_to_mesh(s, rules))
        if all(a is None for a in spec):
            return x
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(
        one, tree, specs, is_leaf=lambda v: v is None)


def make_shardings(specs, rules=None, mesh: Optional[Mesh] = None):
    """Pytree of logical-axis tuples -> pytree of NamedShardings.

    ``None`` spec entries pass through as ``None`` — partial trees
    (e.g. a LoRA adapter tree whose non-target positions are structural
    placeholders) shard only where a spec exists."""
    mesh = mesh or topology.get_mesh()
    return jax.tree_util.tree_map(
        lambda s: (None if s is None
                   else NamedSharding(mesh, logical_to_mesh(s, rules))),
        specs,
        is_leaf=lambda v: isinstance(v, tuple) or v is None,
    )


def shard_params(params, specs, rules=None, mesh: Optional[Mesh] = None):
    """device_put a host-side param pytree onto the mesh per its specs.

    ``None`` placeholders (both sides) pass through untouched, so
    partial trees (LoRA adapters) shard without a fully-populated spec
    tree."""
    shardings = make_shardings(specs, rules, mesh)
    return jax.tree_util.tree_map(
        lambda x, s: x if s is None else jax.device_put(x, s),
        params, shardings,
        is_leaf=lambda v: v is None,
    )
