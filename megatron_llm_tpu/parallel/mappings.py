"""Autograd-aware collective mappings over the tp axis, for shard_map code.

Reference: ``megatron/core/tensor_parallel/mappings.py`` — seven
torch.autograd.Function classes pairing a forward collective with its
transpose in backward:

| reference class (mappings.py line)              | here |
|-------------------------------------------------|------|
| _CopyToModelParallelRegion (:127)               | copy_to_tensor_model_parallel_region |
| _ReduceFromModelParallelRegion (:143)           | reduce_from_tensor_model_parallel_region |
| _ScatterToModelParallelRegion (:159)            | scatter_to_tensor_model_parallel_region |
| _GatherFromModelParallelRegion (:175)           | gather_from_tensor_model_parallel_region |
| _ScatterToSequenceParallelRegion (:191)         | scatter_to_sequence_parallel_region |
| _GatherFromSequenceParallelRegion (:207)        | gather_from_sequence_parallel_region |
| _ReduceScatterToSequenceParallelRegion (:233)   | reduce_scatter_to_sequence_parallel_region |

These are used by the explicit shard_map implementation path (pipeline
stages, tests mirroring ``tests/tensor_parallel/test_mappings.py``).  The
pjit/GSPMD path doesn't call them — XLA inserts the same collectives from
sharding constraints.

Each is a ``jax.custom_vjp`` so the backward collective is exactly the
reference's, independent of JAX's default transposition rules.
All functions take the mesh axis name as a static first argument.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _split_last(x, n, idx):
    size = x.shape[-1] // n
    return lax.dynamic_slice_in_dim(x, idx * size, size, axis=-1)


def _split_first(x, n, idx):
    size = x.shape[0] // n
    return lax.dynamic_slice_in_dim(x, idx * size, size, axis=0)


# -- copy: identity fwd, allreduce bwd (mappings.py:127-141) ----------------

@partial(jax.custom_vjp, nondiff_argnums=(0,))
def copy_to_tensor_model_parallel_region(axis_name: str, x):
    return x


def _copy_fwd(axis_name, x):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


copy_to_tensor_model_parallel_region.defvjp(_copy_fwd, _copy_bwd)


# -- reduce: allreduce fwd, identity bwd (mappings.py:143-157) --------------

@partial(jax.custom_vjp, nondiff_argnums=(0,))
def reduce_from_tensor_model_parallel_region(axis_name: str, x):
    return lax.psum(x, axis_name)


def _reduce_fwd(axis_name, x):
    return lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


reduce_from_tensor_model_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


# -- scatter: split last dim fwd, all-gather bwd (mappings.py:159-173) ------

@partial(jax.custom_vjp, nondiff_argnums=(0,))
def scatter_to_tensor_model_parallel_region(axis_name: str, x):
    n = lax.psum(1, axis_name)
    return _split_last(x, n, lax.axis_index(axis_name))


def _scatter_fwd(axis_name, x):
    return scatter_to_tensor_model_parallel_region(axis_name, x), None


def _scatter_bwd(axis_name, _, g):
    return (lax.all_gather(g, axis_name, axis=g.ndim - 1, tiled=True),)


scatter_to_tensor_model_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


# -- gather: all-gather last dim fwd, split bwd (mappings.py:175-189) -------

@partial(jax.custom_vjp, nondiff_argnums=(0,))
def gather_from_tensor_model_parallel_region(axis_name: str, x):
    return lax.all_gather(x, axis_name, axis=x.ndim - 1, tiled=True)


def _gather_fwd(axis_name, x):
    return gather_from_tensor_model_parallel_region(axis_name, x), None


def _gather_bwd(axis_name, _, g):
    n = lax.psum(1, axis_name)
    return (_split_last(g, n, lax.axis_index(axis_name)),)


gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# -- SP scatter: split seq (first) dim fwd, all-gather bwd (:191-205) -------

@partial(jax.custom_vjp, nondiff_argnums=(0,))
def scatter_to_sequence_parallel_region(axis_name: str, x):
    n = lax.psum(1, axis_name)
    return _split_first(x, n, lax.axis_index(axis_name))


def _sp_scatter_fwd(axis_name, x):
    return scatter_to_sequence_parallel_region(axis_name, x), None


def _sp_scatter_bwd(axis_name, _, g):
    return (lax.all_gather(g, axis_name, axis=0, tiled=True),)


scatter_to_sequence_parallel_region.defvjp(_sp_scatter_fwd, _sp_scatter_bwd)


# -- SP gather: all-gather seq fwd, reduce-scatter bwd (:207-231) -----------
# (the backward is reduce-scatter, NOT split: forward output is consumed by
# tp-replicated compute, so grads from all tp ranks must be summed)

@partial(jax.custom_vjp, nondiff_argnums=(0,))
def gather_from_sequence_parallel_region(axis_name: str, x):
    return lax.all_gather(x, axis_name, axis=0, tiled=True)


def _sp_gather_fwd(axis_name, x):
    return gather_from_sequence_parallel_region(axis_name, x), None


def _sp_gather_bwd(axis_name, _, g):
    return (lax.psum_scatter(g, axis_name, scatter_dimension=0, tiled=True),)


gather_from_sequence_parallel_region.defvjp(_sp_gather_fwd, _sp_gather_bwd)


# -- SP reduce-scatter fwd, all-gather bwd (:233-251) -----------------------

@partial(jax.custom_vjp, nondiff_argnums=(0,))
def reduce_scatter_to_sequence_parallel_region(axis_name: str, x):
    return lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)


def _sp_rs_fwd(axis_name, x):
    return reduce_scatter_to_sequence_parallel_region(axis_name, x), None


def _sp_rs_bwd(axis_name, _, g):
    return (lax.all_gather(g, axis_name, axis=0, tiled=True),)


reduce_scatter_to_sequence_parallel_region.defvjp(_sp_rs_fwd, _sp_rs_bwd)
