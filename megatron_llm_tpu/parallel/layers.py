"""Tensor-parallel layers: vocab-parallel embedding, column/row parallel linear.

Reference: ``megatron/core/tensor_parallel/layers.py`` —
``VocabParallelEmbedding`` (:128-210), ``ColumnParallelLinear`` (:410-563),
``RowParallelLinear`` (:566-701), and the fused autograd function
``LinearWithGradAccumulationAndAsyncCommunication`` (:213-317) that
(a) all-gathers sequence-parallel inputs in forward, (b) overlaps the
backward grad allreduce / reduce-scatter with the weight-grad GEMM, and
(c) optionally accumulates wgrad straight into the fp32 main-grad buffer
with a CUDA kernel.

TPU design: the layers are pure functions over param pytrees; placement is
declared with logical-axis sharding constraints (``parallel/sharding.py``)
and GSPMD inserts the collectives:

* ColumnParallel: kernel sharded ('hidden','ffn'→tp).  With sequence
  parallelism the input activation is sharded ('batch','seq_tp',None) and
  XLA materialises the same all-gather-then-GEMM forward / reduce-scatter
  backward as the reference's fused function — and *schedules it to overlap*
  with neighbouring compute, replacing the CUDA-stream trick that required
  CUDA_DEVICE_MAX_CONNECTIONS=1 (layers.py:344-351).
* RowParallel: kernel sharded ('ffn'→tp,'hidden'); output constrained to
  replicated (allreduce) or sequence-sharded (reduce-scatter, the SP path).
* Gradient accumulation into fp32 main grads is the optimizer's job here
  (grads are computed in fp32 master space by jax.grad with a cast), so no
  wgrad-fusion kernel is needed.

The math ignores mesh entirely — the same functions run unsharded in unit
tests and golden comparisons.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from megatron_llm_tpu.parallel.sharding import constrain
from megatron_llm_tpu.quantization import dequantize_kernel


# ---------------------------------------------------------------------------
# Init methods (reference: megatron/model/utils.py init_method_normal /
# scaled_init_method_normal; full-tensor init then slice semantics in
# layers.py:79-125 — with a single-controller mesh we just init the full
# tensor, so TP-size-invariant initialization holds by construction).
# ---------------------------------------------------------------------------

def init_method_normal(std: float):
    def init(key, shape, dtype=jnp.float32):
        return std * jax.random.normal(key, shape, dtype=jnp.float32).astype(dtype)

    return init


def init_method_for(cfg):
    """Trunk weight init from config: xavier-uniform when the reference's
    ``--init_method_xavier_uniform`` is set, else normal(std)."""
    if getattr(cfg, "init_method_xavier_uniform", False):
        glorot = jax.nn.initializers.glorot_uniform()

        def init(key, shape, dtype=jnp.float32):
            if len(shape) >= 2:
                return glorot(key, shape, dtype)
            return jnp.zeros(shape, dtype)

        return init
    return init_method_normal(cfg.init_method_std)


def scaled_init_method_normal(std: float, num_layers: int):
    scaled = std / math.sqrt(2.0 * num_layers)
    return init_method_normal(scaled)


def init_linear_params(
    key,
    in_dim: int,
    out_dim: int,
    *,
    bias: bool = True,
    init_method=None,
    dtype=jnp.float32,
):
    if init_method is None:
        init_method = init_method_normal(0.02)
    params = {"kernel": init_method(key, (in_dim, out_dim), dtype)}
    if bias:
        params["bias"] = jnp.zeros((out_dim,), dtype=dtype)
    return params


def init_embedding_params(
    key, vocab_size: int, hidden: int, *, init_method=None, dtype=jnp.float32
):
    if init_method is None:
        init_method = init_method_normal(0.02)
    return {"embedding": init_method(key, (vocab_size, hidden), dtype)}


# ---------------------------------------------------------------------------
# Apply functions.
# ---------------------------------------------------------------------------

def vocab_parallel_embedding(
    tokens: jax.Array, params, compute_dtype=None
) -> jax.Array:
    """Embedding lookup over a vocab-sharded table.

    Reference (layers.py:128-210) masks out-of-shard ids, looks up locally
    and allreduces.  Under GSPMD a gather from a ('vocab'→tp,'hidden') table
    lowers to exactly that masked-lookup + allreduce; we just write the
    gather.
    """
    table = params["embedding"]
    if compute_dtype is not None:
        table = table.astype(compute_dtype)
    out = jnp.take(table, tokens, axis=0)
    return constrain(out, "batch", "seq", None)


def _lora_delta(x, params):
    """LoRA low-rank path (lora.py): two thin matmuls, never the
    materialized [in, out] update.  lora_scale is a CONSTANT (alpha/r):
    stop_gradient keeps it out of training even though it rides in the
    trainable tree for structure (the optimizer also WD-excludes it)."""
    a = params["lora_A"].astype(x.dtype)
    b = params["lora_B"].astype(x.dtype)
    scale = jax.lax.stop_gradient(params["lora_scale"]).astype(x.dtype)
    return jnp.einsum("...r,ro->...o",
                      jnp.einsum("...i,ir->...r", x, a), b) * scale


def column_parallel_linear(
    x: jax.Array,
    params,
    *,
    out_logical: str = "ffn",
    sequence_parallel: bool = False,
    compute_dtype=None,
    skip_bias_add: bool = False,
):
    """y = x @ W (+ b); W is output-dim sharded over tp.

    Reference: ColumnParallelLinear.forward (layers.py:531-563).  When
    ``sequence_parallel`` the incoming x is sequence-sharded and GSPMD
    all-gathers it (the reference's explicit fwd all-gather,
    layers.py:225-243).
    """
    kernel = dequantize_kernel(params, compute_dtype)
    bias = params.get("bias")
    if compute_dtype is not None:
        bias = bias.astype(compute_dtype) if bias is not None else None
    if sequence_parallel:
        x = constrain(x, "batch", "seq_tp", None)
    y = jnp.einsum("...h,hf->...f", x, kernel)
    if "lora_A" in params:
        y = y + _lora_delta(x, params)
    y = constrain(y, "batch", "seq", out_logical)
    if bias is not None and not skip_bias_add:
        y = y + bias
    if skip_bias_add:
        return y, bias
    return y


def row_parallel_linear(
    x: jax.Array,
    params,
    *,
    in_logical: str = "ffn",
    sequence_parallel: bool = False,
    compute_dtype=None,
    skip_bias_add: bool = False,
):
    """y = x @ W (+ b); W is input-dim sharded over tp, so the partial
    products are summed across tp.

    Reference: RowParallelLinear.forward (layers.py:665-701) — allreduce of
    the output, or reduce-scatter along sequence when sequence-parallel.
    GSPMD derives the same from the constraint on y: ('batch','seq',None)
    forces allreduce; ('batch','seq_tp',None) forces reduce-scatter.
    Bias is added *after* the reduction, on the full output (reference adds
    bias post-reduction so it is applied once, not tp times).
    """
    kernel = dequantize_kernel(params, compute_dtype)
    bias = params.get("bias")
    if compute_dtype is not None:
        bias = bias.astype(compute_dtype) if bias is not None else None
    x = constrain(x, "batch", "seq", in_logical)
    y = jnp.einsum("...f,fh->...h", x, kernel)
    if "lora_A" in params:
        y = y + _lora_delta(x, params)
    if sequence_parallel:
        y = constrain(y, "batch", "seq_tp", None)
    else:
        y = constrain(y, "batch", "seq", None)
    if bias is not None and not skip_bias_add:
        y = y + bias
    if skip_bias_add:
        return y, bias
    return y


def parallel_lm_logits(
    hidden: jax.Array,
    word_embedding_or_head: jax.Array,
    *,
    sequence_parallel: bool = False,
    compute_dtype=None,
) -> jax.Array:
    """Logits = hidden @ E^T over the (tied or untied) vocab-sharded matrix.

    Reference: ``parallel_lm_logits`` (megatron/model/language_model.py:24-53)
    — a column-parallel matmul against the embedding transpose, output kept
    vocab-parallel (logits feed the vocab-parallel CE).
    """
    w = word_embedding_or_head
    if compute_dtype is not None:
        w = w.astype(compute_dtype)
    if sequence_parallel:
        hidden = constrain(hidden, "batch", "seq_tp", None)
    logits = jnp.einsum("...h,vh->...v", hidden, w)
    return constrain(logits, "batch", "seq", "vocab")
