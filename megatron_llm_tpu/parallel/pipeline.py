"""Pipeline-parallel engine: compiled lock-step schedules over the ``pp``
mesh axis.

Reference: ``megatron/schedules.py`` (1F1B :606-722, interleaved :253-502)
+ ``megatron/p2p_communication.py`` (batched NCCL isend/irecv :101-251) +
layer-to-stage assignment (``megatron/model/transformer.py:1045-1090``) +
embedding-tie grad sync across first/last stages
(``megatron/optimizer/optimizer.py:203-229``).

TPU re-design — none of that machinery survives translation.  Two engines,
both a single jitted ``lax.scan`` over pipeline ticks inside a ``shard_map``
that is *manual over pp only* (dp/tp stay under GSPMD, so tensor-parallel
collectives inside each stage remain compiler-placed), with ``lax.ppermute``
as the p2p isend/irecv replacement:

1. **Streaming schedule** (``build_pipeline_loss_fn``) — autodiff engine,
   supports interleaved virtual pipelining (VPP).  Work items are
   (microbatch m, virtual chunk v) pairs; device k executes item
   ``w = g*S*V + v*S + r`` (mixed radix, m = g*S + r) at tick ``t = w + k``.
   The mapping is collision-free (each device runs exactly one chunk per
   tick) and gives the interleaved schedule's bubble, (S-1)/(M*V + S - 1)
   of fine ticks — the same 1/V bubble shrink as the reference's
   interleaved 1F1B (schedules.py:253-502).  Microbatch t's embedding is
   computed *inside* tick t on the first stage and cross entropy is
   streamed *inside* the tick on the last stage, so nothing of size
   O(M) or O(vocab x global-batch) is ever materialized.  Backward is
   autodiff through the scan (the transpose of ``ppermute`` is the
   reverse rotation); per-tick ``jax.checkpoint`` plus an outer blocked
   scan bound live activations to O(sqrt(T)) tick-carries.

2. **Manual 1F1B** (``build_pipeline_grad_fn``) — hand-written backward
   with the reference's O(S) in-flight activation cap
   (schedules.py:606-722).  Each tick does one forward chunk AND one
   backward chunk (the steady-state 1F1B rhythm); forward chunk inputs
   are stashed in a ring buffer of 2S slots, backward recomputes the
   chunk from the stashed input (``jax.vjp``) and accumulates parameter
   gradients in the scan carry.  Nothing is ever autodiffed through the
   scan, so activation memory is FLAT in the number of microbatches:
   carry = one fwd activation + one bwd cotangent + 2S stash slots +
   the gradient accumulators.  Backward of microbatch m runs on device k
   at tick ``m + 2S - 1 - k``; cotangents ride the reverse rotation.

* **Embedding and LM head live inside the shard_map** replicated over pp
  (still vocab-sharded over tp by GSPMD); every stage computes them each
  tick and the results are masked to the owning stage.  In lock-step SPMD
  the tick latency is the max over stages either way, which is exactly
  the reference's bottleneck (its last stage pays head+CE per microbatch).
* **Embedding tie**: the word embedding is one logical parameter used at
  ingest (lookup) and by the head (logits); in the autodiff engine its
  gradient sums both uses by linearity, in the manual engine both
  contributions are accumulated per stage and summed across pp outside
  the shard_map — the reference's embedding-group all-reduce
  (optimizer.py:203-229) has no analogue to write.  The word table stays
  **vocab-sharded over tp**: the lookup is ``vocab_parallel_lookup_manual``
  (masked local gather + tp-psum inside a nested tp-manual shard_map, the
  reference's VocabParallelEmbedding), with a local one-hot-einsum
  backward — XLA's gather/scatter partitioners, which check-fail on
  vocab-sharded operands under the manual submesh, never see it.

Layer-to-stage assignment is a *sharding spec*, not code: the stacked
layer axis [L, ...] is sharded over pp, giving each stage a contiguous
block of L/S rows.  For VPP the stacking order is **stage-major**
(device k's rows hold its V chunks contiguously, chunk v of device k =
natural layers [(v*S+k)*cl, (v*S+k+1)*cl)); use
``permute_layer_stack`` / ``unpermute_layer_stack`` to convert
(reference chunk math: transformer.py:1045-1090).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from megatron_llm_tpu import topology
from megatron_llm_tpu.config import TransformerConfig
from megatron_llm_tpu.models.language_model import (
    embedding_forward,
    lm_head_weight,
)
from megatron_llm_tpu.models.transformer import rotary_freqs, transformer_layer
from megatron_llm_tpu.ops.cross_entropy import vocab_parallel_cross_entropy
from megatron_llm_tpu.ops.layernorm import apply_norm
from megatron_llm_tpu.parallel.layers import parallel_lm_logits

# ---------------------------------------------------------------------------
# VPP layer-stack layout
# ---------------------------------------------------------------------------

def vpp_stage_major_permutation(num_layers: int, pp: int, vpp: int):
    """Index array ``perm`` with ``stage_major = natural[perm]``.

    Stacked row ``j = k*(L/S) + v*cl + i`` holds natural layer
    ``(v*S + k)*cl + i`` so that a P('pp') sharding of the leading axis
    gives device k exactly its V interleaved chunks, in chunk order.
    """
    L, S, V = num_layers, pp, vpp
    assert L % (S * V) == 0, f"num_layers {L} must divide pp*vpp {S * V}"
    cl = L // (S * V)
    perm = np.empty(L, np.int64)
    j = 0
    for k in range(S):
        for v in range(V):
            for i in range(cl):
                perm[j] = (v * S + k) * cl + i
                j += 1
    return perm


def permute_layer_stack(layers, num_layers: int, pp: int, vpp: int):
    """Natural layer order -> stage-major order (no-op when vpp<=1)."""
    if vpp <= 1:
        return layers
    perm = vpp_stage_major_permutation(num_layers, pp, vpp)
    return jax.tree_util.tree_map(lambda x: x[perm], layers)


def unpermute_layer_stack(layers, num_layers: int, pp: int, vpp: int):
    """Stage-major order -> natural layer order (no-op when vpp<=1)."""
    if vpp <= 1:
        return layers
    perm = vpp_stage_major_permutation(num_layers, pp, vpp)
    inv = np.argsort(perm)
    return jax.tree_util.tree_map(lambda x: x[inv], layers)


def convert_params_layout(params, num_layers: int, pp: int, vpp: int,
                          *, to_stage_major: bool):
    """Permute the ``transformer.layers`` subtree of a params-like pytree
    between natural order (checkpoints, converters) and stage-major
    training order.  No-op when vpp<=1 or the subtree is absent."""
    if vpp <= 1 or params is None:
        return params
    tr = params.get("transformer") if isinstance(params, dict) else None
    if not isinstance(tr, dict) or "layers" not in tr:
        return params
    fn = permute_layer_stack if to_stage_major else unpermute_layer_stack
    out = dict(params)
    out["transformer"] = dict(tr)
    out["transformer"]["layers"] = fn(tr["layers"], num_layers, pp, vpp)
    return out


def convert_opt_state_layout(opt_state, num_layers: int, pp: int, vpp: int,
                             *, to_stage_major: bool):
    """Apply ``convert_params_layout`` to every params-shaped tree inside
    an ``OptimizerState`` (exp_avg / exp_avg_sq / master_params)."""
    if vpp <= 1 or opt_state is None:
        return opt_state

    def conv(tree):
        return convert_params_layout(tree, num_layers, pp, vpp,
                                     to_stage_major=to_stage_major)

    return opt_state._replace(
        exp_avg=conv(opt_state.exp_avg),
        exp_avg_sq=conv(opt_state.exp_avg_sq),
        master_params=conv(opt_state.master_params),
    )


# ---------------------------------------------------------------------------
# Shared per-tick pieces
# ---------------------------------------------------------------------------

def _decode_item(w, M: int, S: int, V: int):
    """Work item w -> (microbatch m, chunk v, valid).  Mixed radix
    w = g*(S*V) + v*S + r with m = g*S + r; V==1 degenerates to m = w."""
    valid = (w >= 0) & (w < M * V)
    wc = jnp.clip(w, 0, M * V - 1)
    if V == 1:
        return wc, jnp.zeros_like(wc), valid
    g = wc // (S * V)
    rem = wc % (S * V)
    v = rem // S
    r = rem % S
    return g * S + r, v, valid


def _index_mb(arr, m):
    return lax.dynamic_index_in_dim(arr, m, 0, keepdims=False)


def _pipeline_embedding_layout(tree, mesh):
    """Replicate the small aux embedding tables (learned position /
    tokentype — their in-shard_map gathers need a replicated operand);
    the word table keeps its vocab(tp)-sharded layout.

    The word lookup inside the pp-manual shard_map goes through
    ``vocab_parallel_lookup_manual`` (masked local gather + tp-psum in a
    nested tp-manual region, the reference's VocabParallelEmbedding,
    ``layers.py:128-210``), so the GSPMD gather partitioner — which
    check-fails on a vocab-sharded operand under a manual submesh
    (spmd_partitioner_util.cc:495) — never sees it.  This replaces the
    round-2 workaround of all-gathering the full table per step
    (V*H replicated bytes per device: ~0.5 GB at 70B, plus a V*H fp32
    grad accumulator in the 1F1B carry)."""
    from jax.sharding import NamedSharding

    rep = NamedSharding(mesh, P())
    out = {
        k: jax.tree_util.tree_map(
            lambda x: jax.lax.with_sharding_constraint(x, rep), v)
        for k, v in tree.items() if k != "word"
    }
    out["word"] = tree["word"]
    return out


def _fwd_rotation(S):
    return [(i, (i + 1) % S) for i in range(S)]


def _bwd_rotation(S):
    return [(i, (i - 1) % S) for i in range(S)]


# ---------------------------------------------------------------------------
# Engine 1: streaming autodiff schedule (supports VPP)
# ---------------------------------------------------------------------------

def build_pipeline_loss_fn(
    model,
    pp_size: int,
    num_microbatches: int,
    *,
    num_virtual: int = 1,
    sequence_parallel: bool = False,
    remat_block_ticks: Optional[int] = None,
):
    """Returns ``loss_fn(params, batch, rng_key, scale, train) ->
    (scaled_loss, loss)`` computing the full pipelined global-batch loss.
    For MoE configs (``num_experts > 1``) the return is
    ``(scaled_total, (loss, aux))`` where ``scaled_total`` includes the
    weighted routing losses and ``aux`` is the ``[lb, z]`` mean.

    ``batch``: dict with tokens/labels/loss_mask of shape [M, mb, s].
    ``params``: the standard model pytree; ``transformer.layers`` leaves
    (leading axis L) must be sharded over pp, in **stage-major order**
    when ``num_virtual > 1`` (see ``permute_layer_stack``).
    """
    cfg: TransformerConfig = model.cfg
    moe_on = cfg.num_experts > 1
    S, V, M, L = pp_size, num_virtual, num_microbatches, cfg.num_layers
    assert L % (S * V) == 0, f"num_layers {L} must divide pp*vpp {S * V}"
    if V > 1:
        # same constraint as the reference's interleaved schedule
        # (schedules.py:253-266: microbatches grouped by pipeline size)
        assert M % S == 0, (
            f"interleaved VPP requires num_microbatches ({M}) divisible by "
            f"pipeline size ({S})"
        )
    cl = L // (S * V)          # layers per chunk
    local_L = L // S           # layers per device
    W = M * V                  # work items
    T = W + S - 1              # fine ticks

    train_has_dropout = cfg.hidden_dropout > 0.0 or cfg.attention_dropout > 0.0

    def loss_fn(params, batch, rng_key, scale=1.0, train: bool = True):
        mesh = topology.get_mesh()
        emb_p = params["embedding"]
        trans = params["transformer"]
        head_w = lm_head_weight(params)
        freqs = rotary_freqs(cfg)
        tokens, labels, loss_mask = (
            batch["tokens"], batch["labels"], batch["loss_mask"],
        )
        mb, s = tokens.shape[1], tokens.shape[2]
        use_dropout = train and train_has_dropout

        def shmap_fn(layers_local, emb_p_, head_w_, fnorm_, tokens_,
                     labels_, mask_, rng_key_):
            pp_rank = lax.axis_index("pp")
            is_first = pp_rank == 0
            is_last = pp_rank == S - 1
            emb_key0 = jax.random.fold_in(rng_key_, 1)
            lay_key0 = jax.random.fold_in(rng_key_, 2)

            @jax.named_scope("pp_chunk")
            def run_chunk(h, v, m):
                """Apply this device's chunk v; returns (h, aux [2]) where
                aux is the chunk's accumulated MoE routing losses (zeros
                for dense models)."""
                def layer_body(carry, i):
                    hh, aux = carry
                    li = v * cl + i                       # local stacked row
                    lp = jax.tree_util.tree_map(
                        lambda x: lax.dynamic_index_in_dim(
                            x, li, 0, keepdims=False),
                        layers_local,
                    )
                    key = jax.random.fold_in(
                        jax.random.fold_in(lay_key0, m),
                        pp_rank * local_L + li,
                    )
                    out, _, a = transformer_layer(
                        hh, lp, cfg,
                        freqs=freqs, attention_mask=None, position_ids=None,
                        rng_key=key if use_dropout else None,
                        train=use_dropout,
                        sequence_parallel=sequence_parallel,
                    )
                    if moe_on:
                        aux = aux + a
                    return (out, aux), None

                (h, aux), _ = lax.scan(
                    layer_body, (h, jnp.zeros((2,), jnp.float32)),
                    jnp.arange(cl))
                return h, aux

            def tick(carry, t):
                act, ce_sum, tok_sum, aux_sum = carry
                w = t - pp_rank
                m, v, valid = _decode_item(w, M, S, V)
                toks_m = _index_mb(tokens_, m)
                h_emb = embedding_forward(
                    toks_m, None, emb_p_, cfg,
                    rng_key=(jax.random.fold_in(emb_key0, m)
                             if use_dropout else None),
                    train=use_dropout,
                    vocab_parallel_manual=True,
                ).astype(cfg.compute_jnp_dtype)
                inp = jnp.where(is_first & (v == 0), h_emb, act)
                out, aux_c = run_chunk(inp, v, m)
                # every stage owns cl layers of every valid item, so the
                # routing aux accrues on all stages (unlike CE)
                aux_sum = aux_sum + aux_c * valid.astype(jnp.float32)

                # streamed head + CE: valid only on (last stage, last chunk)
                h_fin = apply_norm(
                    out, fnorm_, cfg.normalization,
                    eps=cfg.layernorm_epsilon, fp32_compute=cfg.norm_in_fp32,
                )
                logits = parallel_lm_logits(
                    h_fin, head_w_,
                    sequence_parallel=False,
                    compute_dtype=cfg.compute_jnp_dtype,
                )
                ce = vocab_parallel_cross_entropy(
                    logits.astype(jnp.float32), _index_mb(labels_, m)
                )
                take = (is_last & (v == V - 1) & valid).astype(jnp.float32)
                wgt = _index_mb(mask_, m).astype(jnp.float32) * take
                act_next = lax.ppermute(out, "pp", _fwd_rotation(S))
                return (
                    act_next,
                    ce_sum + jnp.sum(ce * wgt),
                    tok_sum + jnp.sum(wgt),
                    aux_sum,
                ), None

            tick_fn = jax.checkpoint(
                tick, policy=jax.checkpoint_policies.nothing_saveable
            )

            # blocked outer scan: backward stores T/B block-carries and
            # recomputes B tick-carries per block -> O(sqrt(T)) live carries
            B = remat_block_ticks or max(1, int(np.ceil(np.sqrt(T))))
            n_blocks = -(-T // B)

            def block(carry, b):
                return lax.scan(tick_fn, carry, b * B + jnp.arange(B))

            block_fn = jax.checkpoint(
                block, policy=jax.checkpoint_policies.nothing_saveable
            )
            act0 = jnp.zeros((mb, s, cfg.hidden_size), cfg.compute_jnp_dtype)
            # the CE/token accumulators stay (1,)-shaped through this region:
            # a SCALAR residual of this shard_map trips a transpose bug on
            # pre-0.6 jax (it evades _promote_scalar_residuals and fails the
            # in_names check with {0: all_axes} on a rank-0 aval)
            (act_f, ce_sum, tok_sum, aux_sum), _ = lax.scan(
                block_fn,
                (act0, jnp.zeros((1,), jnp.float32),
                 jnp.zeros((1,), jnp.float32),
                 jnp.zeros((2,), jnp.float32)),
                jnp.arange(n_blocks),
            )
            # ticks beyond T (block padding) decode to invalid items -> masked
            ce_tot = lax.psum(ce_sum, "pp")
            tok_tot = lax.psum(tok_sum, "pp")
            aux_tot = lax.psum(aux_sum, "pp")
            return ce_tot, tok_tot, aux_tot

        layer_in_spec = jax.tree_util.tree_map(lambda _: P("pp"),
                                               trans["layers"])
        rep = jax.tree_util.tree_map(lambda _: P(), emb_p)
        fnorm_spec = jax.tree_util.tree_map(lambda _: P(),
                                            trans["final_norm"])
        ce_tot, tok_tot, aux_tot = topology.shard_map(
            shmap_fn,
            mesh=mesh,
            in_specs=(layer_in_spec, rep, P(), fnorm_spec, P(), P(), P(), P()),
            out_specs=(P(), P(), P()),
            axis_names={"pp"},
            check_vma=False,
        )(trans["layers"], _pipeline_embedding_layout(emb_p, mesh), head_w,
          trans["final_norm"], tokens, labels, loss_mask, rng_key)

        loss = (ce_tot / jnp.maximum(tok_tot, 1.0))[0]
        if moe_on:
            # mean routing aux per microbatch enters the objective with the
            # configured coefficients; (loss, aux) is reported for logging
            aux_mean = aux_tot / M
            total = (loss + cfg.moe_aux_loss_coeff * aux_mean[0]
                     + cfg.moe_z_loss_coeff * aux_mean[1])
            return total * scale, (loss, aux_mean)
        return loss * scale, loss

    return loss_fn


# ---------------------------------------------------------------------------
# Engine 2: manual 1F1B with O(S) activation stash (V=1)
# ---------------------------------------------------------------------------

def build_pipeline_grad_fn(
    model,
    pp_size: int,
    num_microbatches: int,
    *,
    sequence_parallel: bool = False,
):
    """Returns ``grad_fn(params, batch, rng_key, scale, train) ->
    (loss, grads)`` with a hand-scheduled 1F1B backward; for MoE configs
    (``num_experts > 1``) it returns ``(loss, grads, aux)`` with the
    ``[lb, z]`` routing-aux mean, and ``grads`` are gradients of the full
    weighted objective.

    Activation memory is flat in M: the scan is never autodiffed, so the
    only live state is the carry — one fwd activation, one bwd cotangent,
    a 2S-slot input stash (the reference's in-flight cap,
    schedules.py:606-722), and fp32 gradient accumulators.  ``grads`` are
    gradients of ``scale * mean CE`` in fp32, matching
    ``jax.grad(loss_fn)`` of the streaming engine.
    """
    cfg: TransformerConfig = model.cfg
    moe_on = cfg.num_experts > 1
    S, M, L = pp_size, num_microbatches, cfg.num_layers
    assert L % S == 0, f"num_layers {L} must divide pp {S}"
    cl = L // S
    R = 2 * S                     # stash ring slots (max residence 2S-1)
    T = M + 2 * S - 1             # fwd item f = t - k; bwd item b = t - (2S-1-k)

    train_has_dropout = cfg.hidden_dropout > 0.0 or cfg.attention_dropout > 0.0

    def grad_fn(params, batch, rng_key, scale=1.0, train: bool = True):
        mesh = topology.get_mesh()
        emb_p = params["embedding"]
        trans = params["transformer"]
        untied = "lm_head" in params
        head_w = lm_head_weight(params)
        freqs = rotary_freqs(cfg)
        tokens, labels, loss_mask = (
            batch["tokens"], batch["labels"], batch["loss_mask"],
        )
        mb, s = tokens.shape[1], tokens.shape[2]
        use_dropout = train and train_has_dropout
        # total token count is known before the pipeline runs; each item's
        # cotangent seed folds in the 1/total normalization
        tok_tot = jnp.maximum(jnp.sum(loss_mask.astype(jnp.float32)), 1.0)

        def shmap_fn(layers_local, emb_p_, head_w_, fnorm_, tokens_,
                     labels_, mask_, rng_key_, seed_, aux_seed_):
            pp_rank = lax.axis_index("pp")
            is_first = (pp_rank == 0).astype(jnp.float32)
            is_last = (pp_rank == S - 1).astype(jnp.float32)
            emb_key0 = jax.random.fold_in(rng_key_, 1)
            lay_key0 = jax.random.fold_in(rng_key_, 2)

            @jax.named_scope("pp_chunk_fwd")
            def chunk_fwd(h, layers_loc, m):
                """(h, aux [2]): this stage's cl layers + its MoE routing
                losses (zeros for dense models)."""
                def layer_body(carry, i):
                    hh, aux = carry
                    lp = jax.tree_util.tree_map(
                        lambda x: lax.dynamic_index_in_dim(
                            x, i, 0, keepdims=False),
                        layers_loc,
                    )
                    key = jax.random.fold_in(
                        jax.random.fold_in(lay_key0, m), pp_rank * cl + i
                    )
                    out, _, a = transformer_layer(
                        hh, lp, cfg,
                        freqs=freqs, attention_mask=None, position_ids=None,
                        rng_key=key if use_dropout else None,
                        train=use_dropout,
                        sequence_parallel=sequence_parallel,
                    )
                    if moe_on:
                        aux = aux + a
                    return (out, aux), None

                (h, aux), _ = lax.scan(
                    layer_body, (h, jnp.zeros((2,), jnp.float32)),
                    jnp.arange(cl))
                return h, aux

            @jax.named_scope("pp_embed")
            def embed(emb_params, m):
                toks_m = _index_mb(tokens_, m)
                return embedding_forward(
                    toks_m, None, emb_params, cfg,
                    rng_key=(jax.random.fold_in(emb_key0, m)
                             if use_dropout else None),
                    train=use_dropout,
                    vocab_parallel_manual=True,
                ).astype(cfg.compute_jnp_dtype)

            @jax.named_scope("pp_head_ce")
            def head_ce(out, head_w_in, fnorm_in, m):
                h_fin = apply_norm(
                    out, fnorm_in, cfg.normalization,
                    eps=cfg.layernorm_epsilon, fp32_compute=cfg.norm_in_fp32,
                )
                logits = parallel_lm_logits(
                    h_fin, head_w_in,
                    sequence_parallel=False,
                    compute_dtype=cfg.compute_jnp_dtype,
                )
                ce = vocab_parallel_cross_entropy(
                    logits.astype(jnp.float32), _index_mb(labels_, m)
                )
                wgt = _index_mb(mask_, m).astype(jnp.float32)
                return jnp.sum(ce * wgt), jnp.sum(wgt)

            def tick(carry, t):
                act_f, act_b, stash, g_lay, g_emb, g_head, g_norm, \
                    ce_sum, tok_sum, aux_sum = carry

                # ---------------- forward chunk ---------------------------
                f = t - pp_rank
                m_f, _, valid_f = _decode_item(f, M, S, 1)
                h_emb = embed(emb_p_, m_f)
                inp = jnp.where((pp_rank == 0), h_emb, act_f)
                out, _ = chunk_fwd(inp, layers_local, m_f)
                # stash the chunk input for the backward recompute
                slot_f = jnp.mod(f, R)
                old = lax.dynamic_index_in_dim(stash, slot_f, 0,
                                               keepdims=False)
                stash = lax.dynamic_update_index_in_dim(
                    stash,
                    jnp.where(valid_f, inp, old),
                    slot_f, 0,
                )
                act_f_next = lax.ppermute(out, "pp", _fwd_rotation(S))

                # ---------------- backward chunk --------------------------
                b = t - (2 * S - 1 - pp_rank)
                m_b, _, valid_b = _decode_item(b, M, S, 1)
                vmask = valid_b.astype(jnp.float32)
                slot_b = jnp.mod(b, R)
                x = lax.dynamic_index_in_dim(stash, slot_b, 0, keepdims=False)

                def fwd_path(x_in, layers_loc, head_in, fnorm_in):
                    o, aux_c = chunk_fwd(x_in, layers_loc, m_b)
                    ce, wgt = head_ce(o, head_in, fnorm_in, m_b)
                    return o, ce, wgt, aux_c

                (o_b, ce_b, wgt_b, aux_b), vjp = jax.vjp(
                    fwd_path, x, layers_local, head_w_, fnorm_
                )
                # last stage seeds from CE; other stages from the incoming
                # cotangent (zeroed on the last stage).  The routing aux is
                # seeded on EVERY stage (each owns its layers' routers).
                cot_o = (act_b * (1.0 - is_last)).astype(o_b.dtype)
                cot_ce = (seed_ * is_last * vmask).astype(ce_b.dtype)
                cot_aux = aux_seed_ * vmask
                dx, d_lay, d_head, d_norm = vjp(
                    (cot_o, cot_ce, jnp.zeros_like(wgt_b), cot_aux)
                )
                # first stage: push dx through the embedding lookup
                _, emb_vjp = jax.vjp(lambda ep: embed(ep, m_b), emb_p_)
                (d_emb,) = emb_vjp(
                    (dx * is_first * vmask).astype(cfg.compute_jnp_dtype)
                )

                g_lay = jax.tree_util.tree_map(
                    lambda g, d: g + d.astype(jnp.float32) * vmask,
                    g_lay, d_lay)
                g_emb = jax.tree_util.tree_map(
                    lambda g, d: g + d.astype(jnp.float32), g_emb, d_emb)
                g_head = g_head + d_head.astype(jnp.float32) * (is_last * vmask)
                g_norm = jax.tree_util.tree_map(
                    lambda g, d: g + d.astype(jnp.float32) * (is_last * vmask),
                    g_norm, d_norm)
                ce_sum = ce_sum + ce_b * is_last * vmask
                tok_sum = tok_sum + wgt_b * is_last * vmask
                aux_sum = aux_sum + aux_b * vmask

                act_b_next = lax.ppermute(
                    (dx * vmask).astype(cfg.compute_jnp_dtype),
                    "pp", _bwd_rotation(S),
                )
                return (act_f_next, act_b_next, stash, g_lay, g_emb,
                        g_head, g_norm, ce_sum, tok_sum, aux_sum), None

            zeros_f32 = lambda tree: jax.tree_util.tree_map(  # noqa: E731
                lambda x: jnp.zeros(x.shape, jnp.float32), tree)
            act0 = jnp.zeros((mb, s, cfg.hidden_size), cfg.compute_jnp_dtype)
            carry0 = (
                act0,
                act0,
                jnp.zeros((R, mb, s, cfg.hidden_size), cfg.compute_jnp_dtype),
                zeros_f32(layers_local),
                zeros_f32(emb_p_),
                jnp.zeros(head_w_.shape, jnp.float32),
                zeros_f32(fnorm_),
                jnp.float32(0.0),
                jnp.float32(0.0),
                jnp.zeros((2,), jnp.float32),
            )
            carry, _ = lax.scan(tick, carry0, jnp.arange(T))
            (_, _, _, g_lay, g_emb, g_head, g_norm,
             ce_sum, tok_sum, aux_sum) = carry
            # replicated-param grads: emit per-stage contributions stacked
            # over pp and sum them outside the shard_map — an in-body psum
            # of a tp-auto-sharded array over the manual pp axis trips the
            # same partitioner check as the vocab-sharded gather
            stack = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda g: g[None], t)
            ce_tot = lax.psum(ce_sum, "pp")
            tok_tot_ = lax.psum(tok_sum, "pp")
            aux_tot = lax.psum(aux_sum, "pp")
            return (g_lay, stack(g_emb), g_head[None], stack(g_norm),
                    ce_tot, tok_tot_, aux_tot)

        layer_in_spec = jax.tree_util.tree_map(lambda _: P("pp"),
                                               trans["layers"])
        rep_emb = jax.tree_util.tree_map(lambda _: P(), emb_p)
        fnorm_spec = jax.tree_util.tree_map(lambda _: P(),
                                            trans["final_norm"])
        stacked_emb = jax.tree_util.tree_map(lambda _: P("pp"), emb_p)
        stacked_fnorm = jax.tree_util.tree_map(lambda _: P("pp"),
                                               trans["final_norm"])
        # cotangent seed: d(scale * mean CE)/d(per-item CE sum)
        seed = jnp.float32(scale) / tok_tot
        # routing-aux cotangent: d(scale * coeff . mean-per-microbatch aux)
        aux_seed = (jnp.float32(scale) / M) * jnp.asarray(
            [cfg.moe_aux_loss_coeff, cfg.moe_z_loss_coeff], jnp.float32)
        g_lay, g_emb, g_head, g_norm, ce_tot, tok_tot_, aux_tot = topology.shard_map(
            shmap_fn,
            mesh=mesh,
            in_specs=(layer_in_spec, rep_emb, P(), fnorm_spec,
                      P(), P(), P(), P(), P(), P()),
            out_specs=(layer_in_spec, stacked_emb, P("pp"), stacked_fnorm,
                       P(), P(), P()),
            axis_names={"pp"},
            check_vma=False,
        )(trans["layers"], _pipeline_embedding_layout(emb_p, mesh), head_w,
          trans["final_norm"], tokens, labels, loss_mask, rng_key, seed,
          aux_seed)
        sum_pp = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda g: jnp.sum(g, axis=0), t)
        g_emb = sum_pp(g_emb)
        g_head = jnp.sum(g_head, axis=0)
        g_norm = sum_pp(g_norm)

        loss = ce_tot / jnp.maximum(tok_tot_, 1.0)
        grads = {
            "embedding": g_emb,
            "transformer": {"layers": g_lay, "final_norm": g_norm},
        }
        if untied:
            grads["lm_head"] = {"weight": g_head}
        else:
            grads["embedding"]["word"]["embedding"] = (
                grads["embedding"]["word"]["embedding"] + g_head
            )
        if moe_on:
            return loss, grads, aux_tot / M
        return loss, grads

    return grad_fn


# ---------------------------------------------------------------------------
# Train step
# ---------------------------------------------------------------------------

def build_pipeline_train_step(
    model,
    optimizer,
    parallel_cfg,
    num_microbatches: int,
    *,
    schedule: Optional[str] = None,
    layer_stats: bool = False,
):
    """Pipelined analogue of ``training.build_train_step``: full global batch
    through the pipeline, then the functional optimizer step.

    ``schedule``: '1f1b' (manual backward, O(S) activation stash; V=1 only)
    or 'stream' (autodiff engine, supports VPP).  Default: 1f1b when
    vpp==1, stream otherwise.

    ``layer_stats`` threads the model-health observatory (``health.py``)
    through both schedules: the grads the pipeline grad fn returns are a
    full (pp-sharded) param-tree pytree at the top level of the jitted
    step, so the per-group reductions run under GSPMD exactly like the
    single-program path and ``metrics['layer_stats']`` matches it.  NB
    with interleaved VPP the stacked-layer rows are stage-major, so the
    ``layer_NNN`` group names index stacked rows, not execution order.
    """
    pp = parallel_cfg.pipeline_model_parallel_size
    vpp = parallel_cfg.virtual_pipeline_model_parallel_size or 1
    if schedule is None:
        schedule = "1f1b" if vpp == 1 else "stream"
    if schedule == "1f1b" and vpp > 1:
        raise ValueError("manual 1f1b schedule supports vpp=1 only; "
                         "use schedule='stream' for interleaved VPP")

    moe_on = model.cfg.num_experts > 1

    def moe_metrics(metrics, aux):
        metrics["moe aux loss"] = aux[0]
        if model.cfg.moe_z_loss_coeff > 0.0:
            metrics["moe z loss"] = aux[1]

    if schedule == "1f1b":
        grad_fn = build_pipeline_grad_fn(
            model, pp, num_microbatches,
            sequence_parallel=parallel_cfg.sequence_parallel,
        )

        def train_step(params, opt_state, batch, rng_key, lr, wd):
            scale = opt_state.grad_scaler.scale
            out = grad_fn(params, batch, rng_key, scale)
            loss, grads = out[0], out[1]
            new_params, new_opt_state, stats = optimizer.step(
                params, grads, opt_state, lr, wd, layer_stats=layer_stats
            )
            metrics = {
                "lm loss": loss,
                "grad_norm": stats["grad_norm"],
                "loss_scale": stats["loss_scale"],
                "skipped_iter": stats["found_inf"].astype(jnp.int32),
            }
            if layer_stats:
                metrics["layer_stats"] = stats["layer_stats"]
            if moe_on:
                moe_metrics(metrics, out[2])
            return new_params, new_opt_state, metrics

        return jax.jit(train_step, donate_argnums=(0, 1))

    loss_fn = build_pipeline_loss_fn(
        model, pp, num_microbatches,
        num_virtual=vpp,
        sequence_parallel=parallel_cfg.sequence_parallel,
    )

    def train_step(params, opt_state, batch, rng_key, lr, wd):
        scale = opt_state.grad_scaler.scale

        def scaled_loss(p):
            return loss_fn(p, batch, rng_key, scale)

        (_, lfaux), grads = jax.value_and_grad(scaled_loss, has_aux=True)(params)
        loss, moe_aux = lfaux if moe_on else (lfaux, None)
        grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
        new_params, new_opt_state, stats = optimizer.step(
            params, grads, opt_state, lr, wd, layer_stats=layer_stats
        )
        metrics = {
            "lm loss": loss,
            "grad_norm": stats["grad_norm"],
            "loss_scale": stats["loss_scale"],
            "skipped_iter": stats["found_inf"].astype(jnp.int32),
        }
        if layer_stats:
            metrics["layer_stats"] = stats["layer_stats"]
        if moe_on:
            moe_metrics(metrics, moe_aux)
        return new_params, new_opt_state, metrics

    return jax.jit(train_step, donate_argnums=(0, 1))
