"""Pipeline-parallel engine: a compiled 1F1B-class schedule over the ``pp``
mesh axis.

Reference: ``megatron/schedules.py`` (1F1B :606-722, interleaved :253-502)
+ ``megatron/p2p_communication.py`` (batched NCCL isend/irecv :101-251) +
layer-to-stage assignment (``megatron/model/transformer.py:1045-1090``) +
embedding-tie grad sync across first/last stages
(``megatron/optimizer/optimizer.py:203-229``).

TPU re-design — none of that machinery survives translation:

* The schedule is a **single jitted ``lax.scan`` over pipeline ticks**
  inside a ``shard_map`` that is *manual over pp only* (dp/tp stay under
  GSPMD, so tensor-parallel collectives inside each stage remain
  compiler-placed).  Tick ``t``: stage 0 ingests microbatch ``t``'s
  embedded activations; every stage applies its layer block;
  ``lax.ppermute`` rotates activations to the next stage over ICI (the
  p2p isend/irecv replacement); each stage's per-tick output is emitted
  as scan ``ys`` — the last stage's emissions, re-indexed, are the
  completed microbatches.
* **Embedding and LM head run outside the shard_map** under plain GSPMD:
  all microbatches are embedded up front and the head consumes the
  stacked last-stage outputs.  This is both the robust partitioning path
  (XLA's gather partitioner dislikes vocab-sharded gathers under a
  manual submesh) and good MXU shape hygiene (one big [M*mb*s, h] x
  [h, V] matmul instead of M small ones).
* **Backward is autodiff through the scan**: the transpose of ``ppermute``
  is the reverse rotation, so XLA derives the backward pipeline
  (warmup/cooldown) mechanically; fwd/bwd interleaving — the point of
  1F1B — is XLA scheduling freedom.  Per-tick ``jax.checkpoint`` bounds
  live activations to one carry per tick plus the emitted last-stage
  outputs, the same asymptotics as 1F1B's activation stash.
* **Embedding tie**: the word embedding is one logical parameter used at
  ingest (lookup) and by the head (logits); its gradient sums both uses
  by linearity — the reference's embedding-group all-reduce
  (optimizer.py:203-229) has no analogue to write.

Layer-to-stage assignment is a *sharding spec*, not code: the stacked
layer axis [L, ...] is sharded over pp, giving each stage the contiguous
block of L/pp layers (transformer.py:1045-1090 semantics).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from megatron_llm_tpu import topology
from megatron_llm_tpu.config import TransformerConfig
from megatron_llm_tpu.models.language_model import embedding_forward
from megatron_llm_tpu.models.transformer import rotary_freqs, transformer_layer
from megatron_llm_tpu.ops.cross_entropy import vocab_parallel_cross_entropy
from megatron_llm_tpu.ops.layernorm import apply_norm
from megatron_llm_tpu.parallel.layers import parallel_lm_logits
from megatron_llm_tpu.parallel.sharding import constrain


def build_pipeline_loss_fn(
    model,
    pp_size: int,
    num_microbatches: int,
    *,
    num_virtual: int = 1,
    sequence_parallel: bool = False,
):
    """Returns ``loss_fn(params, batch, rng_key, scale) -> (scaled_loss, loss)``
    computing the full pipelined global-batch loss.

    ``batch``: dict with tokens/labels/loss_mask of shape [M, mb, s].
    ``params``: the standard model pytree; ``transformer.layers`` leaves
    (leading axis L) must be sharded over pp (logical axis 'stage').
    """
    cfg: TransformerConfig = model.cfg
    S = pp_size
    V = num_virtual
    M = num_microbatches
    L = cfg.num_layers
    if V > 1:
        raise NotImplementedError(
            "interleaved virtual pipeline (VPP>1) requires per-stage "
            "multi-buffer chunk scheduling; planned — use VPP=1"
        )
    assert L % S == 0, f"num_layers ({L}) must divide pp ({S})"
    chunk = L // S
    T = M + S - 1  # pipeline ticks

    train_has_dropout = cfg.hidden_dropout > 0.0 or cfg.attention_dropout > 0.0

    def loss_fn(params, batch, rng_key, scale=1.0, train: bool = True):
        mesh = topology.get_mesh()
        emb_p = params["embedding"]
        trans = params["transformer"]
        head_w = (
            params["lm_head"]["weight"]
            if "lm_head" in params
            else emb_p["word"]["embedding"]
        )
        freqs = rotary_freqs(cfg)
        tokens, labels, loss_mask = (
            batch["tokens"], batch["labels"], batch["loss_mask"],
        )
        mb, s = tokens.shape[1], tokens.shape[2]
        use_dropout = train and train_has_dropout

        # ---- embed all microbatches under plain GSPMD -------------------
        def embed_one(toks, key):
            return embedding_forward(
                toks, None, emb_p, cfg,
                rng_key=key if use_dropout else None, train=use_dropout,
            )

        emb_keys = jax.random.split(jax.random.fold_in(rng_key, 1), M)
        h_all = jax.vmap(embed_one)(tokens, emb_keys)  # [M, mb, s, h]
        h_all = h_all.astype(cfg.compute_jnp_dtype)

        # ---- pipelined stack under shard_map(manual pp) -----------------
        def shmap_fn(layers_local, h_all, rng_key):
            pp_rank = lax.axis_index("pp")
            is_first = pp_rank == 0

            def run_chunk(h, tick_key):
                def layer_body(carry, i):
                    lp = jax.tree_util.tree_map(
                        lambda x: lax.dynamic_index_in_dim(x, i, 0,
                                                           keepdims=False),
                        layers_local,
                    )
                    key = jax.random.fold_in(tick_key, i)
                    out = transformer_layer(
                        carry, lp, cfg,
                        freqs=freqs, attention_mask=None, position_ids=None,
                        rng_key=key if use_dropout else None,
                        train=use_dropout,
                        sequence_parallel=sequence_parallel,
                    )
                    return out, None

                h, _ = lax.scan(layer_body, h, jnp.arange(chunk))
                return h

            def tick(carry, t):
                act = carry
                tick_key = jax.random.fold_in(jax.random.fold_in(rng_key, 2), t)
                m_in = jnp.clip(t, 0, M - 1)
                h_in = lax.dynamic_index_in_dim(h_all, m_in, 0, keepdims=False)
                inp = jnp.where(is_first, h_in, act)
                out = run_chunk(inp, tick_key)
                act_next = lax.ppermute(
                    out, "pp", [(i, (i + 1) % S) for i in range(S)]
                )
                return act_next, out

            tick_fn = jax.checkpoint(
                tick, policy=jax.checkpoint_policies.nothing_saveable
            )
            act0 = jnp.zeros((mb, s, cfg.hidden_size), cfg.compute_jnp_dtype)
            _, outs = lax.scan(tick_fn, act0, jnp.arange(T))
            return outs  # [T, mb, s, h] per stage

        layer_in_spec = jax.tree_util.tree_map(lambda _: P("pp"),
                                               trans["layers"])
        outs = jax.shard_map(
            shmap_fn,
            mesh=mesh,
            in_specs=(layer_in_spec, P(), P()),
            out_specs=P("pp"),            # stacked: [S*T, mb, s, h]
            axis_names={"pp"},
            check_vma=False,
        )(trans["layers"], h_all, rng_key)

        # last stage's emissions, ticks S-1 .. T-1 == microbatches 0..M-1
        last = lax.slice_in_dim(outs, (S - 1) * T + (S - 1), S * T, axis=0)
        # [M, mb, s, h]

        # ---- final norm + head + CE under plain GSPMD -------------------
        h_fin = apply_norm(
            last, trans["final_norm"], cfg.normalization,
            eps=cfg.layernorm_epsilon, fp32_compute=cfg.norm_in_fp32,
        )
        logits = parallel_lm_logits(
            h_fin.reshape(M * mb, s, -1), head_w,
            sequence_parallel=False,
            compute_dtype=cfg.compute_jnp_dtype,
        )
        loss_tok = vocab_parallel_cross_entropy(
            logits.astype(jnp.float32), labels.reshape(M * mb, s)
        )
        lm = loss_mask.reshape(M * mb, s).astype(jnp.float32)
        loss = jnp.sum(loss_tok * lm) / jnp.maximum(jnp.sum(lm), 1.0)
        return loss * scale, loss

    return loss_fn


def build_pipeline_train_step(
    model,
    optimizer,
    parallel_cfg,
    num_microbatches: int,
):
    """Pipelined analogue of ``training.build_train_step``: full global batch
    through the pipeline, then the functional optimizer step."""
    pp = parallel_cfg.pipeline_model_parallel_size
    vpp = parallel_cfg.virtual_pipeline_model_parallel_size or 1
    loss_fn = build_pipeline_loss_fn(
        model, pp, num_microbatches,
        num_virtual=vpp,
        sequence_parallel=parallel_cfg.sequence_parallel,
    )

    def train_step(params, opt_state, batch, rng_key, lr, wd):
        scale = opt_state.grad_scaler.scale

        def scaled_loss(p):
            return loss_fn(p, batch, rng_key, scale)

        (_, loss), grads = jax.value_and_grad(scaled_loss, has_aux=True)(params)
        grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
        new_params, new_opt_state, stats = optimizer.step(
            params, grads, opt_state, lr, wd
        )
        metrics = {
            "lm loss": loss,
            "grad_norm": stats["grad_norm"],
            "loss_scale": stats["loss_scale"],
            "skipped_iter": stats["found_inf"].astype(jnp.int32),
        }
        return new_params, new_opt_state, metrics

    return jax.jit(train_step, donate_argnums=(0, 1))
