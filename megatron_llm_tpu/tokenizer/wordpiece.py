"""Self-contained WordPiece tokenizer (no ``transformers`` dependency).

The reference embeds the original Google BERT tokenization stack
(``megatron/tokenizer/bert_tokenization.py``: BasicTokenizer +
WordpieceTokenizer).  This module provides the same behavior as a
fallback backend for ``_BertWordPieceTokenizer`` when the
``transformers`` fast tokenizers are unavailable — the framework stays
fully standalone.  The algorithm (whitespace/punctuation basic split
with lowercase + accent stripping + CJK spacing, then greedy
longest-match-first sub-word segmentation with ``##`` continuations) is
the published BERT tokenization; parity with ``BertTokenizerFast`` is
asserted in ``tests/test_tokenizer_standalone.py``.
"""

from __future__ import annotations

import unicodedata
from typing import Dict, List


def load_vocab(vocab_file: str) -> Dict[str, int]:
    vocab: Dict[str, int] = {}
    with open(vocab_file, encoding="utf-8") as f:
        for i, line in enumerate(f):
            tok = line.rstrip("\n")
            if tok:
                vocab[tok] = i
    return vocab


def _is_whitespace(ch: str) -> bool:
    return ch in (" ", "\t", "\n", "\r") or unicodedata.category(ch) == "Zs"


def _is_control(ch: str) -> bool:
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    # ASCII non-alphanumeric ranges count as punctuation (matches BERT:
    # treats characters like '$' and '@' as splittable even though
    # unicode classes them as symbols)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
            or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
            or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)


class BasicTokenizer:
    """Whitespace/punctuation splitting with cleanup (BERT basic step)."""

    def __init__(self, lower_case: bool = True):
        self.lower_case = lower_case

    def tokenize(self, text: str) -> List[str]:
        # cleanup: drop control chars / NUL / replacement, normalize ws
        out = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            out.append(" " if _is_whitespace(ch) else ch)
        text = "".join(out)
        # CJK characters tokenize one-per-character
        text = "".join(
            f" {ch} " if _is_cjk(ord(ch)) else ch for ch in text)

        tokens: List[str] = []
        for word in text.split():
            if self.lower_case:
                word = word.lower()
                # strip accents (NFD then drop combining marks)
                word = "".join(
                    c for c in unicodedata.normalize("NFD", word)
                    if unicodedata.category(c) != "Mn")
            # split on punctuation, keeping each punct char as a token
            cur: List[str] = []
            for ch in word:
                if _is_punctuation(ch):
                    if cur:
                        tokens.append("".join(cur))
                        cur = []
                    tokens.append(ch)
                else:
                    cur.append(ch)
            if cur:
                tokens.append("".join(cur))
        return tokens


class WordpieceTokenizer:
    """Greedy longest-match-first sub-word segmentation."""

    def __init__(self, vocab: Dict[str, int], unk_token: str = "[UNK]",
                 max_chars_per_word: int = 200):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_chars_per_word = max_chars_per_word

    def tokenize(self, word: str) -> List[str]:
        if len(word) > self.max_chars_per_word:
            return [self.unk_token]
        pieces: List[str] = []
        start = 0
        while start < len(word):
            end = len(word)
            piece = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    piece = sub
                    break
                end -= 1
            if piece is None:
                return [self.unk_token]
            pieces.append(piece)
            start = end
        return pieces


class StandaloneWordPiece:
    """Drop-in for the parts of ``BertTokenizerFast`` the framework uses:
    encode without special tokens, decode, vocab, special-token ids, and
    ``add_special_tokens`` growing the vocab."""

    def __init__(self, vocab_file: str, do_lower_case: bool = True):
        self._vocab = load_vocab(vocab_file)
        self._inv = {i: t for t, i in self._vocab.items()}
        self._basic = BasicTokenizer(do_lower_case)
        self._wp = WordpieceTokenizer(self._vocab)
        self.cls_token_id = self._vocab.get("[CLS]")
        self.sep_token_id = self._vocab.get("[SEP]")
        self.pad_token_id = self._vocab.get("[PAD]")
        self.mask_token_id = self._vocab.get("[MASK]")
        self.unk_token_id = self._vocab.get("[UNK]")
        self.bos_token_id = None
        self.eos_token_id = None
        self.additional_special_tokens_ids: List[int] = []
        # special tokens are matched atomically in encode (HF behavior:
        # never split even with add_special_tokens=False)
        self._specials = {t for t in ("[CLS]", "[SEP]", "[PAD]", "[MASK]",
                                      "[UNK]") if t in self._vocab}

    # -- vocab ----------------------------------------------------------
    def __len__(self):
        return len(self._vocab)

    def get_vocab(self):
        return dict(self._vocab)

    def _add_token(self, tok: str) -> int:
        if tok in self._vocab:
            self._specials.add(tok)
            return self._vocab[tok]
        # max existing id + 1, NOT len(vocab): blank/duplicate vocab
        # lines make the two differ and len() would collide
        idx = max(self._inv, default=-1) + 1
        self._vocab[tok] = idx
        self._inv[idx] = tok
        self._specials.add(tok)
        return idx

    def add_special_tokens(self, mapping: dict):
        for key, val in mapping.items():
            if key == "additional_special_tokens":
                self.additional_special_tokens_ids = [
                    self._add_token(t) for t in val]
            else:
                setattr(self, f"{key}_id", self._add_token(val))

    # -- encode / decode ------------------------------------------------
    def encode(self, text: str, add_special_tokens: bool = False):
        import re

        ids: List[int] = []
        unk = self.unk_token_id
        # split out special tokens first so they encode atomically
        if self._specials:
            pat = "(" + "|".join(
                re.escape(t) for t in sorted(self._specials, key=len,
                                             reverse=True)) + ")"
            chunks = re.split(pat, text)
        else:
            chunks = [text]
        for chunk in chunks:
            if chunk in self._specials:
                ids.append(self._vocab[chunk])
                continue
            for word in self._basic.tokenize(chunk):
                for piece in self._wp.tokenize(word):
                    ids.append(self._vocab.get(piece, unk))
        if add_special_tokens:
            ids = [self.cls_token_id] + ids + [self.sep_token_id]
        return ids

    def decode(self, ids) -> str:
        toks = [self._inv.get(int(i), "[UNK]") for i in ids]
        out: List[str] = []
        for t in toks:
            if t.startswith("##") and out:
                out[-1] = out[-1] + t[2:]
            else:
                out.append(t)
        return " ".join(out)
