"""Tokenizer zoo + vocab padding.

Reference: ``megatron/tokenizer/tokenizer.py`` — ``build_tokenizer`` (:12-63)
with vocab padding to ``make_vocab_size_divisible_by x tp_size``;
``_BertWordPieceTokenizer`` (:123), ``_GPT2BPETokenizer`` (:254),
``_FalconTokenizer`` (:288), ``_SentencePieceTokenizer`` (:326, llama/
mistral with special-token handling and ``--no_new_tokens``).

TPU build: tokenization is pure host-side; the implementations wrap the
baked-in ``transformers``/``tokenizers`` fast backends when available,
falling back to the self-contained WordPiece / byte-BPE implementations
in ``tokenizer/wordpiece.py`` and ``tokenizer/bpe.py`` rather than
vendoring BPE code.  ``sentencepiece`` is optional in this image — the
SentencePiece path degrades to a clear error (or the HF fast tokenizer for
the same model when given a directory).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List, Optional


def build_tokenizer(args):
    """args needs: tokenizer_type, vocab_file/merges_file/tokenizer_path
    (per type), make_vocab_size_divisible_by, tensor_model_parallel_size,
    optional vocab_extra_ids / new_tokens."""
    t = args.tokenizer_type
    if t == "GPT2BPETokenizer":
        tokenizer = _GPT2BPETokenizer(args.vocab_file, args.merge_file)
    elif t in ("BertWordPieceLowerCase", "BertWordPieceCase"):
        tokenizer = _BertWordPieceTokenizer(
            args.vocab_file, lower_case=(t == "BertWordPieceLowerCase"),
            vocab_extra_ids=getattr(args, "vocab_extra_ids", 0),
        )
    elif t == "SentencePieceTokenizer":
        # reference flag is --tokenizer_model (the .model file); accept
        # --vocab_file as a fallback spelling
        tokenizer = _SentencePieceTokenizer(
            getattr(args, "tokenizer_model", None) or args.vocab_file,
            vocab_extra_ids=getattr(args, "vocab_extra_ids", 0),
            new_tokens=getattr(args, "new_tokens", True),
        )
    elif t == "FalconTokenizer":
        tokenizer = _FalconTokenizer(getattr(args, "tokenizer_path", None))
    elif t == "HFAutoTokenizer":
        tokenizer = _HFAutoTokenizer(args.tokenizer_path)
    elif t == "NullTokenizer":
        tokenizer = _NullTokenizer(args.vocab_size)
    else:
        raise NotImplementedError(f"tokenizer type {t!r}")

    extra_list = getattr(args, "vocab_extra_ids_list", None)
    if extra_list:
        # reference --vocab_extra_ids_list: literal tokens appended as
        # additional special tokens (HF-backed tokenizers only)
        tokens = [s for s in extra_list.split(",") if s]
        hf = getattr(tokenizer, "_tok", None) or getattr(
            tokenizer, "_sp", None)
        if hf is not None and hasattr(hf, "add_special_tokens"):
            hf.add_special_tokens({"additional_special_tokens": tokens})
            tokenizer._inv_vocab_cache = None
        else:
            raise NotImplementedError(
                f"--vocab_extra_ids_list is not supported for "
                f"tokenizer type {t!r}")

    args.padded_vocab_size = _vocab_size_with_padding(tokenizer.vocab_size, args)
    return tokenizer


def _vocab_size_with_padding(orig_vocab_size: int, args) -> int:
    """Pad to make_vocab_size_divisible_by x tp (reference: tokenizer.py:46-63)."""
    after = orig_vocab_size
    multiple = args.make_vocab_size_divisible_by * args.tensor_model_parallel_size
    while after % multiple != 0:
        after += 1
    if getattr(args, "rank", 0) == 0 and after != orig_vocab_size:
        print(f" > padded vocab (size: {orig_vocab_size}) with "
              f"{after - orig_vocab_size} dummy tokens "
              f"(new size: {after})", flush=True)
    # re-fire the fused-CE policy now that the tokenizer-derived vocab
    # is known (validate_args ran before the tokenizer was built); the
    # guard keeps the preprocess CLIs (no such flags) out of it
    if getattr(args, "fused_ce_user_explicit", None) is not None:
        from megatron_llm_tpu.arguments import apply_fused_ce_policy
        apply_fused_ce_policy(args, vocab=after)
    return after


class AbstractTokenizer(ABC):
    @property
    @abstractmethod
    def vocab_size(self) -> int: ...

    @abstractmethod
    def tokenize(self, text: str) -> List[int]: ...

    def detokenize(self, token_ids: List[int]) -> str:
        raise NotImplementedError

    @property
    def cls(self) -> int:
        raise NotImplementedError

    @property
    def sep(self) -> int:
        raise NotImplementedError

    @property
    def pad(self) -> int:
        raise NotImplementedError

    @property
    def eod(self) -> int:
        raise NotImplementedError

    @property
    def mask(self) -> int:
        raise NotImplementedError

    @property
    def vocab(self):
        raise NotImplementedError

    @property
    def inv_vocab(self):
        """id -> token dict, cached (used by whole-word masking)."""
        cached = getattr(self, "_inv_vocab_cache", None)
        if cached is None:
            cached = {i: t for t, i in self.vocab.items()}
            self._inv_vocab_cache = cached
        return cached

    @property
    def bos_token_id(self) -> int:
        return self.cls

    @property
    def eos_token_id(self) -> int:
        return self.eod

    @property
    def additional_special_tokens_ids(self) -> List[int]:
        return []


class _GPT2BPETokenizer(AbstractTokenizer):
    """GPT-2 byte-level BPE from local vocab.json + merges.txt."""

    def __init__(self, vocab_file: str, merge_file: str):
        try:
            from transformers import GPT2TokenizerFast

            self._tok = GPT2TokenizerFast(vocab_file=vocab_file,
                                          merges_file=merge_file)
        except ImportError:
            # standalone byte-level BPE (tokenizer/bpe.py) — same
            # algorithm, no transformers dependency
            from megatron_llm_tpu.tokenizer.bpe import StandaloneGPT2BPE

            self._tok = StandaloneGPT2BPE(vocab_file, merge_file)
        self._eod = self._tok.convert_tokens_to_ids("<|endoftext|>")

    @property
    def vocab_size(self):
        return len(self._tok)

    @property
    def vocab(self):
        return self._tok.get_vocab()

    def tokenize(self, text):
        return self._tok.encode(text)

    def detokenize(self, ids):
        return self._tok.decode(ids)

    @property
    def eod(self):
        return self._eod

    @property
    def pad(self):
        return self._eod


class _BertWordPieceTokenizer(AbstractTokenizer):
    def __init__(self, vocab_file: str, lower_case: bool = True,
                 vocab_extra_ids: int = 0):
        try:
            from transformers import BertTokenizerFast

            self._tok = BertTokenizerFast(vocab_file=vocab_file,
                                          do_lower_case=lower_case)
        except ImportError:
            # standalone WordPiece (tokenizer/wordpiece.py) — same
            # algorithm, no transformers dependency
            from megatron_llm_tpu.tokenizer.wordpiece import (
                StandaloneWordPiece,
            )

            self._tok = StandaloneWordPiece(vocab_file,
                                            do_lower_case=lower_case)
        # dedicated [BOS]/[EOS] tokens, matching the reference's
        # _BertWordPieceTokenizer (tokenizer.py:156-200: add_token('[BOS]'),
        # add_token('[EOS]')) — bos/eos must NOT collide with CLS/SEP/eod,
        # or T5 decoder-start tokens alias segment separators
        self._tok.add_special_tokens(
            {"bos_token": "[BOS]", "eos_token": "[EOS]"})
        if vocab_extra_ids > 0:
            # T5-style span sentinels (reference: tokenizer.py:123+ adds
            # <extra_id_N> when --vocab_extra_ids is set)
            self._tok.add_special_tokens({
                "additional_special_tokens": [
                    f"<extra_id_{i}>" for i in range(vocab_extra_ids)
                ]
            })

    @property
    def additional_special_tokens_ids(self):
        return self._tok.additional_special_tokens_ids

    @property
    def vocab_size(self):
        return len(self._tok)

    @property
    def vocab(self):
        return self._tok.get_vocab()

    def tokenize(self, text):
        return self._tok.encode(text, add_special_tokens=False)

    def detokenize(self, ids):
        return self._tok.decode(ids)

    @property
    def cls(self):
        return self._tok.cls_token_id

    @property
    def sep(self):
        return self._tok.sep_token_id

    @property
    def pad(self):
        return self._tok.pad_token_id

    @property
    def mask(self):
        return self._tok.mask_token_id

    @property
    def eod(self):
        return self._tok.sep_token_id

    @property
    def bos_token_id(self):
        return self._tok.bos_token_id

    @property
    def eos_token_id(self):
        return self._tok.eos_token_id


class _SentencePieceTokenizer(AbstractTokenizer):
    """Llama/Mistral .model tokenizer (reference: tokenizer.py:326+ with
    special tokens and --no_new_tokens)."""

    def __init__(self, model_file: str, vocab_extra_ids: int = 0,
                 new_tokens: bool = True):
        try:
            import sentencepiece as spm
            self._sp = spm.SentencePieceProcessor(model_file=model_file)
            self._backend = "spm"
        except ImportError:
            # fall back to HF fast tokenizer when given a directory with
            # tokenizer.json (covers llama/mistral checkpoints)
            from transformers import AutoTokenizer

            self._sp = AutoTokenizer.from_pretrained(model_file)
            self._backend = "hf"
        self._new_tokens = new_tokens
        self._extra = vocab_extra_ids

    @property
    def vocab_size(self):
        n = (self._sp.get_piece_size() if self._backend == "spm"
             else len(self._sp))
        return n + (self._extra if self._new_tokens else 0)

    def tokenize(self, text):
        if self._backend == "spm":
            return [self._sp.bos_id()] + self._sp.encode(text)
        return self._sp.encode(text)

    def detokenize(self, ids):
        return self._sp.decode(ids)

    @property
    def bos(self):
        return (self._sp.bos_id() if self._backend == "spm"
                else self._sp.bos_token_id)

    @property
    def eod(self):
        return (self._sp.eos_id() if self._backend == "spm"
                else self._sp.eos_token_id)

    @property
    def pad(self):
        if self._backend == "spm":
            pid = self._sp.pad_id()
            return pid if pid >= 0 else self.eod
        return self._sp.pad_token_id or self.eod


class _FalconTokenizer(AbstractTokenizer):
    def __init__(self, tokenizer_path: Optional[str] = None):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(
            tokenizer_path or "tiiuae/falcon-40b"
        )

    @property
    def vocab_size(self):
        return len(self._tok)

    @property
    def vocab(self):
        return self._tok.get_vocab()

    def tokenize(self, text):
        return self._tok.encode(text)

    def detokenize(self, ids):
        return self._tok.decode(ids)

    @property
    def eod(self):
        return self._tok.eos_token_id

    @property
    def pad(self):
        return self._tok.pad_token_id or self.eod


class _HFAutoTokenizer(AbstractTokenizer):
    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path)

    @property
    def vocab_size(self):
        return len(self._tok)

    def tokenize(self, text):
        return self._tok.encode(text)

    def detokenize(self, ids):
        return self._tok.decode(ids)

    @property
    def eod(self):
        return self._tok.eos_token_id

    @property
    def pad(self):
        return self._tok.pad_token_id or self.eod


class _NullTokenizer(AbstractTokenizer):
    """Whitespace-int tokenizer for tests and synthetic data."""

    def __init__(self, vocab_size: int):
        self._n = int(vocab_size)

    @property
    def vocab_size(self):
        return self._n + 1  # + eod

    def tokenize(self, text):
        return [int(t) for t in text.split()]

    def detokenize(self, ids):
        return " ".join(str(i) for i in ids)

    @property
    def eod(self):
        return self._n

    @property
    def pad(self):
        return self._n
