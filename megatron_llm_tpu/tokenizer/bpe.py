"""Self-contained GPT-2 byte-level BPE (no ``transformers`` dependency).

The reference embeds the original OpenAI GPT-2 encoder
(``megatron/tokenizer/gpt2_tokenization.py``); this module is the
fallback backend for ``_GPT2BPETokenizer`` when the ``transformers``
fast tokenizers are unavailable.  The byte-to-unicode table, split
pattern, and merge procedure are the published GPT-2 BPE algorithm;
parity with ``GPT2TokenizerFast`` is asserted in
``tests/test_tokenizer_standalone.py``.
"""

from __future__ import annotations

import functools
import json
from typing import Dict, List, Tuple

try:
    import regex as _re  # the GPT-2 pattern needs \p{L}/\p{N}
except ImportError:  # pragma: no cover - regex ships in the image
    _re = None

_PAT = (r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+|"
        r" ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+")


@functools.lru_cache()
def bytes_to_unicode() -> Dict[int, str]:
    """Reversible byte -> printable-unicode map (GPT-2's trick to make
    arbitrary bytes regex-safe)."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("\xa1"), ord("\xac") + 1))
          + list(range(ord("\xae"), ord("\xff") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, (chr(c) for c in cs)))


def _get_pairs(word: Tuple[str, ...]):
    return {(word[i], word[i + 1]) for i in range(len(word) - 1)}


class StandaloneGPT2BPE:
    """Drop-in for the parts of ``GPT2TokenizerFast`` the framework uses:
    encode, decode, vocab, ``convert_tokens_to_ids``."""

    def __init__(self, vocab_file: str, merges_file: str):
        if _re is None:
            raise ImportError(
                "standalone GPT-2 BPE needs the 'regex' module")
        with open(vocab_file, encoding="utf-8") as f:
            self._vocab: Dict[str, int] = json.load(f)
        self._inv = {i: t for t, i in self._vocab.items()}
        with open(merges_file, encoding="utf-8") as f:
            lines = f.read().split("\n")
        merges = [tuple(l.split()) for l in lines
                  if l and not l.startswith("#version") and len(l.split()) == 2]
        self._ranks = {m: i for i, m in enumerate(merges)}
        self._b2u = bytes_to_unicode()
        self._u2b = {u: b for b, u in self._b2u.items()}
        self._pat = _re.compile(_PAT)
        self._cache: Dict[str, List[str]] = {}
        # added special tokens are matched atomically in encode
        self._specials = {"<|endoftext|>"} & set(self._vocab)
        self.additional_special_tokens_ids: List[int] = []

    def __len__(self):
        return len(self._vocab)

    def get_vocab(self):
        return dict(self._vocab)

    def convert_tokens_to_ids(self, token: str) -> int:
        return self._vocab[token]

    def add_special_tokens(self, mapping: dict) -> int:
        """HF-compatible subset: named keys and the
        'additional_special_tokens' list; new tokens get fresh ids and
        are matched atomically by encode."""
        added = 0

        def add(tok: str) -> int:
            nonlocal added
            if tok not in self._vocab:
                idx = max(self._inv, default=-1) + 1
                self._vocab[tok] = idx
                self._inv[idx] = tok
                added += 1
            self._specials.add(tok)
            return self._vocab[tok]

        for key, val in mapping.items():
            if key == "additional_special_tokens":
                self.additional_special_tokens_ids = [add(t) for t in val]
            else:
                setattr(self, f"{key}_id", add(val))
        return added

    def _bpe(self, token: str) -> List[str]:
        if token in self._cache:
            return self._cache[token]
        word: Tuple[str, ...] = tuple(token)
        pairs = _get_pairs(word)
        while pairs:
            best = min(pairs, key=lambda p: self._ranks.get(p, 1 << 30))
            if best not in self._ranks:
                break
            a, b = best
            new: List[str] = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(a, i)
                except ValueError:
                    new.extend(word[i:])
                    break
                new.extend(word[i:j])
                if j < len(word) - 1 and word[j + 1] == b:
                    new.append(a + b)
                    i = j + 2
                else:
                    new.append(word[j])
                    i = j + 1
            word = tuple(new)
            if len(word) == 1:
                break
            pairs = _get_pairs(word)
        out = list(word)
        self._cache[token] = out
        return out

    def encode(self, text: str) -> List[int]:
        import re as _stdre

        ids: List[int] = []
        if self._specials:
            pat = "(" + "|".join(
                _stdre.escape(t) for t in sorted(self._specials, key=len,
                                                 reverse=True)) + ")"
            chunks = _stdre.split(pat, text)
        else:
            chunks = [text]
        for chunk in chunks:
            if chunk in self._specials:
                ids.append(self._vocab[chunk])
                continue
            for tok in self._pat.findall(chunk):
                mapped = "".join(self._b2u[b] for b in tok.encode("utf-8"))
                ids.extend(self._vocab[p] for p in self._bpe(mapped))
        return ids

    def decode(self, ids) -> str:
        text = "".join(self._inv.get(int(i), "") for i in ids)
        data = bytes(self._u2b[u] for u in text if u in self._u2b)
        return data.decode("utf-8", errors="replace")
