"""Device-mesh topology — the TPU-native replacement for
``megatron/core/parallel_state.py``.

The reference builds ~7 families of NCCL process groups from the
(tp, pp, vpp) sizes with rank arithmetic (``parallel_state.py:51-205``) and
exposes ~40 getters.  On TPU the entire fabric is one
``jax.sharding.Mesh`` with axes ``('pp', 'dp', 'tp')`` — the same rank
order as the reference (pp outer, dp middle, tp inner; TP groups are
contiguous device blocks, ``parallel_state.py:146-151``) so TP collectives
ride nearest-neighbour ICI links.

"Groups" become mesh axes; "group getters" become axis-size/axis-index
queries.  Rank predicates used inside sharded code (e.g.
``is_pipeline_last_stage`` inside the 1F1B loop) use ``jax.lax.axis_index``
under ``shard_map`` instead of global rank math.

Multi-host bootstrap: ``jax.distributed.initialize`` over DCN replaces the
torchrun/NCCL rendezvous (reference: ``megatron/initialize.py:124-151``).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical mesh-axis names.  The reference has no context-parallel groups
# (SURVEY §5.7: no ring attention / Ulysses); cp is this framework's
# first-class long-context axis — sequence-sharded activations with ring
# attention over ICI neighbours (parallel/ring_attention.py).
# ``slice`` is the outermost, DCN-connected axis: one entry per pod slice
# in a MegaScale-style multi-slice job (multislice.py).  It is size 1 in
# ordinary single-slice runs, so every spec/getter below is unchanged
# semantically unless --num_slices > 1.
SLICE_AXIS = "slice"
PP_AXIS = "pp"
DP_AXIS = "dp"
CP_AXIS = "cp"
TP_AXIS = "tp"
MESH_AXES = (SLICE_AXIS, PP_AXIS, DP_AXIS, CP_AXIS, TP_AXIS)

# env contract for slice identity (the MEGASCALE_SLICE_ID convention used
# by multi-slice TPU launchers); validated against the process-derived id
SLICE_ID_ENV = "MEGASCALE_SLICE_ID"

_MESH: Optional[Mesh] = None
_VIRTUAL_PIPELINE_MODEL_PARALLEL_SIZE: Optional[int] = None


def initialize_model_parallel(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    context_parallel_size: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    num_slices: int = 1,
) -> Mesh:
    """Build the global device mesh.

    Mirrors ``initialize_model_parallel`` (parallel_state.py:51-205) but
    returns a Mesh; dp size is derived as world // (slice*tp*pp*cp) exactly
    like the reference derives dp in arguments.py:76.

    ``num_slices`` partitions the fleet into that many DCN-connected pod
    slices (outermost mesh axis).  Device order from ``jax.devices()`` is
    process-major, so slices are contiguous process blocks: process p
    belongs to slice ``p * num_slices // process_count`` — the contract
    ``multislice.py`` documents and ``MEGASCALE_SLICE_ID`` is checked
    against.
    """
    global _MESH, _VIRTUAL_PIPELINE_MODEL_PARALLEL_SIZE
    if devices is None:
        devices = jax.devices()
    world = len(devices)
    tp, pp = tensor_model_parallel_size, pipeline_model_parallel_size
    cp, sl = context_parallel_size, num_slices
    if sl < 1 or world % sl != 0:
        raise RuntimeError(
            f"world size ({world}) is not divisible by num_slices ({sl})")
    if world % (sl * tp * pp * cp) != 0:
        raise RuntimeError(
            f"world size ({world}) is not divisible by num_slices ({sl}) "
            f"x tensor parallel size ({tp}) x pipeline parallel size "
            f"({pp}) x context parallel size ({cp})"
        )
    dp = world // (sl * tp * pp * cp)
    # Rank order (slice outermost — DCN boundaries between contiguous
    # device blocks; then pp, dp, cp, tp inner) — tp innermost keeps TP
    # collectives on nearest-neighbour ICI (parallel_state.py:116-171), cp
    # next so the ring permute is also neighbour-local.
    dev_array = np.asarray(devices).reshape(sl, pp, dp, cp, tp)
    _MESH = Mesh(dev_array, MESH_AXES)
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_SIZE = virtual_pipeline_model_parallel_size
    if sl > 1:
        declared = os.environ.get(SLICE_ID_ENV)
        if declared is not None:
            derived = slice_id()
            if derived is not None and int(declared) != derived:
                print(f" > WARNING: {SLICE_ID_ENV}={declared} but process "
                      f"{jax.process_index()} maps to slice {derived} by "
                      f"device order; check the launch rank ordering",
                      flush=True)
    return _MESH


def model_parallel_is_initialized() -> bool:
    return _MESH is not None


def get_mesh() -> Mesh:
    if _MESH is None:
        raise RuntimeError("model parallel mesh is not initialized")
    return _MESH


def set_mesh(mesh: Mesh) -> None:
    global _MESH
    _MESH = mesh


def destroy_model_parallel() -> None:
    # reference: parallel_state.py:497
    global _MESH, _VIRTUAL_PIPELINE_MODEL_PARALLEL_SIZE
    _MESH = None
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_SIZE = None


# ---------------------------------------------------------------------------
# Size getters (reference: parallel_state.py:217-320).
# ---------------------------------------------------------------------------

def get_tensor_model_parallel_world_size() -> int:
    return get_mesh().shape[TP_AXIS]


def get_pipeline_model_parallel_world_size() -> int:
    return get_mesh().shape[PP_AXIS]


def get_data_parallel_world_size() -> int:
    return get_mesh().shape[DP_AXIS]


def get_context_parallel_world_size() -> int:
    return get_mesh().shape[CP_AXIS]


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _VIRTUAL_PIPELINE_MODEL_PARALLEL_SIZE


def get_num_slices() -> int:
    """Size of the outer DCN ``slice`` axis (1 unless --num_slices > 1)."""
    return get_mesh().shape[SLICE_AXIS]


def num_slices_or_default(default: int = 1) -> int:
    """``get_num_slices()`` that tolerates an uninitialized mesh (pure
    single-device paths and numpy-golden tests)."""
    return _MESH.shape[SLICE_AXIS] if _MESH is not None else default


def slice_id() -> Optional[int]:
    """Which slice THIS process's devices belong to (host-side query).

    With ``slice`` outermost and jax's process-major device order, slices
    are contiguous process blocks.  Returns None when one process hosts
    more than one slice (single-process virtual-device runs) and the
    membership is therefore ambiguous — except slice 0 when there is only
    one slice.
    """
    sl = get_num_slices()
    if sl == 1:
        return 0
    procs = jax.process_count()
    if procs % sl != 0:
        return None if procs < sl else jax.process_index() * sl // procs
    return jax.process_index() // (procs // sl)


def get_world_size() -> int:
    m = get_mesh()
    return (m.shape[SLICE_AXIS] * m.shape[PP_AXIS] * m.shape[DP_AXIS]
            * m.shape[CP_AXIS] * m.shape[TP_AXIS])


# ---------------------------------------------------------------------------
# In-shard rank queries — valid *inside* shard_map over the mesh.
# (reference rank getters parallel_state.py:322-481 are process-global;
# under SPMD the analogue is the per-shard axis index.)
# ---------------------------------------------------------------------------

def get_tensor_model_parallel_rank():
    return jax.lax.axis_index(TP_AXIS)


def get_pipeline_model_parallel_rank():
    return jax.lax.axis_index(PP_AXIS)


def get_data_parallel_rank():
    return jax.lax.axis_index(DP_AXIS)


def get_slice_rank():
    return jax.lax.axis_index(SLICE_AXIS)


def is_pipeline_first_stage():
    # reference: parallel_state.py:322-341
    return jax.lax.axis_index(PP_AXIS) == 0


def is_pipeline_last_stage():
    return jax.lax.axis_index(PP_AXIS) == get_pipeline_model_parallel_world_size() - 1


# ---------------------------------------------------------------------------
# Host-side process queries (multi-host data loading).
# ---------------------------------------------------------------------------

def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bootstrap over DCN (reference: initialize.py:124-151 uses
    torchrun env vars + NCCL TCP rendezvous; here it is
    ``jax.distributed.initialize``, driven by the same env conventions)."""
    if num_processes is None:
        num_processes = int(os.environ.get("WORLD_SIZE", "1"))
    if num_processes <= 1:
        return
    if process_id is None:
        process_id = int(os.environ.get("RANK", "0"))
    if coordinator_address is None:
        addr = os.environ.get("MASTER_ADDR", "127.0.0.1")
        port = os.environ.get("MASTER_PORT", "8476")
        coordinator_address = f"{addr}:{port}"
    # Multi-process *CPU* runs (the 2-process integration tests) need the
    # gloo cross-host collectives backend selected before the CPU client
    # is created; without it every cross-process computation fails with
    # "Multiprocess computations aren't implemented on the CPU backend".
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass    # option absent on this jaxlib: TPU-only build
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


# ---------------------------------------------------------------------------
# Sharding constructors.
# ---------------------------------------------------------------------------

def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=False):
    """``jax.shard_map`` with the modern keyword API, bridged to
    ``jax.experimental.shard_map`` on pre-0.6 jax (where the stable alias
    does not exist and partial manualization is spelled ``auto=`` instead
    of ``axis_names=``, and ``check_vma`` is ``check_rep``)."""
    native = getattr(jax, "shard_map", None)
    if native is not None:
        kw = {} if axis_names is None else {"axis_names": set(axis_names)}
        return native(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma, **kw)
    # Pre-0.6 fallback: partial manualization (auto=...) mislowers
    # named-axis collectives to PartitionId on this jax, so manualize
    # EVERY axis instead.  Unmentioned spec axes then mean "replicated",
    # which keeps the math identical and only forgoes sharding the
    # region over the axes the caller left auto.
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def _bound_manual_axis_sizes() -> dict:
    """``{axis_name: size}`` for axes bound by an enclosing manual region
    (shard_map/pmap), read from the tracing axis env.  Empty outside any
    manual region, or when this jax version hides the axis env."""
    try:
        from jax._src.core import get_axis_env
        env = get_axis_env()
        sizes = getattr(env, "axis_sizes", None)
        return dict(sizes) if sizes else {}
    except Exception:
        return {}


def current_mesh_and_manual():
    """(governing mesh, already-Manual axis names) for building a
    shard_map that may nest inside another manual region — the abstract
    context mesh when one is active (inside jit/manual regions jax
    requires it plus re-declaration of every already-Manual axis), else
    the concrete global mesh.  ``(None, set())`` when no mesh governs."""
    get_abstract_mesh = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_abstract_mesh is None:
        # older jax spells it jax._src.mesh.get_abstract_mesh
        from jax._src.mesh import get_abstract_mesh
    mesh = get_abstract_mesh()
    if mesh is None or not getattr(mesh, "axis_names", None):
        # Pre-0.5 jax never sets the abstract-mesh context during a
        # shard_map trace, but the axis env still records which axes the
        # enclosing manual region bound (and their sizes).
        bound = _bound_manual_axis_sizes()
        if bound:
            if _MESH is not None and all(
                    _MESH.shape.get(n) == s for n, s in bound.items()):
                # manual region over the global mesh: nested shard_maps
                # must re-declare these already-Manual axes
                return _MESH, set(bound)
            # manual region over some OTHER mesh: there is no safe
            # global fallback (see nesting_mesh)
            return None, set(bound)
        # not inside any mesh context: the concrete global mesh governs
        mesh = _MESH
    if mesh is None:
        return None, set()
    # axis_types is None on jax builds where every axis is still Auto
    axis_types = getattr(mesh, "axis_types", None) or ()
    manual = {
        name for name, t in zip(mesh.axis_names, axis_types)
        if "Manual" in str(t)
    }
    return mesh, manual


def sharded_auto_mesh_active() -> bool:
    """True when the governing mesh has a size>1 axis still under GSPMD
    auto-sharding — i.e. auto partitioning is in play and a bare Mosaic
    custom call is a lowering error.  Axes already Manual don't count:
    inside a fully-manual region the arrays are device-local and pallas
    is legal."""
    mesh, manual = current_mesh_and_manual()
    return mesh is not None and any(
        mesh.shape[a] > 1 for a in mesh.axis_names if a not in manual)


def nesting_mesh(required_axis: str):
    """Mesh + already-manual axes for a shard_map that may nest inside
    another manual region (the pipeline engines).

    Returns ``(mesh, manual_axes)``, or ``(None, None)`` when
    ``required_axis`` is absent or size 1 in the governing mesh — the
    caller should fall back to its unsharded path.  NOTE: when an
    abstract mesh is active but lacks the axis we must NOT silently
    switch to the global mesh (a nested shard_map over a different mesh
    than the enclosing context fails with an opaque jax error —
    round-3 advisor finding).  Shared by ``vocab_parallel_lookup_manual``
    and ``context_parallel_attention``."""
    mesh, manual = current_mesh_and_manual()
    if (mesh is None or required_axis not in mesh.axis_names
            or mesh.shape[required_axis] == 1):
        return None, None
    return mesh, manual


def data_axes():
    """The mesh axes the global batch dimension spans: ``('slice', 'dp')``
    in a multi-slice run (data parallelism crosses the DCN axis too),
    plain ``('dp',)`` otherwise.  Usable directly as one PartitionSpec
    entry — ``P(None, data_axes(), None)``."""
    if _MESH is not None and _MESH.shape[SLICE_AXIS] > 1:
        return (SLICE_AXIS, DP_AXIS)
    return (DP_AXIS,)


def named_sharding(*spec) -> NamedSharding:
    return NamedSharding(get_mesh(), P(*spec))


def replicated_sharding() -> NamedSharding:
    return NamedSharding(get_mesh(), P())
