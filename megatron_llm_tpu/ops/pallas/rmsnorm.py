"""Fused RMSNorm Pallas kernel (stub dispatching to jnp until the kernel
milestone; the jnp path matches the reference RMSNorm numerics,
``megatron/model/fused_layer_norm.py:125-139``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from megatron_llm_tpu.ops.layernorm import rms_norm


def fused_rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    return rms_norm(x, scale, eps=eps, fp32_compute=True)
