"""Fused RMSNorm Pallas TPU kernel with custom VJP.

Replaces the reference's mixed-precision fused LayerNorm/RMSNorm CUDA
kernels (``megatron/fused_kernels/layer_norm_cuda_kernel.cu``,
``megatron/model/fused_layer_norm.py:125-139``): one pass over VMEM rows,
fp32 accumulation, bf16 I/O.

Forward: y = x * rsqrt(mean(x^2) + eps) * scale, computed per row-block.
Backward (hand-derived, matching the CUDA kernel's two-reduction form):
  dx = rstd * (g*scale - x * rstd^2 * mean(g*scale*x))
  dscale = sum over rows of g * x * rstd

Dispatch: TPU backend -> kernel; elsewhere -> jnp reference
(``ops.layernorm.rms_norm``).  Tested in interpret mode on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from megatron_llm_tpu.ops.layernorm import rms_norm

_INTERPRET = False
_BLOCK_ROWS = 256


def _use_pallas() -> bool:
    from megatron_llm_tpu import topology
    from megatron_llm_tpu.ops.pallas import pallas_backend_available

    if topology.sharded_auto_mesh_active():
        # GSPMD cannot auto-partition Mosaic kernels; unlike flash
        # attention (head/batch-local, shard_map-wrapped), the norm
        # kernels see a [tokens, hidden] view that mixes batch and
        # sharded-seq axes, so under auto sharding they defer to the
        # XLA norm (which fuses well and partitions).  Fully-manual
        # regions (pp-only pipelines) keep the pallas kernel.
        return False
    return _INTERPRET or pallas_backend_available()


def _pick_rows(n: int, h: int, itemsize: int) -> int:
    """Row-block height: <=1 MiB per (rows, h) block so the handful of
    double-buffered VMEM blocks (x, g, dx...) stay inside the ~16 MiB
    scoped-vmem budget at any hidden size; multiple of 8 sublanes."""
    budget = 1 << 20
    rows = max(8, min(_BLOCK_ROWS, budget // max(1, h * itemsize) // 8 * 8))
    return min(rows, max(8, n))


def _fwd_kernel(x_ref, s_ref, y_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    y = x * rstd * s_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    rstd_ref[:] = rstd                      # [rows, 1]


def _bwd_kernel(x_ref, s_ref, g_ref, rstd_ref, dx_ref, ds_ref, ds_scr,
                *, eps, n, rows):
    i = pl.program_id(0)
    nblocks = pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        ds_scr[:] = jnp.zeros_like(ds_scr)

    # mask padded rows of the final block (block padding is undefined
    # memory; it must not leak into the cross-row dscale reduction)
    row_valid = (i * rows + jax.lax.broadcasted_iota(
        jnp.int32, (rows, 1), 0)) < n
    x = jnp.where(row_valid, x_ref[:].astype(jnp.float32), 0.0)
    g = jnp.where(row_valid, g_ref[:].astype(jnp.float32), 0.0)
    s = s_ref[:].astype(jnp.float32)        # [1, h]
    rstd = jnp.where(row_valid, rstd_ref[:], 0.0)  # [rows, 1]
    gs = g * s
    h = x.shape[-1]
    m = jnp.sum(gs * x, axis=-1, keepdims=True) / h
    dx = rstd * (gs - x * (rstd * rstd) * m)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    # dscale accumulates across the (sequential) TPU grid in VMEM scratch
    ds_scr[:] += jnp.sum(g * x * rstd, axis=0, keepdims=True)

    @pl.when(i == nblocks - 1)
    def _finish():
        ds_ref[:] = ds_scr[:]


def _fwd_call(x2d, scale, eps):
    n, h = x2d.shape
    rows = _pick_rows(n, h, x2d.dtype.itemsize)
    grid = (pl.cdiv(n, rows),)
    y, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2d.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(x2d, scale.reshape(1, h))
    return y, rstd


def _bwd_call(x2d, scale, g2d, rstd, eps):
    n, h = x2d.shape
    rows = _pick_rows(n, h, x2d.dtype.itemsize)
    nblocks = pl.cdiv(n, rows)
    dx, ds = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps, n=n, rows=rows),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, h), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2d.dtype),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((1, h), jnp.float32)],
        interpret=_INTERPRET,
    )(x2d, scale.reshape(1, h), g2d, rstd)
    return dx, ds[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fused_rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-5):
    if not _use_pallas():
        return rms_norm(x, scale, eps=eps, fp32_compute=True)
    shape = x.shape
    y, _ = _fwd_call(x.reshape(-1, shape[-1]), scale, eps)
    return y.reshape(shape)


def _vjp_fwd(x, scale, eps):
    if not _use_pallas():
        return rms_norm(x, scale, eps=eps, fp32_compute=True), (x, scale, None)
    shape = x.shape
    y, rstd = _fwd_call(x.reshape(-1, shape[-1]), scale, eps)
    return y.reshape(shape), (x, scale, rstd)


def _vjp_bwd(eps, res, g):
    x, scale, rstd = res
    shape = x.shape
    if rstd is None:
        # jnp fallback backward
        _, vjp = jax.vjp(
            lambda xx, ss: rms_norm(xx, ss, eps=eps, fp32_compute=True),
            x, scale,
        )
        return vjp(g)
    dx, ds = _bwd_call(
        x.reshape(-1, shape[-1]), scale, g.reshape(-1, shape[-1]), rstd, eps
    )
    return dx.reshape(shape), ds.astype(scale.dtype)


fused_rms_norm.defvjp(_vjp_fwd, _vjp_bwd)
