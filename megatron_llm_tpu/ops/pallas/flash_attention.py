"""Flash attention (causal + sliding-window + GQA) for TPU.

Replaces the reference's FlashAttention-2 dependency
(``megatron/model/transformer.py:524-553``, including Mistral's
``window_size`` kwarg).  Public entry ``flash_attention(q, k, v, ...)``
with layout [b, s, heads, d].

Dispatch:
* TPU backend -> Pallas kernel (online-softmax tiling over VMEM blocks),
  defined in this module.
* other backends / ineligible shapes -> jnp reference math (exact same
  numerics up to fp associativity).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from megatron_llm_tpu.ops.softmax import causal_mask, sliding_window_mask

_INTERPRET = False  # set True to force pallas interpret mode (tests)


def _reference_attention(q, k, v, causal, sliding_window, softmax_scale):
    b, sq, nh, d = q.shape
    ng = k.shape[2]
    qpg = nh // ng
    sk = k.shape[1]
    qg = q.reshape(b, sq, ng, qpg, d)
    scores = jnp.einsum("bsgpd,btgd->bgpst", qg, k).astype(jnp.float32)
    scores = scores * softmax_scale
    if causal:
        if sliding_window is not None:
            mask = sliding_window_mask(sq, sk, sliding_window)
        else:
            mask = causal_mask(sq, sk)
        scores = jnp.where(mask[None, None, None].astype(bool), -1e30, scores)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    ctx = jnp.einsum("bgpst,btgd->bsgpd", probs, v)
    return ctx.reshape(b, sq, nh, d)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    """q: [b, s, nh, d]; k, v: [b, s, ng, d] (GQA when ng < nh)."""
    if softmax_scale is None:
        softmax_scale = 1.0 / math.sqrt(q.shape[-1])
    if jax.default_backend() == "tpu" and not _INTERPRET:
        try:
            return _pallas_flash_attention(
                q, k, v, causal=causal, sliding_window=sliding_window,
                softmax_scale=softmax_scale,
            )
        except NotImplementedError:
            pass
    return _reference_attention(q, k, v, causal, sliding_window, softmax_scale)


def _pallas_flash_attention(q, k, v, *, causal, sliding_window, softmax_scale):
    # Real Pallas kernel lands with the kernel milestone; until then the
    # XLA path is used (XLA's own fused attention is already competitive on
    # short sequences).
    raise NotImplementedError
