"""Flash attention for TPU (Pallas Mosaic kernels).

Replaces the reference's FlashAttention-2 dependency
(``megatron/model/transformer.py:524-553``), including Mistral's
sliding-window ``window_size`` and GQA/MQA head grouping.

Public entry ``flash_attention(q, k, v, ...)`` with layout
[b, s, heads, d] (batch-major, matching the rest of the framework).

Kernel structure (standard online-softmax tiling):

* forward: grid (batch, q_head, q_blocks, k_blocks), k innermost —
  sequential on TPU, so fp32 scratch (m, l, acc) carries across k blocks;
  fully-masked blocks (beyond causal diagonal / outside sliding window)
  are skipped with ``pl.when``.  Emits O and the per-row logsumexp L for
  the backward pass.  Per-row stats (L, delta) live in lane-broadcast
  ``[..., s, LANES]`` fp32 arrays so every BlockSpec keeps a Mosaic-legal
  (8, 128) trailing tile — a ``(1, 1, bq)`` row-vector out-spec does NOT
  lower on TPU (sublane block 1 over the head axis violates tiling).
* backward: two kernels — dQ (grid over q blocks, k innermost) and
  dK/dV (grid over k blocks, q innermost), both using the saved L and the
  delta = rowsum(dO * O) trick, computing p = exp(s - L) without
  re-running softmax reductions.  GQA: dK/dV are produced per *query*
  head and group-summed outside the kernel.

Dispatch: TPU backend -> kernels; otherwise -> jnp reference math
(identical numerics up to fp associativity).  Interpret-mode tests run the
kernels on CPU.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from megatron_llm_tpu.ops.softmax import causal_mask, sliding_window_mask

_INTERPRET = False
# Measured on TPU v5e (round 3, llama-400M, seq 2048, bf16): 128x128 blocks
# give 0.17 MFU, 512x512 0.37, 1024x1024 0.39 — the (qi, ki) grid overhead
# and per-block DMA dominate at small tiles.  1024 blocks fit VMEM at
# d=128 (4 MB fp32 score tile) and are clamped to the sequence length for
# short inputs; 2048 tiles fail to compile (scoped-vmem OOM).
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
NEG_INF = -1e30
# trailing lane width for per-row stats (LSE, delta): Mosaic requires the
# minor-most block dim to be a multiple of 128 (or the full array dim), so
# row stats are stored value-broadcast across a 128-lane axis.
LANES = 128


def _use_pallas() -> bool:
    from megatron_llm_tpu.ops.pallas import pallas_backend_available

    return _INTERPRET or pallas_backend_available()


# ---------------------------------------------------------------------------
# reference math (non-TPU fallback)
# ---------------------------------------------------------------------------

def _reference_attention(q, k, v, causal, sliding_window, softmax_scale):
    b, sq, nh, d = q.shape
    ng = k.shape[2]
    qpg = nh // ng
    sk = k.shape[1]
    qg = q.reshape(b, sq, ng, qpg, d)
    scores = jnp.einsum("bsgpd,btgd->bgpst", qg, k).astype(jnp.float32)
    scores = scores * softmax_scale
    if causal:
        if sliding_window is not None:
            mask = sliding_window_mask(sq, sk, sliding_window)
        else:
            mask = causal_mask(sq, sk)
        scores = jnp.where(mask[None, None, None].astype(bool), NEG_INF,
                           scores)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    ctx = jnp.einsum("bgpst,btgd->bsgpd", probs, v)
    return ctx.reshape(b, sq, nh, d)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr,
                *, scale, block_q, block_k, causal, window, kv_len, q_len):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q
    k_start = ki * block_k

    # block-level skip test (host-static grid; runtime predicate)
    run = jnp.bool_(True)
    if causal:
        run = run & (k_start <= q_start + block_q - 1)
    if window is not None:
        run = run & (k_start + block_k - 1 > q_start - window)

    @pl.when(run)
    def _compute():
        # sanitize padded rows (pallas block padding is undefined memory;
        # NaNs there would poison the whole block through the matmuls)
        k_row_valid = (k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)) < kv_len
        q = q_ref[0, 0].astype(jnp.float32)          # [bq, d]
        k = jnp.where(k_row_valid, k_ref[0, 0].astype(jnp.float32), 0.0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                     # [bq, bk]

        q_ids = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_ids = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = (k_ids < kv_len) & (q_ids < q_len)
        if causal:
            mask &= k_ids <= q_ids
        if window is not None:
            mask &= k_ids > q_ids - window
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:]                             # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        l_new = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        v = jnp.where(k_row_valid, v_ref[0, 0].astype(jnp.float32), 0.0)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:]                                  # [bq, 1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(l == 0.0, NEG_INF, m_scr[:] + jnp.log(l_safe))
        lse_ref[0, 0] = jnp.broadcast_to(lse, (lse.shape[0], LANES))


def _fwd_call(q, k, v, *, scale, causal, window, block_q, block_k):
    """q [b, nh, sq, d]; k, v [b, ng, sk, d] -> (o, lse)."""
    b, nh, sq, d = q.shape
    ng, sk = k.shape[1], k.shape[2]
    qpg = nh // ng
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    nq = pl.cdiv(sq, bq)
    nk = pl.cdiv(sk, bk)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=bq, block_k=bk,
        causal=causal, window=window, kv_len=sk, q_len=sq,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=(b, nh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bb, h, qi, ki: (bb, h, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bb, h, qi, ki: (bb, h // qpg, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bb, h, qi, ki: (bb, h // qpg, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bb, h, qi, ki: (bb, h, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, LANES),
                         lambda bb, h, qi, ki: (bb, h, qi, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, nh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, nh, sq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr,
                   *, scale, block_q, block_k, causal, window, kv_len,
                   q_len):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_start = qi * block_q
    k_start = ki * block_k
    run = jnp.bool_(True)
    if causal:
        run = run & (k_start <= q_start + block_q - 1)
    if window is not None:
        run = run & (k_start + block_k - 1 > q_start - window)

    @pl.when(run)
    def _compute():
        k_row_valid = (k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)) < kv_len
        q_row_valid = (q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)) < q_len
        q = jnp.where(q_row_valid, q_ref[0, 0].astype(jnp.float32), 0.0)
        k = jnp.where(k_row_valid, k_ref[0, 0].astype(jnp.float32), 0.0)
        v = jnp.where(k_row_valid, v_ref[0, 0].astype(jnp.float32), 0.0)
        do = jnp.where(q_row_valid, do_ref[0, 0].astype(jnp.float32), 0.0)
        # stats arrive lane-broadcast [bq, LANES]; any lane reduction
        # recovers the row value (max also tolerates padded-row garbage)
        lse = jnp.where(q_row_valid,
                        jnp.max(lse_ref[0, 0], axis=-1, keepdims=True), 0.0)
        delta = jnp.where(q_row_valid,
                          jnp.max(delta_ref[0, 0], axis=-1, keepdims=True),
                          0.0)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        q_ids = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_ids = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = (k_ids < kv_len) & (q_ids < q_len)
        if causal:
            mask &= k_ids <= q_ids
        if window is not None:
            mask &= k_ids > q_ids - window
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_scr[:] += jax.lax.dot(
            ds, k, preferred_element_type=jnp.float32) * scale

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale, block_q, block_k, causal, window, kv_len,
                    q_len):
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_start = qi * block_q
    k_start = ki * block_k
    run = jnp.bool_(True)
    if causal:
        run = run & (k_start <= q_start + block_q - 1)
    if window is not None:
        run = run & (k_start + block_k - 1 > q_start - window)

    @pl.when(run)
    def _compute():
        k_row_valid = (k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)) < kv_len
        q_row_valid = (q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)) < q_len
        q = jnp.where(q_row_valid, q_ref[0, 0].astype(jnp.float32), 0.0)
        k = jnp.where(k_row_valid, k_ref[0, 0].astype(jnp.float32), 0.0)
        v = jnp.where(k_row_valid, v_ref[0, 0].astype(jnp.float32), 0.0)
        do = jnp.where(q_row_valid, do_ref[0, 0].astype(jnp.float32), 0.0)
        # stats arrive lane-broadcast [bq, LANES]; any lane reduction
        # recovers the row value (max also tolerates padded-row garbage)
        lse = jnp.where(q_row_valid,
                        jnp.max(lse_ref[0, 0], axis=-1, keepdims=True), 0.0)
        delta = jnp.where(q_row_valid,
                          jnp.max(delta_ref[0, 0], axis=-1, keepdims=True),
                          0.0)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        q_ids = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_ids = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = (k_ids < kv_len) & (q_ids < q_len)
        if causal:
            mask &= k_ids <= q_ids
        if window is not None:
            mask &= k_ids > q_ids - window
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)        # [bq, bk]
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        ds = p * (dp - delta)
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [bk, d]

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                      *, scale, block_q, block_k, causal, window, kv_len,
                      q_len):
    """Single-pass backward: one sweep of the (ki, qi) block grid computes
    dq, dk, dv together, sharing the s = q k^T recompute and the
    dp = do v^T matmul that the two-kernel structure (below) performs
    twice — 5 block matmuls instead of 7 (the round-3 'known headroom',
    docs/perf_tpu.md).

    dq accumulation: the dq output block is the FULL [sq, d] fp32 slab
    per (b, h), whose index map ignores (ki, qi) — consecutive revisits
    keep it VMEM-resident across the whole sweep, so the row slice for
    each qi accumulates in place with no HBM round trip; it is written
    back once when (b, h) advances."""
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when((ki == 0) & (qi == 0))
    def _init_dq():
        dq_ref[0, 0] = jnp.zeros_like(dq_ref[0, 0])

    @pl.when(qi == 0)
    def _init_kv():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_start = qi * block_q
    k_start = ki * block_k
    run = jnp.bool_(True)
    if causal:
        run = run & (k_start <= q_start + block_q - 1)
    if window is not None:
        run = run & (k_start + block_k - 1 > q_start - window)

    @pl.when(run)
    def _compute():
        k_row_valid = (k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)) < kv_len
        q_row_valid = (q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)) < q_len
        q = jnp.where(q_row_valid, q_ref[0, 0].astype(jnp.float32), 0.0)
        k = jnp.where(k_row_valid, k_ref[0, 0].astype(jnp.float32), 0.0)
        v = jnp.where(k_row_valid, v_ref[0, 0].astype(jnp.float32), 0.0)
        do = jnp.where(q_row_valid, do_ref[0, 0].astype(jnp.float32), 0.0)
        lse = jnp.where(q_row_valid,
                        jnp.max(lse_ref[0, 0], axis=-1, keepdims=True), 0.0)
        delta = jnp.where(q_row_valid,
                          jnp.max(delta_ref[0, 0], axis=-1, keepdims=True),
                          0.0)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        q_ids = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_ids = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = (k_ids < kv_len) & (q_ids < q_len)
        if causal:
            mask &= k_ids <= q_ids
        if window is not None:
            mask &= k_ids > q_ids - window
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)         # [bq, bk]
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        ds = p * (dp - delta)
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [bk, d]
        rows = pl.ds(q_start, block_q)
        dq_ref[0, 0, rows, :] += jax.lax.dot(
            ds, k, preferred_element_type=jnp.float32) * scale

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_fused_call(q, k, v, do, lse, delta, *, scale, causal, window,
                    bq, bk, nq, nk):
    b, nh, sq, d = q.shape
    ng, sk = k.shape[1], k.shape[2]
    qpg = nh // ng
    kw = dict(scale=scale, block_q=bq, block_k=bk, causal=causal,
              window=window, kv_len=sk, q_len=sq)
    dq, dk_h, dv_h = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, **kw),
        grid=(b, nh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bb, h, ki, qi: (bb, h, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bb, h, ki, qi: (bb, h // qpg, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bb, h, ki, qi: (bb, h // qpg, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, d), lambda bb, h, ki, qi: (bb, h, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, LANES),
                         lambda bb, h, ki, qi: (bb, h, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, LANES),
                         lambda bb, h, ki, qi: (bb, h, qi, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            # full-seq dq slab; index map ignores (ki, qi) -> VMEM-resident
            # for the whole (b, h) sweep (see kernel docstring)
            pl.BlockSpec((1, 1, sq, d), lambda bb, h, ki, qi: (bb, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bb, h, ki, qi: (bb, h, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bb, h, ki, qi: (bb, h, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, nh, sq, d), jnp.float32),
            jax.ShapeDtypeStruct((b, nh, sk, d), q.dtype),
            jax.ShapeDtypeStruct((b, nh, sk, d), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(q, k, v, do, lse, delta)
    dk = dk_h.reshape(b, ng, qpg, sk, d).sum(axis=2)
    dv = dv_h.reshape(b, ng, qpg, sk, d).sum(axis=2)
    return dq.astype(q.dtype), dk, dv


# The fused single-pass backward is the default; the two-kernel structure
# below is kept as the fallback (bench.py kernel smoke degrades to it if
# the fused kernel fails to lower on some libtpu, and partial trailing
# blocks only support it).
FUSED_BACKWARD = True
# The fused kernel keeps the whole [sq, d] fp32 dq slab VMEM-resident; the
# round-3 tile sweep put 1024x1024 score tiles near the scoped-vmem limit,
# so cap the slab (4 MB = seq 8192 at d 128) and route longer sequences to
# the two-kernel structure instead of risking a compile-time OOM at
# exactly the long-context lengths the fallback ladder protects.
FUSED_BWD_MAX_SLAB_BYTES = 4 << 20
# The fused kernel's own block sizes.  They are SMALLER than the
# two-kernel 1024 defaults because its scoped-vmem working set carries
# four bq x bk fp32 score-tile intermediates (s, p, dp, ds) PLUS the
# full-seq dq slab: at 1024x1024 that is ~15 MB of tiles before the slab
# and the real compiler rejects it (verified via tools/compile_stats.py
# — 16.05 MB needed vs the 16 MB scoped-vmem limit at seq 2048, worse at
# longer seq).  512x512 tiles cost 4 MB total, leaving room for the slab
# at every supported length.  Whether fused@512 beats two-kernel@1024
# on-chip is exactly what `tools/mfu_sweep.py fusedbwd` measures.
FUSED_BLOCK_Q = 512
FUSED_BLOCK_K = 512


def _bwd_call(q, k, v, o, lse, do, *, scale, causal, window,
              block_q, block_k):
    b, nh, sq, d = q.shape
    ng, sk = k.shape[1], k.shape[2]
    qpg = nh // ng
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    nq = pl.cdiv(sq, bq)
    nk = pl.cdiv(sk, bk)

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, LANES))

    # caller block sizes act as an upper bound (explicit tuning hints,
    # e.g. tests at 64); the fused defaults shrink the usual 1024s to a
    # scoped-vmem-safe size
    fbq = min(FUSED_BLOCK_Q, bq)
    fbk = min(FUSED_BLOCK_K, bk)
    if (FUSED_BACKWARD and sq % fbq == 0 and sk % fbk == 0
            and sq * d * 4 <= FUSED_BWD_MAX_SLAB_BYTES):
        # full blocks only: the fused kernel's in-place row-slice
        # accumulation into the dq slab assumes every q block is complete
        return _bwd_fused_call(
            q, k, v, do, lse, delta, scale=scale, causal=causal,
            window=window, bq=fbq, bk=fbk,
            nq=pl.cdiv(sq, fbq), nk=pl.cdiv(sk, fbk))

    kw = dict(scale=scale, block_q=bq, block_k=bk, causal=causal,
              window=window, kv_len=sk, q_len=sq)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **kw),
        grid=(b, nh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bb, h, qi, ki: (bb, h, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bb, h, qi, ki: (bb, h // qpg, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bb, h, qi, ki: (bb, h // qpg, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, d), lambda bb, h, qi, ki: (bb, h, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, LANES),
                         lambda bb, h, qi, ki: (bb, h, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, LANES),
                         lambda bb, h, qi, ki: (bb, h, qi, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda bb, h, qi, ki: (bb, h, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, nh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_INTERPRET,
    )(q, k, v, do, lse, delta)

    # dk/dv per query head, group-summed afterwards (GQA)
    dk_h, dv_h = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **kw),
        grid=(b, nh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bb, h, ki, qi: (bb, h, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bb, h, ki, qi: (bb, h // qpg, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bb, h, ki, qi: (bb, h // qpg, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, d), lambda bb, h, ki, qi: (bb, h, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, LANES),
                         lambda bb, h, ki, qi: (bb, h, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, LANES),
                         lambda bb, h, ki, qi: (bb, h, qi, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d),
                         lambda bb, h, ki, qi: (bb, h, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bb, h, ki, qi: (bb, h, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, nh, sk, d), q.dtype),
            jax.ShapeDtypeStruct((b, nh, sk, d), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(q, k, v, do, lse, delta)

    dk = dk_h.reshape(b, ng, qpg, sk, d).sum(axis=2)
    dv = dv_h.reshape(b, ng, qpg, sk, d).sum(axis=2)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry (custom VJP over [b, s, h, d] layout)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, window, scale, block_q, block_k):
    o, _ = _fwd_call(
        jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2),
        scale=scale, causal=causal, window=window,
        block_q=block_q, block_k=block_k,
    )
    return jnp.swapaxes(o, 1, 2)


def _flash_fwd(q, k, v, causal, window, scale, block_q, block_k):
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    o, lse = _fwd_call(qt, kt, vt, scale=scale, causal=causal, window=window,
                       block_q=block_q, block_k=block_k)
    return jnp.swapaxes(o, 1, 2), (qt, kt, vt, o, lse)


def _flash_bwd(causal, window, scale, block_q, block_k, res, g):
    qt, kt, vt, o, lse = res
    do = jnp.swapaxes(g, 1, 2)
    dq, dk, dv = _bwd_call(qt, kt, vt, o, lse, do, scale=scale,
                           causal=causal, window=window,
                           block_q=block_q, block_k=block_k)
    return (jnp.swapaxes(dq, 1, 2), jnp.swapaxes(dk, 1, 2),
            jnp.swapaxes(dv, 1, 2))


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    softmax_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
) -> jax.Array:
    """q: [b, s, nh, d]; k, v: [b, s, ng, d] (GQA when ng < nh).

    block_q/block_k default to the module-level DEFAULT_BLOCK_Q/K *at call
    time* so benchmarks and configs can retune them without re-importing.
    """
    if block_q is None:
        block_q = DEFAULT_BLOCK_Q
    if block_k is None:
        block_k = DEFAULT_BLOCK_K
    if softmax_scale is None:
        softmax_scale = 1.0 / math.sqrt(q.shape[-1])
    if not _use_pallas():
        return _reference_attention(q, k, v, causal, sliding_window,
                                    softmax_scale)
    return _flash(q, k, v, causal, sliding_window, softmax_scale,
                  block_q, block_k)


def sharded_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    softmax_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
) -> jax.Array:
    """``flash_attention`` under an active device mesh.

    GSPMD cannot auto-partition Mosaic custom calls ("Mosaic kernels
    cannot be automatically partitioned" — surfaced the moment AOT
    compiles engaged the real kernels, round 5), so under a mesh the
    pallas call must run inside an explicit ``shard_map``.  Attention is
    batch-local and head-local, so the manual region maps batch over dp
    and heads over tp with no collectives: each device runs the kernel
    on its local [b/dp, s, nh/tp, d] slab.  GQA kv heads shard over tp
    when divisible; MQA (ng=1) replicates kv, which preserves the local
    q-heads-per-group ratio.  Falls back to the plain call when no mesh
    axis actually shards the inputs.  Nests inside the pipeline engines'
    pp-manual regions the same way ring attention does
    (``topology.nesting_mesh`` semantics: abstract mesh + re-declared
    manual axes).
    """
    kw = dict(causal=causal, sliding_window=sliding_window,
              softmax_scale=softmax_scale, block_q=block_q,
              block_k=block_k)
    if not _use_pallas():
        # XLA fallback attention partitions automatically; no wrapper
        return flash_attention(q, k, v, **kw)

    from jax.sharding import PartitionSpec as P

    from megatron_llm_tpu import topology

    if not isinstance(q, jax.core.Tracer):
        # eager call (no jit): subset-manual shard_map needs a tracing
        # context, and eager arrays are device-local anyway
        return flash_attention(q, k, v, **kw)

    mesh, manual = topology.current_mesh_and_manual()
    if mesh is None:
        return flash_attention(q, k, v, **kw)

    b, _, nh, _ = q.shape
    ng = k.shape[2]

    def auto_size(name):
        return (mesh.shape[name]
                if name in mesh.axis_names and name not in manual else 1)

    def usable(name, dim_size):
        return auto_size(name) > 1 and dim_size % mesh.shape[name] == 0

    def xla_fallback():
        # a combo the manual mapping can't express: the raw pallas call
        # would hit the GSPMD 'Mosaic kernels cannot be automatically
        # partitioned' lowering error (the arrays may be sharded even
        # when not evenly divisible), so use partitionable XLA math —
        # q-chunked past the length where the [s, s] score tensor is a
        # compile hazard
        from megatron_llm_tpu.ops.chunked_attention import (
            CHUNKED_ATTENTION_MIN_SEQ,
            chunked_causal_attention,
        )

        if q.shape[1] >= CHUNKED_ATTENTION_MIN_SEQ:
            # chunked path handles causal=False too — the [s, s] score
            # hazard doesn't care about masking
            return chunked_causal_attention(
                q, k, v, causal=causal, sliding_window=sliding_window,
                softmax_scale=softmax_scale)
        return _reference_attention(q, k, v, causal, sliding_window,
                                    softmax_scale
                                    or 1.0 / math.sqrt(q.shape[-1]))

    dp = topology.DP_AXIS if usable(topology.DP_AXIS, b) else None
    tp_q = topology.TP_AXIS if usable(topology.TP_AXIS, nh) else None
    tp_kv = tp_q if (tp_q and ng % mesh.shape[tp_q] == 0) else None
    if dp is None and tp_q is None:
        if auto_size(topology.DP_AXIS) == 1 and \
                auto_size(topology.TP_AXIS) == 1:
            # nothing can shard batch/heads: plain pallas is safe
            return flash_attention(q, k, v, **kw)
        return xla_fallback()  # axes exist but dims don't divide
    if tp_q and tp_kv is None and ng > 1:
        # GQA kv heads not divisible by tp: sharding q but replicating kv
        # would change the local q-per-group ratio — unsupported combo
        return xla_fallback()

    qspec = P(dp, None, tp_q, None)
    kvspec = P(dp, None, tp_kv, None)
    # ALL mesh axes go manual, not just the ones in the specs: with a
    # subset, the Mosaic call still sits inside an auto-sharding region
    # for the remaining axes and the GSPMD partitioner refuses it even
    # when those axes are size 1 / unused.  Unmentioned manual axes mean
    # "replicated", which matches the activation layout here (and inside
    # an enclosing pp/cp-manual region, matches per-group locality).
    return topology.shard_map(
        lambda ql, kl, vl: flash_attention(ql, kl, vl, **kw),
        mesh=mesh,
        in_specs=(qspec, kvspec, kvspec),
        out_specs=qspec,
        axis_names=set(mesh.axis_names),
        check_vma=False,
    )(q, k, v)
