"""Ragged paged-attention kernels for the serving engine (Pallas Mosaic
TPU) — decode (one query token per slot) and chunked prefill (a [C]-token
query block per slot) share one kernel body.

The XLA paged branch in ``models/transformer.py`` gathers every slot's
FULL block table into a dense ``[b, M*bs, g, d]`` view (dequantizing
every int8 page) before masked attention — each call moves the
worst-case context for every slot.  These kernels walk each slot's block
table directly in the grid instead, reading only the pages the slot
actually owns (arXiv:2604.15464 is the blueprint; paged-KV HBM traffic
is the serving throughput ceiling, arXiv:2605.25645).

Shape contract (the serving engine's paged programs):

* ``q`` — decode ``[S, nh, d]``: ONE query token per slot; prefill
  ``[S, C, nh, d]``: a C-token chunk per slot (the engine's ``[1, C]``
  chunked-prefill call).
* ``k_pages``/``v_pages`` — ``[P, bs, g, d]`` shared page pool, already
  containing this call's scatter-on-write (the query tokens' K/V sit at
  positions ``context_lens[s] .. context_lens[s]+C-1``).  int8 pools
  ship per-(page, position, group) fp32 absmax scales ``[P, bs, g]``
  and are dequantized in-kernel, so int8 is what crosses HBM.
* ``block_tables`` — ``[S, M]`` int32, entries beyond a slot's
  allocation = 0 (the reserved garbage block).
* ``context_lens`` — ``[S]`` int32: tokens already cached BEFORE this
  call's queries.  Decode attends keys ``0..context_lens[s]``
  inclusive; prefill row ``j`` attends ``0..context_lens[s]+j`` (causal
  within the chunk on top of the full paged history).  A sliding window
  additionally drops ``key_pos <= query_pos - window``.

Kernel structure: grid ``(slot, q-block, page)`` with the page dimension
innermost — sequential on TPU, so fp32 scratch (m, l, acc) carries the
online-softmax state across a (slot, q-block)'s pages.  The page index
map clamps out-of-range grid steps to the nearest live page: Mosaic
skips the DMA when consecutive grid steps map a block to the same index,
so a slot with 3 live pages out of M=128 moves exactly 3 pages of KV per
q-block.  All query heads ride in one block per grid step (GQA groups
are a static in-kernel loop), so each page is fetched once, not once per
head.  Decode is the ``C == block_q == 1`` instance of the same body —
one scaffold, two entry points.

Dispatch mirrors ``flash_attention.py``: TPU backend -> kernel;
otherwise -> jnp reference math (the same dense-gather computation as
the transformer's XLA branch).  Interpret-mode tests run the kernels on
CPU via the module-level ``_INTERPRET`` flag.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_INTERPRET = False
NEG_INF = -1e30
# default prefill q-block rows (clipped to the chunk; kept MXU-sized so
# the fp32 scratch [block_q*nh, d] stays well inside VMEM)
_PREFILL_BLOCK_Q = 128


def _use_pallas() -> bool:
    from megatron_llm_tpu.ops.pallas import pallas_backend_available

    return _INTERPRET or pallas_backend_available()


def decode_kernel_available() -> bool:
    """True when ``paged_attention_decode`` would run the Pallas kernel
    (TPU backend, or interpret mode in tests) — the transformer's
    ``--serve_paged_kernel auto`` predicate and the engine's
    ``paged_kernel: pallas|xla`` attribution both key off this."""
    return _use_pallas()


def prefill_kernel_available() -> bool:
    """Same gate for ``paged_attention_prefill`` (the kernels share a
    backend, so today this equals :func:`decode_kernel_available`; kept
    separate so ``--serve_prefill_kernel auto`` and the engine's
    ``prefill_kernel`` attribution have their own seam)."""
    return _use_pallas()


# ---------------------------------------------------------------------------
# reference math (non-TPU fallback; identical to the XLA paged branch)
# ---------------------------------------------------------------------------

def _reference_paged_prefill(q, k_pages, v_pages, block_tables,
                             context_lens, k_scales, v_scales,
                             scale, window):
    """Dense-gather chunked prefill: q [S, C, nh, d], row ``j`` of slot
    ``s`` attends key positions ``0..context_lens[s]+j`` (minus the
    sliding window) — the same math as the transformer's XLA branch."""
    S, C, nh, d = q.shape
    bs, g = k_pages.shape[1], k_pages.shape[2]
    M = block_tables.shape[1]
    qpg = nh // g
    k = k_pages[block_tables].reshape(S, M * bs, g, d).astype(jnp.float32)
    v = v_pages[block_tables].reshape(S, M * bs, g, d).astype(jnp.float32)
    if k_scales is not None:
        k = k * k_scales[block_tables].reshape(S, M * bs, g, 1)
        v = v * v_scales[block_tables].reshape(S, M * bs, g, 1)
    qg = q.reshape(S, C, g, qpg, d).astype(jnp.float32)
    scores = jnp.einsum("bsgpd,btgd->bgpst", qg, k) * scale
    key_pos = jnp.arange(M * bs)
    pos = context_lens[:, None] + jnp.arange(C)[None, :]        # [S, C]
    valid = key_pos[None, None, :] <= pos[:, :, None]           # [S, C, T]
    if window is not None:
        valid &= key_pos[None, None, :] > (pos[:, :, None] - window)
    scores = jnp.where(valid[:, None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bgpst,btgd->bsgpd", probs, v)
    return out.reshape(S, C, nh, d).astype(q.dtype)


def _reference_paged_attention(q, k_pages, v_pages, block_tables,
                               context_lens, k_scales, v_scales,
                               scale, window):
    """Decode reference — the C == 1 instance of the prefill reference."""
    return _reference_paged_prefill(
        q[:, None], k_pages, v_pages, block_tables, context_lens,
        k_scales, v_scales, scale, window)[:, 0]


# ---------------------------------------------------------------------------
# shared ragged kernel body (decode == block_q 1)
# ---------------------------------------------------------------------------

def _ragged_body(bt_ref, cl_ref, q_ref, k_ref, v_ref, o_ref,
                 m_scr, l_scr, acc_scr,
                 *, ks_ref, vs_ref, scale, block_size, block_q, window, qpg):
    s = pl.program_id(0)
    qi = pl.program_id(1)
    pi = pl.program_id(2)
    npi = pl.num_programs(2)
    bs = block_size
    bq = block_q
    g = k_ref.shape[2]
    d = k_ref.shape[3]
    # scratch rows per GQA group: the q-block's [bq, qpg, d] query slice
    # flattened to [R, d] so scores stay 2-D for the MXU; flat row r is
    # (chunk row r // qpg, in-group head r % qpg)
    R = bq * qpg

    @pl.when(pi == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    ctx = cl_ref[s]                       # keys cached before this call
    q0 = qi * bq                          # first chunk row of this q-block
    last = (ctx + q0 + bq - 1) // bs      # newest page any row attends
    if window is None:
        first = 0
    else:
        first = jnp.maximum(ctx + q0 - window + 1, 0) // bs

    @pl.when((pi >= first) & (pi <= last))
    def _compute():
        k = k_ref[0].astype(jnp.float32)              # [bs, g, d]
        v = v_ref[0].astype(jnp.float32)
        if ks_ref is not None:
            k = k * ks_ref[0][:, :, None]             # [bs, g] scales
            v = v * vs_ref[0][:, :, None]
        qh = q_ref[0].astype(jnp.float32)             # [bq, nh, d]
        key_pos = pi * bs + jax.lax.broadcasted_iota(
            jnp.int32, (R, bs), 1)
        # per-row causal bound: flat row r belongs to chunk row r // qpg
        pos = ctx + q0 + jax.lax.broadcasted_iota(
            jnp.int32, (R, bs), 0) // qpg
        valid = key_pos <= pos
        if window is not None:
            valid &= key_pos > pos - window
        # one page DMA serves every query head: GQA groups are a static
        # unrolled loop over the head block's row slices
        for grp in range(g):
            rows = slice(grp * R, (grp + 1) * R)
            q2 = qh[:, grp * qpg:(grp + 1) * qpg, :].reshape(R, d)
            sq = jax.lax.dot_general(
                q2, k[:, grp, :], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                 # [R, bs]
            sq = jnp.where(valid, sq, NEG_INF)
            m_prev = m_scr[rows]                      # [R, 1]
            m_new = jnp.maximum(m_prev,
                                jnp.max(sq, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.where(valid, jnp.exp(sq - m_new), 0.0)
            l_scr[rows] = l_scr[rows] * alpha + jnp.sum(p, axis=-1,
                                                        keepdims=True)
            acc_scr[rows] = acc_scr[rows] * alpha + jax.lax.dot(
                p, v[:, grp, :], preferred_element_type=jnp.float32)
            m_scr[rows] = m_new

    @pl.when(pi == npi - 1)
    def _finish():
        outs = []
        for grp in range(g):
            rows = slice(grp * R, (grp + 1) * R)
            l = l_scr[rows]                           # [R, 1]
            l_safe = jnp.where(l == 0.0, 1.0, l)
            outs.append((acc_scr[rows] / l_safe).reshape(bq, qpg, d))
        out = outs[0] if g == 1 else jnp.concatenate(outs, axis=1)
        o_ref[0] = out.astype(o_ref.dtype)            # [bq, nh, d]


def _ragged_kernel_plain(bt, cl, q, k, v, o, m, l, acc, **kw):
    _ragged_body(bt, cl, q, k, v, o, m, l, acc,
                 ks_ref=None, vs_ref=None, **kw)


def _ragged_kernel_quant(bt, cl, q, k, ks, v, vs, o, m, l, acc, **kw):
    _ragged_body(bt, cl, q, k, v, o, m, l, acc,
                 ks_ref=ks, vs_ref=vs, **kw)


def _ragged_call(q, k_pages, v_pages, block_tables, context_lens,
                 k_scales, v_scales, *, scale, window, block_q):
    """Shared pallas_call scaffold: q [S, C, nh, d] with block_q | C.
    Decode is the C == block_q == 1 instance."""
    S, C, nh, d = q.shape
    bs, g = k_pages.shape[1], k_pages.shape[2]
    M = block_tables.shape[1]
    qpg = nh // g
    bq = block_q
    assert C % bq == 0, (C, bq)
    nq = C // bq
    quantized = k_scales is not None

    def page_map(s, qi, pi, bt_ref, cl_ref):
        # clamp out-of-range grid steps to the nearest page this
        # (slot, q-block) attends: Mosaic skips the block copy when
        # consecutive steps map to the same index, so only the live
        # pages up to ceil((ctx + (qi+1)*bq)/bs) (minus any fully
        # outside the sliding window) are fetched
        hi = jnp.minimum((cl_ref[s] + (qi + 1) * bq - 1) // bs, M - 1)
        lo = (jnp.maximum(cl_ref[s] + qi * bq - window + 1, 0) // bs
              if window is not None else 0)
        return (bt_ref[s, jnp.clip(pi, lo, hi)], 0, 0, 0)

    def scale_map(s, qi, pi, bt_ref, cl_ref):
        return page_map(s, qi, pi, bt_ref, cl_ref)[:3]

    def q_map(s, qi, pi, bt_ref, cl_ref):
        return (s, qi, 0, 0)

    q_spec = pl.BlockSpec((1, bq, nh, d), q_map, memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, bs, g, d), page_map,
                           memory_space=pltpu.VMEM)
    sc_spec = pl.BlockSpec((1, bs, g), scale_map,
                           memory_space=pltpu.VMEM)
    if quantized:
        kernel = _ragged_kernel_quant
        in_specs = [q_spec, kv_spec, sc_spec, kv_spec, sc_spec]
        operands = (q, k_pages, k_scales.astype(jnp.float32),
                    v_pages, v_scales.astype(jnp.float32))
    else:
        kernel = _ragged_kernel_plain
        in_specs = [q_spec, kv_spec, kv_spec]
        operands = (q, k_pages, v_pages)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, nq, M),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, nh, d), q_map,
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((bq * nh, 1), jnp.float32),
            pltpu.VMEM((bq * nh, 1), jnp.float32),
            pltpu.VMEM((bq * nh, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(kernel, scale=scale, block_size=bs,
                          block_q=bq, window=window, qpg=qpg),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, C, nh, d), q.dtype),
        interpret=_INTERPRET,
    )(block_tables.astype(jnp.int32), context_lens.astype(jnp.int32),
      *operands)


# ---------------------------------------------------------------------------
# public entries
# ---------------------------------------------------------------------------

def paged_attention_decode(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    block_tables: jax.Array,
    context_lens: jax.Array,
    *,
    k_scales: Optional[jax.Array] = None,
    v_scales: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Ragged paged attention for one decode token per slot.

    ``q``: [S, nh, d]; pools: [P, bs, g, d] (GQA when g < nh; pass the
    int8 pools plus ``k_scales``/``v_scales`` [P, bs, g] for in-kernel
    dequant); ``block_tables``: [S, M]; ``context_lens``: [S] query
    positions.  Returns [S, nh, d] in ``q.dtype``."""
    assert q.ndim == 3 and k_pages.ndim == 4, (q.shape, k_pages.shape)
    assert q.shape[0] == block_tables.shape[0] == context_lens.shape[0]
    assert (k_scales is None) == (v_scales is None)
    if softmax_scale is None:
        softmax_scale = 1.0 / math.sqrt(q.shape[-1])
    if not _use_pallas():
        return _reference_paged_attention(
            q, k_pages, v_pages, block_tables, context_lens,
            k_scales, v_scales, softmax_scale, sliding_window)
    return _ragged_call(
        q[:, None], k_pages, v_pages, block_tables, context_lens,
        k_scales, v_scales, scale=softmax_scale, window=sliding_window,
        block_q=1)[:, 0]


def paged_attention_prefill(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    block_tables: jax.Array,
    context_lens: jax.Array,
    *,
    k_scales: Optional[jax.Array] = None,
    v_scales: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    block_q: Optional[int] = None,
) -> jax.Array:
    """Ragged paged attention for one prefill chunk per slot.

    ``q``: [S, C, nh, d] — C query tokens per slot sitting at absolute
    positions ``context_lens[s] .. context_lens[s]+C-1`` (their K/V must
    already be scattered into the pools, as the transformer's paged
    branch does before the read).  Row ``j`` attends the full paged
    history plus its own causal prefix of the chunk; padded tail rows of
    a short final chunk compute garbage-in-garbage-out exactly like the
    XLA branch (the engine only reads the last valid row's logits).
    Returns [S, C, nh, d] in ``q.dtype``."""
    assert q.ndim == 4 and k_pages.ndim == 4, (q.shape, k_pages.shape)
    assert q.shape[0] == block_tables.shape[0] == context_lens.shape[0]
    assert (k_scales is None) == (v_scales is None)
    if softmax_scale is None:
        softmax_scale = 1.0 / math.sqrt(q.shape[-1])
    if not _use_pallas():
        return _reference_paged_prefill(
            q, k_pages, v_pages, block_tables, context_lens,
            k_scales, v_scales, softmax_scale, sliding_window)
    C = q.shape[1]
    bq = min(block_q or _PREFILL_BLOCK_Q, C)
    while C % bq:       # q-blocks must tile the chunk exactly; static
        bq -= 1         # (power-of-two chunks keep the full block size)
    return _ragged_call(
        q, k_pages, v_pages, block_tables, context_lens,
        k_scales, v_scales, scale=softmax_scale, window=sliding_window,
        block_q=bq)
