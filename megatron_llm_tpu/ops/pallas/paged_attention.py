"""Ragged paged-attention decode kernel for the serving engine (Pallas
Mosaic TPU).

The XLA paged branch in ``models/transformer.py`` gathers every slot's
FULL block table into a dense ``[b, M*bs, g, d]`` view (dequantizing
every int8 page) before masked attention — each decode step moves the
worst-case context for every slot.  This kernel walks each slot's block
table directly in the grid instead, reading only the
``ceil((context_len+1)/block_size)`` pages the slot actually owns
(arXiv:2604.15464 is the blueprint; decode HBM traffic is the serving
throughput ceiling, arXiv:2605.25645).

Shape contract (the serving engine's decode step):

* ``q`` — ``[S, nh, d]``: ONE query token per slot (the decode-shaped
  ``n == 1`` call; prefill chunks keep the XLA branch).
* ``k_pages``/``v_pages`` — ``[P, bs, g, d]`` shared page pool, already
  containing this step's scatter-on-write (the query token's K/V sit at
  position ``context_lens[s]``).  int8 pools ship per-(page, position,
  group) fp32 absmax scales ``[P, bs, g]`` and are dequantized
  in-kernel, so int8 is what crosses HBM.
* ``block_tables`` — ``[S, M]`` int32, entries beyond a slot's
  allocation = 0 (the reserved garbage block).
* ``context_lens`` — ``[S]`` int32: the query token's position; keys at
  positions ``0..context_lens[s]`` inclusive are attended (causal), and
  a sliding window drops ``key_pos <= context_lens[s] - window``.

Kernel structure: grid ``(slot, page)`` with the page dimension
innermost — sequential on TPU, so fp32 scratch (m, l, acc) carries the
online-softmax state across a slot's pages.  The page index map clamps
out-of-range grid steps to the nearest real page: Mosaic skips the DMA
when consecutive grid steps map a block to the same index, so a slot
with 3 live pages out of M=128 moves exactly 3 pages of KV.  All query
heads of a slot ride in one block per grid step (GQA groups are a
static in-kernel loop), so each page is fetched once, not once per
head.

Dispatch mirrors ``flash_attention.py``: TPU backend -> kernel;
otherwise -> jnp reference math (the same dense-gather computation as
the transformer's XLA branch).  Interpret-mode tests run the kernel on
CPU via the module-level ``_INTERPRET`` flag.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_INTERPRET = False
NEG_INF = -1e30


def _use_pallas() -> bool:
    from megatron_llm_tpu.ops.pallas import pallas_backend_available

    return _INTERPRET or pallas_backend_available()


def decode_kernel_available() -> bool:
    """True when ``paged_attention_decode`` would run the Pallas kernel
    (TPU backend, or interpret mode in tests) — the transformer's
    ``--serve_paged_kernel auto`` predicate and the engine's
    ``paged_kernel: pallas|xla`` attribution both key off this."""
    return _use_pallas()


# ---------------------------------------------------------------------------
# reference math (non-TPU fallback; identical to the XLA paged branch)
# ---------------------------------------------------------------------------

def _reference_paged_attention(q, k_pages, v_pages, block_tables,
                               context_lens, k_scales, v_scales,
                               scale, window):
    S, nh, d = q.shape
    bs, g = k_pages.shape[1], k_pages.shape[2]
    M = block_tables.shape[1]
    qpg = nh // g
    k = k_pages[block_tables].reshape(S, M * bs, g, d).astype(jnp.float32)
    v = v_pages[block_tables].reshape(S, M * bs, g, d).astype(jnp.float32)
    if k_scales is not None:
        k = k * k_scales[block_tables].reshape(S, M * bs, g, 1)
        v = v * v_scales[block_tables].reshape(S, M * bs, g, 1)
    qg = q.reshape(S, 1, g, qpg, d).astype(jnp.float32)
    scores = jnp.einsum("bsgpd,btgd->bgpst", qg, k) * scale
    key_pos = jnp.arange(M * bs)
    valid = key_pos[None, :] <= context_lens[:, None]
    if window is not None:
        valid &= key_pos[None, :] > (context_lens[:, None] - window)
    scores = jnp.where(valid[:, None, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bgpst,btgd->bsgpd", probs, v)
    return out.reshape(S, nh, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# decode kernel
# ---------------------------------------------------------------------------

def _decode_body(bt_ref, cl_ref, q_ref, k_ref, v_ref, o_ref,
                 m_scr, l_scr, acc_scr,
                 *, ks_ref, vs_ref, scale, block_size, window, qpg):
    s = pl.program_id(0)
    pi = pl.program_id(1)
    npi = pl.num_programs(1)
    bs = block_size

    @pl.when(pi == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = cl_ref[s]                       # query position = keys cached
    last = pos // bs                      # last live page of this slot
    if window is None:
        first = 0
    else:
        first = jnp.maximum(pos - window + 1, 0) // bs

    @pl.when((pi >= first) & (pi <= last))
    def _compute():
        k = k_ref[0].astype(jnp.float32)              # [bs, g, d]
        v = v_ref[0].astype(jnp.float32)
        if ks_ref is not None:
            k = k * ks_ref[0][:, :, None]             # [bs, g] scales
            v = v * vs_ref[0][:, :, None]
        qh = q_ref[0].astype(jnp.float32)             # [nh, d]
        key_pos = pi * bs + jax.lax.broadcasted_iota(
            jnp.int32, (1, bs), 1)
        valid = key_pos <= pos
        if window is not None:
            valid &= key_pos > pos - window
        # one page DMA serves every query head: GQA groups are a static
        # unrolled loop over the head block's row slices
        for grp in range(k.shape[1]):
            rows = slice(grp * qpg, (grp + 1) * qpg)
            sq = jax.lax.dot_general(
                qh[rows], k[:, grp, :], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                 # [qpg, bs]
            sq = jnp.where(valid, sq, NEG_INF)
            m_prev = m_scr[rows]                      # [qpg, 1]
            m_new = jnp.maximum(m_prev,
                                jnp.max(sq, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.where(valid, jnp.exp(sq - m_new), 0.0)
            l_scr[rows] = l_scr[rows] * alpha + jnp.sum(p, axis=-1,
                                                        keepdims=True)
            acc_scr[rows] = acc_scr[rows] * alpha + jax.lax.dot(
                p, v[:, grp, :], preferred_element_type=jnp.float32)
            m_scr[rows] = m_new

    @pl.when(pi == npi - 1)
    def _finish():
        l = l_scr[:]                                  # [nh, 1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _decode_kernel_plain(bt, cl, q, k, v, o, m, l, acc, **kw):
    _decode_body(bt, cl, q, k, v, o, m, l, acc,
                 ks_ref=None, vs_ref=None, **kw)


def _decode_kernel_quant(bt, cl, q, k, ks, v, vs, o, m, l, acc, **kw):
    _decode_body(bt, cl, q, k, v, o, m, l, acc,
                 ks_ref=ks, vs_ref=vs, **kw)


def _decode_call(q, k_pages, v_pages, block_tables, context_lens,
                 k_scales, v_scales, *, scale, window):
    S, nh, d = q.shape
    bs, g = k_pages.shape[1], k_pages.shape[2]
    M = block_tables.shape[1]
    qpg = nh // g
    quantized = k_scales is not None

    def page_map(s, pi, bt_ref, cl_ref):
        # clamp out-of-range grid steps to the nearest live page: Mosaic
        # skips the block copy when consecutive steps map to the same
        # index, so only the slot's ceil((pos+1)/bs) real pages (minus
        # any fully outside the sliding window) are fetched
        pos = cl_ref[s]
        hi = pos // bs
        lo = (jnp.maximum(pos - window + 1, 0) // bs
              if window is not None else 0)
        return (bt_ref[s, jnp.clip(pi, lo, hi)], 0, 0, 0)

    def scale_map(s, pi, bt_ref, cl_ref):
        return page_map(s, pi, bt_ref, cl_ref)[:3]

    q_spec = pl.BlockSpec((1, nh, d), lambda s, pi, bt, cl: (s, 0, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, bs, g, d), page_map,
                           memory_space=pltpu.VMEM)
    sc_spec = pl.BlockSpec((1, bs, g), scale_map,
                           memory_space=pltpu.VMEM)
    if quantized:
        kernel = _decode_kernel_quant
        in_specs = [q_spec, kv_spec, sc_spec, kv_spec, sc_spec]
        operands = (q, k_pages, k_scales.astype(jnp.float32),
                    v_pages, v_scales.astype(jnp.float32))
    else:
        kernel = _decode_kernel_plain
        in_specs = [q_spec, kv_spec, kv_spec]
        operands = (q, k_pages, v_pages)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, M),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, nh, d), lambda s, pi, bt, cl: (s, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((nh, 1), jnp.float32),
            pltpu.VMEM((nh, 1), jnp.float32),
            pltpu.VMEM((nh, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(kernel, scale=scale, block_size=bs,
                          window=window, qpg=qpg),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, nh, d), q.dtype),
        interpret=_INTERPRET,
    )(block_tables.astype(jnp.int32), context_lens.astype(jnp.int32),
      *operands)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def paged_attention_decode(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    block_tables: jax.Array,
    context_lens: jax.Array,
    *,
    k_scales: Optional[jax.Array] = None,
    v_scales: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Ragged paged attention for one decode token per slot.

    ``q``: [S, nh, d]; pools: [P, bs, g, d] (GQA when g < nh; pass the
    int8 pools plus ``k_scales``/``v_scales`` [P, bs, g] for in-kernel
    dequant); ``block_tables``: [S, M]; ``context_lens``: [S] query
    positions.  Returns [S, nh, d] in ``q.dtype``."""
    assert q.ndim == 3 and k_pages.ndim == 4, (q.shape, k_pages.shape)
    assert q.shape[0] == block_tables.shape[0] == context_lens.shape[0]
    assert (k_scales is None) == (v_scales is None)
    if softmax_scale is None:
        softmax_scale = 1.0 / math.sqrt(q.shape[-1])
    if not _use_pallas():
        return _reference_paged_attention(
            q, k_pages, v_pages, block_tables, context_lens,
            k_scales, v_scales, softmax_scale, sliding_window)
    return _decode_call(
        q, k_pages, v_pages, block_tables, context_lens,
        k_scales, v_scales, scale=softmax_scale, window=sliding_window)
