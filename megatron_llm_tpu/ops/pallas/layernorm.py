"""Fused LayerNorm (mean+variance, scale+bias) Pallas TPU kernel.

Completes the reference's mixed-precision fused LayerNorm family
(``megatron/fused_kernels/layer_norm_cuda_kernel.cu``,
``megatron/model/fused_layer_norm.py``) alongside the RMSNorm kernel
(``rmsnorm.py`` — same Mosaic-legal layout rules: (1, h) row-vector
blocks for the affine params and their grads, (n, 1) per-row stats,
cross-row grad reductions accumulated in VMEM scratch across the
sequential TPU grid, padded rows masked out of reductions).

Forward:  y = (x - mu) * rstd * gamma + beta,  rstd = 1/sqrt(var + eps)
Backward (two-reduction form of the CUDA kernel):
  xhat   = (x - mu) * rstd
  ggam   = g * gamma
  dx     = rstd * (ggam - mean(ggam) - xhat * mean(ggam * xhat))
  dgamma = sum over rows of g * xhat ;  dbeta = sum over rows of g

Dispatch: TPU backend -> kernel; elsewhere -> jnp reference
(``ops.layernorm.layer_norm``).  Interpret-mode tests run on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from megatron_llm_tpu.ops.layernorm import layer_norm
# shared with the RMSNorm kernel: the VMEM-budgeted row-block heuristic
# (see rmsnorm._pick_rows's docstring for the 1 MiB / 8-sublane invariants)
from megatron_llm_tpu.ops.pallas.rmsnorm import _pick_rows

_INTERPRET = False


def _use_pallas() -> bool:
    from megatron_llm_tpu import topology
    from megatron_llm_tpu.ops.pallas import pallas_backend_available

    if topology.sharded_auto_mesh_active():
        # see rmsnorm.py: norm kernels defer to the partitionable XLA
        # norm under GSPMD auto sharding (manual-only regions keep it)
        return False
    return _INTERPRET or pallas_backend_available()


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, mu_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    rstd = jax.lax.rsqrt(jnp.mean(xc * xc, axis=-1, keepdims=True) + eps)
    y = xc * rstd * g_ref[:].astype(jnp.float32) \
        + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mu_ref[:] = mu
    rstd_ref[:] = rstd


def _bwd_kernel(x_ref, g_ref, gr_ref, mu_ref, rstd_ref,
                dx_ref, dg_ref, db_ref, dg_scr, db_scr, *, n, rows):
    i = pl.program_id(0)
    nblocks = pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        dg_scr[:] = jnp.zeros_like(dg_scr)
        db_scr[:] = jnp.zeros_like(db_scr)

    row_valid = (i * rows + jax.lax.broadcasted_iota(
        jnp.int32, (rows, 1), 0)) < n
    x = jnp.where(row_valid, x_ref[:].astype(jnp.float32), 0.0)
    g = jnp.where(row_valid, gr_ref[:].astype(jnp.float32), 0.0)
    gamma = g_ref[:].astype(jnp.float32)            # [1, h]
    mu = jnp.where(row_valid, mu_ref[:], 0.0)       # [rows, 1]
    rstd = jnp.where(row_valid, rstd_ref[:], 0.0)
    xhat = (x - mu) * rstd
    ggam = g * gamma
    m1 = jnp.mean(ggam, axis=-1, keepdims=True)
    m2 = jnp.mean(ggam * xhat, axis=-1, keepdims=True)
    dx = rstd * (ggam - m1 - xhat * m2)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dg_scr[:] += jnp.sum(g * xhat, axis=0, keepdims=True)
    db_scr[:] += jnp.sum(g, axis=0, keepdims=True)

    @pl.when(i == nblocks - 1)
    def _finish():
        dg_ref[:] = dg_scr[:]
        db_ref[:] = db_scr[:]


def _fwd_call(x2d, scale, bias, eps):
    n, h = x2d.shape
    rows = _pick_rows(n, h, x2d.dtype.itemsize)
    y, mu, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(pl.cdiv(n, rows),),
        in_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2d.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(x2d, scale.reshape(1, h), bias.reshape(1, h))
    return y, mu, rstd


def _bwd_call(x2d, scale, g2d, mu, rstd, eps):
    n, h = x2d.shape
    rows = _pick_rows(n, h, x2d.dtype.itemsize)
    dx, dg, db = pl.pallas_call(
        functools.partial(_bwd_kernel, n=n, rows=rows),
        grid=(pl.cdiv(n, rows),),
        in_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, h), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2d.dtype),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((1, h), jnp.float32),
                        pltpu.VMEM((1, h), jnp.float32)],
        interpret=_INTERPRET,
    )(x2d, scale.reshape(1, h), g2d, mu, rstd)
    return dx, dg[0], db[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
                     eps: float = 1e-5):
    if not _use_pallas():
        return layer_norm(x, scale, bias, eps=eps, fp32_compute=True)
    shape = x.shape
    y, _, _ = _fwd_call(x.reshape(-1, shape[-1]), scale, bias, eps)
    return y.reshape(shape)


def _vjp_fwd(x, scale, bias, eps):
    if not _use_pallas():
        return (layer_norm(x, scale, bias, eps=eps, fp32_compute=True),
                (x, scale, bias, None, None))
    shape = x.shape
    y, mu, rstd = _fwd_call(x.reshape(-1, shape[-1]), scale, bias, eps)
    return y.reshape(shape), (x, scale, bias, mu, rstd)


def _vjp_bwd(eps, res, g):
    x, scale, bias, mu, rstd = res
    shape = x.shape
    if mu is None:
        _, vjp = jax.vjp(
            lambda xx, ss, bb: layer_norm(xx, ss, bb, eps=eps,
                                          fp32_compute=True),
            x, scale, bias,
        )
        return vjp(g)
    dx, dg, db = _bwd_call(
        x.reshape(-1, shape[-1]), scale, g.reshape(-1, shape[-1]),
        mu, rstd, eps,
    )
    return (dx.reshape(shape), dg.astype(scale.dtype),
            db.astype(bias.dtype))


fused_layer_norm.defvjp(_vjp_fwd, _vjp_bwd)
