"""Pallas Mosaic-TPU kernels — the TPU-native replacement for the
reference's CUDA ``megatron/fused_kernels`` + FlashAttention-2.

Every kernel has an XLA (plain jnp) fallback used on non-TPU backends and
in interpret-mode tests; dispatch is by ``jax.default_backend()``.
"""
