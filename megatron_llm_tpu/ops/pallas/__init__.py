"""Pallas Mosaic-TPU kernels — the TPU-native replacement for the
reference's CUDA ``megatron/fused_kernels`` + FlashAttention-2.

Every kernel has an XLA (plain jnp) fallback used on non-TPU backends and
in interpret-mode tests; dispatch is by ``jax.default_backend()``.
"""

import os

import jax


def pallas_backend_available() -> bool:
    """Shared backend gate for every kernel module's ``_use_pallas``.

    MLT_FORCE_PALLAS: AOT compiles (jax.experimental.topologies) run
    with a CPU default backend while lowering FOR a TPU topology —
    without the override they'd silently compile the XLA fallbacks
    (tools/aot_memcheck.py and tools/compile_stats.py set it).
    """
    return (jax.default_backend() == "tpu"
            or os.environ.get("MLT_FORCE_PALLAS") == "1")
