"""Q-chunked exact attention — the long-context XLA fallback.

On this stack plain XLA attention cannot compile at seq >= 4096: the
[b, heads, s, s] fp32 score tensor crashes the remote compiler
(docs/perf_tpu.md).  When the Pallas flash kernel is unavailable
(degraded by bench.py's kernel smoke, or ``use_flash_attn=False``), the
naive fallback therefore dies exactly where a fallback is needed most.

This op processes Q in row chunks (the same inner-chunk structure as
``parallel/ring_attention.ring_self_attention``, minus the ring): each
chunk materialises only [b, g, p, qc, sk] scores — full softmax over the
key axis per chunk, no online-softmax carry needed since every chunk
sees all keys.  Q-rows are independent in attention, so the chunking is
exact; each chunk is ``jax.checkpoint``-ed so the backward re-derives
scores per chunk instead of stashing the full score tensor.

Reference behavior being replaced: ``CoreAttention``
(megatron/model/transformer.py:144-277) under FlashAttention-less
configs.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30
DEFAULT_Q_CHUNK = 1024
# below this many query rows the plain [s, s] path compiles fine and is
# one fused softmax instead of a scan — no reason to chunk
CHUNKED_ATTENTION_MIN_SEQ = 4096


def chunked_causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    softmax_scale: Optional[float] = None,
    q_chunk_size: int = DEFAULT_Q_CHUNK,
) -> jax.Array:
    """q [b, sq, nh, d]; k, v [b, sk, ng, d] (GQA when ng < nh) -> ctx
    [b, sq, nh, d].  Exact (same numerics as the unchunked softmax up to
    fp associativity); supports causal and sliding-window masking but not
    arbitrary masks or dropout (the callers' flash-eligibility conditions,
    models/transformer.py ``attention``)."""
    if softmax_scale is None:
        softmax_scale = 1.0 / math.sqrt(q.shape[-1])
    b, sq, nh, d = q.shape
    sk, ng = k.shape[1], k.shape[2]
    qpg = nh // ng

    # pad sq up to a chunk multiple instead of hunting for a divisor (a
    # near-prime sq would otherwise degrade to single-row chunks); the pad
    # rows compute garbage attention that is sliced off at the end
    qc = min(q_chunk_size, sq)
    n_qc = -(-sq // qc)
    pad = n_qc * qc - sq
    if pad:
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))

    k_pos = jnp.arange(sk)

    def chunk(ci):
        q_i = lax.dynamic_slice_in_dim(q, ci * qc, qc, axis=1)
        qg = q_i.reshape(b, qc, ng, qpg, d)
        # native-dtype matmuls with fp32 accumulation (not an input
        # upcast, which would force slow fp32 MXU passes on bf16 inputs)
        scores = jnp.einsum("bsgpd,btgd->bgpst", qg, k,
                            preferred_element_type=jnp.float32)
        scores = scores * softmax_scale
        q_pos = ci * qc + jnp.arange(qc)
        mask = jnp.ones((qc, sk), bool)
        if causal:
            mask &= k_pos[None, :] <= q_pos[:, None]
        if sliding_window is not None:
            mask &= k_pos[None, :] > q_pos[:, None] - sliding_window
        scores = jnp.where(mask[None, None, None], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bgpst,btgd->bsgpd", probs.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
        return ctx.reshape(b, qc, nh, d).astype(q.dtype)

    if n_qc == 1:
        out = chunk(jnp.int32(0))
        return out[:, :sq] if pad else out

    _, out = lax.scan(
        lambda _, ci: (None, jax.checkpoint(chunk)(ci)),
        None, jnp.arange(n_qc))
    # out [n_qc, b, qc, nh, d] -> [b, n_qc*qc, nh, d] -> drop pad rows
    out = jnp.moveaxis(out, 0, 1).reshape(b, n_qc * qc, nh, d)
    return out[:, :sq] if pad else out
