"""Rotary positional embeddings with position-interpolation scaling.

Reference: ``megatron/model/positional_embeddings.py:7-51`` —
``precompute_freqs_cis`` builds complex e^{i t theta^-2k/d} with the RoPE
*scaling* divisor ``t /= scaling_factor`` (linear position interpolation
for context extension, flag ``--rope_scaling_factor`` arguments.py:465),
and ``apply_rotary_emb`` rotates (q, k) by complex multiply over
*interleaved* even/odd feature pairs, with optional non-monotonic
``position_ids``.

TPU design: complex dtypes lower poorly on TPU, so the rotation is done as
the equivalent real cos/sin rotation over interleaved pairs — numerically
identical (same pairing as the Meta/Llama layout, which is why the HF
converter's rotary permutation in ``weights_conversion/hf_to_megatron.py:
117-160`` has an exact analogue here).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def llama3_scale_freqs(
    freqs: jax.Array,
    factor: float = 8.0,
    low_freq_factor: float = 1.0,
    high_freq_factor: float = 4.0,
    original_max_position: int = 8192,
) -> jax.Array:
    """Llama-3.1 NTK-by-parts frequency remap (the published scheme, as
    in HF ``modeling_rope_utils._compute_llama3_parameters``): leave
    high-frequency components (wavelength shorter than
    original_max/high_freq_factor) untouched, divide low-frequency
    components (wavelength longer than original_max/low_freq_factor) by
    ``factor``, and smoothly interpolate between the two bands."""
    two_pi = 2.0 * jnp.pi
    wavelen = two_pi / freqs
    low_freq_wavelen = original_max_position / low_freq_factor
    high_freq_wavelen = original_max_position / high_freq_factor
    # smooth factor in the interpolation band
    smooth = (original_max_position / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor
    )
    interp = (1.0 - smooth) * (freqs / factor) + smooth * freqs
    out = jnp.where(wavelen > low_freq_wavelen, freqs / factor, freqs)
    in_band = (wavelen <= low_freq_wavelen) & (wavelen >= high_freq_wavelen)
    return jnp.where(in_band, interp, out)


def precompute_freqs_cis(
    dim: int,
    end: int,
    theta: float = 10000.0,
    scaling_factor: float = 1.0,
    llama3_scaling: dict | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (cos, sin), each [end, dim // 2], fp32.

    reference: positional_embeddings.py:7-14 (including ``t /= scaling_factor``).
    ``llama3_scaling``: optional kwargs for :func:`llama3_scale_freqs`
    (Llama-3.1+ checkpoints; mutually exclusive with linear scaling).
    """
    freqs = 1.0 / (
        theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32)[: dim // 2] / dim)
    )
    if llama3_scaling:
        if scaling_factor != 1.0:
            raise ValueError(
                "rope llama3 scaling and linear scaling_factor "
                f"({scaling_factor}) are mutually exclusive — no "
                "checkpoint is trained with both")
        freqs = llama3_scale_freqs(freqs, **llama3_scaling)
    t = jnp.arange(end, dtype=jnp.float32) / scaling_factor
    freqs = jnp.outer(t, freqs)  # [end, dim/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rotary_emb(
    x: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    position_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Rotate interleaved feature pairs of ``x``.

    x: [..., seq, heads, head_dim] (seq is axis -3)
    cos/sin: [max_pos, head_dim // 2]
    position_ids: optional int array broadcastable to x's batch+seq dims
      (reference supports non-monotonic ids for packed sequences,
      positional_embeddings.py:33-44).
    """
    orig_dtype = x.dtype
    *lead, s, h, d = x.shape
    rot_d = 2 * cos.shape[-1]
    if rot_d < d:
        # partial rotary (GPT-NeoX/Pythia rotary_pct): rotate the first
        # rot_d dims of each head, pass the rest through unchanged
        out_rot = apply_rotary_emb(x[..., :rot_d], cos, sin, position_ids)
        return jnp.concatenate([out_rot, x[..., rot_d:]], axis=-1)
    if position_ids is None:
        c = cos[:s]  # [s, d/2]
        sn = sin[:s]
        c = c[:, None, :]  # [s, 1, d/2]
        sn = sn[:, None, :]
    else:
        c = cos[position_ids]  # [..., s, d/2]
        sn = sin[position_ids]
        c = c[..., :, None, :]
        sn = sn[..., :, None, :]
    xf = x.astype(jnp.float32).reshape(*lead, s, h, d // 2, 2)
    x_even = xf[..., 0]
    x_odd = xf[..., 1]
    # (a + ib) * (cos + i sin) = (a cos - b sin) + i(a sin + b cos)
    out_even = x_even * c - x_odd * sn
    out_odd = x_even * sn + x_odd * c
    out = jnp.stack([out_even, out_odd], axis=-1).reshape(*lead, s, h, d)
    return out.astype(orig_dtype)
