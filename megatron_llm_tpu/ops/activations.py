"""Activations: GLU family + (bias-)GeLU.

Reference: ``megatron/model/glu_activations.py:8-49`` (liglu/geglu/reglu/
swiglu as chunk-multiply modules) and ``megatron/model/fused_bias_gelu.py``
(a torch.jit fused bias+tanh-gelu with hand-written backward).

On TPU none of these need custom kernels: XLA fuses bias-add + gelu into
the producing matmul's epilogue, and the GLU chunk-multiply is a single
fused elementwise op.  The math (tanh-approximate gelu constants) matches
the reference so losses are comparable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def gelu(x: jax.Array) -> jax.Array:
    """Tanh-approximate gelu — same polynomial as the reference's
    fused_bias_gelu.py:15-20."""
    return 0.5 * x * (1.0 + jnp.tanh(0.79788456 * x * (1.0 + 0.044715 * x * x)))


def bias_gelu(bias: jax.Array, x: jax.Array) -> jax.Array:
    # reference: fused_bias_gelu.py:18-20
    return gelu(x + bias)


def squared_relu(x: jax.Array) -> jax.Array:
    return jnp.square(jax.nn.relu(x))


def _split2(x: jax.Array):
    return jnp.split(x, 2, axis=-1)


def liglu(x: jax.Array) -> jax.Array:
    # reference: glu_activations.py (LiGLU: linear gate)
    a, b = _split2(x)
    return a * b


def geglu(x: jax.Array) -> jax.Array:
    a, b = _split2(x)
    return gelu(a) * b


def reglu(x: jax.Array) -> jax.Array:
    a, b = _split2(x)
    return jax.nn.relu(a) * b


def swiglu(x: jax.Array) -> jax.Array:
    # reference: glu_activations.py:38-42 (silu(a) * b)
    a, b = _split2(x)
    return jax.nn.silu(a) * b


GLU_ACTIVATIONS = {
    "liglu": liglu,
    "geglu": geglu,
    "reglu": reglu,
    "swiglu": swiglu,
}


def glu_activation(name: str, x: jax.Array) -> jax.Array:
    return GLU_ACTIVATIONS[name](x)


def apply_mlp_activation(h: jax.Array, cfg) -> jax.Array:
    """The MLP nonlinearity selected by config — GLU family (halves the
    doubled first projection) or a gelu variant ('exact' = erf gelu for
    Falcon, else the GPT-2/Megatron tanh polynomial).  Shared by the dense
    MLP (models/transformer.py) and the MoE experts (models/moe.py)."""
    if cfg.glu_activation:
        return GLU_ACTIVATIONS[cfg.glu_activation](h)
    if cfg.gelu_variant == "exact":
        return jax.nn.gelu(h, approximate=False)
    return gelu(h)
