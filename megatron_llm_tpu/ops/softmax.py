"""Scaled / masked softmax for attention scores.

Reference: the three CUDA kernel families in ``megatron/fused_kernels``
(scaled_upper_triang_masked_softmax, scaled_masked_softmax, scaled_softmax)
behind the eligibility-dispatch wrapper ``FusedScaleMaskSoftmax``
(``megatron/model/fused_softmax.py:102-213``).

TPU design: one function.  ``scale -> mask -> softmax`` is an elementwise
chain plus a row reduction; XLA fuses it into a single pass over VMEM, so
the CUDA kernels' raison d'etre (avoiding HBM round trips) is served by the
compiler.  fp32 accumulation is kept when ``softmax_in_fp32`` (matching the
reference's ``attention_softmax_in_fp32`` semantics).  The flash-attention
path (``ops/pallas/flash_attention.py``) bypasses this entirely, as the
reference bypasses it with FlashAttention-2.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -10000.0  # reference uses -10000.0 in get_ltor_masks / kernels


def causal_mask(sq: int, sk: int, dtype=jnp.bool_) -> jax.Array:
    """True = masked-out (reference mask convention: 1 means 'mask away',
    utils.py:137-194)."""
    # offset so the last sq rows of an sk-length history are causal
    i = jnp.arange(sq)[:, None]
    j = jnp.arange(sk)[None, :]
    return (j > (i + (sk - sq))).astype(dtype)


def sliding_window_mask(sq: int, sk: int, window: int, dtype=jnp.bool_) -> jax.Array:
    """Causal + sliding window (reference: Mistral window_size through
    FlashAttention-2, transformer.py:528-537)."""
    i = jnp.arange(sq)[:, None] + (sk - sq)
    j = jnp.arange(sk)[None, :]
    causal = j > i
    too_old = j <= i - window
    return (causal | too_old).astype(dtype)


def fused_scale_mask_softmax(
    scores: jax.Array,
    mask: Optional[jax.Array],
    scale: Optional[float] = None,
    softmax_in_fp32: bool = True,
) -> jax.Array:
    """scores: [..., sq, sk]; mask: broadcastable bool (True = masked)."""
    dtype = scores.dtype
    if softmax_in_fp32:
        scores = scores.astype(jnp.float32)
    if scale is not None:
        scores = scores * scale
    if mask is not None:
        scores = jnp.where(mask, jnp.float32(NEG_INF), scores)
    probs = jax.nn.softmax(scores, axis=-1)
    return probs.astype(dtype)
