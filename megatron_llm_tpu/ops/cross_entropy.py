"""Vocab-parallel cross entropy.

Reference: ``megatron/core/tensor_parallel/cross_entropy.py:14-175`` —
a hand-written autograd Function over vocab-sharded logits: allreduce(MAX)
of per-shard logit maxima, masked gather of the target logit +
allreduce(SUM), allreduce(SUM) of the partial sum-exp, optional label
smoothing, and ``vocab_parallel_max_indices`` (argmax across shards) for
accuracy metrics.

TPU design — two equivalent implementations:

* ``vocab_parallel_cross_entropy``: written in plain jnp against the
  *global* logits array.  Under pjit/GSPMD with the vocab axis sharded over
  the ``tp`` mesh axis, XLA lowers the max/sum reductions to exactly the
  allreduce(MAX)/allreduce(SUM) pair the reference issues by hand, and the
  one-hot target gather stays local to the owning shard.  Autodiff derives
  the same softmax-minus-one-hot backward the reference hand-writes.
* ``shard_vocab_parallel_cross_entropy``: the explicit-collective version
  for use inside ``shard_map`` code (pipeline last stage), taking the local
  vocab shard + axis name — a line-by-line semantic mirror of the
  reference kernel, with ``lax.pmax``/``lax.psum`` in place of
  ``torch.distributed.all_reduce``.

Layout note: this framework is batch-major ``[b, s, ...]`` everywhere
(the reference is sequence-major ``[s, b, ...]``; on TPU batch-major keeps
the trailing (seq, vocab/hidden) dims aligned with the (sublane, lane)
tiling).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def vocab_parallel_cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    label_smoothing: float = 0.0,
) -> jax.Array:
    """Per-token CE loss.

    logits: [..., vocab] (fp32 recommended; sharded over tp on the vocab dim)
    labels: [...] int32
    returns: [...] fp32 loss
    """
    logits = logits.astype(jnp.float32)
    logits_max = jnp.max(logits, axis=-1, keepdims=True)   # -> allreduce(MAX) under GSPMD
    shifted = logits - jax.lax.stop_gradient(logits_max)
    sum_exp = jnp.sum(jnp.exp(shifted), axis=-1)           # -> allreduce(SUM)
    log_z = jnp.log(sum_exp)
    # target pick as a one-hot-masked reduction rather than a gather: this is
    # the reference's masked-select + allreduce(SUM) (cross_entropy.py:28-55),
    # partitions trivially when the vocab axis is sharded (XLA's gather
    # partitioner check-fails on take_along_axis under a manual submesh),
    # and XLA fuses the iota+select so no [.., vocab] one-hot materializes.
    iota = jax.lax.broadcasted_iota(jnp.int32, shifted.shape,
                                    shifted.ndim - 1)
    one_hot = iota == labels[..., None].astype(jnp.int32)
    target_logit = jnp.sum(jnp.where(one_hot, shifted, 0.0), axis=-1)
    loss = log_z - target_logit
    if label_smoothing > 0.0:
        # reference: cross_entropy.py:87-109 — smooth against the uniform
        # distribution over the vocab.
        vocab_size = logits.shape[-1]
        smoothing = label_smoothing * vocab_size / (vocab_size - 1)
        mean_log_probs = jnp.mean(shifted, axis=-1) - log_z
        loss = (1.0 - smoothing) * loss - smoothing * mean_log_probs
    return loss


def dense_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-example CE over a small unsharded class axis (fp32 compute) —
    the classification/SOP-head counterpart of
    ``vocab_parallel_cross_entropy`` (reference: plain F.cross_entropy in
    pretrain_bert.py / tasks finetune_utils)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(
        logp, labels[..., None].astype(jnp.int32), axis=-1
    )[..., 0]


def vocab_parallel_max_indices(logits: jax.Array) -> jax.Array:
    """Global argmax over the (possibly tp-sharded) vocab axis
    (reference: cross_entropy.py:146-175)."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Explicit-collective versions for shard_map code.
# ---------------------------------------------------------------------------

def shard_vocab_parallel_cross_entropy(
    local_logits: jax.Array,
    labels: jax.Array,
    axis_name: str,
    label_smoothing: float = 0.0,
) -> jax.Array:
    """CE over a local vocab shard inside shard_map.

    local_logits: [..., vocab/tp]; labels are *global* vocab ids.
    Mirrors _VocabParallelCrossEntropy (cross_entropy.py:14-127).
    """
    local_logits = local_logits.astype(jnp.float32)
    vocab_shard = local_logits.shape[-1]
    rank = jax.lax.axis_index(axis_name)
    vocab_start = rank * vocab_shard

    # 1) global max (allreduce MAX) — cross_entropy.py:20-24
    local_max = jnp.max(local_logits, axis=-1)
    global_max = jax.lax.pmax(local_max, axis_name)
    shifted = local_logits - jax.lax.stop_gradient(global_max)[..., None]

    # 2) target logit: mask labels outside this shard, gather, psum
    #    — cross_entropy.py:28-55
    local_labels = labels.astype(jnp.int32) - vocab_start
    in_shard = (local_labels >= 0) & (local_labels < vocab_shard)
    safe_labels = jnp.clip(local_labels, 0, vocab_shard - 1)
    picked = jnp.take_along_axis(shifted, safe_labels[..., None], axis=-1)[..., 0]
    target_logit = jax.lax.psum(jnp.where(in_shard, picked, 0.0), axis_name)

    # 3) partial sum-exp, psum — cross_entropy.py:57-64
    sum_exp = jax.lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), axis_name)
    log_z = jnp.log(sum_exp)
    loss = log_z - target_logit

    if label_smoothing > 0.0:
        vocab_size = vocab_shard * jax.lax.psum(1, axis_name)
        smoothing = label_smoothing * vocab_size / (vocab_size - 1)
        mean_log_probs = (
            jax.lax.psum(jnp.sum(shifted, axis=-1), axis_name) / vocab_size - log_z
        )
        loss = (1.0 - smoothing) * loss - smoothing * mean_log_probs
    return loss


def shard_vocab_parallel_max_indices(
    local_logits: jax.Array, axis_name: str
) -> jax.Array:
    """Argmax across vocab shards (reference: cross_entropy.py:146-175)."""
    vocab_shard = local_logits.shape[-1]
    rank = jax.lax.axis_index(axis_name)
    local_max = jnp.max(local_logits, axis=-1)
    local_arg = jnp.argmax(local_logits, axis=-1).astype(jnp.int32) + rank * vocab_shard
    global_max = jax.lax.pmax(local_max, axis_name)
    # ties broken toward the lowest vocab id, like a sequential argmax
    cand = jnp.where(local_max >= global_max, local_arg, jnp.int32(2**31 - 1))
    return jax.lax.pmin(cand, axis_name)
