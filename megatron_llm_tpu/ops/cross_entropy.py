"""Vocab-parallel cross entropy.

Reference: ``megatron/core/tensor_parallel/cross_entropy.py:14-175`` —
a hand-written autograd Function over vocab-sharded logits: allreduce(MAX)
of per-shard logit maxima, masked gather of the target logit +
allreduce(SUM), allreduce(SUM) of the partial sum-exp, optional label
smoothing, and ``vocab_parallel_max_indices`` (argmax across shards) for
accuracy metrics.

TPU design — two equivalent implementations:

* ``vocab_parallel_cross_entropy``: written in plain jnp against the
  *global* logits array.  Under pjit/GSPMD with the vocab axis sharded over
  the ``tp`` mesh axis, XLA lowers the max/sum reductions to exactly the
  allreduce(MAX)/allreduce(SUM) pair the reference issues by hand, and the
  one-hot target gather stays local to the owning shard.  Autodiff derives
  the same softmax-minus-one-hot backward the reference hand-writes.
* ``shard_vocab_parallel_cross_entropy``: the explicit-collective version
  for use inside ``shard_map`` code (pipeline last stage), taking the local
  vocab shard + axis name — a line-by-line semantic mirror of the
  reference kernel, with ``lax.pmax``/``lax.psum`` in place of
  ``torch.distributed.all_reduce``.

Layout note: this framework is batch-major ``[b, s, ...]`` everywhere
(the reference is sequence-major ``[s, b, ...]``; on TPU batch-major keeps
the trailing (seq, vocab/hidden) dims aligned with the (sublane, lane)
tiling).
"""

from __future__ import annotations

import functools

from typing import Optional

import jax
import jax.numpy as jnp


def vocab_parallel_cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    label_smoothing: float = 0.0,
) -> jax.Array:
    """Per-token CE loss.

    logits: [..., vocab] (fp32 recommended; sharded over tp on the vocab dim)
    labels: [...] int32
    returns: [...] fp32 loss
    """
    logits = logits.astype(jnp.float32)
    logits_max = jnp.max(logits, axis=-1, keepdims=True)   # -> allreduce(MAX) under GSPMD
    shifted = logits - jax.lax.stop_gradient(logits_max)
    sum_exp = jnp.sum(jnp.exp(shifted), axis=-1)           # -> allreduce(SUM)
    log_z = jnp.log(sum_exp)
    # target pick as a one-hot-masked reduction rather than a gather: this is
    # the reference's masked-select + allreduce(SUM) (cross_entropy.py:28-55),
    # partitions trivially when the vocab axis is sharded (XLA's gather
    # partitioner check-fails on take_along_axis under a manual submesh),
    # and XLA fuses the iota+select so no [.., vocab] one-hot materializes.
    iota = jax.lax.broadcasted_iota(jnp.int32, shifted.shape,
                                    shifted.ndim - 1)
    one_hot = iota == labels[..., None].astype(jnp.int32)
    target_logit = jnp.sum(jnp.where(one_hot, shifted, 0.0), axis=-1)
    loss = log_z - target_logit
    if label_smoothing > 0.0:
        # reference: cross_entropy.py:87-109 — smooth against the uniform
        # distribution over the vocab.
        vocab_size = logits.shape[-1]
        smoothing = label_smoothing * vocab_size / (vocab_size - 1)
        mean_log_probs = jnp.mean(shifted, axis=-1) - log_z
        loss = (1.0 - smoothing) * loss - smoothing * mean_log_probs
    return loss


def dense_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-example CE over a small unsharded class axis (fp32 compute) —
    the classification/SOP-head counterpart of
    ``vocab_parallel_cross_entropy`` (reference: plain F.cross_entropy in
    pretrain_bert.py / tasks finetune_utils)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(
        logp, labels[..., None].astype(jnp.int32), axis=-1
    )[..., 0]


def vocab_parallel_max_indices(logits: jax.Array) -> jax.Array:
    """Global argmax over the (possibly tp-sharded) vocab axis
    (reference: cross_entropy.py:146-175)."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Explicit-collective versions for shard_map code.
# ---------------------------------------------------------------------------

def shard_vocab_parallel_cross_entropy(
    local_logits: jax.Array,
    labels: jax.Array,
    axis_name: str,
    label_smoothing: float = 0.0,
) -> jax.Array:
    """CE over a local vocab shard inside shard_map.

    local_logits: [..., vocab/tp]; labels are *global* vocab ids.
    Mirrors _VocabParallelCrossEntropy (cross_entropy.py:14-127).
    """
    local_logits = local_logits.astype(jnp.float32)
    vocab_shard = local_logits.shape[-1]
    rank = jax.lax.axis_index(axis_name)
    vocab_start = rank * vocab_shard

    # 1) global max (allreduce MAX) — cross_entropy.py:20-24
    local_max = jnp.max(local_logits, axis=-1)
    global_max = jax.lax.pmax(local_max, axis_name)
    shifted = local_logits - jax.lax.stop_gradient(global_max)[..., None]

    # 2) target logit: mask labels outside this shard, gather, psum
    #    — cross_entropy.py:28-55
    local_labels = labels.astype(jnp.int32) - vocab_start
    in_shard = (local_labels >= 0) & (local_labels < vocab_shard)
    safe_labels = jnp.clip(local_labels, 0, vocab_shard - 1)
    picked = jnp.take_along_axis(shifted, safe_labels[..., None], axis=-1)[..., 0]
    target_logit = jax.lax.psum(jnp.where(in_shard, picked, 0.0), axis_name)

    # 3) partial sum-exp, psum — cross_entropy.py:57-64
    sum_exp = jax.lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), axis_name)
    log_z = jnp.log(sum_exp)
    loss = log_z - target_logit

    if label_smoothing > 0.0:
        vocab_size = vocab_shard * jax.lax.psum(1, axis_name)
        smoothing = label_smoothing * vocab_size / (vocab_size - 1)
        mean_log_probs = (
            jax.lax.psum(jnp.sum(shifted, axis=-1), axis_name) / vocab_size - log_z
        )
        loss = (1.0 - smoothing) * loss - smoothing * mean_log_probs
    return loss


def shard_vocab_parallel_max_indices(
    local_logits: jax.Array, axis_name: str
) -> jax.Array:
    """Argmax across vocab shards (reference: cross_entropy.py:146-175)."""
    vocab_shard = local_logits.shape[-1]
    rank = jax.lax.axis_index(axis_name)
    local_max = jnp.max(local_logits, axis=-1)
    local_arg = jnp.argmax(local_logits, axis=-1).astype(jnp.int32) + rank * vocab_shard
    global_max = jax.lax.pmax(local_max, axis_name)
    # ties broken toward the lowest vocab id, like a sequential argmax
    cand = jnp.where(local_max >= global_max, local_arg, jnp.int32(2**31 - 1))
    return jax.lax.pmin(cand, axis_name)


# ---------------------------------------------------------------------------
# fused (chunked) linear + cross entropy
# ---------------------------------------------------------------------------

def _flce_pick_chunk(v: int, chunk: int) -> int:
    """Largest divisor of ``v`` that is <= ``chunk``.

    Guards against flag misuse: ``chunk`` must be positive, and if the
    vocab has no divisor anywhere near the request (e.g. an unpadded
    prime-ish vocab whose best divisor is tiny) the scan would silently
    serialize into thousands of micro-matmuls — refuse instead and tell
    the user to pad the vocab (``--make_vocab_size_divisible_by`` already
    pads to a 128 multiple on the normal path)."""
    if chunk < 1:
        raise ValueError(f"fused_ce_chunk_size must be >= 1, got {chunk}")
    c = min(chunk, v)
    while v % c != 0:
        c -= 1
    if c < min(chunk, v) // 16:
        raise ValueError(
            f"vocab size {v} has no divisor near chunk_size {chunk} "
            f"(best is {c}); pad the vocab to a multiple of 128 or pick "
            f"a chunk_size that divides it")
    return c


def _flce_forward(h2, w, labels, chunk):
    """h2 [N, H] (compute dtype), w [V, H], labels [N] -> (loss [N], lse [N]).

    Scans vocab chunks with an online logsumexp so the [N, V] logits are
    never materialized (one [N, chunk] fp32 block lives at a time)."""
    n = h2.shape[0]
    v = w.shape[0]
    vc = _flce_pick_chunk(v, chunk)
    ws = w.reshape(v // vc, vc, -1)
    offs = jnp.arange(v // vc) * vc

    def body(carry, sc):
        m, l, picked = carry
        wc, off = sc
        logits = jax.lax.dot_general(
            h2, wc, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                             # [N, vc] fp32
        m_c = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_c)
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        local = labels - off
        valid = (local >= 0) & (local < vc)
        got = jnp.take_along_axis(
            logits, jnp.clip(local, 0, vc - 1)[:, None], axis=-1)[:, 0]
        picked = picked + jnp.where(valid, got, 0.0)
        return (m_new, l, picked), None

    m0 = jnp.full((n,), -jnp.inf, jnp.float32)
    (m, l, picked), _ = jax.lax.scan(
        body, (m0, jnp.zeros((n,), jnp.float32),
               jnp.zeros((n,), jnp.float32)), (ws, offs))
    lse = m + jnp.log(l)
    return lse - picked, lse


def _flce_backward(h2, w, labels, lse, g, chunk):
    """Cotangents (dh [N, H], dw [V, H]) given d(loss) = g [N].

    Per-token gradient of CE wrt logits is softmax - onehot; each chunk's
    logits are recomputed (same trade as flash attention's backward)."""
    v = w.shape[0]
    vc = _flce_pick_chunk(v, chunk)
    ws = w.reshape(v // vc, vc, -1)
    offs = jnp.arange(v // vc) * vc
    gf = g.astype(jnp.float32)

    def body(dh, sc):
        wc, off = sc
        logits = jax.lax.dot_general(
            h2, wc, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        p = jnp.exp(logits - lse[:, None])            # softmax chunk
        local = labels - off
        valid = (local >= 0) & (local < vc)
        onehot = (jnp.arange(vc)[None, :] == local[:, None]) & valid[:, None]
        dlogits = (p - onehot.astype(jnp.float32)) * gf[:, None]
        dlogits = dlogits.astype(h2.dtype)
        # dh accumulates in fp32 across chunks (bf16 partial sums would
        # compound rounding into the hidden-state gradient)
        dh = dh + jax.lax.dot_general(
            dlogits, wc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                             # [N, H] fp32
        dwc = jax.lax.dot_general(
            dlogits, h2, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(w.dtype)                             # [vc, H]
        return dh, dwc

    dh0 = jnp.zeros(h2.shape, jnp.float32)
    dh, dws = jax.lax.scan(body, dh0, (ws, offs))
    return dh.astype(h2.dtype), dws.reshape(w.shape)


def fused_linear_cross_entropy(
    h: jax.Array,
    weight: jax.Array,
    labels: jax.Array,
    chunk_size: int = 8192,
) -> jax.Array:
    """Per-token CE of ``softmax(h @ weight.T)`` without materializing the
    [tokens, vocab] logits — the head matmul and the loss are fused over
    vocab chunks (the memory-bound half of the reference's
    ``post_language_model_processing``; at 32k vocab this replaces >1 GB
    of fp32 logits + softmax intermediates per microbatch with one
    [tokens, chunk] block).

    h: [..., H] compute-dtype hidden states; weight: [V, H]; labels [...].
    Unsharded-vocab path only (tp=1) — under tensor parallelism the
    vocab-parallel CE handles the sharded head.  Numerics match
    ``vocab_parallel_cross_entropy(parallel_lm_logits(...))`` up to fp
    association.
    """
    shape = labels.shape
    h2 = h.reshape(-1, h.shape[-1])
    return _flce(h2, weight, labels.reshape(-1), chunk_size).reshape(shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flce(h2, weight, labels, chunk_size):
    loss, _ = _flce_forward(h2, weight, labels, chunk_size)
    return loss


def _flce_vjp_fwd(h2, weight, labels, chunk_size):
    loss, lse = _flce_forward(h2, weight, labels, chunk_size)
    return loss, (h2, weight, labels, lse)


def _flce_vjp_bwd(chunk_size, res, g):
    h2, weight, labels, lse = res
    dh, dw = _flce_backward(h2, weight, labels, lse, g, chunk_size)
    return dh, dw, None


_flce.defvjp(_flce_vjp_fwd, _flce_vjp_bwd)
