"""LayerNorm / RMSNorm.

Reference: ``megatron/model/fused_layer_norm.py`` — a CUDA mixed-precision
fused LayerNorm (``layer_norm_cuda_kernel.cu``, Welford accumulation in
fp32 with fp16/bf16 I/O) and a plain-PyTorch RMSNorm computed in fp32
(``fused_layer_norm.py:125-139``).

TPU design: the math is written in plain jnp with fp32 internal
accumulation; XLA fuses it into neighbouring ops, which already removes
the memory round-trips the CUDA fusion exists for.  A Pallas fused RMSNorm
(``ops/pallas/rmsnorm.py``) is used on the TPU backend for long rows where
a single-pass kernel beats the XLA fusion.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def init_norm_params(hidden_size: int, normalization: str, dtype=jnp.float32):
    """Norm parameter pytree.  LayerNorm: {'scale','bias'}; RMSNorm: {'scale'}."""
    if normalization == "rmsnorm":
        return {"scale": jnp.ones((hidden_size,), dtype=dtype)}
    elif normalization == "layernorm":
        return {
            "scale": jnp.ones((hidden_size,), dtype=dtype),
            "bias": jnp.zeros((hidden_size,), dtype=dtype),
        }
    raise ValueError(f"unknown normalization {normalization!r}")


def layer_norm(
    x: jax.Array,
    scale: jax.Array,
    bias: Optional[jax.Array],
    eps: float = 1e-5,
    fp32_compute: bool = True,
) -> jax.Array:
    """LayerNorm over the last axis with fp32 accumulation (matching the
    reference CUDA kernel's mixed-precision contract)."""
    dtype = x.dtype
    if fp32_compute:
        x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    y = y * scale.astype(y.dtype)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y.astype(dtype)


def rms_norm(
    x: jax.Array,
    scale: jax.Array,
    eps: float = 1e-5,
    fp32_compute: bool = True,
) -> jax.Array:
    """RMSNorm (reference: fused_layer_norm.py:125-139 — fp32 compute,
    cast back to input dtype, elementwise scale)."""
    dtype = x.dtype
    if fp32_compute:
        x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(y.dtype)).astype(dtype)


def apply_norm(
    x: jax.Array,
    params,
    normalization: str,
    eps: float = 1e-5,
    fp32_compute: bool = True,
    use_pallas: bool = False,
) -> jax.Array:
    if normalization == "rmsnorm":
        if use_pallas:
            from megatron_llm_tpu.ops.pallas.rmsnorm import fused_rms_norm

            return fused_rms_norm(x, params["scale"], eps=eps)
        return rms_norm(x, params["scale"], eps=eps, fp32_compute=fp32_compute)
    elif normalization == "layernorm":
        # the fused kernel always accumulates in fp32, so it only stands
        # in for the fp32_compute path (norm_in_fp32=False keeps the jnp
        # implementation to preserve its numerics)
        if use_pallas and fp32_compute and params.get("bias") is not None:
            from megatron_llm_tpu.ops.pallas.layernorm import fused_layer_norm

            return fused_layer_norm(x, params["scale"], params["bias"],
                                    eps=eps)
        return layer_norm(
            x, params["scale"], params.get("bias"), eps=eps, fp32_compute=fp32_compute
        )
    raise ValueError(f"unknown normalization {normalization!r}")
