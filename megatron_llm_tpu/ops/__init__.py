"""Compute ops: norms, activations, rotary embeddings, attention, losses.

Replaces the reference's ``megatron/fused_kernels`` (CUDA) +
``megatron/model/fused_*.py`` wrappers.  On TPU the default path is plain
jnp — XLA fuses elementwise chains into the surrounding matmuls — with
Pallas kernels (``megatron_llm_tpu.ops.pallas``) for the ops where a
hand-written kernel beats XLA (flash attention, long-seq softmax,
fused RMSNorm).
"""

from megatron_llm_tpu.ops.layernorm import layer_norm, rms_norm, init_norm_params, apply_norm
from megatron_llm_tpu.ops.activations import (
    GLU_ACTIVATIONS,
    bias_gelu,
    gelu,
    glu_activation,
    squared_relu,
)
from megatron_llm_tpu.ops.rope import precompute_freqs_cis, apply_rotary_emb
from megatron_llm_tpu.ops.softmax import fused_scale_mask_softmax
from megatron_llm_tpu.ops.cross_entropy import (
    vocab_parallel_cross_entropy,
    vocab_parallel_max_indices,
)
