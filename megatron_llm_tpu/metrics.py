"""Pluggable validation metrics.

Reference: ``megatron/metrics.py:11-110`` — a ``METRICS`` registry mapping
name -> callable(MetricInput) -> dict, selected with ``--metrics
[all|names]`` (arguments.py:550) and evaluated inside ``loss_func`` during
validation (finetune.py:211-217).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional

import jax.numpy as jnp

from megatron_llm_tpu.ops.cross_entropy import vocab_parallel_max_indices


@dataclass
class MetricInput:
    """reference: metrics.py MetricInput."""

    batch: dict                 # tokens/labels/loss_mask (+ masks)
    logits: jnp.ndarray         # [b, s, V]
    avg_loss: jnp.ndarray       # scalar masked-mean CE


def perplexity(inp: MetricInput) -> Dict[str, jnp.ndarray]:
    return {"perplexity": jnp.exp(inp.avg_loss)}


def accuracy(inp: MetricInput) -> Dict[str, jnp.ndarray]:
    """Top-1 next-token accuracy over unmasked positions
    (reference uses vocab_parallel_max_indices, metrics.py)."""
    pred = vocab_parallel_max_indices(inp.logits)
    labels = inp.batch["labels"]
    mask = inp.batch.get("loss_mask")
    correct = (pred == labels).astype(jnp.float32)
    if mask is not None:
        mask = (mask > 0).astype(jnp.float32)
        return {"accuracy": jnp.sum(correct * mask)
                / jnp.maximum(jnp.sum(mask), 1.0)}
    return {"accuracy": jnp.mean(correct)}


def count_loss_mask(inp: MetricInput) -> Dict[str, jnp.ndarray]:
    mask = inp.batch.get("loss_mask")
    if mask is None:
        return {"count_loss_mask": jnp.float32(0.0)}
    return {"count_loss_mask": jnp.mean(jnp.sum(mask > 0, axis=-1)
                                        .astype(jnp.float32))}


METRICS: Dict[str, Callable[[MetricInput], Dict[str, jnp.ndarray]]] = {
    "perplexity": perplexity,
    "accuracy": accuracy,
    "count_loss_mask": count_loss_mask,
}


def recovery_counters() -> Dict[str, int]:
    """Host-side fault-tolerance counters (rewinds, save_retries,
    watchdog_fires, signal_saves) — merged into the training log /
    TB/W&B stream and the bench.py artifacts.  Re-exported here so
    metrics consumers need not import resilience."""
    from megatron_llm_tpu.resilience import recovery_counters as rc

    return rc()


def telemetry_summary() -> Optional[Dict[str, float]]:
    """The active run's aggregate telemetry (mean MFU, mean
    tokens/sec/device, mean step time) from the --structured_log_dir
    stream; None when no stream is installed.  Re-exported here (like
    ``recovery_counters``) so metrics consumers need not import
    telemetry."""
    from megatron_llm_tpu.telemetry import run_summary

    return run_summary()


def get_metric(name: str):
    if name not in METRICS:
        raise KeyError(
            f"unknown metric {name!r}; available: {sorted(METRICS)}"
        )
    return METRICS[name]


def resolve_metric_names(names):
    if names and "all" in names:
        return sorted(METRICS)
    return list(names or [])
