"""Span tracing, goodput accounting, and straggler/recompile diagnostics.

Motivation (MegaScale, arXiv:2402.15627 §5; Megatron-LM scaling,
arXiv:2104.04473): telemetry (telemetry.py) tells you *how fast* the run
is; it does not tell you *where the wall-clock went*, *which host is
slow*, or *why step time spiked*.  This module is that attribution
layer — host-side only, nothing enters the jitted step:

* **SpanTracer** — a thread-safe, ring-buffered span recorder with a
  context-manager API (``with tracer.span("checkpoint_save",
  "checkpoint"): ...``) and Chrome ``trace_event`` JSON export, loadable
  in Perfetto (ui.perfetto.dev) or ``chrome://tracing``.  The training
  loop, checkpointing, resilience rewinds, eval, and data iteration all
  open spans; the whole run nests under one root ``train`` span so the
  trace covers (essentially) every second of wall-clock.

* **GoodputAccounter** — classifies wall-clock into
  productive-``step`` / ``compile`` / ``checkpoint`` / ``eval`` /
  ``rewind`` (restart-recovery) / ``data`` (input stall) / other, fed by
  span closes (outermost goodput-category span wins, so nesting never
  double-counts).  ``goodput_pct`` = productive step seconds over total
  wall seconds — MegaScale's headline reliability metric — and surfaces
  in the JSONL stream, ``run_summary()``, the wandb/TB finish summary,
  and ``bench.py``'s BENCH json.

* **RecompileDetector** — a ``jax.monitoring`` duration-event listener
  on ``/jax/core/compile/backend_compile_duration``: every XLA compile
  is timestamped; compiles after ``mark_steady()`` (the loop calls it
  once the first step has compiled) are *recompiles* — the silent
  step-time killer (a shape or layout leak retraces the whole step).
  On jax builds without ``jax.monitoring`` the detector degrades to a
  step-time-outlier heuristic (``observe_step_time``).  Recompiles
  count in ``counters['recompiles']`` and emit trace spans + flight-
  recorder entries.

* **StragglerDetector** — at log boundaries the driver allgathers
  per-host section times (the ``timers.py`` ``process_allgather`` path)
  and hands them here; any host exceeding ``threshold`` x the median is
  flagged as a structured straggler event (trace instant + flight
  recorder + ``counters['straggler_events']`` + a printed line).
  Single-host runs can never flag (median of one).

``tools/trace_report.py`` renders the goodput breakdown, top-N slowest
spans, and the recompile/straggler timelines from the exported trace
(plus the JSONL stream) — pure stdlib, runs anywhere the files do.

Collective discipline matches the rest of the codebase: nothing here
performs a collective; the straggler gather happens in the caller at
deterministic log boundaries only (see ``timers.Timers``).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass
from statistics import median
from typing import Any, Dict, List, Optional

import jax

from megatron_llm_tpu.global_vars import get_counters

# wall-clock categories the goodput accounting attributes time to; spans
# in any other category (e.g. the root "run" span) are trace-only
GOODPUT_CATEGORIES = ("step", "compile", "checkpoint", "eval", "rewind",
                      "data")
_GOODPUT_SET = frozenset(GOODPUT_CATEGORIES)

TRACE_FILENAME = "trace.json"

# the jax.monitoring duration event XLA emits once per backend compile
# (fires on shape-change retraces too; silent on cache hits)
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


# ---------------------------------------------------------------------------
# Goodput accounting
# ---------------------------------------------------------------------------

class GoodputAccounter:
    """Seconds of wall-clock per category + the goodput ratio.

    ``clock`` is injectable for tests; production uses ``perf_counter``
    so the wall denominator and the span durations share a clock."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._t0 = clock()
        self._secs: Dict[str, float] = {c: 0.0 for c in GOODPUT_CATEGORIES}
        self._lock = threading.Lock()
        # multi-slice: seconds the fleet spent waiting on each slice
        # (fed from the per-slice step-time lag at log boundaries) — the
        # slice dimension of goodput, aggregated offline by
        # tools/telemetry_report.py
        self._slice_stall: Dict[int, float] = {}

    def add_slice_stall(self, slice_id: int, secs: float) -> None:
        """Attribute fleet wait time to the slice that caused it (its
        step-time lag over the median of the others)."""
        with self._lock:
            self._slice_stall[int(slice_id)] = \
                self._slice_stall.get(int(slice_id), 0.0) \
                + max(float(secs), 0.0)

    def add(self, category: str, secs: float) -> None:
        with self._lock:
            self._secs[category] = self._secs.get(category, 0.0) \
                + max(float(secs), 0.0)

    def move(self, src: str, dst: str, secs: float) -> float:
        """Reattribute up to ``secs`` from ``src`` to ``dst`` (e.g. a
        compile observed inside a step span belongs to 'compile', not
        'step').  Clamped at what ``src`` holds; returns the moved
        amount."""
        with self._lock:
            m = min(max(float(secs), 0.0), self._secs.get(src, 0.0))
            self._secs[src] -= m
            self._secs[dst] = self._secs.get(dst, 0.0) + m
            return m

    def wall_secs(self) -> float:
        return max(self._clock() - self._t0, 1e-9)

    def summary(self) -> Dict[str, float]:
        """Per-category seconds, the unattributed remainder, and
        ``goodput_pct`` (productive-step share of total wall-clock)."""
        wall = self.wall_secs()
        with self._lock:
            secs = dict(self._secs)
        out = {f"{c}_secs": secs.get(c, 0.0) for c in GOODPUT_CATEGORIES}
        out["other_secs"] = max(wall - sum(secs.values()), 0.0)
        out["wall_secs"] = wall
        out["goodput_pct"] = 100.0 * secs.get("step", 0.0) / wall
        with self._lock:
            if self._slice_stall:
                out["slice_stall_secs"] = {
                    str(s): round(v, 6)
                    for s, v in sorted(self._slice_stall.items())}
        return out


# ---------------------------------------------------------------------------
# Span tracer
# ---------------------------------------------------------------------------

class _SpanHandle:
    """Yielded by ``span()`` so the body can attach attributes
    (``s.args["bytes"] = n``) that land in the trace event."""

    __slots__ = ("name", "category", "args")

    def __init__(self, name: str, category: str, args: Dict[str, Any]):
        self.name = name
        self.category = category
        self.args = args


class SpanTracer:
    """Thread-safe ring buffer of Chrome ``trace_event`` records.

    Durations ride ``perf_counter``; the epoch offset is stamped once so
    the export also carries absolute time.  The ring (``capacity``
    events) bounds memory on long runs — eviction drops the *oldest*
    events and counts them in ``dropped``, so a multi-day run keeps its
    freshest history like the flight recorder does."""

    def __init__(self, capacity: int = 100_000):
        self.capacity = max(int(capacity), 1)
        self.goodput = GoodputAccounter()
        self.dropped = 0
        self._events: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._t0 = time.perf_counter()
        self._unix0 = time.time()

    # -- recording ------------------------------------------------------

    def _stack(self) -> List[_SpanHandle]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _append(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) == self.capacity:
                self.dropped += 1
            self._events.append(ev)

    @contextmanager
    def span(self, name: str, category: str = "other", **attrs):
        """Record one complete ('X') event around the body.  Goodput is
        fed by the *outermost* span whose category is a goodput
        category, so nested phases (a checkpoint_write inside a
        checkpoint_save inside an eval) never double-count."""
        stack = self._stack()
        enclosed = any(s.category in _GOODPUT_SET for s in stack)
        h = _SpanHandle(name, category, dict(attrs))
        stack.append(h)
        start = time.perf_counter()
        try:
            yield h
        finally:
            dur = time.perf_counter() - start
            stack.pop()
            counted = category in _GOODPUT_SET and not enclosed
            if counted:
                self.goodput.add(category, dur)
                h.args["goodput"] = category
            self._append({
                "ph": "X", "name": name, "cat": category,
                "ts": (start - self._t0) * 1e6, "dur": dur * 1e6,
                "tid": threading.get_ident(), "args": h.args,
            })

    def completed(self, name: str, category: str, start: float,
                  dur_secs: float, **attrs) -> None:
        """Record an already-finished interval (``start`` on the
        perf_counter clock) — how the recompile listener logs a compile
        it only hears about at its end."""
        self._append({
            "ph": "X", "name": name, "cat": category,
            "ts": (start - self._t0) * 1e6,
            "dur": max(dur_secs, 0.0) * 1e6,
            "tid": threading.get_ident(), "args": dict(attrs),
        })

    def instant(self, name: str, category: str = "other", **attrs) -> None:
        """A zero-duration marker ('i' event — Perfetto draws a flag)."""
        self._append({
            "ph": "i", "name": name, "cat": category, "s": "p",
            "ts": (time.perf_counter() - self._t0) * 1e6,
            "tid": threading.get_ident(), "args": dict(attrs),
        })

    def __len__(self) -> int:
        return len(self._events)

    # -- export ---------------------------------------------------------

    def chrome_trace(self, reason: str = "") -> Dict[str, Any]:
        """The Chrome ``trace_event`` JSON object (Perfetto-loadable)."""
        try:
            pid = jax.process_index()
        except Exception:
            pid = 0
        with self._lock:
            events = list(self._events)
        # map raw thread idents to small tids + name metadata rows
        names = {t.ident: t.name for t in threading.enumerate()}
        tids: Dict[int, int] = {}
        out_events: List[Dict[str, Any]] = [{
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"host{pid}"},
        }]
        for ev in events:
            ident = ev["tid"]
            if ident not in tids:
                tids[ident] = len(tids)
                out_events.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": tids[ident],
                    "args": {"name": names.get(ident, f"thread-{ident}")},
                })
            out_events.append({**ev, "pid": pid, "tid": tids[ident]})
        return {
            "displayTimeUnit": "ms",
            "otherData": {
                "reason": reason,
                "process_index": pid,
                "trace_start_unix": self._unix0,
                "dropped_events": self.dropped,
                "goodput": self.goodput.summary(),
                "recompiles": int(get_counters().get("recompiles", 0)),
                "straggler_events":
                    int(get_counters().get("straggler_events", 0)),
            },
            "traceEvents": out_events,
        }

    def write(self, path: str, reason: str = "") -> str:
        """Atomic (tmp + rename): the caller may be a watchdog thread
        racing ``os._exit``."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.chrome_trace(reason=reason), f)
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------------
# Recompile detection
# ---------------------------------------------------------------------------

class RecompileDetector:
    """Counts and timestamps XLA compiles; compiles after
    ``mark_steady()`` are recompiles (MegaScale's "why did step time
    spike" class).  ``pause()``/``resume()`` bracket phases where a
    fresh compile is *expected* (eval's forward-only program, a skipped
    iteration's program) so they never count as recompiles.

    With ``use_monitoring`` (default on any jax that has
    ``jax.monitoring``) detection is exact — the listener hears every
    backend compile.  The fallback flags steady-state step times beyond
    ``outlier_factor`` x the rolling median as *suspected* recompiles."""

    def __init__(self, tracer: Optional[SpanTracer] = None,
                 max_events: int = 256,
                 use_monitoring: Optional[bool] = None,
                 outlier_factor: float = 3.0,
                 outlier_window: int = 32):
        if use_monitoring is None:
            use_monitoring = hasattr(jax, "monitoring") and hasattr(
                jax.monitoring, "register_event_duration_secs_listener")
        self.use_monitoring = bool(use_monitoring)
        self.tracer = tracer
        self.outlier_factor = float(outlier_factor)
        self.compiles = 0                   # every compile heard
        self.recompiles = 0                 # compiles while steady
        self.compile_secs_total = 0.0
        self.events: deque = deque(maxlen=max(int(max_events), 1))
        self._steady = False
        self._paused = 0
        self._pending_n = 0
        self._pending_secs = 0.0
        self._recent: deque = deque(maxlen=max(int(outlier_window), 4))
        self._lock = threading.Lock()

    # -- exact path (jax.monitoring) ------------------------------------

    def on_compile(self, duration_secs: float) -> None:
        """Called by the module-level jax.monitoring listener at each
        backend-compile completion."""
        now = time.perf_counter()
        with self._lock:
            if self._paused:
                return
            self.compiles += 1
            self.compile_secs_total += duration_secs
            self._pending_n += 1
            self._pending_secs += duration_secs
            is_recompile = self._steady
            if is_recompile:
                self.recompiles += 1
                get_counters()["recompiles"] += 1
                self.events.append({
                    "kind": "recompile", "secs": float(duration_secs),
                    "time_unix": time.time(),
                })
        if self.tracer is not None:
            self.tracer.completed(
                "recompile" if is_recompile else "backend_compile",
                "compile", start=now - duration_secs,
                dur_secs=duration_secs)
        if is_recompile:
            print(f" [tracing] RECOMPILE detected: backend compile "
                  f"{duration_secs:.2f}s after steady state — a shape/"
                  f"layout change retraced the step", flush=True)
            try:
                from megatron_llm_tpu import telemetry

                fr = telemetry.get_flight_recorder()
                if fr is not None:
                    fr.record({"kind": "recompile", "time_unix": time.time(),
                               "secs": float(duration_secs)})
            except Exception:
                pass

    # -- fallback path (no jax.monitoring) ------------------------------

    def observe_step_time(self, secs: float) -> bool:
        """Outlier fallback: a steady-state step beyond
        ``outlier_factor`` x the rolling median is a *suspected*
        recompile.  No-op (False) when the exact listener is active."""
        if self.use_monitoring:
            return False
        with self._lock:
            baseline = list(self._recent)
            suspected = (self._steady and not self._paused
                         and len(baseline) >= 4
                         and secs > self.outlier_factor * median(baseline))
            if suspected:
                self.recompiles += 1
                get_counters()["recompiles"] += 1
                self.events.append({
                    "kind": "suspected_recompile", "secs": float(secs),
                    "time_unix": time.time(),
                })
            else:
                self._recent.append(float(secs))
        if suspected:
            if self.tracer is not None:
                self.tracer.instant("suspected_recompile", "compile",
                                    step_secs=float(secs))
            print(f" [tracing] suspected recompile: step took {secs:.2f}s "
                  f"vs rolling median {median(baseline):.2f}s", flush=True)
        return suspected

    # -- driver hooks ---------------------------------------------------

    def mark_steady(self) -> None:
        """The first step has compiled; compiles from here on are
        recompiles."""
        self._steady = True

    def pause(self) -> None:
        with self._lock:
            self._paused += 1

    def resume(self) -> None:
        with self._lock:
            self._paused = max(self._paused - 1, 0)

    def drain(self):
        """(count, seconds) of compiles since the last drain — the loop
        uses this to reattribute a step span's compile time to the
        'compile' goodput category."""
        with self._lock:
            n, secs = self._pending_n, self._pending_secs
            self._pending_n, self._pending_secs = 0, 0.0
        return n, secs


# One listener forever (jax.monitoring has no unregister); it dispatches
# to whichever detector is currently installed and is a cheap no-op
# otherwise, so tests can install/uninstall freely.
_ACTIVE_DETECTOR: Optional[RecompileDetector] = None
_LISTENER_REGISTERED = False


def _monitor_callback(event: str, duration: float, **kw) -> None:
    d = _ACTIVE_DETECTOR
    if d is not None and event == _COMPILE_EVENT:
        try:
            d.on_compile(float(duration))
        except Exception:
            pass                    # diagnostics must never break a compile


def install_detector(detector: Optional[RecompileDetector]) -> None:
    global _ACTIVE_DETECTOR, _LISTENER_REGISTERED
    _ACTIVE_DETECTOR = detector
    if (detector is not None and detector.use_monitoring
            and not _LISTENER_REGISTERED):
        jax.monitoring.register_event_duration_secs_listener(
            _monitor_callback)
        _LISTENER_REGISTERED = True


# ---------------------------------------------------------------------------
# Straggler detection
# ---------------------------------------------------------------------------

class StragglerDetector:
    """Flags hosts whose per-section time exceeds ``threshold`` x the
    cross-host median (MegaScale §5.2's automated straggler hunt).  The
    caller supplies already-gathered per-host values (the ``timers.py``
    ``process_allgather`` path) at deterministic log boundaries — this
    class performs no collective itself."""

    def __init__(self, threshold: float = 1.5, min_secs: float = 0.005,
                 tracer: Optional[SpanTracer] = None,
                 max_events: int = 256,
                 printer=print,
                 host_slice_map: Optional[List[int]] = None):
        self.threshold = float(threshold)
        self.min_secs = float(min_secs)     # ignore sub-noise spreads
        self.tracer = tracer
        self.printer = printer
        self.events: deque = deque(maxlen=max(int(max_events), 1))
        self.total = 0
        # host index -> slice id (multislice.host_slice_map); when set,
        # every event names the slice the straggling host belongs to —
        # the MegaScale "which slice is the fleet waiting on" dimension
        self.host_slice_map = host_slice_map

    def check(self, per_host: Dict[str, List[float]],
              iteration: int) -> List[Dict[str, Any]]:
        """One boundary's straggler scan; returns (and records) the
        structured events.  ``per_host`` maps section name -> one value
        per host (e.g. ``timers.report()``'s gathered snapshot)."""
        found: List[Dict[str, Any]] = []
        for section in sorted(per_host):
            values = per_host[section]
            if len(values) < 2:
                continue                    # single host: no medians to lag
            med = median(values)
            if med <= 0:
                continue
            for host, v in enumerate(values):
                if v > self.threshold * med and (v - med) >= self.min_secs:
                    ev = {
                        "kind": "straggler", "iteration": int(iteration),
                        "section": section, "host": int(host),
                        "secs": float(v), "median_secs": float(med),
                        "ratio": float(v / med),
                        "time_unix": time.time(),
                    }
                    hsm = self.host_slice_map
                    if hsm is not None and host < len(hsm):
                        ev["slice"] = int(hsm[host])
                    found.append(ev)
        if found:
            self.total += len(found)
            get_counters()["straggler_events"] += len(found)
            for ev in found:
                self.events.append(ev)
                if self.tracer is not None:
                    keys = ("iteration", "section", "host",
                            "secs", "median_secs", "ratio")
                    if "slice" in ev:
                        keys = keys + ("slice",)
                    self.tracer.instant("straggler", "straggler",
                                        **{k: ev[k] for k in keys})
                who = (f"slice {ev['slice']} host {ev['host']}"
                       if "slice" in ev else f"host {ev['host']}")
                self.printer(
                    f" [tracing] STRAGGLER {who} at iteration "
                    f"{ev['iteration']}: {ev['section']} "
                    f"{ev['secs'] * 1000:.1f} ms = {ev['ratio']:.2f}x the "
                    f"median ({ev['median_secs'] * 1000:.1f} ms)")
            try:
                from megatron_llm_tpu import telemetry

                fr = telemetry.get_flight_recorder()
                if fr is not None:
                    for ev in found:
                        fr.record(dict(ev))
            except Exception:
                pass
        return found


# ---------------------------------------------------------------------------
# Bundle + CLI wiring + module-level access
# ---------------------------------------------------------------------------

@dataclass
class Tracing:
    """Everything the observability layer needs, in one bundle."""

    tracer: SpanTracer
    recompile: Optional[RecompileDetector] = None
    straggler: Optional[StragglerDetector] = None
    trace_dir: Optional[str] = None

    def goodput_summary(self) -> Dict[str, float]:
        return self.tracer.goodput.summary()

    def trace_path(self) -> Optional[str]:
        if not self.trace_dir:
            return None
        try:
            idx = jax.process_index()
        except Exception:
            idx = 0
        name = TRACE_FILENAME if idx == 0 else f"trace_p{idx}.json"
        return os.path.join(self.trace_dir, name)

    def write_trace(self, reason: str = "") -> Optional[str]:
        path = self.trace_path()
        if path is None:
            return None
        os.makedirs(self.trace_dir, exist_ok=True)
        return self.tracer.write(path, reason=reason)

    def close(self) -> None:
        try:
            self.write_trace(reason="close")
        except Exception:
            pass
        if get_tracing() is self:
            install_tracing(None)


_ACTIVE: Optional[Tracing] = None


def install_tracing(tracing: Optional[Tracing]) -> None:
    """Register the run's Tracing so checkpointing/resilience/telemetry
    reach it without threading it through every call chain (same pattern
    as telemetry.install_stream)."""
    global _ACTIVE
    _ACTIVE = tracing
    install_detector(tracing.recompile if tracing is not None else None)


def get_tracing() -> Optional[Tracing]:
    return _ACTIVE


def get_tracer() -> Optional[SpanTracer]:
    return _ACTIVE.tracer if _ACTIVE is not None else None


@contextmanager
def span(name: str, category: str = "other", **attrs):
    """Module-level span that no-ops when no tracer is installed — how
    checkpointing / resilience / the train loop open spans without
    caring whether tracing is on."""
    t = _ACTIVE
    if t is None:
        yield None
        return
    with t.tracer.span(name, category, **attrs) as h:
        yield h


def instant(name: str, category: str = "other", **attrs) -> None:
    t = _ACTIVE
    if t is not None:
        t.tracer.instant(name, category, **attrs)


def goodput_summary() -> Optional[Dict[str, float]]:
    return _ACTIVE.goodput_summary() if _ACTIVE is not None else None


def dump_trace(reason: str = "") -> Optional[str]:
    """Write the active trace (crash/watchdog path — never raises)."""
    try:
        if _ACTIVE is None:
            return None
        return _ACTIVE.write_trace(reason=reason)
    except Exception:
        return None


def new_trace_id() -> str:
    """A fleet-unique request trace id (the ``X-Request-Trace`` value).
    16 hex chars: short enough to read in logs, unique enough for any
    realistic request volume.  The serving router mints one per inbound
    request; replicas mint their own only for direct (router-less)
    traffic."""
    return uuid.uuid4().hex[:16]


def start_trace_flusher(bundle: Tracing,
                        interval_secs: float = 5.0) -> threading.Thread:
    """Periodically write ``bundle``'s trace file from a daemon thread.

    Long-lived serving processes never reach the trainer's clean
    ``close()`` boundary — without a flusher the Chrome trace only
    exists after graceful shutdown, which is exactly when you don't
    need it.  The returned thread carries a ``stop`` Event; set it (and
    optionally join) to stop flushing."""
    stop = threading.Event()

    def loop():
        while not stop.wait(interval_secs):
            try:
                bundle.write_trace(reason="periodic")
            except Exception:
                pass

    t = threading.Thread(target=loop, name="trace-flusher", daemon=True)
    t.stop = stop           # type: ignore[attr-defined]
    t.start()
    return t


def build_tracing(args) -> Optional[Tracing]:
    """CLI wiring: a Tracing bundle from parsed args, or None when
    ``--trace_dir`` is unset."""
    trace_dir = getattr(args, "trace_dir", None)
    if not trace_dir:
        return None
    tracer = SpanTracer(
        capacity=getattr(args, "trace_buffer_size", 100_000) or 100_000)
    t = Tracing(
        tracer=tracer,
        recompile=RecompileDetector(tracer=tracer),
        straggler=StragglerDetector(
            threshold=getattr(args, "straggler_threshold", 1.5) or 1.5,
            tracer=tracer),
        trace_dir=trace_dir,
    )
    install_tracing(t)
    return t
