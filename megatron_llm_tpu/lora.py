"""LoRA finetuning (beyond-reference).

Low-Rank Adaptation: every targeted linear ``W [.., in, out]`` gains a
pair ``A [.., in, r]`` (gaussian / sqrt(r)) and ``B [.., r, out]``
(zeros), and the layer computes ``y = x W + (x A) B * (alpha / r)`` —
at init B=0 makes the adapted model exactly the base model.  Only the
adapters train: the optimizer sees a tree that is ~0.1-1% of the model,
so Adam state and checkpoints shrink accordingly, and the frozen base
params are closed over by the train step (no grads, no master copies).

TPU notes: the low-rank path stays as two thin matmuls (x@A then @B) —
never materialize W + BA [in, out] in the forward, it would double the
weight HBM traffic the freeze avoids.  Shardings: A inherits the
kernel's input-axis sharding with a replicated rank axis, B mirrors the
kernel's output axis, so tp/sp layouts work unchanged
(tests/test_lora.py proves tp=2 parity).

Usage (library)::

    lora = init_lora(model, params, rank=8, key=key)     # adapter tree
    adapter = LoraAdapter(model, params)                  # train-step model
    step = build_train_step(adapter, opt, pc, M)          # opt over lora only
    merged = merge_lora(params, lora)                     # export to base fmt

CLI: ``finetune.py --lora_rank=8 [--lora_alpha=16]
[--lora_targets=query_key_value,dense,...]``.
"""

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp

# default targets: the attention projections (the standard LoRA recipe);
# names are the param-dict keys used across the model families
DEFAULT_TARGETS = ("query_key_value", "dense")


def _is_linear(node) -> bool:
    k = node.get("kernel") if isinstance(node, dict) else None
    return k is not None and hasattr(k, "ndim") and k.ndim >= 2


def init_lora(model, params: Any, rank: int, key,
              alpha: Optional[float] = None,
              targets: Sequence[str] = DEFAULT_TARGETS):
    """Adapter tree mirroring ``params``: targeted linear dicts map to
    {'lora_A', 'lora_B', 'lora_scale'}; everything else maps to None
    (structural placeholder, ignored by merge/apply)."""
    alpha = float(alpha if alpha is not None else 2 * rank)
    scaling = alpha / rank
    keys = iter(jax.random.split(key, 4096))

    def walk(node, name=""):
        if isinstance(node, dict):
            if name in targets and _is_linear(node):
                kern = node["kernel"]
                *lead, fan_in, fan_out = kern.shape
                a = jax.random.normal(
                    next(keys), (*lead, fan_in, rank), jnp.float32
                ) / jnp.sqrt(float(rank))
                return {
                    "lora_A": a.astype(kern.dtype),
                    "lora_B": jnp.zeros((*lead, rank, fan_out),
                                        kern.dtype),
                    # lead dims mirror the kernel's (the scanned layer
                    # stack slices EVERY leaf's leading axis)
                    "lora_scale": jnp.full(tuple(lead), scaling,
                                           jnp.float32),
                }
            return {k: walk(v, k) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v, name) for v in node)
        return None

    return walk(params)


def attach_lora(params: Any, lora: Any):
    """Forward-time view: targeted linear dicts gain the lora leaves
    (parallel/layers.py applies the low-rank path when they are
    present).  Base leaves are shared, not copied."""
    def walk(p, l):
        if isinstance(p, dict):
            if isinstance(l, dict) and "lora_A" in l:
                return {**p, **l}
            return {k: walk(v, l.get(k) if isinstance(l, dict) else None)
                    for k, v in p.items()}
        if isinstance(p, (list, tuple)):
            return type(p)(walk(v, l[i] if isinstance(l, (list, tuple))
                                else None) for i, v in enumerate(p))
        return p

    return walk(params, lora)


def merge_lora(params: Any, lora: Any):
    """Export: fold the adapters into the base kernels
    (kernel += scale * A @ B) so the result loads anywhere a base
    checkpoint does.  The [.., in, out] update is materialized ONCE
    here — never in the forward."""
    def walk(p, l):
        if isinstance(p, dict):
            if isinstance(l, dict) and "lora_A" in l:
                kern = p["kernel"]
                scale = l["lora_scale"]
                upd = jnp.einsum(
                    "...ir,...ro->...io",
                    l["lora_A"].astype(jnp.float32),
                    l["lora_B"].astype(jnp.float32)) \
                    * scale.reshape(scale.shape + (1, 1))
                return {**p, "kernel": (kern.astype(jnp.float32)
                                        + upd).astype(kern.dtype)}
            return {k: walk(v, l.get(k) if isinstance(l, dict) else None)
                    for k, v in p.items()}
        if isinstance(p, (list, tuple)):
            return type(p)(walk(v, l[i] if isinstance(l, (list, tuple))
                                else None) for i, v in enumerate(p))
        return p

    return walk(params, lora)


def lora_param_specs(model, params_or_shape, lora: Any):
    """Sharding specs for the adapter tree: A inherits the kernel's
    input-axis sharding (rank axis replicated), B mirrors the output
    axis (rank axis replicated)."""
    base_specs = model.param_specs(params_or_shape)

    def walk(sp, l):
        if isinstance(l, dict) and "lora_A" in l:
            kspec = tuple(sp["kernel"])
            return {
                "lora_A": kspec[:-1] + (None,),
                "lora_B": kspec[:-2] + (None,) + kspec[-1:],
                "lora_scale": kspec[:-2],
            }
        if isinstance(l, dict):
            return {k: walk(sp[k] if isinstance(sp, dict) else None, v)
                    for k, v in l.items()}
        if isinstance(l, (list, tuple)):
            return type(l)(walk(sp[i] if isinstance(sp, (list, tuple))
                                else None, v) for i, v in enumerate(l))
        return None

    return walk(base_specs, lora)


class LoraAdapter:
    """Model wrapper whose trainable pytree is the LoRA tree.

    Quacks like the wrapped model for ``build_train_step`` /
    ``MegatronOptimizer``: ``__call__(lora, tokens, ...)`` runs the base
    model with adapters attached; the frozen base params are a closure
    constant (no grads, no optimizer state, no fp32 masters)."""

    def __init__(self, model, base_params):
        self.model = model
        self.base_params = base_params
        self.cfg = model.cfg

    def __call__(self, lora, *args, **kwargs):
        return self.model(attach_lora(self.base_params, lora),
                          *args, **kwargs)

    def init_lora(self, rank: int, key, alpha=None,
                  targets: Sequence[str] = DEFAULT_TARGETS):
        return init_lora(self.model, self.base_params, rank, key,
                         alpha=alpha, targets=targets)

    def param_specs(self, lora):
        return lora_param_specs(self.model, self.base_params, lora)

    def num_params(self, lora):
        return sum(int(x.size) for x in jax.tree_util.tree_leaves(lora)
                   if hasattr(x, "size"))

    def flops_per_token(self):
        return self.model.flops_per_token()
