"""Unified runtime telemetry: throughput/MFU stream, structured JSONL log,
flight recorder, in-loop profiler capture.

Motivation (MegaScale, arXiv:2402.15627 §5): at scale, "is the run
healthy and fast?" must be answerable from the run itself — per-step
telemetry in a structured stream, in-situ profiler capture, and a flight
recorder consulted on failure.  The reference Megatron-LM computes a
throughput estimate inside ``training_log`` (arXiv:2104.04473;
training.py:591-609) but has no machine-readable stream and no profiler
integration; ``bench.py`` here measures MFU out-of-band only.  This
module puts that layer *in* the training loop:

* **ThroughputCalculator** — tokens/sec, tokens/sec/device, achieved
  TFLOPs/device and MFU from the model-level ``flops_per_token()`` and
  the per-chip peak-FLOPs table (shared with ``bench.py`` — one source
  of truth).  MFU carries the same > ``MFU_SANITY_LIMIT`` fabrication
  guard the bench uses: a physically impossible number means the timing
  failed to sync with the device, and is reported as null, never as a
  value.

* **TelemetryStream** (``--structured_log_dir``) — one JSONL record per
  log boundary: iteration, losses, grad_norm, lr, step time, throughput
  / MFU, per-device ``memory_stats()``, recovery counters.  Records are
  versioned (``schema``) and written line-buffered by process 0 only.

* **FlightRecorder** — bounded in-memory deque of the last K step
  records (lightweight per-iteration dispatch entries + the full
  log-boundary records).  The resilience watchdog/crash path dumps it
  next to its thread-stack report (``resilience.dump_stacks_and_memory``)
  and, when a structured log dir exists, as ``flight_recorder.json``
  beside the stream — MegaScale's "what were the last things the run
  did" forensics.

* **ProfilerSession** (``--profile --profile_step_start N
  --profile_step_end M --profile_dir D``) — wraps the chosen step window
  in ``jax.profiler`` trace capture during real training (subsuming
  ``tools/profile_step.py``'s one-shot flow); ``--profiler_port`` starts
  ``jax.profiler.start_server`` for live TensorBoard capture.
  ``jax.named_scope`` annotations on the embedding / transformer layers
  / pipeline stages make the resulting xplane legible.

Everything here is host-side: nothing enters the jitted step, so
telemetry costs nothing on the XLA program.  Collective discipline
matches ``dist_signal_handler.py``: any cross-host reduction happens
only at deterministic log boundaries (see ``timers.Timers``).
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax

from megatron_llm_tpu.global_vars import get_counters

# ---------------------------------------------------------------------------
# Peak FLOPs / MFU
# ---------------------------------------------------------------------------

# bf16 peak per chip, keyed by device_kind substrings; spellings vary
# across libtpu versions (v5e reports "TPU v5 lite" or "TPU v5e").
# Single source of truth — bench.py imports this table.
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# MFU above this is physically impossible — the timing loop failed to
# sync with the device (bench.py round-3 caught a 1380-MFU "measurement"
# this way).  Shared by bench.py (which aborts) and the runtime stream
# (which reports null).
MFU_SANITY_LIMIT = 0.95


def peak_flops_for_kind(device_kind: str,
                        assume_tpu: bool = False) -> Optional[float]:
    """Peak bf16 FLOPs for a device_kind string, or None when the
    hardware has no meaningful peak (CPU) — a null peak means MFU is
    never fabricated.  ``assume_tpu`` supplies the v5e default for TPU
    device kinds the table doesn't spell (new libtpu spellings)."""
    for k, v in PEAK_FLOPS.items():
        if k in device_kind:
            return v
    if assume_tpu or "TPU" in device_kind:
        return 197e12
    return None


def peak_flops_for_local_device() -> Optional[float]:
    """Peak FLOPs of this host's first device (None on CPU)."""
    try:
        dev = jax.local_devices()[0]
    except Exception:
        return None
    on_tpu = jax.default_backend() in ("tpu", "axon") \
        or "TPU" in dev.device_kind
    return peak_flops_for_kind(dev.device_kind, assume_tpu=on_tpu)


class ThroughputCalculator:
    """Tokens/sec(/device), achieved TFLOPs/device and MFU from wall time.

    ``flops_per_token`` is the model-level fwd+bwd estimate
    (``model.flops_per_token()``, models/language_model.py); ``peak_flops``
    the per-chip bf16 peak (None => MFU is always null).  All host-side
    float arithmetic — free at log boundaries."""

    def __init__(self, flops_per_token: Optional[float] = None,
                 device_count: Optional[int] = None,
                 peak_flops: Optional[float] = None):
        self.flops_per_token = flops_per_token
        self._device_count = device_count
        self.peak_flops = peak_flops

    @classmethod
    def from_model(cls, model, device_count: Optional[int] = None,
                   peak_flops: Optional[float] = "auto"):
        """Build from any model exposing ``flops_per_token()`` (models
        without one still get tokens/sec accounting)."""
        fpt = None
        fn = getattr(model, "flops_per_token", None)
        if callable(fn):
            try:
                fpt = float(fn())
            except Exception:
                fpt = None
        if peak_flops == "auto":
            peak_flops = peak_flops_for_local_device()
        return cls(flops_per_token=fpt, device_count=device_count,
                   peak_flops=peak_flops)

    @property
    def device_count(self) -> int:
        if self._device_count is None:
            self._device_count = jax.device_count()
        return self._device_count

    def compute(self, tokens: float, elapsed_secs: float) -> Dict[str, Any]:
        """One log boundary's throughput record.  ``tokens`` is the global
        token count per iteration, ``elapsed_secs`` the per-iteration wall
        time.  MFU is null when the peak is unknown (CPU) or the number
        trips the fabrication guard — never a made-up value."""
        n = max(self.device_count, 1)
        tps = tokens / max(elapsed_secs, 1e-9)
        out: Dict[str, Any] = {
            "tokens_per_sec": tps,
            "tokens_per_sec_per_device": tps / n,
            "tflops_per_device": None,
            "mfu": None,
        }
        if self.flops_per_token:
            achieved = tps * self.flops_per_token / n
            out["tflops_per_device"] = achieved / 1e12
            if self.peak_flops:
                mfu = achieved / self.peak_flops
                out["mfu"] = mfu if mfu <= MFU_SANITY_LIMIT else None
        return out


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Bounded deque of the last K step records (MegaScale §5.3: the
    record consulted when a run dies).  Two record kinds: ``dispatch``
    (per-iteration, host-only — never syncs the device) and ``log`` (the
    full log-boundary record)."""

    def __init__(self, capacity: int = 64):
        self.capacity = int(capacity)
        self._records: deque = deque(maxlen=max(self.capacity, 1))

    def record(self, rec: Dict[str, Any]) -> None:
        self._records.append(rec)

    def records(self) -> List[Dict[str, Any]]:
        return list(self._records)

    def __len__(self) -> int:
        return len(self._records)

    def dump(self, path: str, reason: str = "") -> str:
        """Write the recorder as JSON (atomic: tmp + rename — the caller
        may be a watchdog thread racing process death)."""
        payload = {
            "dumped_at_unix": time.time(),
            "reason": reason,
            "capacity": self.capacity,
            "records": self.records(),
        }
        return atomic_write_json(path, payload)


# ---------------------------------------------------------------------------
# Atomic snapshot writing (shared by the flight recorder, the crash
# path's stack dump, and the serving alert engine's postmortem bundles)
# ---------------------------------------------------------------------------

def atomic_write_json(path: str, payload: Any, indent: int = 1) -> str:
    """Write JSON atomically (tmp + rename): a reader — or a scraper
    racing process death — sees either the old file or the complete new
    one, never a truncated write."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=indent, default=str)
    os.replace(tmp, path)
    return path


def capture_thread_stacks() -> str:
    """All-thread stack report (the watchdog/crash dump and the alert
    bundles share this): one block per thread with name/daemon flag and
    the formatted frames from ``sys._current_frames``."""
    import sys
    import traceback

    frames = sys._current_frames()
    names = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in sorted(frames.items()):
        t = names.get(ident)
        label = f"{t.name}{' (daemon)' if t.daemon else ''}" \
            if t is not None else "unknown"
        out.append(f"--- thread {label} (ident {ident}) ---")
        out.append("".join(traceback.format_stack(frame)))
    return "\n".join(out)


def write_snapshot_bundle(dir_path: str, parts: Dict[str, Any],
                          max_bytes_per_part: int = 2_000_000,
                          manifest_extra: Optional[Dict[str, Any]] = None
                          ) -> str:
    """Write a postmortem bundle as an atomically-published directory.

    ``parts`` maps part name -> payload: a str becomes ``<name>.txt``,
    anything else JSON-serializes to ``<name>.json``.  Every part is
    size-bounded (oversize payloads are truncated with a marker, never
    dropped silently) and the bundle carries a ``manifest.json`` listing
    what landed.  The whole directory is staged under a pid-suffixed tmp
    name and published with one ``os.replace`` so a reader never sees a
    half-written bundle — the same tmp+rename discipline as
    :func:`atomic_write_json`, at directory granularity."""
    tmp = f"{dir_path}.tmp.{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    manifest: Dict[str, Any] = {
        "written_at_unix": time.time(),
        "parts": {},
    }
    if manifest_extra:
        manifest.update(manifest_extra)
    for name, payload in sorted(parts.items()):
        try:
            if isinstance(payload, str):
                fname, data = f"{name}.txt", payload
            else:
                fname, data = f"{name}.json", json.dumps(
                    payload, indent=1, default=str)
            truncated = False
            if len(data) > max_bytes_per_part:
                data = data[:max_bytes_per_part] \
                    + "\n...[truncated by bundle size bound]"
                truncated = True
            with open(os.path.join(tmp, fname), "w") as f:
                f.write(data)
            manifest["parts"][name] = {"file": fname,
                                       "bytes": len(data),
                                       "truncated": truncated}
        except Exception as exc:    # noqa: BLE001 - forensics: best effort
            manifest["parts"][name] = {"error": repr(exc)}
    atomic_write_json(os.path.join(tmp, "manifest.json"), manifest)
    if os.path.isdir(dir_path):     # an older bundle with the same name
        os.replace(os.path.join(tmp, "manifest.json"),
                   os.path.join(dir_path, "manifest.json"))
        for f in os.listdir(tmp):
            os.replace(os.path.join(tmp, f), os.path.join(dir_path, f))
        os.rmdir(tmp)
    else:
        os.replace(tmp, dir_path)
    return dir_path


# ---------------------------------------------------------------------------
# Device memory
# ---------------------------------------------------------------------------

def device_memory_stats(device=None) -> Dict[str, int]:
    """``memory_stats()`` of one local device, reduced to the portable
    keys (bytes_in_use, peak_bytes_in_use, largest_alloc_size, num_allocs
    — whichever the backend reports).  {} when unavailable (CPU backends
    often return None)."""
    try:
        if device is None:
            device = jax.local_devices()[0]
        stats = device.memory_stats() or {}
    except Exception:
        return {}
    keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
            "largest_alloc_size", "num_allocs")
    return {k: int(stats[k]) for k in keep if k in stats}


# ---------------------------------------------------------------------------
# Fixed-bucket histograms (SLO accounting)
# ---------------------------------------------------------------------------

# Prometheus-style latency buckets (seconds).  Fixed across the fleet so
# replica histograms merge by bucket-sum in the router's /metrics.
DEFAULT_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

_INF_LABEL = "+Inf"


def _bucket_label(bound: float) -> str:
    return format(bound, "g")


class Histogram:
    """Stdlib fixed-bucket histogram, mergeable by bucket-sum.

    Snapshots carry per-bucket (non-cumulative) counts keyed by the
    bucket's upper bound, plus ``count`` and ``sum`` — all additive, so
    the router's recursive numeric sum over replica snapshots IS the
    fleet histogram.  Percentiles come from ``histogram_percentile``
    (linear interpolation inside the winning bucket), computed at read
    time and never stored, so they can't be accidentally summed."""

    def __init__(self, bounds=DEFAULT_LATENCY_BUCKETS):
        self.bounds = tuple(sorted(float(b) for b in bounds))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.bounds) + 1)     # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value) -> None:
        if value is None:
            return
        v = float(value)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        buckets = {_bucket_label(b): counts[i]
                   for i, b in enumerate(self.bounds)}
        buckets[_INF_LABEL] = counts[-1]
        return {"buckets": buckets, "count": total, "sum": round(s, 9)}


def is_histogram_snapshot(d: Any) -> bool:
    """Structural check shared by the Prometheus renderer and the router
    aggregation: a dict with a str->number ``buckets`` dict plus
    ``count``/``sum`` leaves."""
    return (isinstance(d, dict) and "count" in d and "sum" in d
            and isinstance(d.get("buckets"), dict))


def histogram_percentile(snap: Dict[str, Any], q: float) -> Optional[float]:
    """Estimate the q-quantile from a (possibly merged) histogram
    snapshot.  Linear interpolation within the winning bucket; the +Inf
    bucket answers with its lower edge (the largest finite bound) — an
    under-estimate, never an invention.  None on an empty histogram."""
    if not is_histogram_snapshot(snap):
        return None
    total = snap.get("count") or 0
    if total <= 0:
        return None
    items = []
    for k, v in snap["buckets"].items():
        bound = float("inf") if k in (_INF_LABEL, "inf") else float(k)
        items.append((bound, int(v)))
    items.sort()
    target = max(min(float(q), 1.0), 0.0) * total
    cum = 0
    lo = 0.0
    for bound, c in items:
        if c > 0 and cum + c >= target:
            if bound == float("inf"):
                return lo
            frac = (target - cum) / c if c else 1.0
            return lo + (bound - lo) * max(min(frac, 1.0), 0.0)
        cum += c
        if bound != float("inf"):
            lo = bound
    return lo


# ---------------------------------------------------------------------------
# Prometheus text exposition (shared by serving /metrics, the router's
# fleet /metrics, and the trainer's --status_port endpoint)
# ---------------------------------------------------------------------------

def _metric_name(name: str) -> str:
    name = "".join(c if (c.isalnum() and c.isascii()) or c == "_"
                   else "_" for c in name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def prometheus_exposition(snapshot: dict,
                          prefix: str = "megatron_serve_") -> str:
    """Render a metrics snapshot dict as Prometheus text exposition
    format (0.0.4) so standard scrapers can hit ``/metrics`` without a
    JSON-translating sidecar.  Nested dicts (the ``engine`` block, its
    per-reason completion counts) flatten into underscore-joined names;
    None values (e.g. empty-window percentiles) are omitted; numbers are
    exported as gauges — the scraper cannot tell a monotone counter from
    a level, and gauge is always safe.  Histogram snapshots (the
    ``Histogram.snapshot()`` shape) render as proper Prometheus
    histograms: cumulative ``_bucket{le=...}`` series plus ``_sum`` and
    ``_count``.  An ``alerts`` block (the serving alert engine's
    snapshot shape) renders its firing list as the labeled gauge
    ``megatron_alert_firing{rule=...,scope=...} 1`` — the one labeled
    series in the exposition, with a fixed unprefixed name so the same
    alerting config scrapes replica and fleet endpoints alike — and its
    numeric counters as ordinary gauges."""
    lines = []

    def esc(v):
        return str(v).replace("\\", "\\\\").replace('"', '\\"')

    def emit_alert_block(path, block):
        lines.append("# TYPE megatron_alert_firing gauge")
        for entry in block.get("firing") or []:
            if not isinstance(entry, dict):
                continue
            lines.append(
                f'megatron_alert_firing{{rule="{esc(entry.get("rule"))}"'
                f',scope="{esc(entry.get("scope"))}"'
                f',severity="{esc(entry.get("severity"))}"}} 1')
        rest = {k: v for k, v in block.items()
                if k not in ("firing", "pending")}
        walk(rest, path)

    def emit(name, value):
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return
        name = _metric_name(name)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {float(value):g}")

    def emit_histogram(name, snap):
        name = _metric_name(name)
        items = []
        for k, v in snap["buckets"].items():
            bound = float("inf") if k in (_INF_LABEL, "inf") else float(k)
            items.append((bound, k, int(v)))
        items.sort()
        lines.append(f"# TYPE {name} histogram")
        cum = 0
        for bound, label, c in items:
            cum += c
            lines.append(f'{name}_bucket{{le="{label}"}} {cum}')
        lines.append(f"{name}_sum {float(snap.get('sum') or 0.0):g}")
        lines.append(f"{name}_count {int(snap.get('count') or 0)}")

    def walk(d, path):
        for k, v in sorted(d.items()):
            if is_histogram_snapshot(v):
                emit_histogram(f"{path}{k}", v)
            elif k == "alerts" and isinstance(v, dict) \
                    and isinstance(v.get("firing"), list):
                emit_alert_block(f"{path}{k}_", v)
            elif isinstance(v, dict):
                walk(v, f"{path}{k}_")
            else:
                emit(f"{path}{k}", v)

    walk(snapshot, prefix)
    return "\n".join(lines) + "\n"


def _wants_prometheus(path: str, accept: str) -> bool:
    """Content negotiation for /metrics: an explicit ?format=prometheus
    query wins; otherwise an Accept header preferring text/plain (what
    the Prometheus scraper sends) selects the text exposition."""
    query = path.partition("?")[2]
    for pair in query.split("&"):
        if pair.partition("=")[::2] == ("format", "prometheus"):
            return True
    accept = accept.lower()
    return ("text/plain" in accept or "openmetrics" in accept) \
        and "application/json" not in accept


# ---------------------------------------------------------------------------
# Structured JSONL stream
# ---------------------------------------------------------------------------

# 2: + interval_time_secs / goodput / tracing
# 3: + layer_stats (per-group grad/param/update norms, non-finite counts —
#    see health.py) on records at --log_layer_stats_interval boundaries
# 4: + per-slice attribution on multi-slice runs (slice_times /
#    worst_slice / goodput.slice_stall_secs) and the elastic_resume /
#    preempt_rescue event kinds — see multislice.py
# 5: serve request_done records gain trace_id (the router-minted
#    X-Request-Trace id), per-request phase attribution (phases.queue_secs
#    / admission_secs / prefill_secs / decode_secs / stream_write_secs),
#    tpot_secs (amortized per-output-token decode latency), decode_tokens
#    and prefill_computed_tokens — see serving/engine.py and
#    tools/serve_report.py
# 6: serve request_done records gain prefill_kernel (the resolved
#    chunked-prefill attention path, 'pallas'|'xla', alongside the
#    existing decode-path paged_kernel) — see serving/engine.py
# 7: + kind="fleet" supervisor events (replica_spawned / replica_died /
#    replica_respawned / scale_up / scale_down / brownout, each with
#    slot/url/reason fields) — see serving/supervisor.py and
#    tools/serve_report.py's fleet-event timeline
# 8: serve request_done records gain speculative-decoding attribution:
#    drafted_tokens / accepted_tokens (prompt-lookup proposals this
#    request rode into verify steps and the subset verification
#    committed) and accept_rate (accepted/drafted, null when the request
#    never drafted) — see serving/engine.py and serving/drafter.py
# 9: + router-tier fleet events (router_spawned / router_died /
#    router_respawned / router_scale_up / router_scale_down, with
#    slot/url and the dispatch-p95/in-flight readings behind scaling
#    decisions) — see serving/supervisor.py's sharded front door
# 10: + kind="serve" event="engine_loop_stats" records (periodic
#    engine-loop goodput rollups: per-phase schedule / draft /
#    build_inputs / device / emit seconds, device_busy_pct /
#    host_bubble_pct, dispatch-gap stall count, windowed recents and
#    phase p50/p95) — see serving/loop_profiler.py and
#    tools/serve_report.py's loop-goodput section
# 11: + kind="serve" event="cache_stats" records (periodic KV
#    prefix-cache observatory rollups: salted-digest heat top-K,
#    miss-cause taxonomy cold/evicted, capacity-vs-churn eviction
#    forensics, ghost-tier hit projections at 2x/4x/10x capacity);
#    request_done records gain miss_cold_blocks / miss_evicted_blocks
#    (per-request prefix miss causes; evicted = the evicted-then-
#    wanted-again regret signal) — see serving/cache_observatory.py
#    and tools/serve_report.py's cache-observatory section
# 12: hierarchical KV cache (host-RAM spill tier under the HBM pool;
#    serving/host_cache.py): request_done records gain host_hit_blocks
#    (prefix blocks rescued from the host tier) and swap_in_secs (the
#    host→device scatter time the request paid for them); cache_stats
#    records gain host_hits / host_hit_tokens / swap_in_blocks and a
#    "host" sub-block (spill/eviction/swap-in counters, budget usage)
# 13: + alert_transition events (serving/alerts.py SLO sentinel):
#    kind="serve" per-replica (and kind="fleet" at the supervisor's
#    merged scope) records with rule / scope / state
#    (pending|firing|resolved) / severity / value / threshold /
#    window_secs / since_unix / bundle (the postmortem bundle directory
#    captured on firing) — see serving/alerts.py and
#    tools/serve_report.py's incident timeline
TELEMETRY_SCHEMA_VERSION = 13
STREAM_FILENAME = "telemetry.jsonl"
FLIGHT_RECORDER_FILENAME = "flight_recorder.json"


class TelemetryStream:
    """One JSONL record per log boundary under ``log_dir`` (process 0
    writes; every process keeps the flight recorder).  Tracks running
    aggregates for the end-of-run summary (mean MFU etc. — percentiles
    are the offline ``tools/telemetry_report.py``'s job)."""

    def __init__(self, log_dir: Optional[str] = None,
                 flight_recorder_size: int = 64):
        self.log_dir = log_dir
        self.flight_recorder = FlightRecorder(flight_recorder_size)
        self._file = None
        # a StatusServer (--status_port) sees every emitted record; None
        # when no live endpoint is attached
        self.status_server: Optional["StatusServer"] = None
        self._sums = {"steps": 0, "mfu": 0.0, "mfu_n": 0,
                      "tokens_per_sec_per_device": 0.0, "step_time": 0.0}
        if log_dir and jax.process_index() == 0:
            os.makedirs(log_dir, exist_ok=True)
            self._file = open(os.path.join(log_dir, STREAM_FILENAME),
                              "a", buffering=1)

    def emit(self, record: Dict[str, Any]) -> Dict[str, Any]:
        """Stamp, persist, and flight-record one log-boundary record."""
        rec = {"schema": TELEMETRY_SCHEMA_VERSION, "kind": "log",
               "time_unix": time.time(), **record}
        if self._file is not None:
            try:
                self._file.write(json.dumps(rec) + "\n")
            except ValueError:
                pass    # closed mid-shutdown while the engine retires
        if self.status_server is not None:
            self.status_server.update(rec)
        self.flight_recorder.record(rec)
        s = self._sums
        s["steps"] += 1
        s["step_time"] += float(rec.get("step_time_secs") or 0.0)
        s["tokens_per_sec_per_device"] += float(
            rec.get("tokens_per_sec_per_device") or 0.0)
        if rec.get("mfu") is not None:
            s["mfu"] += float(rec["mfu"])
            s["mfu_n"] += 1
        return rec

    def record_dispatch(self, rec: Dict[str, Any]) -> None:
        """Lightweight per-iteration entry — host-side fields only, never
        a device sync, so it is safe (and cheap) every step."""
        self.flight_recorder.record({"kind": "dispatch",
                                     "time_unix": time.time(), **rec})

    def summary(self) -> Dict[str, Any]:
        s = self._sums
        n = max(s["steps"], 1)
        return {
            "log_boundaries": s["steps"],
            "mean_step_time_secs": s["step_time"] / n,
            "mean_tokens_per_sec_per_device":
                s["tokens_per_sec_per_device"] / n,
            "mean_mfu": (s["mfu"] / s["mfu_n"]) if s["mfu_n"] else None,
        }

    def dump_flight_recorder(self, reason: str = "") -> Optional[str]:
        if self.log_dir is None or not len(self.flight_recorder):
            return None
        path = os.path.join(self.log_dir, FLIGHT_RECORDER_FILENAME)
        return self.flight_recorder.dump(path, reason=reason)

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


# Active stream registry: the watchdog/crash path (resilience.py) and the
# wandb finish() summary reach the run's telemetry without threading it
# through every call chain — same pattern as resilience's save-fault hook.
_ACTIVE_STREAM: Optional[TelemetryStream] = None


def install_stream(stream: Optional[TelemetryStream]) -> None:
    global _ACTIVE_STREAM
    _ACTIVE_STREAM = stream


def get_stream() -> Optional[TelemetryStream]:
    return _ACTIVE_STREAM


def get_flight_recorder() -> Optional[FlightRecorder]:
    return _ACTIVE_STREAM.flight_recorder if _ACTIVE_STREAM else None


def dump_flight_recorder(reason: str = "") -> Optional[str]:
    """Dump the active run's flight recorder next to its JSONL stream
    (no-op without an installed stream or records).  Diagnostics path —
    never raises."""
    try:
        if _ACTIVE_STREAM is None:
            return None
        return _ACTIVE_STREAM.dump_flight_recorder(reason=reason)
    except Exception:
        return None


def run_summary() -> Optional[Dict[str, Any]]:
    """The active stream's aggregate summary (wandb finish() pulls this),
    merged with the active tracer's goodput breakdown + recompile /
    straggler counts when tracing is on."""
    out = _ACTIVE_STREAM.summary() if _ACTIVE_STREAM else None
    from megatron_llm_tpu import tracing

    g = tracing.goodput_summary()
    if g is not None:
        out = dict(out or {})
        out["goodput_pct"] = g["goodput_pct"]
        out["goodput"] = g
        out["recompiles"] = int(get_counters().get("recompiles", 0))
        out["straggler_events"] = int(
            get_counters().get("straggler_events", 0))
    return out


# ---------------------------------------------------------------------------
# In-loop profiler capture
# ---------------------------------------------------------------------------

class ProfilerSession:
    """Wraps a chosen iteration window ``[step_start, step_end]`` in
    ``jax.profiler`` trace capture during real training.  The loop calls
    ``maybe_start(upcoming_iteration)`` before dispatch and
    ``maybe_stop(completed_iteration, sync=...)`` after; ``sync`` blocks
    on the step's outputs so the traced window contains the device work,
    not just its dispatch.  One-shot: the window fires once per run."""

    def __init__(self, profile_dir: str, step_start: int, step_end: int,
                 port: Optional[int] = None):
        if step_end < step_start:
            raise ValueError(
                f"profile_step_end ({step_end}) < profile_step_start "
                f"({step_start})")
        self.profile_dir = profile_dir
        self.step_start = int(step_start)
        self.step_end = int(step_end)
        self.active = False
        self.done = False
        self._server = None
        if port:
            # live-capture endpoint (TensorBoard "capture profile")
            self._server = jax.profiler.start_server(int(port))

    def maybe_start(self, upcoming_iteration: int) -> bool:
        if self.done or self.active \
                or upcoming_iteration != self.step_start:
            return False
        os.makedirs(self.profile_dir, exist_ok=True)
        jax.profiler.start_trace(self.profile_dir)
        self.active = True
        print(f" [profiler] trace started at iteration "
              f"{upcoming_iteration} -> {self.profile_dir}", flush=True)
        return True

    def maybe_stop(self, completed_iteration: int,
                   sync: Optional[Callable[[], Any]] = None) -> bool:
        if not self.active or completed_iteration < self.step_end:
            return False
        if sync is not None:
            sync()      # device work of the window lands inside the trace
        jax.profiler.stop_trace()
        self.active = False
        self.done = True
        print(f" [profiler] trace stopped after iteration "
              f"{completed_iteration} (view: tensorboard --logdir "
              f"{self.profile_dir}, profile plugin / Perfetto)", flush=True)
        return True

    def close(self) -> None:
        """Stop an in-flight trace on any exit path (a truncated window
        still yields a usable xplane)."""
        if self.active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self.active = False
            self.done = True


# ---------------------------------------------------------------------------
# Trainer live-status endpoint (--status_port)
# ---------------------------------------------------------------------------

class StatusServer:
    """Stdlib HTTP ``/health`` + ``/metrics`` over the latest telemetry
    record — the trainer-side twin of the serving server's endpoints, so
    the same scraper config covers both halves of the system.  Runs as a
    daemon thread on process 0 only; ``update()`` is called from the
    stream's ``emit()`` so it costs one dict assignment per log boundary.

    ``/health``  -> {"status": "ok", "iteration", "secs_since_last_record",
                     "uptime_secs"}
    ``/metrics`` -> the latest record as JSON, or Prometheus text
                    exposition under the usual negotiation
                    (?format=prometheus or an Accept preferring
                    text/plain), prefix ``megatron_train_``.
    """

    def __init__(self, port: int, host: str = "0.0.0.0"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self._latest: Optional[Dict[str, Any]] = None
        self._latest_at: Optional[float] = None
        self._t_start = time.time()
        self._lock = threading.Lock()
        status = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *a):     # silence per-request noise
                pass

            def _send(self, code, payload, content_type="application/json"):
                body = payload if isinstance(payload, bytes) \
                    else json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path
                if path.partition("?")[0] == "/health":
                    self._send(200, status.health())
                elif path.partition("?")[0] == "/metrics":
                    latest = status.latest() or {}
                    if _wants_prometheus(path,
                                         self.headers.get("Accept", "")):
                        text = prometheus_exposition(
                            latest, prefix="megatron_train_")
                        self._send(200, text.encode(),
                                   content_type="text/plain; version=0.0.4")
                    else:
                        self._send(200, latest)
                else:
                    self._send(404, {"message": "not found"})

        self.httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]    # resolved when port=0
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="status-server",
            daemon=True)
        self._thread.start()

    def update(self, rec: Dict[str, Any]) -> None:
        # keep only JSON-serializable leaves; the record already is
        with self._lock:
            self._latest = rec
            self._latest_at = time.time()

    def latest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._latest) if self._latest else None

    def health(self) -> Dict[str, Any]:
        with self._lock:
            latest, at = self._latest, self._latest_at
        return {
            "status": "ok",
            "iteration": (latest or {}).get("iteration"),
            "secs_since_last_record":
                (round(time.time() - at, 3) if at else None),
            "uptime_secs": round(time.time() - self._t_start, 3),
        }

    def close(self) -> None:
        try:
            self.httpd.shutdown()
            self.httpd.server_close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Bundle + CLI wiring
# ---------------------------------------------------------------------------

@dataclass
class Telemetry:
    """Everything the train loop needs, in one optional argument."""

    throughput: Optional[ThroughputCalculator] = None
    stream: Optional[TelemetryStream] = None
    profiler: Optional[ProfilerSession] = None
    tracing: Optional[Any] = None       # a tracing.Tracing bundle
    status: Optional[StatusServer] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def default(cls, model) -> "Telemetry":
        """Throughput-only telemetry (free): every run reports
        tokens/sec/device + MFU at log boundaries even with no flags."""
        return cls(throughput=ThroughputCalculator.from_model(model))

    def close(self) -> None:
        if self.profiler is not None:
            self.profiler.close()
        if self.tracing is not None:
            # writes the trace file, then uninstalls the module registry
            self.tracing.close()
        if self.status is not None:
            self.status.close()
        if self.stream is not None:
            if get_stream() is self.stream:
                install_stream(None)
            self.stream.close()


def recovery_counters() -> Dict[str, int]:
    from megatron_llm_tpu.resilience import recovery_counters as rc

    return rc()


def build_telemetry(args, model) -> Telemetry:
    """CLI wiring: a Telemetry bundle from parsed args.  Always returns a
    bundle (throughput accounting is free); the stream/profiler members
    exist only when their flags ask for them."""
    t = Telemetry.default(model)
    log_dir = getattr(args, "structured_log_dir", None)
    if log_dir:
        t.stream = TelemetryStream(
            log_dir,
            flight_recorder_size=getattr(args, "flight_recorder_size", 64))
        install_stream(t.stream)
    if getattr(args, "profile", False):
        profile_dir = getattr(args, "profile_dir", None) \
            or (os.path.join(log_dir, "profile") if log_dir
                else "profile_trace")
        t.profiler = ProfilerSession(
            profile_dir,
            step_start=getattr(args, "profile_step_start", 10),
            step_end=getattr(args, "profile_step_end", 12),
            port=getattr(args, "profiler_port", None),
        )
    elif getattr(args, "profiler_port", None):
        # a live-capture server without a pre-chosen window
        jax.profiler.start_server(int(args.profiler_port))
    status_port = getattr(args, "status_port", None)
    if status_port is not None and jax.process_index() == 0:
        if t.stream is None:
            # in-memory stream: the endpoint needs emit() records even
            # when nothing asked for the JSONL file
            t.stream = TelemetryStream(
                None,
                flight_recorder_size=getattr(
                    args, "flight_recorder_size", 64))
            install_stream(t.stream)
        t.status = StatusServer(int(status_port))
        t.stream.status_server = t.status
        print(f" [telemetry] status endpoint on port {t.status.port} "
              f"(/health, /metrics)", flush=True)
    from megatron_llm_tpu import tracing as _tracing

    t.tracing = _tracing.build_tracing(args)    # None without --trace_dir
    return t
