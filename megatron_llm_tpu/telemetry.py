"""Unified runtime telemetry: throughput/MFU stream, structured JSONL log,
flight recorder, in-loop profiler capture.

Motivation (MegaScale, arXiv:2402.15627 §5): at scale, "is the run
healthy and fast?" must be answerable from the run itself — per-step
telemetry in a structured stream, in-situ profiler capture, and a flight
recorder consulted on failure.  The reference Megatron-LM computes a
throughput estimate inside ``training_log`` (arXiv:2104.04473;
training.py:591-609) but has no machine-readable stream and no profiler
integration; ``bench.py`` here measures MFU out-of-band only.  This
module puts that layer *in* the training loop:

* **ThroughputCalculator** — tokens/sec, tokens/sec/device, achieved
  TFLOPs/device and MFU from the model-level ``flops_per_token()`` and
  the per-chip peak-FLOPs table (shared with ``bench.py`` — one source
  of truth).  MFU carries the same > ``MFU_SANITY_LIMIT`` fabrication
  guard the bench uses: a physically impossible number means the timing
  failed to sync with the device, and is reported as null, never as a
  value.

* **TelemetryStream** (``--structured_log_dir``) — one JSONL record per
  log boundary: iteration, losses, grad_norm, lr, step time, throughput
  / MFU, per-device ``memory_stats()``, recovery counters.  Records are
  versioned (``schema``) and written line-buffered by process 0 only.

* **FlightRecorder** — bounded in-memory deque of the last K step
  records (lightweight per-iteration dispatch entries + the full
  log-boundary records).  The resilience watchdog/crash path dumps it
  next to its thread-stack report (``resilience.dump_stacks_and_memory``)
  and, when a structured log dir exists, as ``flight_recorder.json``
  beside the stream — MegaScale's "what were the last things the run
  did" forensics.

* **ProfilerSession** (``--profile --profile_step_start N
  --profile_step_end M --profile_dir D``) — wraps the chosen step window
  in ``jax.profiler`` trace capture during real training (subsuming
  ``tools/profile_step.py``'s one-shot flow); ``--profiler_port`` starts
  ``jax.profiler.start_server`` for live TensorBoard capture.
  ``jax.named_scope`` annotations on the embedding / transformer layers
  / pipeline stages make the resulting xplane legible.

Everything here is host-side: nothing enters the jitted step, so
telemetry costs nothing on the XLA program.  Collective discipline
matches ``dist_signal_handler.py``: any cross-host reduction happens
only at deterministic log boundaries (see ``timers.Timers``).
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax

from megatron_llm_tpu.global_vars import get_counters

# ---------------------------------------------------------------------------
# Peak FLOPs / MFU
# ---------------------------------------------------------------------------

# bf16 peak per chip, keyed by device_kind substrings; spellings vary
# across libtpu versions (v5e reports "TPU v5 lite" or "TPU v5e").
# Single source of truth — bench.py imports this table.
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# MFU above this is physically impossible — the timing loop failed to
# sync with the device (bench.py round-3 caught a 1380-MFU "measurement"
# this way).  Shared by bench.py (which aborts) and the runtime stream
# (which reports null).
MFU_SANITY_LIMIT = 0.95


def peak_flops_for_kind(device_kind: str,
                        assume_tpu: bool = False) -> Optional[float]:
    """Peak bf16 FLOPs for a device_kind string, or None when the
    hardware has no meaningful peak (CPU) — a null peak means MFU is
    never fabricated.  ``assume_tpu`` supplies the v5e default for TPU
    device kinds the table doesn't spell (new libtpu spellings)."""
    for k, v in PEAK_FLOPS.items():
        if k in device_kind:
            return v
    if assume_tpu or "TPU" in device_kind:
        return 197e12
    return None


def peak_flops_for_local_device() -> Optional[float]:
    """Peak FLOPs of this host's first device (None on CPU)."""
    try:
        dev = jax.local_devices()[0]
    except Exception:
        return None
    on_tpu = jax.default_backend() in ("tpu", "axon") \
        or "TPU" in dev.device_kind
    return peak_flops_for_kind(dev.device_kind, assume_tpu=on_tpu)


class ThroughputCalculator:
    """Tokens/sec(/device), achieved TFLOPs/device and MFU from wall time.

    ``flops_per_token`` is the model-level fwd+bwd estimate
    (``model.flops_per_token()``, models/language_model.py); ``peak_flops``
    the per-chip bf16 peak (None => MFU is always null).  All host-side
    float arithmetic — free at log boundaries."""

    def __init__(self, flops_per_token: Optional[float] = None,
                 device_count: Optional[int] = None,
                 peak_flops: Optional[float] = None):
        self.flops_per_token = flops_per_token
        self._device_count = device_count
        self.peak_flops = peak_flops

    @classmethod
    def from_model(cls, model, device_count: Optional[int] = None,
                   peak_flops: Optional[float] = "auto"):
        """Build from any model exposing ``flops_per_token()`` (models
        without one still get tokens/sec accounting)."""
        fpt = None
        fn = getattr(model, "flops_per_token", None)
        if callable(fn):
            try:
                fpt = float(fn())
            except Exception:
                fpt = None
        if peak_flops == "auto":
            peak_flops = peak_flops_for_local_device()
        return cls(flops_per_token=fpt, device_count=device_count,
                   peak_flops=peak_flops)

    @property
    def device_count(self) -> int:
        if self._device_count is None:
            self._device_count = jax.device_count()
        return self._device_count

    def compute(self, tokens: float, elapsed_secs: float) -> Dict[str, Any]:
        """One log boundary's throughput record.  ``tokens`` is the global
        token count per iteration, ``elapsed_secs`` the per-iteration wall
        time.  MFU is null when the peak is unknown (CPU) or the number
        trips the fabrication guard — never a made-up value."""
        n = max(self.device_count, 1)
        tps = tokens / max(elapsed_secs, 1e-9)
        out: Dict[str, Any] = {
            "tokens_per_sec": tps,
            "tokens_per_sec_per_device": tps / n,
            "tflops_per_device": None,
            "mfu": None,
        }
        if self.flops_per_token:
            achieved = tps * self.flops_per_token / n
            out["tflops_per_device"] = achieved / 1e12
            if self.peak_flops:
                mfu = achieved / self.peak_flops
                out["mfu"] = mfu if mfu <= MFU_SANITY_LIMIT else None
        return out


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Bounded deque of the last K step records (MegaScale §5.3: the
    record consulted when a run dies).  Two record kinds: ``dispatch``
    (per-iteration, host-only — never syncs the device) and ``log`` (the
    full log-boundary record)."""

    def __init__(self, capacity: int = 64):
        self.capacity = int(capacity)
        self._records: deque = deque(maxlen=max(self.capacity, 1))

    def record(self, rec: Dict[str, Any]) -> None:
        self._records.append(rec)

    def records(self) -> List[Dict[str, Any]]:
        return list(self._records)

    def __len__(self) -> int:
        return len(self._records)

    def dump(self, path: str, reason: str = "") -> str:
        """Write the recorder as JSON (atomic: tmp + rename — the caller
        may be a watchdog thread racing process death)."""
        payload = {
            "dumped_at_unix": time.time(),
            "reason": reason,
            "capacity": self.capacity,
            "records": self.records(),
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------------
# Device memory
# ---------------------------------------------------------------------------

def device_memory_stats(device=None) -> Dict[str, int]:
    """``memory_stats()`` of one local device, reduced to the portable
    keys (bytes_in_use, peak_bytes_in_use, largest_alloc_size, num_allocs
    — whichever the backend reports).  {} when unavailable (CPU backends
    often return None)."""
    try:
        if device is None:
            device = jax.local_devices()[0]
        stats = device.memory_stats() or {}
    except Exception:
        return {}
    keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
            "largest_alloc_size", "num_allocs")
    return {k: int(stats[k]) for k in keep if k in stats}


# ---------------------------------------------------------------------------
# Structured JSONL stream
# ---------------------------------------------------------------------------

# 2: + interval_time_secs / goodput / tracing
# 3: + layer_stats (per-group grad/param/update norms, non-finite counts —
#    see health.py) on records at --log_layer_stats_interval boundaries
# 4: + per-slice attribution on multi-slice runs (slice_times /
#    worst_slice / goodput.slice_stall_secs) and the elastic_resume /
#    preempt_rescue event kinds — see multislice.py
TELEMETRY_SCHEMA_VERSION = 4
STREAM_FILENAME = "telemetry.jsonl"
FLIGHT_RECORDER_FILENAME = "flight_recorder.json"


class TelemetryStream:
    """One JSONL record per log boundary under ``log_dir`` (process 0
    writes; every process keeps the flight recorder).  Tracks running
    aggregates for the end-of-run summary (mean MFU etc. — percentiles
    are the offline ``tools/telemetry_report.py``'s job)."""

    def __init__(self, log_dir: str, flight_recorder_size: int = 64):
        self.log_dir = log_dir
        self.flight_recorder = FlightRecorder(flight_recorder_size)
        self._file = None
        self._sums = {"steps": 0, "mfu": 0.0, "mfu_n": 0,
                      "tokens_per_sec_per_device": 0.0, "step_time": 0.0}
        if jax.process_index() == 0:
            os.makedirs(log_dir, exist_ok=True)
            self._file = open(os.path.join(log_dir, STREAM_FILENAME),
                              "a", buffering=1)

    def emit(self, record: Dict[str, Any]) -> Dict[str, Any]:
        """Stamp, persist, and flight-record one log-boundary record."""
        rec = {"schema": TELEMETRY_SCHEMA_VERSION, "kind": "log",
               "time_unix": time.time(), **record}
        if self._file is not None:
            self._file.write(json.dumps(rec) + "\n")
        self.flight_recorder.record(rec)
        s = self._sums
        s["steps"] += 1
        s["step_time"] += float(rec.get("step_time_secs") or 0.0)
        s["tokens_per_sec_per_device"] += float(
            rec.get("tokens_per_sec_per_device") or 0.0)
        if rec.get("mfu") is not None:
            s["mfu"] += float(rec["mfu"])
            s["mfu_n"] += 1
        return rec

    def record_dispatch(self, rec: Dict[str, Any]) -> None:
        """Lightweight per-iteration entry — host-side fields only, never
        a device sync, so it is safe (and cheap) every step."""
        self.flight_recorder.record({"kind": "dispatch",
                                     "time_unix": time.time(), **rec})

    def summary(self) -> Dict[str, Any]:
        s = self._sums
        n = max(s["steps"], 1)
        return {
            "log_boundaries": s["steps"],
            "mean_step_time_secs": s["step_time"] / n,
            "mean_tokens_per_sec_per_device":
                s["tokens_per_sec_per_device"] / n,
            "mean_mfu": (s["mfu"] / s["mfu_n"]) if s["mfu_n"] else None,
        }

    def dump_flight_recorder(self, reason: str = "") -> Optional[str]:
        if not len(self.flight_recorder):
            return None
        path = os.path.join(self.log_dir, FLIGHT_RECORDER_FILENAME)
        return self.flight_recorder.dump(path, reason=reason)

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


# Active stream registry: the watchdog/crash path (resilience.py) and the
# wandb finish() summary reach the run's telemetry without threading it
# through every call chain — same pattern as resilience's save-fault hook.
_ACTIVE_STREAM: Optional[TelemetryStream] = None


def install_stream(stream: Optional[TelemetryStream]) -> None:
    global _ACTIVE_STREAM
    _ACTIVE_STREAM = stream


def get_stream() -> Optional[TelemetryStream]:
    return _ACTIVE_STREAM


def get_flight_recorder() -> Optional[FlightRecorder]:
    return _ACTIVE_STREAM.flight_recorder if _ACTIVE_STREAM else None


def dump_flight_recorder(reason: str = "") -> Optional[str]:
    """Dump the active run's flight recorder next to its JSONL stream
    (no-op without an installed stream or records).  Diagnostics path —
    never raises."""
    try:
        if _ACTIVE_STREAM is None:
            return None
        return _ACTIVE_STREAM.dump_flight_recorder(reason=reason)
    except Exception:
        return None


def run_summary() -> Optional[Dict[str, Any]]:
    """The active stream's aggregate summary (wandb finish() pulls this),
    merged with the active tracer's goodput breakdown + recompile /
    straggler counts when tracing is on."""
    out = _ACTIVE_STREAM.summary() if _ACTIVE_STREAM else None
    from megatron_llm_tpu import tracing

    g = tracing.goodput_summary()
    if g is not None:
        out = dict(out or {})
        out["goodput_pct"] = g["goodput_pct"]
        out["goodput"] = g
        out["recompiles"] = int(get_counters().get("recompiles", 0))
        out["straggler_events"] = int(
            get_counters().get("straggler_events", 0))
    return out


# ---------------------------------------------------------------------------
# In-loop profiler capture
# ---------------------------------------------------------------------------

class ProfilerSession:
    """Wraps a chosen iteration window ``[step_start, step_end]`` in
    ``jax.profiler`` trace capture during real training.  The loop calls
    ``maybe_start(upcoming_iteration)`` before dispatch and
    ``maybe_stop(completed_iteration, sync=...)`` after; ``sync`` blocks
    on the step's outputs so the traced window contains the device work,
    not just its dispatch.  One-shot: the window fires once per run."""

    def __init__(self, profile_dir: str, step_start: int, step_end: int,
                 port: Optional[int] = None):
        if step_end < step_start:
            raise ValueError(
                f"profile_step_end ({step_end}) < profile_step_start "
                f"({step_start})")
        self.profile_dir = profile_dir
        self.step_start = int(step_start)
        self.step_end = int(step_end)
        self.active = False
        self.done = False
        self._server = None
        if port:
            # live-capture endpoint (TensorBoard "capture profile")
            self._server = jax.profiler.start_server(int(port))

    def maybe_start(self, upcoming_iteration: int) -> bool:
        if self.done or self.active \
                or upcoming_iteration != self.step_start:
            return False
        os.makedirs(self.profile_dir, exist_ok=True)
        jax.profiler.start_trace(self.profile_dir)
        self.active = True
        print(f" [profiler] trace started at iteration "
              f"{upcoming_iteration} -> {self.profile_dir}", flush=True)
        return True

    def maybe_stop(self, completed_iteration: int,
                   sync: Optional[Callable[[], Any]] = None) -> bool:
        if not self.active or completed_iteration < self.step_end:
            return False
        if sync is not None:
            sync()      # device work of the window lands inside the trace
        jax.profiler.stop_trace()
        self.active = False
        self.done = True
        print(f" [profiler] trace stopped after iteration "
              f"{completed_iteration} (view: tensorboard --logdir "
              f"{self.profile_dir}, profile plugin / Perfetto)", flush=True)
        return True

    def close(self) -> None:
        """Stop an in-flight trace on any exit path (a truncated window
        still yields a usable xplane)."""
        if self.active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self.active = False
            self.done = True


# ---------------------------------------------------------------------------
# Bundle + CLI wiring
# ---------------------------------------------------------------------------

@dataclass
class Telemetry:
    """Everything the train loop needs, in one optional argument."""

    throughput: Optional[ThroughputCalculator] = None
    stream: Optional[TelemetryStream] = None
    profiler: Optional[ProfilerSession] = None
    tracing: Optional[Any] = None       # a tracing.Tracing bundle
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def default(cls, model) -> "Telemetry":
        """Throughput-only telemetry (free): every run reports
        tokens/sec/device + MFU at log boundaries even with no flags."""
        return cls(throughput=ThroughputCalculator.from_model(model))

    def close(self) -> None:
        if self.profiler is not None:
            self.profiler.close()
        if self.tracing is not None:
            # writes the trace file, then uninstalls the module registry
            self.tracing.close()
        if self.stream is not None:
            if get_stream() is self.stream:
                install_stream(None)
            self.stream.close()


def recovery_counters() -> Dict[str, int]:
    from megatron_llm_tpu.resilience import recovery_counters as rc

    return rc()


def build_telemetry(args, model) -> Telemetry:
    """CLI wiring: a Telemetry bundle from parsed args.  Always returns a
    bundle (throughput accounting is free); the stream/profiler members
    exist only when their flags ask for them."""
    t = Telemetry.default(model)
    log_dir = getattr(args, "structured_log_dir", None)
    if log_dir:
        t.stream = TelemetryStream(
            log_dir,
            flight_recorder_size=getattr(args, "flight_recorder_size", 64))
        install_stream(t.stream)
    if getattr(args, "profile", False):
        profile_dir = getattr(args, "profile_dir", None) \
            or (os.path.join(log_dir, "profile") if log_dir
                else "profile_trace")
        t.profiler = ProfilerSession(
            profile_dir,
            step_start=getattr(args, "profile_step_start", 10),
            step_end=getattr(args, "profile_step_end", 12),
            port=getattr(args, "profiler_port", None),
        )
    elif getattr(args, "profiler_port", None):
        # a live-capture server without a pre-chosen window
        jax.profiler.start_server(int(args.profiler_port))
    from megatron_llm_tpu import tracing as _tracing

    t.tracing = _tracing.build_tracing(args)    # None without --trace_dir
    return t
