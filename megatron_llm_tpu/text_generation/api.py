"""High-level generation API.

Reference: ``megatron/text_generation/api.py`` —
``generate_and_post_process`` (:19) / ``beam_search_and_post_process``
(:147).  The reference broadcasts inputs from rank 0 to all ranks before
running (api.py:70-146); under a single JAX controller there is nothing to
broadcast — the functions are plain calls.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from megatron_llm_tpu.text_generation.generation import (
    beam_search,
    generate_tokens,
)


def _tokenize_prompts(tokenizer, prompts: Sequence[str], pad_id: int,
                      add_bos: bool = False):
    tokenized = [tokenizer.tokenize(p) for p in prompts]
    if add_bos:
        # reference tokenization.py prepends eod as the BOS sentinel for
        # GPT-family tokenizers; use a real bos id when the tokenizer has
        # one
        bos = getattr(tokenizer, "bos_token_id", None)
        if bos is None:
            bos = tokenizer.eod
        tokenized = [[bos] + t for t in tokenized]
    lengths = [len(t) for t in tokenized]
    max_len = max(lengths)
    arr = np.full((len(prompts), max_len), pad_id, np.int32)
    for i, t in enumerate(tokenized):
        arr[i, : len(t)] = t
    return jnp.asarray(arr), jnp.asarray(lengths, jnp.int32)


def _single_token_id(tokenizer, text, quiet=False):
    # Resolve ``text`` to the single token id it produces
    # mid-sequence.  BPE vocabs encode '\n' to one id; sentencepiece-
    # style tokenizers can encode it to [] (stripped) or to multiple /
    # context-dependent ids, where blindly taking ids[-1] would make
    # the stop/ban target the wrong id and silently never fire.
    ids = tokenizer.tokenize(text)
    if len(ids) == 1:
        return ids[0]
    # Retry with a leading anchor: if 'a'+text adds exactly one id
    # over 'a', that id is the real mid-sequence encoding.  Guarded:
    # int-only tokenizers (NullTokenizer) raise on alphabetic input,
    # and the graceful answer there is the old None-disable.
    try:
        anchor = tokenizer.tokenize("a")
        ctx = tokenizer.tokenize("a" + text)
    except Exception:
        anchor = ctx = None
    if ctx is not None and len(ctx) == len(anchor) + 1 \
            and ctx[:len(anchor)] == anchor:
        return ctx[-1]
    if not quiet:  # "\n\n" callers expect multi-token encodings
        import warnings
        warnings.warn(
            f"tokenizer encodes {text!r} to {len(ids)} ids "
            f"({ids}); stop/ban rules targeting it are "
            + ("disabled" if not ids
               else "approximate (using last id)"))
    return ids[-1] if ids else None


def resolve_stop_rules(tokenizer, stop_on_eol=False,
                       stop_on_double_eol=False,
                       prevent_newline_after_colon=False):
    """(extra_stop_ids, stop_pairs, ban_pairs) token-id rules for the
    server's eol knobs — shared by the batch ``generate`` path and the
    continuous-batching engine (serving/engine.py), so both stop/ban on
    exactly the same ids."""
    extra_stop, stop_pairs, ban_pairs = [], [], []
    if stop_on_eol or stop_on_double_eol:
        eol = _single_token_id(tokenizer, "\n")
        if stop_on_eol and eol is not None:
            extra_stop.append(eol)
        if stop_on_double_eol:
            # quiet: "\n\n" legitimately encodes to two eol ids on many
            # tokenizers, and that case is fully handled by stop_pairs.
            dbl = _single_token_id(tokenizer, "\n\n", quiet=True)
            if dbl is not None and dbl != eol:
                extra_stop.append(dbl)      # single '\n\n' merge token
            if eol is not None:
                stop_pairs.append((eol, eol))  # two consecutive newlines
    if prevent_newline_after_colon:
        colon = _single_token_id(tokenizer, ":")
        eol = _single_token_id(tokenizer, "\n")
        if colon is not None and eol is not None:
            ban_pairs.append((colon, eol))
    return tuple(extra_stop), tuple(stop_pairs), tuple(ban_pairs)


def generate(
    model,
    params,
    tokenizer,
    prompts: Sequence[str],
    tokens_to_generate: int = 64,
    *,
    top_k: int = 0,
    top_p: float = 0.0,
    temperature: float = 1.0,
    greedy: bool = False,
    seed: int = 0,
    return_log_probs: bool = False,
    batch_times_seqlen_threshold: int = 512,
    add_bos: bool = False,
    top_p_decay: float = 0.0,
    top_p_bound: float = 0.0,
    stop_on_eol: bool = False,
    stop_on_double_eol: bool = False,
    prevent_newline_after_colon: bool = False,
    rolling_cache: Optional[bool] = None,
    cache_len: Optional[int] = None,
    int8_kv_cache: bool = False,
):
    """Returns (texts, token_lists, log_probs or None).

    ``cache_len``: minimum KV-cache allocation (slots); decode masks
    the unused tail, outputs are identical
    (tests/test_generation.py::test_cache_len_padding_is_invisible).
    Decouples per-step attention cost from max_new_tokens (used by
    tools/decode_bench.py).  Does not by itself avoid recompiles —
    the jit keys on prompt shape and tokens_to_generate.

    ``batch_times_seqlen_threshold``: micro-batch the prefill forward
    above this batch*seqlen (reference
    ``--inference_batch_times_seqlen_threshold``, default 512).

    ``rolling_cache``: None (default) auto-enables the O(window) ring
    KV cache exactly when it saves memory — a sliding-window model
    decoding past its window; logits are identical either way
    (tests/test_rolling_kv_cache.py)."""
    pad = getattr(tokenizer, "pad", 0) or 0
    eod = getattr(tokenizer, "eod", None)
    toks, lens = _tokenize_prompts(tokenizer, prompts, pad, add_bos)
    if rolling_cache is None:
        window = model.cfg.sliding_window_size
        rolling_cache = (window is not None
                         and toks.shape[1] + tokens_to_generate > window)
    if int8_kv_cache and rolling_cache:
        # checked AFTER the auto-enable above: the ring cache is already
        # O(window) and has no int8 variant — say so instead of silently
        # serving bf16 KV
        print(" > NOTE: int8_kv_cache is ignored for this request — the "
              "rolling (sliding-window) cache engaged and has no int8 "
              "variant; KV stays bf16", flush=True)

    extra_stop, stop_pairs, ban_pairs = resolve_stop_rules(
        tokenizer, stop_on_eol=stop_on_eol,
        stop_on_double_eol=stop_on_double_eol,
        prevent_newline_after_colon=prevent_newline_after_colon)

    out_tokens, _, log_probs = generate_tokens(
        model, params, toks, lens, jax.random.PRNGKey(seed),
        max_new_tokens=tokens_to_generate,
        min_prompt_len=int(lens.min()),
        top_k=top_k, top_p=top_p, temperature=temperature, greedy=greedy,
        eod_id=eod, return_log_probs=return_log_probs,
        batch_times_seqlen_threshold=batch_times_seqlen_threshold,
        top_p_decay=top_p_decay, top_p_bound=top_p_bound,
        extra_stop_ids=tuple(extra_stop), stop_pairs=tuple(stop_pairs),
        ban_pairs=tuple(ban_pairs), rolling_cache=bool(rolling_cache),
        cache_len=cache_len,
        int8_kv_cache=int8_kv_cache and not rolling_cache,
    )
    out_tokens = np.asarray(out_tokens)
    stop_set = set(extra_stop)
    if eod is not None:
        stop_set.add(eod)
    pair_set = set(stop_pairs)
    texts, token_lists = [], []
    for i, row in enumerate(out_tokens):
        row = row.tolist()
        # trim at the first stop condition after the prompt (eod, an
        # extra stop id, or a stop bigram) — rows frozen by a stop leave
        # the rest of the row at its zero init, which must not reach the
        # caller as detokenized id-0 tokens
        start = int(lens[i])
        end = len(row)
        for j in range(start, len(row)):
            if row[j] in stop_set or (j > 0
                                      and (row[j - 1], row[j]) in pair_set):
                end = j + 1
                break
        row = row[:end]
        token_lists.append(row)
        texts.append(tokenizer.detokenize(row))
    return texts, token_lists, (np.asarray(log_probs) if return_log_probs
                                else None)


def generate_and_post_process(
    model, params, tokenizer, prompts,
    tokens_to_generate: int = 64,
    return_output_log_probs: bool = False,
    top_k_sampling: int = 0,
    top_p_sampling: float = 0.0,
    temperature: float = 1.0,
    random_seed: int = 0,
    batch_times_seqlen_threshold: int = 512,
    add_BOS: bool = False,
    top_p_decay: float = 0.0,
    top_p_bound: float = 0.0,
    stop_on_eol: bool = False,
    stop_on_double_eol: bool = False,
    prevent_newline_after_colon: bool = False,
    int8_kv_cache: bool = False,
    **_unused,
):
    """Reference signature compatibility (api.py:19-69)."""
    texts, tokens, log_probs = generate(
        model, params, tokenizer, prompts, tokens_to_generate,
        top_k=top_k_sampling, top_p=top_p_sampling, temperature=temperature,
        greedy=(top_k_sampling == 1), seed=random_seed,
        return_log_probs=return_output_log_probs,
        batch_times_seqlen_threshold=batch_times_seqlen_threshold,
        add_bos=add_BOS, top_p_decay=top_p_decay, top_p_bound=top_p_bound,
        stop_on_eol=stop_on_eol, stop_on_double_eol=stop_on_double_eol,
        prevent_newline_after_colon=prevent_newline_after_colon,
        int8_kv_cache=int8_kv_cache,
    )
    segments = [[tokenizer.detokenize([t]) for t in row] for row in tokens]
    return texts, segments, log_probs, tokens


def beam_search_and_post_process(
    model, params, tokenizer, prompts,
    tokens_to_generate: int = 64,
    beam_size: int = 4,
    length_penalty: float = 1.0,
    stop_token=None,
    add_BOS: bool = False,
    **_unused,
):
    """Reference: api.py:147-201 (batch of 1); ``stop_token`` overrides
    eod as the beam termination token (the server's stop_token knob)."""
    assert len(prompts) == 1, "beam search supports a single prompt"
    toks, lens = _tokenize_prompts(tokenizer, prompts,
                                   getattr(tokenizer, "pad", 0) or 0,
                                   add_BOS)
    beams, scores = beam_search(
        model, params, toks[:1], beam_size=beam_size,
        max_new_tokens=tokens_to_generate,
        eod_id=(int(stop_token) if stop_token is not None
                else tokenizer.eod),
        length_penalty=length_penalty,
    )
    beams = np.asarray(beams)
    texts = [tokenizer.detokenize(b.tolist()) for b in beams]
    return texts, np.asarray(scores)
