"""Inference / serving stack.

Reference: ``megatron/text_generation/`` — sampling (:14-93), the
KV-cached autoregressive loop (generation.py:89-287), beam search
(:288-416), the broadcast-based API (api.py) and the Flask REST server
(text_generation_server.py).

TPU re-design: generation is ONE jitted ``lax.while_loop`` — prefill +
per-token decode + sampling + EOD early-exit all on device (the reference
runs a Python loop with per-token host sync and cross-rank broadcasts).
Ragged prompts use the reference's scheme: decode starts at the shortest
prompt length and prompt tokens override samples until each row's true
length is passed (generation.py:160+ semantics).
"""

from megatron_llm_tpu.text_generation.api import (
    beam_search_and_post_process,
    generate,
    generate_and_post_process,
)
from megatron_llm_tpu.text_generation.sampling import modify_logits, sample
