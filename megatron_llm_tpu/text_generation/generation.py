"""KV-cached autoregressive generation + beam search.

Reference: ``megatron/text_generation/generation.py`` —
``generate_tokens_probs_and_return_on_first_stage`` (:89-287): incremental
forward with an inference KV cache, per-step sampling, EOD early stop,
optional per-token log-probs; beam search (:288-416) with hypothesis
management in ``beam_utils.py``.

TPU design: the whole decode — prefill, while-loop over positions,
sampling, done-flag early exit — is one compiled function; nothing
round-trips to the host per token.  Ragged prompts follow the reference's
scheme: decoding starts at the minimum prompt length and prompt tokens
override samples until each row's length is passed.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from megatron_llm_tpu.config import TransformerConfig
from megatron_llm_tpu.models.language_model import language_model_forward
from megatron_llm_tpu.models.transformer import rotary_freqs
from megatron_llm_tpu.text_generation.sampling import modify_logits, sample

NEG_INF_LOGIT = -1e10


def init_kv_caches(cfg: TransformerConfig, batch: int, max_len: int,
                   dtype=None, rolling: bool = False,
                   quantized: bool = False):
    """Per-layer decode caches.  ``rolling=True`` (sliding-window models
    only) allocates a ring buffer of exactly ``sliding_window_size``
    slots instead of ``max_len`` — decode memory O(window) rather than
    O(total), a beyond-reference memory mode (the reference's inference
    cache is always full-length).  Forwards of any chunk length are
    exact: attention reads [pre-chunk ring || current chunk] and the
    ring is written after (models/transformer.py rolling branch)."""
    dtype = dtype or cfg.compute_jnp_dtype
    ng, d = cfg.num_query_groups, cfg.head_dim
    if rolling:
        assert cfg.sliding_window_size is not None, \
            "rolling caches need a sliding-window model"
        size = min(max_len, cfg.sliding_window_size)
    else:
        size = max_len
    if quantized:
        # int8 K/V + per-(batch, position, group) fp32 absmax scales
        # (models/transformer.py int8 branch) — halves decode KV HBM
        # traffic vs bf16.  Linear cache only (the rolling ring is
        # already O(window)).
        assert not rolling, "int8 KV cache: linear cache only"
        return [
            {
                "k_q": jnp.zeros((batch, size, ng, d), jnp.int8),
                "k_scale": jnp.ones((batch, size, ng), jnp.float32),
                "v_q": jnp.zeros((batch, size, ng, d), jnp.int8),
                "v_scale": jnp.ones((batch, size, ng), jnp.float32),
                "index": jnp.int32(0),
            }
            for _ in range(cfg.num_layers)
        ]
    return [
        {
            "k": jnp.zeros((batch, size, ng, d), dtype),
            "v": jnp.zeros((batch, size, ng, d), dtype),
            "index": jnp.int32(0),
            # presence marker (value None = empty pytree node): the flag
            # must be STRUCTURAL, not a leaf, so the decode while-loop
            # carry doesn't trace it into a bool array
            **({"rolling": None} if rolling else {}),
        }
        for _ in range(cfg.num_layers)
    ]


def init_paged_kv_caches(cfg: TransformerConfig, num_blocks: int,
                         block_size: int, dtype=None,
                         quantized: bool = False):
    """Per-layer PAGED decode pools for the serving engine
    (serving/kv_blocks.py): ``[num_blocks, block_size, groups, head_dim]``
    K/V pages shared by every active request, addressed through per-slot
    block tables (models/transformer.py paged branch).  Block 0 is the
    reserved garbage block — padded chunk tokens and inactive slots write
    there.  Same dtype handling as ``init_kv_caches``: compute dtype for
    the plain pools, int8 + per-(block, position, group) fp32 absmax
    scales when ``quantized`` (halves decode KV HBM traffic vs bf16)."""
    dtype = dtype or cfg.compute_jnp_dtype
    ng, d = cfg.num_query_groups, cfg.head_dim
    if quantized:
        return [
            {
                "k_pages_q": jnp.zeros((num_blocks, block_size, ng, d),
                                       jnp.int8),
                "k_pages_scale": jnp.ones((num_blocks, block_size, ng),
                                          jnp.float32),
                "v_pages_q": jnp.zeros((num_blocks, block_size, ng, d),
                                       jnp.int8),
                "v_pages_scale": jnp.ones((num_blocks, block_size, ng),
                                          jnp.float32),
            }
            for _ in range(cfg.num_layers)
        ]
    return [
        {
            "k_pages": jnp.zeros((num_blocks, block_size, ng, d), dtype),
            "v_pages": jnp.zeros((num_blocks, block_size, ng, d), dtype),
        }
        for _ in range(cfg.num_layers)
    ]


def _forward_with_cache(model, params, tokens, caches, start_pos):
    """Run the model over ``tokens`` [b, n] writing KV at ``start_pos``;
    returns (logits [b, n, V], new caches)."""
    cfg = model.cfg
    caches = [dict(c, index=jnp.int32(start_pos)) for c in caches]
    b, n = tokens.shape
    position_ids = start_pos + jnp.arange(n)[None, :]
    position_ids = jnp.broadcast_to(position_ids, (b, n))
    logits, new_caches = language_model_forward(
        params, tokens, position_ids, None, cfg,
        rng_key=None, train=False, kv_caches=caches,
    )
    return logits, new_caches


def _prefill_chunks(b: int, n: int, threshold: Optional[int]) -> int:
    """Micro-batch count for the prefill forward: smallest divisor C of b
    with (b/C)*n <= threshold.  Reference ``_with_pipelining_forward_step``
    (text_generation/forward_step.py:17-204) splits exactly these
    over-threshold batch*seqlen forwards into micro batches."""
    if threshold is None or b * n <= threshold or b <= 1:
        return 1
    for c in range(2, b + 1):
        if b % c == 0 and (b // c) * n <= threshold:
            return c
    return b


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "min_prompt_len", "top_k",
                     "top_p", "temperature", "greedy", "eod_id",
                     "return_log_probs", "batch_times_seqlen_threshold",
                     "top_p_decay", "top_p_bound", "extra_stop_ids",
                     "stop_pairs", "ban_pairs", "rolling_cache",
                     "cache_len", "int8_kv_cache"),
)
def generate_tokens(
    model,
    params,
    prompt_tokens: jax.Array,      # [b, max_prompt] right-padded
    prompt_lengths: jax.Array,     # [b]
    rng_key,
    *,
    max_new_tokens: int,
    min_prompt_len: int,
    top_k: int = 0,
    top_p: float = 0.0,
    temperature: float = 1.0,
    greedy: bool = False,
    eod_id: Optional[int] = None,
    return_log_probs: bool = False,
    batch_times_seqlen_threshold: Optional[int] = None,
    top_p_decay: float = 0.0,
    top_p_bound: float = 0.0,
    extra_stop_ids: tuple = (),
    stop_pairs: tuple = (),
    ban_pairs: tuple = (),
    rolling_cache: bool = False,
    cache_len: Optional[int] = None,
    int8_kv_cache: bool = False,
):
    """Returns (tokens [b, total], gen_lengths [b], log_probs [b, total]).

    ``cache_len``: allocate the KV cache with at least this many slots
    (>= prompt + max_new_tokens).  Decode masks cache positions beyond
    the current index, so results are identical; per-step attention
    cost then depends on the allocation, not on max_new_tokens — which
    is what lets benchmarks difference two generation lengths at equal
    per-step cost (tools/decode_bench.py).  NOTE this alone does NOT
    make compiles reusable across request shapes: the jit still keys
    on the prompt array shape and the static max_new_tokens — a server
    wanting few compiles must pad prompts to bucket widths and fix
    max_new_tokens per bucket (at which point the cache size is
    already uniform).  Ignored for rolling caches, which are already
    fixed-size (the sliding window).

    ``int8_kv_cache``: store K/V as int8 with per-(batch, position,
    group) absmax scales — half the decode KV HBM traffic vs bf16,
    the dominant bytes at long context.  Logits shift by the ~0.4%
    per-entry quantization error (tests bound the drift); linear cache
    only.

    ``batch_times_seqlen_threshold``: prefill forwards whose batch*seqlen
    exceeds it run micro-batched (sequential ``lax.map`` chunks), so the
    [b, n, vocab] prefill logits are never materialized at once —
    the reference's ``--inference_batch_times_seqlen_threshold``.

    Reference server semantics (text_generation/generation.py:89-287):
    ``top_p_decay``/``top_p_bound`` multiply top_p by decay each generated
    token with a floor at bound; ``extra_stop_ids`` stop a row like eod
    (stop_on_eol / stop_on_double_eol); ``stop_pairs`` stop on a
    (prev, cur) token bigram (two consecutive newlines); ``ban_pairs``
    zero out token ``b`` whenever the previous token is ``a``
    (prevent_newline_after_colon)."""
    cfg = model.cfg
    b, max_prompt = prompt_tokens.shape
    total = max_prompt + max_new_tokens
    cache_total = total if (cache_len is None or rolling_cache) \
        else max(cache_len, total)
    caches = init_kv_caches(cfg, b, cache_total, rolling=rolling_cache,
                            quantized=int8_kv_cache)

    tokens = jnp.concatenate(
        [prompt_tokens,
         jnp.zeros((b, max_new_tokens), prompt_tokens.dtype)], axis=1
    )
    log_probs = jnp.zeros((b, total), jnp.float32)

    # ---- prefill up to the shortest prompt --------------------------------
    prefill = max(min_prompt_len, 1)
    C = _prefill_chunks(b, prefill, batch_times_seqlen_threshold)
    if C == 1:
        logits, caches = _forward_with_cache(
            model, params, tokens[:, :prefill], caches, 0
        )
        last_logits = logits[:, -1]
        if return_log_probs:
            lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            # log_probs[i, t] = logp of tokens[i, t] given prefix (t >= 1)
            picked = jnp.take_along_axis(
                lp[:, :-1], tokens[:, 1:prefill, None].astype(jnp.int32),
                axis=-1,
            )[..., 0]
            log_probs = jax.lax.dynamic_update_slice(log_probs, picked,
                                                     (0, 1))
    else:
        # micro-batched prefill: per-chunk forward reduces its own logits
        # to (last_logits, picked log-probs) so the full [b, n, vocab]
        # tensor never exists
        bc = b // C
        toks_c = tokens[:, :prefill].reshape(C, bc, prefill)
        # generic over cache layouts (plain k/v, int8 k_q/.../scales,
        # rolling marker): batch-leading tensors reshape, index
        # broadcasts, structural markers pass through — a new cache
        # key can't silently miss this path
        caches_c = [
            {key: (jnp.broadcast_to(val, (C,)) if key == "index"
                   else val if val is None
                   else val.reshape(C, bc, *val.shape[1:]))
             for key, val in c.items()}
            for c in caches
        ]

        def one(chunk):
            toks_i, caches_i = chunk
            logits_i, caches_i = _forward_with_cache(
                model, params, toks_i, caches_i, 0)
            if return_log_probs:
                lp_i = jax.nn.log_softmax(logits_i.astype(jnp.float32), -1)
                picked_i = jnp.take_along_axis(
                    lp_i[:, :-1], toks_i[:, 1:, None].astype(jnp.int32),
                    axis=-1)[..., 0]
            else:
                picked_i = jnp.zeros((bc, prefill - 1), jnp.float32)
            return logits_i[:, -1], picked_i, caches_i

        last_c, picked_c, caches_out = jax.lax.map(one, (toks_c, caches_c))
        last_logits = last_c.reshape(b, -1)
        if return_log_probs:
            log_probs = jax.lax.dynamic_update_slice(
                log_probs, picked_c.reshape(b, prefill - 1), (0, 1))
        caches = [
            {key: (val[0] if key == "index" else val if val is None
                   else val.reshape(b, *val.shape[2:]))
             for key, val in c.items()}
            for c in caches_out
        ]

    # ---- decode loop ------------------------------------------------------
    def cond(state):
        pos, _, _, _, _, done, _ = state
        return (pos < total) & ~jnp.all(done)

    def body(state):
        pos, tokens, caches, last_logits, log_probs, done, key = state
        key, sub = jax.random.split(key)
        prev = jax.lax.dynamic_index_in_dim(tokens, pos - 1, 1,
                                            keepdims=False)
        for a, b_id in ban_pairs:
            # ban token b after token a (prevent_newline_after_colon)
            hit = (prev == a)
            last_logits = last_logits.at[:, b_id].add(
                jnp.where(hit, NEG_INF_LOGIT, 0.0))
        if top_p_decay > 0.0 and top_p > 0.0:
            step_ix = (pos - prefill).astype(jnp.float32)
            top_p_t = jnp.maximum(top_p * top_p_decay ** step_ix,
                                  top_p_bound)
        else:
            top_p_t = top_p
        nxt = sample(last_logits, sub, top_k=top_k, top_p=top_p_t,
                     temperature=temperature, greedy=greedy)
        in_prompt = pos < prompt_lengths
        cur = jax.lax.dynamic_index_in_dim(tokens, pos, 1, keepdims=False)
        new_tok = jnp.where(in_prompt, cur, nxt.astype(tokens.dtype))
        new_tok = jnp.where(done, cur, new_tok)
        tokens = jax.lax.dynamic_update_slice(
            tokens, new_tok[:, None], (0, pos)
        )
        if return_log_probs:
            lp = jax.nn.log_softmax(last_logits.astype(jnp.float32), axis=-1)
            picked = jnp.take_along_axis(
                lp, new_tok[:, None].astype(jnp.int32), axis=-1
            )[..., 0]
            log_probs = jax.lax.dynamic_update_slice(
                log_probs, picked[:, None], (0, pos)
            )
        if eod_id is not None:
            done = done | ((new_tok == eod_id) & ~in_prompt)
        for s in extra_stop_ids:
            done = done | ((new_tok == s) & ~in_prompt)
        for a, b_id in stop_pairs:
            done = done | ((prev == a) & (new_tok == b_id) & ~in_prompt)
        logits, caches = _forward_with_cache(
            model, params, new_tok[:, None], caches, pos
        )
        return (pos + 1, tokens, caches, logits[:, -1], log_probs, done, key)

    state = (jnp.int32(prefill), tokens, caches, last_logits, log_probs,
             jnp.zeros((b,), bool), rng_key)
    pos, tokens, caches, last_logits, log_probs, done, _ = (
        jax.lax.while_loop(cond, body, state)
    )
    return tokens, pos, log_probs


def greedy_generate(model, params, prompt_tokens, prompt_lengths,
                    max_new_tokens, eod_id=None):
    return generate_tokens(
        model, params, prompt_tokens, prompt_lengths, jax.random.PRNGKey(0),
        max_new_tokens=max_new_tokens,
        min_prompt_len=int(prompt_lengths.min()),
        greedy=True, eod_id=eod_id,
    )


# ---------------------------------------------------------------------------
# Beam search (reference: generation.py:288-416 + beam_utils.py)
# ---------------------------------------------------------------------------

@functools.partial(
    jax.jit,
    # length_penalty is deliberately TRACED (it only feeds a trailing
    # scalar power): the server reads it per request, and a static arg
    # would recompile the whole decode for every new value.
    static_argnames=("model", "beam_size", "max_new_tokens", "eod_id"),
)
def beam_search(
    model,
    params,
    prompt_tokens: jax.Array,     # [1, prompt_len]
    *,
    beam_size: int,
    max_new_tokens: int,
    eod_id: int,
    length_penalty: float = 1.0,
):
    """Single-prompt beam search.  Beams ride the batch axis; the KV cache
    is gathered along batch on every reorder (the reference mutates
    per-layer cache tensors in place, generation.py:288-416).

    Jitted with a ``lax.while_loop`` decode (early-exits when every beam
    hit EOD), like ``generate_tokens``: one compile instead of a Python
    step loop — and with a mesh active the GSPMD activation constraints
    compile for any beam count, so beams serve under tp-sharded params
    exactly like sampling (the reference serves beams through the same
    TP x PP path, api.py:147-201)."""
    cfg = model.cfg
    _, prompt_len = prompt_tokens.shape
    total = prompt_len + max_new_tokens
    B = beam_size

    # prefill ONCE at batch 1, then broadcast the caches across the beam
    # axis (all beams share the prompt; a tiled prefill would do B-fold
    # redundant FLOPs and cache writes)
    caches = init_kv_caches(cfg, 1, total)
    logits, caches = _forward_with_cache(
        model, params, prompt_tokens, caches, 0
    )
    caches = [dict(c,
                   k=jnp.broadcast_to(c["k"], (B,) + c["k"].shape[1:]),
                   v=jnp.broadcast_to(c["v"], (B,) + c["v"].shape[1:]))
              for c in caches]
    lp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)

    tokens = jnp.tile(prompt_tokens, (B, 1))
    tokens = jnp.concatenate(
        [tokens, jnp.zeros((B, max_new_tokens), tokens.dtype)], axis=1
    )

    # first expansion: take top beam_size from beam 0 only
    top_lp, top_idx = jax.lax.top_k(lp[0], B)
    scores = top_lp
    tokens = tokens.at[:, prompt_len].set(top_idx.astype(tokens.dtype))
    done = top_idx == eod_id
    # per-hypothesis token count (prompt + own generated tokens, incl. a
    # closing EOD; NOT the filler EODs finished beams keep appending)
    hyp_len = jnp.full((B,), prompt_len + 1, jnp.int32)

    V = lp.shape[-1]

    def cond(state):
        pos, _, _, _, done, _ = state
        return (pos < total - 1) & ~jnp.all(done)

    def body(state):
        pos, tokens, caches, scores, done, hyp_len = state
        cur = jax.lax.dynamic_index_in_dim(tokens, pos, 1, keepdims=True)
        logits, caches = _forward_with_cache(model, params, cur, caches,
                                             pos)
        lp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)
        # finished beams only propose EOD with frozen score
        lp = jnp.where(done[:, None],
                       jnp.full_like(lp, -1e9).at[:, eod_id].set(0.0), lp)
        cand = scores[:, None] + lp               # [B, V]
        flat_scores, flat_idx = jax.lax.top_k(cand.reshape(-1), B)
        beam_src = flat_idx // V
        tok_next = (flat_idx % V).astype(tokens.dtype)

        tokens = jax.lax.dynamic_update_slice(
            tokens[beam_src], tok_next[:, None], (0, pos + 1)
        )
        caches = [dict(c, k=c["k"][beam_src], v=c["v"][beam_src])
                  for c in caches]
        was_done = done[beam_src]
        hyp_len = hyp_len[beam_src] + jnp.where(was_done, 0, 1)
        done = was_done | (tok_next == eod_id)
        return (pos + 1, tokens, caches, flat_scores, done, hyp_len)

    state = (jnp.int32(prompt_len), tokens, caches, scores, done, hyp_len)
    _, tokens, _, scores, _, hyp_len = jax.lax.while_loop(cond, body, state)

    # length-penalised final ranking (reference beam_utils score/len**alpha),
    # normalized by each hypothesis's OWN length so a beam's rank never
    # depends on when the other beams finished
    final = scores / (hyp_len.astype(jnp.float32) ** length_penalty)
    order = jnp.argsort(-final)
    return tokens[order], final[order]
