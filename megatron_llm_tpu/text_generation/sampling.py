"""Top-k / top-p / temperature sampling.

Reference: ``megatron/text_generation/sampling.py:14-93`` —
``modify_logits_for_top_k/top_p`` + ``sample``.  Pure-jnp, jit-safe
(static top_k; top_p via sorted cumulative mass).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e10


def modify_logits(
    logits: jax.Array,
    top_k: int = 0,
    top_p: float = 0.0,
    temperature: float = 1.0,
) -> jax.Array:
    """logits [..., V] -> filtered/scaled logits."""
    logits = logits.astype(jnp.float32)
    if temperature != 1.0 and temperature > 0:
        logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    # top_p may be a traced scalar (per-step decayed value, the
    # reference's top_p_decay/top_p_bound machinery) — the filter is then
    # built unconditionally and gated with jnp.where
    dynamic_p = isinstance(top_p, jax.Array)
    if dynamic_p or (top_p > 0.0 and top_p < 1.0):
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens until cumulative mass exceeds top_p (always keep top-1)
        cutoff_idx = jnp.sum((cum - probs) < top_p, axis=-1, keepdims=True) - 1
        cutoff_idx = jnp.maximum(cutoff_idx, 0)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        filtered = jnp.where(logits < cutoff, NEG_INF, logits)
        if dynamic_p:
            active = (top_p > 0.0) & (top_p < 1.0)
            logits = jnp.where(active, filtered, logits)
        else:
            logits = filtered
    return logits


def sample(
    logits: jax.Array,
    key: jax.Array,
    top_k: int = 0,
    top_p: float = 0.0,
    temperature: float = 1.0,
    greedy: bool = False,
) -> jax.Array:
    """Sample token ids from [..., V] logits (reference: sampling.py:45-93;
    greedy when top_k==1 or temperature==0)."""
    if greedy or top_k == 1 or temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = modify_logits(logits, top_k, top_p, temperature)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
