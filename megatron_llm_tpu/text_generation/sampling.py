"""Top-k / top-p / temperature sampling.

Reference: ``megatron/text_generation/sampling.py:14-93`` —
``modify_logits_for_top_k/top_p`` + ``sample``.  Pure-jnp, jit-safe
(static top_k; top_p via sorted cumulative mass).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e10


def modify_logits(
    logits: jax.Array,
    top_k: int = 0,
    top_p: float = 0.0,
    temperature: float = 1.0,
) -> jax.Array:
    """logits [..., V] -> filtered/scaled logits."""
    logits = logits.astype(jnp.float32)
    if temperature != 1.0 and temperature > 0:
        logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    # top_p may be a traced scalar (per-step decayed value, the
    # reference's top_p_decay/top_p_bound machinery) — the filter is then
    # built unconditionally and gated with jnp.where
    dynamic_p = isinstance(top_p, jax.Array)
    if dynamic_p or (top_p > 0.0 and top_p < 1.0):
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens until cumulative mass exceeds top_p (always keep top-1)
        cutoff_idx = jnp.sum((cum - probs) < top_p, axis=-1, keepdims=True) - 1
        cutoff_idx = jnp.maximum(cutoff_idx, 0)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        filtered = jnp.where(logits < cutoff, NEG_INF, logits)
        if dynamic_p:
            active = (top_p > 0.0) & (top_p < 1.0)
            logits = jnp.where(active, filtered, logits)
        else:
            logits = filtered
    return logits


def sample(
    logits: jax.Array,
    key: jax.Array,
    top_k: int = 0,
    top_p: float = 0.0,
    temperature: float = 1.0,
    greedy: bool = False,
) -> jax.Array:
    """Sample token ids from [..., V] logits (reference: sampling.py:45-93;
    greedy when top_k==1 or temperature==0)."""
    if greedy or top_k == 1 or temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = modify_logits(logits, top_k, top_p, temperature)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def modify_logits_batched(
    logits: jax.Array,          # [S, V]
    top_k: jax.Array,           # [S] int32 (0 = off)
    top_p: jax.Array,           # [S] float32 (0 or 1 = off)
    temperature: jax.Array,     # [S] float32 (0 = greedy rows, untouched)
) -> jax.Array:
    """Per-row traced sampling knobs — the serving engine's decode step
    co-batches requests with different params in one fixed-shape call, so
    none of them can be static (a static knob would recompile the step
    whenever a new request joins the batch).  Same semantics as
    ``modify_logits`` applied row-wise: temperature scale, then top-k,
    then top-p over the top-k-filtered distribution."""
    logits = logits.astype(jnp.float32)
    V = logits.shape[-1]
    t = temperature[:, None]
    logits = jnp.where(t > 0.0, logits / jnp.maximum(t, 1e-6), logits)
    # top-k: value of each row's k-th largest logit via one descending sort
    sorted_l = jnp.sort(logits, axis=-1)[..., ::-1]
    kth = jnp.take_along_axis(
        sorted_l, jnp.clip(top_k - 1, 0, V - 1)[:, None], axis=-1)
    k_active = (top_k > 0) & (top_k < V)
    logits = jnp.where(k_active[:, None] & (logits < kth), NEG_INF, logits)
    # top-p on the filtered rows (matches modify_logits' ordering: the
    # cumulative mass is taken over what survived top-k)
    sorted_p = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_p, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.sum((cum - probs) < top_p[:, None], axis=-1,
                         keepdims=True) - 1
    cutoff = jnp.take_along_axis(sorted_p, jnp.maximum(cutoff_idx, 0),
                                 axis=-1)
    p_active = (top_p > 0.0) & (top_p < 1.0)
    return jnp.where(p_active[:, None] & (logits < cutoff), NEG_INF, logits)


def sample_batched(
    logits: jax.Array,          # [S, V]
    keys: jax.Array,            # [S, 2] uint32 — one PRNG chain per slot
    top_k: jax.Array,
    top_p: jax.Array,
    temperature: jax.Array,
) -> jax.Array:
    """Row-wise ``sample``: greedy rows (temperature 0 or top_k 1) take
    the raw argmax exactly like ``sample``'s greedy branch; the rest draw
    from the filtered distribution with their own PRNG key, so a
    request's sample stream is independent of who it shares the batch
    with."""
    greedy = (temperature <= 0.0) | (top_k == 1)
    filtered = modify_logits_batched(logits, top_k, top_p, temperature)
    drawn = jax.vmap(lambda l, k: jax.random.categorical(k, l))(
        filtered, keys)
    return jnp.where(greedy,
                     jnp.argmax(logits.astype(jnp.float32), axis=-1),
                     drawn).astype(jnp.int32)
