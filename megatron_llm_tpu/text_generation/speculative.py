"""Speculative greedy decoding with prompt-lookup (n-gram) drafting.

Beyond-reference serving acceleration.  Decode on TPU is weight-
bandwidth-bound: a forward over K+1 tokens costs barely more than over
1 (same weight bytes cross HBM), so verifying K guessed tokens in one
chunked cache-forward is nearly free — every accepted guess is a decode
step that never pays the per-token weight read.  Drafts come from
prompt-lookup decoding (n-gram continuation): the most recent earlier
occurrence of the current bigram proposes the next K tokens.  Great on
repetitive workloads (summarization, code edit, RAG quoting); on
adversarial text acceptance drops to 0 and the cost approaches vanilla.

EXACTNESS: output is token-for-token identical to vanilla greedy
decoding (tests/test_speculative.py asserts it).  The accept rule
commits argmax(L_i) for i = 0..a where a is the longest prefix with
draft_i == argmax(L_{i-1}); position p'-1's KV may be stale for a
rejected draft, but the next iteration re-forwards from p'-1 and
overwrites it — the cache invariant (KV valid through p'-2) holds.

Scope: batch 1, greedy, linear cache (the interactive-serving case).
"""

from typing import Optional

import functools

import jax
import jax.numpy as jnp

from megatron_llm_tpu.text_generation.generation import (
    _forward_with_cache,
    init_kv_caches,
)


def _lookup_draft(tokens: jax.Array, pos: jax.Array, k: int) -> jax.Array:
    """Most-recent bigram-match continuation: K guesses for positions
    pos..pos+K-1 given committed tokens[0:pos].  tokens is the [total]
    working buffer (committed prefix + zeros)."""
    total = tokens.shape[0]
    idx = jnp.arange(total)
    b0, b1 = tokens[pos - 2], tokens[pos - 1]
    # match j: committed bigram at (j, j+1) equals the current one, with
    # the continuation window starting before pos (j+2 <= pos-? any
    # earlier occurrence strictly before the current bigram)
    nxt = jnp.roll(tokens, -1)
    match = (tokens == b0) & (nxt == b1) & (idx + 2 < pos) & (idx + 1 < total)
    m = jnp.max(jnp.where(match, idx, -1))  # most recent, or -1
    start = jnp.where(m >= 0, m + 2, 0)
    # dynamic_slice clamps start so the window fits — harmless for
    # guesses (bad guesses just get rejected)
    return jax.lax.dynamic_slice(tokens, (start,), (k,))


def speculative_greedy_generate(
    model,
    params,
    prompt_tokens: jax.Array,   # [1, prompt_len] — NOT right-padded
    prompt_lengths: jax.Array,  # [1] (must equal prompt_len)
    *,
    max_new_tokens: int,
    draft_k: int = 8,
    eod_id: Optional[int] = None,
):
    """Returns (tokens [1, total], gen_lengths [1]) — identical to the
    greedy path of ``generate_tokens`` on the same inputs.

    Validation lives in this unjitted wrapper (prompt_lengths is a
    concrete array here): right-padded prompts are a generate_tokens
    feature this scope does not implement — padding would be treated as
    committed context and silently change the output, so refuse
    (batch-1 serving has no reason to pad)."""
    assert prompt_tokens.shape[0] == 1, "speculative decode is batch-1"
    assert int(jnp.asarray(prompt_lengths).reshape(-1)[0]) \
        == prompt_tokens.shape[1], \
        "speculative decode takes an unpadded batch-1 prompt"
    return _spec_impl(model, params, prompt_tokens,
                      max_new_tokens=max_new_tokens, draft_k=draft_k,
                      eod_id=eod_id)


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "draft_k", "eod_id"),
)
def _spec_impl(
    model,
    params,
    prompt_tokens: jax.Array,
    *,
    max_new_tokens: int,
    draft_k: int = 8,
    eod_id: Optional[int] = None,
):
    cfg = model.cfg
    b, max_prompt = prompt_tokens.shape
    total = max_prompt + max_new_tokens
    K = draft_k
    # working buffer padded by K+1 so the verify window never clamps
    buf = jnp.zeros((total + K + 1,), prompt_tokens.dtype)
    buf = jax.lax.dynamic_update_slice(buf, prompt_tokens[0], (0,))

    caches = init_kv_caches(cfg, b, total + K + 1)

    # ---- prefill all but the last prompt token ----------------------------
    prefill = max_prompt - 1
    logits, caches = _forward_with_cache(
        model, params, prompt_tokens[:, :prefill], caches, 0)

    # carry: (pos = #committed tokens, buf, caches, done)
    state = (jnp.int32(max_prompt), buf, caches, jnp.bool_(False))

    def cond(state):
        pos, _, _, done = state
        return (pos < total) & ~done

    def body(state):
        pos, buf, caches, done = state
        draft = _lookup_draft(buf, pos, K)
        # chunk = [last committed token, draft_1..draft_K] at positions
        # pos-1 .. pos+K-1
        chunk = jnp.concatenate(
            [jax.lax.dynamic_slice(buf, (pos - 1,), (1,)), draft])[None, :]
        logits, new_caches = _forward_with_cache(
            model, params, chunk, caches, pos - 1)
        greedy = jnp.argmax(logits[0], axis=-1).astype(buf.dtype)  # [K+1]
        # accept: longest prefix with draft_i == greedy_{i-1}
        agree = draft == greedy[:-1]
        acc = jnp.cumprod(agree.astype(jnp.int32))
        a = jnp.sum(acc)                        # accepted drafts, 0..K
        n_commit = a + 1                        # + the bonus token
        commit = greedy                          # positions pos..pos+K
        if eod_id is not None:
            # stop at the first committed EOD (inclusive)
            is_eod = commit == eod_id
            hits = jnp.where(is_eod, jnp.arange(K + 1), K + 1)
            first_eod = jnp.min(hits)
            done = done | (first_eod < n_commit)
            n_commit = jnp.minimum(n_commit, first_eod + 1)
        # never commit past the generation budget
        n_commit = jnp.minimum(n_commit, total - pos)
        done = done | (pos + n_commit >= total)
        # masked write of the K+1 window: keep old beyond n_commit
        old = jax.lax.dynamic_slice(buf, (pos,), (K + 1,))
        keep = jnp.arange(K + 1) < n_commit
        window = jnp.where(keep, commit, old)
        buf = jax.lax.dynamic_update_slice(buf, window, (pos,))
        return (pos + n_commit, buf, new_caches, done)

    pos, buf, caches, done = jax.lax.while_loop(cond, body, state)
    return buf[None, :total], (pos - max_prompt)[None]
