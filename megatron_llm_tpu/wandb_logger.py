"""Weights & Biases logging as a tensorboard-writer shim.

Reference: ``megatron/wandb_logger.py:13-162`` — ``WandbTBShim`` exposes
``add_scalar`` so the training loop writes one code path for TB and wandb;
config (project/entity/name/id, API-key file) comes from args
(arguments.py:535-549), flushed each step (training.py:724-727).

``wandb`` is not in this image; the shim degrades to a JSONL metrics file
so runs remain inspectable offline, and uses the real wandb package when
importable.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional


class WandbTBShim:
    def __init__(self, config: dict, project: Optional[str] = None,
                 entity: Optional[str] = None, name: Optional[str] = None,
                 run_id: Optional[str] = None,
                 api_key: Optional[str] = None,
                 fallback_path: str = "wandb_offline.jsonl",
                 resume: str = "allow",
                 force_offline: bool = False):
        # force_offline: write the JSONL stream directly without trying
        # wandb (--tensorboard_dir without --wandb_logger must produce the
        # file the user asked for, not a surprise wandb run)
        self._wandb = None
        self._file = None
        if not force_offline:
            try:
                import wandb  # noqa: F401

                if api_key:
                    os.environ.setdefault("WANDB_API_KEY", api_key)
                self._wandb = wandb
                self._run = wandb.init(project=project, entity=entity,
                                       name=name, id=run_id, resume=resume,
                                       config=config)
            except Exception:
                if resume == "must":
                    # --wandb_resume explicitly demanded that run; silently
                    # degrading to the offline file would fake a resume
                    raise
                self._wandb = None
        if self._wandb is None:
            self._file = open(fallback_path, "a", buffering=1)
            self._file.write(json.dumps({"event": "init", "config": config,
                                         "time": time.time()}) + "\n")
        self._pending = {}

    def add_scalar(self, key: str, value, iteration: int):
        self._pending.setdefault(iteration, {})[key] = float(value)

    def flush(self):
        for it in sorted(self._pending):
            payload = self._pending[it]
            if self._wandb is not None:
                self._wandb.log(payload, step=it)
            else:
                self._file.write(json.dumps({"step": it, **payload}) + "\n")
        self._pending.clear()

    def finish(self):
        # run-level recovery summary (rewinds / save_retries /
        # watchdog_fires / signal_saves) so a run's fault history is
        # visible without scanning the per-step stream
        try:
            from megatron_llm_tpu.resilience import recovery_counters

            summary = recovery_counters()
        except Exception:
            summary = None
        # run-level telemetry aggregates (mean MFU, tokens/sec/device,
        # step time) when a --structured_log_dir stream is active
        try:
            from megatron_llm_tpu.telemetry import run_summary

            t_summary = run_summary()
        except Exception:
            t_summary = None
        self.flush()
        if self._wandb is not None:
            if summary:
                for k, v in summary.items():
                    self._run.summary[f"recovery/{k}"] = v
            if t_summary:
                for k, v in t_summary.items():
                    if v is not None:
                        self._run.summary[f"telemetry/{k}"] = v
            self._run.finish()
        elif self._file is not None:
            if summary is not None:
                self._file.write(json.dumps(
                    {"event": "recovery_summary", **summary}) + "\n")
            if t_summary is not None:
                self._file.write(json.dumps(
                    {"event": "telemetry_summary", **t_summary}) + "\n")
            self._file.close()
